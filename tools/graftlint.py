#!/usr/bin/env python
"""graftlint CLI — JAX-aware static analysis for deepspeed_tpu.

    python tools/graftlint.py deepspeed_tpu                # text report
    python tools/graftlint.py deepspeed_tpu --json         # machine-readable
    python tools/graftlint.py deepspeed_tpu --write-baseline
    python tools/graftlint.py path/to/file.py --rules GL001,GL020

Exit codes: 0 = no new violations (relative to the baseline, which is
auto-discovered at ``.graftlint-baseline.json`` in the repo root);
1 = new violations or unparseable files; 2 = usage error.

Rule catalog + suppression/baseline workflow: docs/static-analysis.md.

The linter is stdlib-only; this wrapper stubs the ``deepspeed_tpu``
parent package so linting never pays (or requires) a jax import.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import types

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _import_linter():
    """Import deepspeed_tpu.analysis.linter without executing
    deepspeed_tpu/__init__.py (which imports jax)."""
    if "deepspeed_tpu" not in sys.modules:
        stub = types.ModuleType("deepspeed_tpu")
        stub.__path__ = [os.path.join(_REPO, "deepspeed_tpu")]
        sys.modules["deepspeed_tpu"] = stub
    sys.path.insert(0, _REPO)
    from deepspeed_tpu.analysis import linter
    return linter


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or package roots (default: deepspeed_tpu)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit a JSON report on stdout")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: .graftlint-baseline.json "
                         "in the repo root when present; 'none' disables)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept all current findings into the baseline "
                         "and exit 0")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule GROUPS to run (e.g. "
                         "`--select spmd` runs only the GL060-family "
                         "SPMD pass); combines with --rules")
    ap.add_argument("--disable", default="",
                    help="comma-separated rule ids to skip")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog (grouped) and exit")
    args = ap.parse_args(argv)

    linter = _import_linter()
    from deepspeed_tpu.analysis.rules import (ALL_RULES,
                                              RULE_GROUP_ALIASES,
                                              RULE_GROUPS)

    if args.list_rules:
        by_id = {}
        for group, ids in RULE_GROUPS.items():
            for rid in ids:
                by_id[rid] = group
        for r in ALL_RULES:
            print(f"{r.id}  {r.name}  [{by_id.get(r.id, '?')}]"
                  f"\n    {r.summary}")
        print(f"\ngroups (--select): {', '.join(sorted(RULE_GROUPS))}")
        return 0

    paths = args.paths or [os.path.join(_REPO, "deepspeed_tpu")]
    for i, p in enumerate(paths):
        if not os.path.exists(p):
            # `python tools/graftlint.py deepspeed_tpu` should work from
            # any cwd: fall back to repo-root-relative resolution
            in_repo = os.path.join(_REPO, p)
            if os.path.exists(in_repo):
                paths[i] = in_repo
                continue
            print(f"graftlint: no such path: {p}", file=sys.stderr)
            return 2

    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    if args.select:
        groups = [RULE_GROUP_ALIASES.get(g.strip().lower(), g.strip())
                  for g in args.select.split(",") if g.strip()]
        unknown = [g for g in groups if g not in RULE_GROUPS]
        if unknown:
            print(f"graftlint: unknown rule group(s) {unknown}; "
                  f"available: {sorted(RULE_GROUPS)}", file=sys.stderr)
            return 2
        selected = [rid for g in groups for rid in RULE_GROUPS[g]]
        rules = sorted(set(selected) | set(rules or ()))
    disable = [r.strip() for r in args.disable.split(",") if r.strip()]
    try:
        result = linter.lint_paths(paths, rules=rules, disable=disable,
                                   root=_REPO)
    except ValueError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    if args.baseline == "none":
        baseline_path = None
    elif args.baseline:
        baseline_path = args.baseline
    else:
        cand = os.path.join(_REPO, linter.BASELINE_DEFAULT)
        baseline_path = cand if os.path.exists(cand) \
            or args.write_baseline else None

    if args.write_baseline:
        path = baseline_path or os.path.join(_REPO, linter.BASELINE_DEFAULT)
        linter.save_baseline(path, result.findings)
        print(f"graftlint: wrote {len(result.findings)} finding(s) to {path}")
        return 0

    linter.apply_baseline(result, baseline_path)

    if args.as_json:
        print(json.dumps(result.to_dict(), indent=1, sort_keys=True))
    else:
        print(linter.format_text(result,
                                 baseline_used=baseline_path is not None))
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
