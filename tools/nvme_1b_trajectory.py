"""Host-side >=1B NVMe-tier trajectory (VERDICT r4 #4).

Runs the streamed ZeRO-Infinity NVMe tier (runtime/infinity.py; reference
stage3.py:1926 optimizer-state swap + pipelined_optimizer_swapper.py) at
1B+ parameters with >90% of optimizer state paged from DISK, entirely on
the LOCAL host (JAX CPU backend): compute, pinned staging, and the AIO
swap files all live on one machine, exactly like a production TPU host —
none of the dev harness's client<->chip tunnel is involved, so the disk
traffic and step times are real.

Prints ONE JSON line:
  {"params_b": 1.03, "offloaded_fraction": 0.97, "steps": N,
   "losses": [...], "tokens_per_sec": ..., "nvme_read_gib_per_step": ...,
   "nvme_written_gib_per_step": ..., "nvme_state_gib": ..., ...}

Usage: python tools/nvme_1b_trajectory.py [n_steps] [--out artifact.json]
"""

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

# local CPU backend, one device, before jax import
flags = os.environ.get("XLA_FLAGS", "")
flags = " ".join(f for f in flags.split()
                 if "host_platform_device_count" not in f)
os.environ["XLA_FLAGS"] = (
    flags + " --xla_force_host_platform_device_count=1").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")

import numpy as np  # noqa: E402


def main() -> dict:
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import Llama
    from deepspeed_tpu.runtime.infinity import StreamedZeroEngine

    steps = int(sys.argv[1]) if len(sys.argv) > 1 and \
        not sys.argv[1].startswith("--") else 20
    if os.environ.get("DS_NVME_TRAJ_TINY"):   # CPU-smoke rigs
        model = Llama(size="tiny", max_seq_len=128, tie_embeddings=False)
        micro, seq = 2, 64
    else:
        # ~1.03B params; layer tier (master+moments -> disk) carries 97%
        model = Llama(hidden_size=1792, num_layers=26, num_heads=16,
                      num_kv_heads=16, intermediate_size=4800,
                      vocab_size=8192, max_seq_len=256,
                      tie_embeddings=False)
        micro, seq = 1, 128
    swap = os.environ.get("DS_NVME_TRAJ_DIR", "/tmp/ds_nvme_1b")
    engine, _, _, _ = ds.initialize(model=model, config={
        "train_batch_size": micro,
        "bf16": {"enabled": True},
        "optimizer": {"type": "FusedAdam",
                      "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "gradient_clipping": 1.0,
        "zero_optimization": {
            "stage": 3,
            "offload_param": {"device": "cpu", "stream": True},
            "offload_optimizer": {"device": "nvme", "nvme_path": swap}},
        "steps_per_print": 10 ** 9})
    assert isinstance(engine, StreamedZeroEngine) and engine._nvme
    n_params = model.config.num_params()
    if not os.environ.get("DS_NVME_TRAJ_TINY"):
        assert n_params >= 1.0e9, n_params

    # fixed batch -> memorization: the loss must strictly fall, proving
    # the disk-paged Adam actually updates a coherent 1B state
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, model.config.vocab_size, (micro, seq + 1))
    data = (tokens[:, :-1], tokens[:, 1:])

    losses = []
    t_compile = time.perf_counter()
    losses.append(float(engine.train_batch(data)))   # compile + step 1
    compile_s = time.perf_counter() - t_compile
    rpt = engine.host_memory_report()
    t0 = time.perf_counter()
    for _ in range(steps - 1):
        losses.append(float(engine.train_batch(data)))
    dt = (time.perf_counter() - t0) / max(steps - 1, 1)
    io = engine._last_nvme_io
    out = {
        "params_b": round(n_params / 1e9, 3),
        "offloaded_fraction": round(rpt["offloaded_fraction"], 3),
        "nvme_state_gib": round(rpt["nvme"] / 2 ** 30, 2),
        "host_state_gib": round(rpt["pinned_host"] / 2 ** 30, 2),
        "nvme_read_gib_per_step": round(io["read"] / 2 ** 30, 2),
        "nvme_written_gib_per_step": round(io["written"] / 2 ** 30, 2),
        "steps": steps,
        "losses": [round(l, 4) for l in losses],
        "loss_first": round(losses[0], 4),
        "loss_last": round(losses[-1], 4),
        # decisively decreasing: every loss in the last quarter of the
        # run sits below every loss in the first quarter (robust to the
        # small bounces of early Adam steps and near-zero noise)
        "decreasing": bool(max(losses[-max(len(losses) // 4, 1):])
                           < min(losses[:max(len(losses) // 4, 1)])),
        "step_s": round(dt, 2),
        "tokens_per_sec": round(micro * seq / dt, 1),
        "compile_plus_first_step_s": round(compile_s, 1),
        "platform": "local host (cpu backend + local NVMe)",
    }
    engine.close()
    return out


if __name__ == "__main__":
    res = main()
    line = json.dumps(res)
    print(line)
    if "--out" in sys.argv:
        Path(sys.argv[sys.argv.index("--out") + 1]).write_text(line + "\n")
