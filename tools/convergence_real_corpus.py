"""Real-corpus convergence vs an independent implementation
(VERDICT r4 #8; reference: tests/model/ convergence suites, SURVEY §4).

Trains a GPT-2-architecture byte-level LM on a REAL public text corpus
(the reference project's markdown docs/blogs, ~1.5 MB of prose, routed
through runtime/data_pipeline's MMapIndexedDataset) twice, at IDENTICAL
hyperparameters and identical batch order:

  1. through deepspeed_tpu.initialize (ZeRO stage 1 engine), and
  2. through an INDEPENDENT from-scratch flax.linen + optax
     implementation written here (no deepspeed_tpu model/engine code),

then writes both loss curves to an artifact. Agreement of the curves is
the parity evidence the synthetic induction-head suite cannot give:
any engine-side numerics bug (loss scaling, grad averaging, optimizer
wiring, data path) shows up as curve divergence against the
independent implementation.

Model is the GPT-2 block architecture (learned positions, pre-LN,
GELU, biases) scaled to the harness's single CPU core; byte-level
vocab avoids any tokenizer download (zero-egress rig).

Usage: python tools/convergence_real_corpus.py [steps] [--tiny]
       [--out artifact.json]
"""

import glob
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

flags = os.environ.get("XLA_FLAGS", "")
flags = " ".join(f for f in flags.split()
                 if "host_platform_device_count" not in f)
os.environ["XLA_FLAGS"] = (
    flags + " --xla_force_host_platform_device_count=1").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

CORPUS_GLOB = "/root/reference/**/*.md"
SEQ, BATCH, LR = 256, 8, 3e-4


# ---------------------------------------------------------------------
def build_corpus(tmpdir: str) -> np.ndarray:
    """Real text -> MMapIndexedDataset (one doc per file) -> flat byte
    stream (exercises the data-pipeline indexed format end to end)."""
    from deepspeed_tpu.runtime.data_pipeline.data_sampling.indexed_dataset \
        import MMapIndexedDataset, MMapIndexedDatasetBuilder

    files = sorted(glob.glob(CORPUS_GLOB, recursive=True))
    assert files, "no corpus files found"
    prefix = os.path.join(tmpdir, "corpus")
    b = MMapIndexedDatasetBuilder(prefix, dtype=np.int32)
    for f in files:
        data = np.frombuffer(Path(f).read_bytes(), np.uint8)
        if len(data) > 32:
            b.add_item(data.astype(np.int32))
    b.finalize()
    ds = MMapIndexedDataset(prefix)
    stream = np.concatenate([np.asarray(ds[i]) for i in range(len(ds))])
    return stream.astype(np.int32)


def batches(stream: np.ndarray, steps: int, seq: int, batch: int):
    """Deterministic batch schedule shared by both implementations."""
    rng = np.random.default_rng(1234)
    hi = len(stream) - seq - 1
    for _ in range(steps):
        starts = rng.integers(0, hi, batch)
        tok = np.stack([stream[s:s + seq + 1] for s in starts])
        yield tok[:, :-1], tok[:, 1:]


def warmup_steps(steps: int) -> int:
    return min(100, max(steps // 5, 1))


# ---------------------------------------------------------------------
# independent implementation: flax.linen + optax, written from scratch
def independent_run(stream, steps, cfg) -> list:
    import flax.linen as nn
    import optax

    V, D, L, H, S = (cfg["vocab"], cfg["d"], cfg["layers"], cfg["heads"],
                     cfg["seq"])

    # GPT-2's init is part of the hyperparameters: normal(0.02)
    # everywhere, residual projections scaled by 1/sqrt(2L)
    init = nn.initializers.normal(0.02)
    resid_init = nn.initializers.normal(0.02 / np.sqrt(2 * L))

    class Block(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = nn.LayerNorm(epsilon=1e-5)(x)
            B, T, _ = h.shape
            qkv = nn.Dense(3 * D, kernel_init=init)(h)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(B, T, H, D // H)
            k = k.reshape(B, T, H, D // H)
            v = v.reshape(B, T, H, D // H)
            s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D // H)
            mask = np.tril(np.ones((T, T), bool))
            s = jnp.where(mask, s, -1e30)
            a = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)
            x = x + nn.Dense(D, kernel_init=resid_init)(
                a.reshape(B, T, D))
            h2 = nn.LayerNorm(epsilon=1e-5)(x)
            m = nn.Dense(4 * D, kernel_init=init)(h2)
            m = nn.Dense(D, kernel_init=resid_init)(
                nn.gelu(m, approximate=True))
            return x + m

    class LM(nn.Module):
        @nn.compact
        def __call__(self, tokens):
            x = nn.Embed(V, D, embedding_init=init)(tokens)
            x = x + self.param(
                "wpe", nn.initializers.normal(0.02), (S, D))[None]
            for _ in range(L):
                x = Block()(x)
            x = nn.LayerNorm(epsilon=1e-5)(x)
            # tied head (GPT-2)
            wte = self.variables["params"]["Embed_0"]["embedding"]
            return x @ wte.T

    model = LM()
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, S), jnp.int32))

    sched = optax.warmup_cosine_decay_schedule(
        0.0, LR, warmup_steps(steps), steps)
    tx = optax.chain(optax.clip_by_global_norm(1.0),
                     optax.adamw(sched, b1=0.9, b2=0.999,
                                 weight_decay=0.01))
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, tok, tgt):
        def loss_fn(p):
            logits = model.apply(p, tok)
            ls = jax.nn.log_softmax(logits.astype(jnp.float32))
            nll = -jnp.take_along_axis(ls, tgt[..., None], -1)
            return jnp.mean(nll)
        loss, g = jax.value_and_grad(loss_fn)(params)
        upd, opt_state = tx.update(g, opt_state, params)
        return optax.apply_updates(params, upd), opt_state, loss

    losses = []
    for tok, tgt in batches(stream, steps, S, BATCH):
        params, opt_state, loss = step(params, opt_state,
                                       jnp.asarray(tok), jnp.asarray(tgt))
        losses.append(float(loss))
    return losses


# ---------------------------------------------------------------------
def engine_run(stream, steps, cfg) -> list:
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import GPT2

    model = GPT2(vocab_size=cfg["vocab"], hidden_size=cfg["d"],
                 num_layers=cfg["layers"], num_heads=cfg["heads"],
                 max_seq_len=cfg["seq"],
                 intermediate_size=4 * cfg["d"])
    engine, _, _, _ = ds.initialize(model=model, config={
        "train_batch_size": BATCH,
        "optimizer": {"type": "AdamW",
                      "params": {"lr": LR, "betas": (0.9, 0.999),
                                 "weight_decay": 0.01}},
        "scheduler": {"type": "WarmupCosineLR",
                      "params": {"warmup_num_steps": warmup_steps(steps),
                                 "total_num_steps": steps,
                                 "warmup_min_ratio": 0.0}},
        "gradient_clipping": 1.0,
        "zero_optimization": {"stage": 1},
        "steps_per_print": 10 ** 9})
    losses = []
    for tok, tgt in batches(stream, steps, cfg["seq"], BATCH):
        losses.append(float(engine.train_batch(
            {"tokens": tok, "targets": tgt})))
    return losses


def main():
    argv = sys.argv[1:]
    args = [a for i, a in enumerate(argv)
            if not a.startswith("--")
            and (i == 0 or argv[i - 1] != "--out")]
    steps = int(args[0]) if args else 2000
    tiny = "--tiny" in sys.argv
    cfg = (dict(vocab=256, d=128, layers=2, heads=4, seq=SEQ) if tiny
           else dict(vocab=256, d=256, layers=4, heads=8, seq=SEQ))
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        stream = build_corpus(td)
    t0 = time.time()
    ours = engine_run(stream, steps, cfg)
    t1 = time.time()
    ref = independent_run(stream, steps, cfg)
    t2 = time.time()
    k = max(steps // 10, 1)
    out = {
        "corpus_bytes": int(len(stream)),
        "corpus": "reference project markdown docs/blogs (public text)",
        "config": cfg, "steps": steps, "batch": BATCH, "lr": LR,
        "warmup": warmup_steps(steps),
        "every": 10,
        "engine_losses": [round(l, 4) for l in ours[::10]],
        "flax_losses": [round(l, 4) for l in ref[::10]],
        "engine_final": round(float(np.mean(ours[-k:])), 4),
        "flax_final": round(float(np.mean(ref[-k:])), 4),
        "final_ratio": round(float(np.mean(ours[-k:]))
                             / float(np.mean(ref[-k:])), 4),
        "engine_seconds": round(t1 - t0, 1),
        "flax_seconds": round(t2 - t1, 1),
    }
    line = json.dumps(out)
    print(line)
    if "--out" in sys.argv:
        Path(sys.argv[sys.argv.index("--out") + 1]).write_text(line + "\n")


if __name__ == "__main__":
    main()
