import sys
from pathlib import Path
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
import time
import numpy as np
import jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
import deepspeed_tpu as ds
from deepspeed_tpu.models import Llama

ga = int(sys.argv[1]) if len(sys.argv) > 1 else 8
stream_dtype = sys.argv[2] if len(sys.argv) > 2 else "master"
micro = int(sys.argv[3]) if len(sys.argv) > 3 else 8
loss_chunk = int(sys.argv[4]) if len(sys.argv) > 4 else 0
seq = 2048
batch = micro * ga
model = Llama(hidden_size=4096, num_layers=32, num_heads=32,
              num_kv_heads=32, intermediate_size=11008,
              vocab_size=32000, max_seq_len=2048,
              remat_policy="segments", attn_impl="flash",
              loss_chunk=loss_chunk, tie_embeddings=False)
engine, _, _, _ = ds.initialize(model=model, config={
    "train_batch_size": batch,
    "train_micro_batch_size_per_gpu": micro,
    "bf16": {"enabled": True},
    "optimizer": {"type": "FusedAdam",
                  "params": {"lr": 1e-4, "weight_decay": 0.01}},
    "gradient_clipping": 1.0,
    "zero_optimization": {
        "stage": 3,
        "offload_param": {"device": "cpu",
                          "stream_dtype": stream_dtype},
        "offload_optimizer": {"device": "cpu",
                              "moment_dtype": "bfloat16"}},
    "steps_per_print": 10 ** 9})
rpt = engine.host_memory_report()
print("host GiB", round(rpt["pinned_host"]/2**30,1), "frac", round(rpt["host_fraction"],3))
tokens = jax.random.randint(jax.random.PRNGKey(0), (batch, seq + 1), 0, 32000)
data = (tokens[:, :-1], tokens[:, 1:])
loss = float(engine.train_batch(data))
t0 = time.perf_counter()
loss = float(engine.train_batch(data))
dt = time.perf_counter() - t0
tps = batch * seq / dt
mfu = tps * model.config.flops_per_token(seq) / 197e12
print("ga", ga, "stream", stream_dtype, "micro", micro, "step_s", round(dt,2), "tps", round(tps,1), "mfu", round(mfu,4), "loss", round(loss,4))
