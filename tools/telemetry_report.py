"""Summarize a telemetry dump (ISSUE 2 + 5): span trace + metrics +
executable ledger, multi-rank trace merging, and snapshot diffing.

Usage::

    # per-run report
    python tools/telemetry_report.py TRACE.trace.json \
        [METRICS.prom | METRICS.metrics.json] [--ledger LEDGER.json] \
        [--json]

    # merge per-rank Chrome traces into one Perfetto timeline with
    # rank-labelled tracks (eyeball straggler skew)
    python tools/telemetry_report.py --merge OUT.trace.json \
        r0.trace.json r1.trace.json ...

    # metric-snapshot regression diff (exit 1 on regression)
    python tools/telemetry_report.py --diff A.json B.json \
        [--threshold 0.05]

    # serving regression gate (ISSUE 6 CI wiring): compare the current
    # bench artifact against the previous one, gating ONLY the serving
    # SLO families (tick_p50_ms, dispatches_per_token, TTFT/ITL p99,
    # tokens_per_sec, fused_occupancy) under per-metric direction-aware
    # thresholds; exit 1 on regression
    python tools/telemetry_report.py --diff BENCH_prev.json \
        BENCH_curr.json --gate serving

Reads the Chrome-trace JSON written by
``telemetry.export_artifacts()`` (or any Chrome-trace file with ``X``
events) and prints a per-span-name table — count, total/mean/max ms,
share of top-level wall time — plus, when a metrics file is given, the
scalar metric values (Prometheus text or the registry's JSON snapshot)
and a serving summary rolling up the ``ds_serving_*`` series,
prefix-cache hit/miss/eviction counters included. ``--ledger`` adds
the per-executable device-truth table (FLOPs, HBM, collectives).

``--json`` emits one machine-readable JSON object instead of tables
(the smoke path CI exercises).

``--diff`` flattens ANY two JSON files to numeric leaves (registry
``.metrics.json`` snapshots and ``BENCH_r*.json`` records both work),
prints per-metric deltas, and exits 1 when a metric regressed past
``--threshold`` (relative). Direction is inferred from the metric
name: throughput-like series regress downward, latency-like series
regress upward; unrecognized series are reported but never gate.
"""

from __future__ import annotations

import argparse
import json
import sys


def load_trace(path: str) -> list[dict]:
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    return [e for e in events if e.get("ph") == "X"]


def span_table(events: list[dict]) -> list[dict]:
    """Per-name aggregate over complete ('X') events, sorted by total
    duration descending."""
    agg: dict[str, dict] = {}
    for e in events:
        dur_ms = float(e.get("dur", 0.0)) / 1e3
        a = agg.setdefault(e["name"], {
            "name": e["name"], "count": 0, "total_ms": 0.0,
            "max_ms": 0.0})
        a["count"] += 1
        a["total_ms"] += dur_ms
        a["max_ms"] = max(a["max_ms"], dur_ms)
    rows = sorted(agg.values(), key=lambda r: -r["total_ms"])
    for r in rows:
        r["mean_ms"] = r["total_ms"] / max(r["count"], 1)
    return rows


def parse_prometheus(path: str) -> dict[str, float]:
    """Flat {series: value} from Prometheus text exposition (the
    OpenMetrics exemplar suffix serving histogram buckets carry —
    ``... # {trace_id="..."} v`` — is stripped, keeping the bucket
    count as the series value)."""
    out: dict[str, float] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if " # {" in line:
                line = line.split(" # {", 1)[0].rstrip()
            try:
                series, value = line.rsplit(None, 1)
                out[series] = float(value)
            except ValueError:
                continue
    return out


def parse_metrics_json(path: str) -> dict[str, float]:
    """Flat {series: value} from the registry's JSON snapshot (scalar
    metrics + histogram count/sum/mean)."""
    with open(path) as f:
        snap = json.load(f)
    out: dict[str, float] = {}
    for name, meta in snap.items():
        for entry in meta.get("values", []):
            labels = entry.get("labels") or {}
            suffix = "".join(f"/{k}={v}" for k, v in sorted(labels.items()))
            if meta.get("type") == "histogram":
                out[f"{name}{suffix}_count"] = entry.get("count", 0)
                out[f"{name}{suffix}_sum"] = entry.get("sum", 0.0)
                out[f"{name}{suffix}_mean"] = entry.get("mean", 0.0)
            else:
                out[f"{name}{suffix}"] = entry.get("value", 0.0)
    return out


def serving_summary(metrics: dict) -> dict:
    """Serving-focused rollup of the flat metrics: every
    ``ds_serving_*`` series (fused-decode efficiency, latency histogram
    aggregates, and the prefix-cache hit/miss/eviction counters +
    occupancy gauges), plus a derived block-level
    ``prefix_hit_rate_derived`` when the hit/miss counters are
    present. Runtime-sanitizer violation counters (``ds_blocksan_*`` /
    ``ds_affinity_*``, ISSUE 11; ``ds_meshsan_*``, ISSUE 15) ride
    along when present — a nonzero value there is a correctness
    finding, not a perf number. The MoE router gauges (``ds_moe_*``
    drop-fraction / expert-load / capacity, ISSUE 16) and the fleet
    health gauges (``ds_fleet_*`` per-replica phi / score / state,
    ISSUE 17) join the same table, so MoE and fleet serving health
    read without raw snapshots."""
    out = {k: v for k, v in sorted(metrics.items())
           if "ds_serving_" in k or "ds_blocksan_" in k
           or "ds_affinity_" in k or "ds_meshsan_" in k
           or "ds_kv_" in k or "ds_moe_" in k or "ds_fleet_" in k
           or "ds_numsan_" in k or "ds_steptrace_" in k
           or "ds_train_goodput" in k or "ds_train_badput" in k}

    def total(stem: str):
        vals = [v for k, v in metrics.items() if stem in k
                and not k.endswith(("_mean",))]
        return sum(vals) if vals else None

    hits = total("ds_serving_prefix_hits_total")
    misses = total("ds_serving_prefix_misses_total")
    if hits is not None and misses is not None and hits + misses > 0:
        out["prefix_hit_rate_derived"] = round(hits / (hits + misses), 4)
    return out


def train_summary(metrics: dict) -> dict:
    """Training-focused rollup (ISSUE 18): the ``ds_train_*`` step /
    loss / loss-scale series, the device-truth overflow counter
    (``ds_overflow_steps_total``), and the numsan numerics findings
    (``ds_numsan_violations_total{kind}`` +
    ``ds_numsan_saturation_ratio{site}``) in ONE table — a blown-up
    run reads as "overflow count, which finding kind, which quantize
    site" without raw snapshots. Adds a derived
    ``overflow_rate_derived`` (overflow steps / total steps) when both
    counters are present.

    The steptrace goodput/badput table (ISSUE 20) rides the same
    rollup: ``ds_train_goodput_fraction``,
    ``ds_train_badput_seconds{bucket}``, the per-step component
    p50/p99 gauges and ``ds_steptrace_*`` (recon error, step count,
    regression findings counter) all carry the ``ds_train_`` /
    ``ds_steptrace_`` stems, plus a derived
    ``badput_total_seconds_derived`` sum over the buckets."""
    out = {k: v for k, v in sorted(metrics.items())
           if "ds_train_" in k or "ds_overflow_" in k
           or "ds_numsan_" in k or "ds_steptrace_" in k}
    steps = next((v for k, v in metrics.items()
                  if "ds_train_steps_total" in k), None)
    ov = next((v for k, v in metrics.items()
               if "ds_overflow_steps_total" in k), None)
    if steps and ov is not None and steps > 0:
        out["overflow_rate_derived"] = round(ov / steps, 4)
    badput = [v for k, v in metrics.items()
              if "ds_train_badput_seconds" in k]
    if badput:
        out["badput_total_seconds_derived"] = round(sum(badput), 6)
    return out


def build_report(trace_path: str, metrics_path: str | None,
                 ledger_path: str | None = None) -> dict:
    events = load_trace(trace_path)
    rows = span_table(events)
    report = {
        "trace": trace_path,
        "n_events": len(events),
        "span_names": len(rows),
        "spans": rows,
    }
    if metrics_path:
        if metrics_path.endswith(".json"):
            report["metrics"] = parse_metrics_json(metrics_path)
        else:
            report["metrics"] = parse_prometheus(metrics_path)
        report["serving"] = serving_summary(report["metrics"])
        report["train"] = train_summary(report["metrics"])
    if ledger_path:
        with open(ledger_path) as f:
            report["ledger"] = json.load(f)
    return report


def print_report(report: dict) -> None:
    print(f"trace: {report['trace']} — {report['n_events']} events, "
          f"{report['span_names']} span names")
    print(f"{'span':<28}{'count':>8}{'total ms':>12}{'mean ms':>10}"
          f"{'max ms':>10}")
    for r in report["spans"]:
        print(f"{r['name'][:27]:<28}{r['count']:>8}"
              f"{r['total_ms']:>12.2f}{r['mean_ms']:>10.2f}"
              f"{r['max_ms']:>10.2f}")
    metrics = report.get("metrics")
    if metrics:
        print()
        print(f"{'metric':<64}{'value':>14}")
        for series in sorted(metrics):
            v = metrics[series]
            sval = f"{v:.6g}" if isinstance(v, float) else str(v)
            print(f"{series[:63]:<64}{sval:>14}")
    serving = report.get("serving")
    if serving:
        print()
        print("serving summary (ds_serving_* incl. prefix cache + "
              "graftsan/meshsan sanitizer counters):")
        print(f"{'series':<64}{'value':>14}")
        for series in sorted(serving):
            v = serving[series]
            sval = f"{v:.6g}" if isinstance(v, float) else str(v)
            print(f"{series[:63]:<64}{sval:>14}")
    train = report.get("train")
    if train:
        print()
        print("train summary (ds_train_* + overflow + numsan numerics "
              "findings/saturation):")
        print(f"{'series':<64}{'value':>14}")
        for series in sorted(train):
            v = train[series]
            sval = f"{v:.6g}" if isinstance(v, float) else str(v)
            print(f"{series[:63]:<64}{sval:>14}")
    ledger = report.get("ledger")
    if ledger:
        print()
        print(f"executable ledger ({ledger.get('n_executables', 0)} "
              "executables; compiler cost/memory ground truth):")
        print(f"{'name':<22}{'calls':>7}{'GFLOP':>10}{'GB acc':>9}"
              f"{'peak HBM':>12}{'collectives':>12}  signature")
        for row in ledger.get("executables", []):
            print(f"{row['name'][:21]:<22}{row['calls']:>7}"
                  f"{row['flops'] / 1e9:>10.3f}"
                  f"{row['bytes_accessed'] / 1e9:>9.3f}"
                  f"{row['peak_hbm_bytes']:>12}"
                  f"{len(row.get('collectives', [])):>12}  "
                  f"{row['signature'][:40]}")
        traffic = ledger.get("traffic", {})
        if traffic:
            print("collective traffic (dispatch-weighted, per mesh "
                  "axis):")
            print(f"{'axis/op':<30}{'sites':>7}{'bytes':>16}")
            for key in sorted(traffic):
                row = traffic[key]
                print(f"{key[:29]:<30}{row['sites']:>7}"
                      f"{row['bytes']:>16}")


# ---------------------------------------------------------------------
# --fleet: fleet.json artifact -> per-replica + fleet rollup view
# ---------------------------------------------------------------------

def fleet_report(path: str) -> dict:
    """Per-replica + fleet rollup view from the versioned
    ``fleet.json`` artifact ALONE (``telemetry.export_artifacts``
    writes it when the fleet plane is on) — no registry, no process,
    no other file needed."""
    with open(path) as f:
        doc = json.load(f)
    replicas = doc.get("replicas") or {}
    return {
        "fleet_id": doc.get("fleet_id"),
        "schema_version": doc.get("schema_version"),
        "version": doc.get("version"),
        "n_replicas": len(replicas),
        "replicas": {n: serving_summary(flat)
                     for n, flat in sorted(replicas.items())},
        "fleet": serving_summary(doc.get("fleet_flat") or {}),
        "health": doc.get("health") or {},
        "errors": doc.get("errors") or {},
    }


def print_fleet(report: dict) -> None:
    print(f"fleet '{report['fleet_id']}' — "
          f"{report['n_replicas']} replica(s), artifact version "
          f"{report['version']} (schema v{report['schema_version']})")
    health = report["health"]
    if health:
        print()
        print("replica health (phi-accrual detector + composite "
              "score):")
        print(f"{'replica':<18}{'state':>10}{'phi':>9}{'score':>8}"
              f"{'beats':>8}{'deaths':>8}{'beat age s':>12}")
        for name in sorted(health):
            row = health[name]
            age = row.get("last_heartbeat_age_s")
            print(f"{name[:17]:<18}{row.get('state', '?'):>10}"
                  f"{row.get('phi', 0.0):>9.3f}"
                  f"{row.get('score', 0.0):>8.3f}"
                  f"{row.get('heartbeats', 0):>8}"
                  f"{row.get('deaths', 0):>8}"
                  f"{age if age is not None else '-':>12}")
    names = sorted(report["replicas"])
    series = sorted({s for flat in report["replicas"].values()
                     for s in flat})
    if series:
        print()
        print("per-replica serving series:")
        print(f"{'series':<52}" + "".join(f"{n[:13]:>14}"
                                          for n in names))
        for s in series:
            cells = "".join(
                f"{report['replicas'][n].get(s, ''):>14.6g}"
                if isinstance(report["replicas"][n].get(s), float)
                else f"{report['replicas'][n].get(s, '-')!s:>14}"
                for n in names)
            print(f"{s[:51]:<52}{cells}")
    fleet = report["fleet"]
    if fleet:
        print()
        print("fleet rollup (counters summed exactly across "
              "replicas; gauges summed — see fleet.json aggregates "
              "for min/max/mean):")
        print(f"{'series':<64}{'value':>14}")
        for s in sorted(fleet):
            v = fleet[s]
            sval = f"{v:.6g}" if isinstance(v, float) else str(v)
            print(f"{s[:63]:<64}{sval:>14}")
    if report["errors"]:
        print()
        for name, err in sorted(report["errors"].items()):
            print(f"unreadable replica {name}: {err}")


# ---------------------------------------------------------------------
# --merge: per-rank Chrome traces -> one Perfetto timeline
# ---------------------------------------------------------------------

def merge_traces(out_path: str, inputs: list[str]) -> dict:
    """Merge several per-rank Chrome-trace files into one document
    with rank-labelled process tracks. Each input keeps its own pid
    (re-assigned to its position when inputs collide on pid 0 — the
    common single-process-per-rank case), so Perfetto renders one
    swimlane group per rank and straggler skew is visible at a
    glance."""
    events: list[dict] = []
    seen_pids: set[int] = set()
    meta: dict = {"merged_from": []}
    for rank, path in enumerate(inputs):
        with open(path) as f:
            doc = json.load(f)
        in_events = (doc.get("traceEvents", [])
                     if isinstance(doc, dict) else doc)
        pids = {e.get("pid", 0) for e in in_events}
        remap = {}
        for pid in sorted(pids):
            new = pid if pid not in seen_pids else rank * 10000 + pid
            while new in seen_pids:
                new += 1
            remap[pid] = new
            seen_pids.add(new)
        label_done = set()
        for e in in_events:
            e = dict(e)
            pid = remap.get(e.get("pid", 0), e.get("pid", 0))
            e["pid"] = pid
            if e.get("ph") == "M" and e.get("name") == "process_name":
                # one rank-qualified label per merged process track
                e = {**e, "args": {"name": f"rank {rank}: "
                     f"{(e.get('args') or {}).get('name', '')}"}}
                label_done.add(pid)
            events.append(e)
        for pid in remap.values():
            if pid not in label_done:
                events.append({"name": "process_name", "ph": "M",
                               "pid": pid, "tid": 0,
                               "args": {"name": f"rank {rank}"}})
        meta["merged_from"].append({"rank": rank, "path": path,
                                    "events": len(in_events)})
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "otherData": meta}
    with open(out_path, "w") as f:
        json.dump(doc, f)
    return doc


# ---------------------------------------------------------------------
# --diff: metric-snapshot regression gate
# ---------------------------------------------------------------------

# substrings deciding a metric's good direction for the gate. Checked
# lower-is-better FIRST: latency suffixes are more specific than the
# throughput stems (e.g. ..._tokens_per_sec vs ..._ttft_seconds_mean).
_LOWER_IS_BETTER = ("_seconds", "_ms", "latency", "ttft", "itl",
                    "skew", "dispatches_per_token", "_time")
_HIGHER_IS_BETTER = ("tokens_per_sec", "samples_per_second", "mfu",
                     "tflops", "hit_rate", "occupancy", "throughput",
                     "headroom", "/value")

# --gate serving (ISSUE 6): the serving regression gate CI runs against
# the previous bench artifact (BENCH_r*.json or a telemetry
# .metrics.json snapshot). Only metrics matching these substrings
# participate, each with its own direction (+1 higher-is-better) and
# relative threshold — the serving-SLO numbers get tighter gates than
# the generic --threshold sweep.
_GATES = {
    "serving": (
        ("fused_tick_p50_ms", -1, 0.10),
        ("tick_p50_ms", -1, 0.10),
        ("tick_vs_compute_ratio", -1, 0.10),
        ("dispatches_per_token", -1, 0.05),
        ("ttft_p99", -1, 0.15),
        ("ttft_seconds", -1, 0.15),
        ("itl_p99", -1, 0.15),
        ("itl_seconds", -1, 0.15),
        # speculative decoding (ISSUE 9): draft acceptance and the
        # tokens-committed-per-(row, tick)-slot multiplier must not
        # shrink, and the spec-on overhead on a drafts-never-hit
        # workload must not creep up. Listed before tokens_per_sec /
        # the _ms stems so the more specific names match first.
        ("spec_overhead_ms", -1, 0.10),
        ("acceptance_rate", +1, 0.05),
        ("tokens_per_dispatch", +1, 0.05),
        # per-request latency decomposition (ISSUE 10): bench
        # serve_openloop's `<component>_p50/p99_ms` fields (registry
        # gauge snapshots flatten without the component label, so
        # only the bench JSON participates). Only the OVERHEAD
        # components gate (queue wait, prefill, first-drain,
        # chain-boundary gap, preemption stall); decode_active scales
        # with tokens generated, so gating it would flag longer
        # outputs as regressions.
        # serving control plane (ISSUE 19, bench serve_openloop
        # load-step phase + serve_autotune stage): goodput under the
        # declared SLOs with the shed/controller armed must not
        # shrink, the controlled queue-wait p99 must not creep back up
        # (the BENCH_r06 failure), and the offline plan must keep
        # beating the hand-tuned baseline it was ranked against. The
        # deliberately-saturated control arms (uncontrolled_*,
        # baseline_/plan_ ttft/itl points) are excluded below.
        ("goodput_under_slo", +1, 0.05),
        ("queue_wait_p99", -1, 0.15),
        ("plan_vs_baseline", +1, 0.05),
        ("queue_wait", -1, 0.15),
        ("first_drain", -1, 0.15),
        ("boundary_gap", -1, 0.15),
        ("preempt_stall", -1, 0.15),
        ("prefill_p", -1, 0.15),
        # disaggregated serving (ISSUE 13, bench `disagg` stage): the
        # cross-mesh KV hand-off leg of the TTFT telescoping must not
        # creep up, and N-replica aggregate throughput must keep
        # scaling (replica_scaling = aggregate / (N x single-replica)).
        # The disagg ITL-flatness ratio (disagg_itl_p99_drift_...)
        # gates through the existing "itl_p99" stem; the deliberately-
        # unmitigated single-engine control figures are excluded below.
        ("migrate", -1, 0.15),
        ("replica_scaling", +1, 0.05),
        # quantized KV cache (ISSUE 12, bench `kvquant` stage): the
        # per-cached-token byte cost must not creep back up and the
        # resident-batch capacity at equal pool bytes must not shrink
        # (the stage's headline 2-4x lever). Tight thresholds — both
        # are deterministic layout arithmetic, not timing.
        ("kv_bytes_per_token", -1, 0.02),
        ("max_resident_batch", +1, 0.02),
        ("tokens_per_sec", +1, 0.05),
        ("fused_occupancy", +1, 0.05),
    ),
    # autotune stage (ISSUE 7): the planner's cost model must not get
    # less accurate (prediction_rel_err: worst relative error over the
    # measured top-K), and the chosen plan's measured throughput must
    # not regress — neither absolutely nor against the hand-tuned
    # baseline config measured in the same stage (plan_vs_baseline).
    "autotune": (
        ("prediction_rel_err", -1, 0.30),
        ("plan_vs_baseline", +1, 0.05),
        ("plan_tokens_per_sec", +1, 0.05),
    ),
    # comms gate (ISSUE 8): the ZeRO++ quantized-wire win is CI-checked
    # against the previous bench artifact / metrics snapshot — HLO-
    # accounted collective payload must not creep back up (a sharding
    # or wire-protocol regression shows up as bytes before it shows up
    # as time, and the static accounting is noise-free so the
    # threshold is tight), the achieved sharded-DP reduction must not
    # shrink, and throughput stays within the usual ±5%.
    "comms": (
        ("wire_reduction", +1, 0.02),
        ("wire_bytes_per_el", -1, 0.02),
        ("wire_bytes", -1, 0.02),
        ("collective_bytes", -1, 0.02),
        ("tokens_per_sec", +1, 0.05),
    ),
    # MoE gate (ISSUE 16, bench `moe_train` + `moe_serve` stages):
    # training MFU on active-params accounting and its ratio against
    # the equal-active-params dense run must not shrink; the int8
    # dispatch-wire slow-link cut is static HLO byte arithmetic (tight
    # threshold), its loss fidelity must not drift; fused-decode
    # throughput, its step-up vs the equal-active-size dense engine,
    # and the greedy-parity horizon gate the serving half.
    "moe": (
        ("dispatch_wire_cut_slow", +1, 0.02),
        ("dispatch_slow_bytes", -1, 0.02),
        ("loss_rel_err_int8_wire", -1, 0.50),
        ("mfu_vs_dense", +1, 0.05),
        ("moe_mfu", +1, 0.05),
        ("moe_vs_dense", +1, 0.05),
        ("greedy_parity_horizon", +1, 0.0),
        ("tokens_per_sec", +1, 0.05),
    ),
    # fleet gate (ISSUE 17, bench `fleet` stage): a replica is killed
    # under open-loop load — how fast the phi-accrual detector marks
    # it and the router stops placing onto it (detection /
    # detection-to-reroute latency), the multi-window SLO burn rates
    # during the incident, and the per-replica placement skew must not
    # creep up; dropped requests are ZERO-tolerance (the drain-and-
    # reroute contract — any drop from a zero baseline gates), and
    # surviving-fleet throughput must hold.
    "fleet": (
        ("detection_to_reroute_ms", -1, 0.25),
        ("detection_ms", -1, 0.25),
        ("slo_burn_rate", -1, 0.25),
        ("dropped", -1, 0.0),
        ("replica_skew", -1, 0.15),
        ("tokens_per_sec", +1, 0.05),
    ),
    # numerics gate (ISSUE 18, bench `numsan` stage + training
    # snapshots): quantize-site saturation must not creep up from the
    # healthy baseline (silent clipping shows up here long before it
    # shows up as loss), fp16 overflow-skipped steps must not grow
    # (zero-tolerance against a zero baseline), the numsan-disabled
    # path must keep compiling ZERO extra executables (deterministic,
    # zero-tolerance), and the armed-probe run's throughput stays
    # within the usual ±5%.
    "numerics": (
        ("saturation_ratio", -1, 0.0),
        ("overflow_steps", -1, 0.0),
        ("extra_executables", -1, 0.0),
        ("tokens_per_sec", +1, 0.05),
    ),
    # train gate (ISSUE 20, steptrace): run goodput must not shrink,
    # the host-overhead legs of the step telescoping (data wait,
    # checkpoint stall) must not creep up — the stems match the
    # component p50/p99 gauges, the bench fields AND the aggregated
    # JSONL step log (data_wait_ms_p99 etc. via _load_numeric) — and
    # the steptrace-disabled path must keep compiling ZERO extra
    # executables (deterministic, zero-tolerance). Throughput rides at
    # the usual ±5%.
    "train": (
        ("goodput_fraction", +1, 0.05),
        ("data_wait", -1, 0.15),
        ("ckpt_stall", -1, 0.15),
        ("component=checkpoint", -1, 0.15),
        ("checkpoint_ms", -1, 0.15),
        ("extra_executables", -1, 0.0),
        ("tokens_per_sec", +1, 0.05),
    ),
}

# metric families a gate must NOT touch even though a stem matches by
# substring: the host-in-loop per-tick scheduler figures ride the dev
# tunnel RTT (serve7b `per_tick_p50_ms`, serving `v2_tick_p50_ms`) and
# would flap the gate on dispatch-path jitter unrelated to the engine.
_GATE_EXCLUDE = {
    # ... plus the disagg stage's CONTROL-arm figures: the single-
    # engine drift ratio and raw per-length chat ITL points exist to
    # show the degradation disaggregation removes — inherently noisy
    # and not a product metric (the disagg_* drift ratio still gates)
    # ... and the ISSUE 19 control arms: the uncontrolled load-step
    # run exists to be terrible (its queue grows unbounded by design),
    # and the saturated serve_autotune latency points grade the
    # traffic, not the engine — the goodput ratios above still gate
    "serving": ("per_tick", "v2_tick", "single_itl", "chat_itl_p99_ms",
                "uncontrolled", "baseline_ttft", "plan_ttft",
                "baseline_itl", "plan_itl", "ctl_itl", "ctl_ttft"),
    # the all-measured error includes the short-step base candidate,
    # the noisiest row — informational, the top-K figure gates
    "autotune": ("rel_err_all",),
}


def _gate_rule(name: str, gate: str):
    """(direction, threshold) for a gated metric, or None when the
    metric does not participate in this gate. First match wins —
    order the table most-specific-first."""
    low = name.lower()
    if any(excl in low for excl in _GATE_EXCLUDE.get(gate, ())):
        return None
    for stem, direction, threshold in _GATES[gate]:
        if stem in low:
            return direction, threshold
    return None


def _flatten_numeric(obj, prefix="") -> dict[str, float]:
    """Any JSON document -> {path: number} over numeric leaves (bool
    excluded). Registry snapshots, bench records, plain dicts all
    flatten the same way."""
    out: dict[str, float] = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            out.update(_flatten_numeric(v, f"{prefix}/{k}" if prefix
                                        else str(k)))
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            out.update(_flatten_numeric(v, f"{prefix}[{i}]"))
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        out[prefix] = float(obj)
    return out


def _load_numeric(path: str) -> dict[str, float]:
    """Numeric leaves of a snapshot file. Accepts a single JSON
    document (registry snapshot, bench record) — or a JSONL log (the
    steptrace step log, the reqtrace access log): JSONL rows aggregate
    per numeric key into ``<key>_{mean,p50,p99,max}`` plus a ``rows``
    count, so two runs of different lengths diff cleanly."""
    with open(path) as f:
        text = f.read()
    try:
        return _flatten_numeric(json.loads(text))
    except json.JSONDecodeError:
        pass
    series: dict[str, list[float]] = {}
    rows = 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        rows += 1
        for k, v in _flatten_numeric(json.loads(line)).items():
            series.setdefault(k, []).append(v)
    out: dict[str, float] = {"rows": float(rows)}
    for k, vals in series.items():
        vals.sort()
        out[f"{k}_mean"] = sum(vals) / len(vals)
        out[f"{k}_p50"] = vals[len(vals) // 2]
        out[f"{k}_p99"] = vals[min(len(vals) - 1, int(len(vals) * 0.99))]
        out[f"{k}_max"] = vals[-1]
    return out


def _direction(name: str) -> int:
    """+1 higher-is-better, -1 lower-is-better, 0 report-only."""
    low = name.lower()
    for stem in _LOWER_IS_BETTER:
        if stem in low:
            return -1
    for stem in _HIGHER_IS_BETTER:
        if stem in low:
            return +1
    return 0


def diff_snapshots(path_a: str, path_b: str,
                   threshold: float = 0.05,
                   gate: str | None = None) -> dict:
    """Compare two metric snapshots (A = baseline, B = candidate).
    Returns {rows, regressions, added, removed}; a row regresses when
    its direction-aware relative change exceeds ``threshold``. With
    ``gate`` (e.g. ``"serving"``) only the gate's metric families
    participate, each under its own per-metric threshold."""
    a = _load_numeric(path_a)
    b = _load_numeric(path_b)
    rows, regressions = [], []
    for name in sorted(set(a) & set(b)):
        va, vb = a[name], b[name]
        if gate is not None:
            rule = _gate_rule(name, gate)
            if rule is None:
                continue
            direction, row_threshold = rule
        else:
            direction, row_threshold = _direction(name), threshold
        rel = (vb - va) / abs(va) if va else (0.0 if vb == va
                                             else float("inf"))
        regressed = bool(
            direction == +1 and rel < -row_threshold
            or direction == -1 and rel > row_threshold)
        row = {"metric": name, "a": va, "b": vb, "rel": rel,
               "direction": direction, "threshold": row_threshold,
               "regressed": regressed}
        rows.append(row)
        if regressed:
            regressions.append(row)
    return {"rows": rows, "regressions": regressions,
            "added": sorted(set(b) - set(a)),
            "removed": sorted(set(a) - set(b)),
            "threshold": threshold, "gate": gate}


def print_diff(diff: dict) -> None:
    print(f"{'metric':<58}{'A':>13}{'B':>13}{'delta%':>9}  gate")
    for row in diff["rows"]:
        rel = row["rel"]
        pct = f"{rel * 100:+.2f}" if abs(rel) != float("inf") else "inf"
        gate = ("REGRESSED" if row["regressed"]
                else {1: "up-good", -1: "down-good", 0: ""}
                [row["direction"]])
        print(f"{row['metric'][:57]:<58}{row['a']:>13.6g}"
              f"{row['b']:>13.6g}{pct:>9}  {gate}")
    for name in diff["removed"]:
        print(f"{name[:57]:<58}{'':>13}{'-':>13}{'':>9}  removed")
    for name in diff["added"]:
        print(f"{name[:57]:<58}{'-':>13}{'':>13}{'':>9}  added")
    n = len(diff["regressions"])
    scope = (f"gate '{diff['gate']}' (per-metric thresholds)"
             if diff.get("gate")
             else f"±{diff['threshold'] * 100:.1f}%")
    print(f"\n{n} regression(s) past {scope} "
          f"over {len(diff['rows'])} shared metrics")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="summarize / merge / diff deepspeed_tpu telemetry "
                    "dumps")
    ap.add_argument("paths", nargs="*",
                    help="report mode: TRACE [METRICS]; --merge mode: "
                         "per-rank trace inputs; --diff mode: A B")
    ap.add_argument("--ledger", default=None,
                    help="per-executable ledger JSON "
                         "(telemetry *.ledger.json)")
    ap.add_argument("--merge", metavar="OUT", default=None,
                    help="merge the input Chrome traces into OUT with "
                         "rank-labelled tracks")
    ap.add_argument("--diff", action="store_true",
                    help="diff two metric snapshots (A B) — JSON "
                         "documents or JSONL logs (steptrace step "
                         "logs aggregate per-key mean/p50/p99/max); "
                         "exit 1 on regression past --threshold")
    ap.add_argument("--threshold", type=float, default=0.05,
                    help="relative regression threshold for --diff "
                         "(default 0.05)")
    ap.add_argument("--gate", choices=sorted(_GATES), default=None,
                    help="restrict --diff to a named gate's metric "
                         "families with per-metric direction-aware "
                         "thresholds (e.g. 'serving': tick_p50_ms, "
                         "dispatches_per_token, TTFT/ITL p99, "
                         "tokens_per_sec); exit 1 on regression")
    ap.add_argument("--fleet", metavar="FLEET_JSON", default=None,
                    help="render per-replica + fleet rollup + health "
                         "views from a telemetry *.fleet.json "
                         "artifact (standalone mode)")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON object")
    args = ap.parse_args(argv)

    if args.fleet:
        report = fleet_report(args.fleet)
        if args.json:
            json.dump(report, sys.stdout)
            print()
        else:
            print_fleet(report)
        return 0

    if args.merge:
        if len(args.paths) < 1:
            ap.error("--merge needs at least one input trace")
        doc = merge_traces(args.merge, args.paths)
        print(f"merged {len(doc['otherData']['merged_from'])} traces "
              f"({len(doc['traceEvents'])} events) -> {args.merge}")
        return 0

    if args.diff:
        if len(args.paths) != 2:
            ap.error("--diff needs exactly two snapshot paths: A B")
        diff = diff_snapshots(args.paths[0], args.paths[1],
                              threshold=args.threshold, gate=args.gate)
        if args.json:
            json.dump(diff, sys.stdout)
            print()
        else:
            print_diff(diff)
        return 1 if diff["regressions"] else 0

    if not args.paths:
        ap.error("report mode needs a trace path "
                 "(or use --merge / --diff)")
    report = build_report(args.paths[0],
                          args.paths[1] if len(args.paths) > 1 else None,
                          ledger_path=args.ledger)
    if args.json:
        json.dump(report, sys.stdout)
        print()
    else:
        print_report(report)
    return 0


if __name__ == "__main__":
    sys.exit(main())
