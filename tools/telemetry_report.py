"""Summarize a telemetry dump (ISSUE 2): span trace + metrics.

Usage::

    python tools/telemetry_report.py TRACE.trace.json [METRICS.prom | METRICS.metrics.json] [--json]

Reads the Chrome-trace JSON written by
``telemetry.export_artifacts()`` (or any Chrome-trace file with ``X``
events) and prints a per-span-name table — count, total/mean/max ms,
share of top-level wall time — plus, when a metrics file is given, the
scalar metric values (Prometheus text or the registry's JSON snapshot)
and a serving summary rolling up the ``ds_serving_*`` series,
prefix-cache hit/miss/eviction counters included.

``--json`` emits one machine-readable JSON object instead of tables
(the smoke path CI exercises).
"""

from __future__ import annotations

import argparse
import json
import sys


def load_trace(path: str) -> list[dict]:
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    return [e for e in events if e.get("ph") == "X"]


def span_table(events: list[dict]) -> list[dict]:
    """Per-name aggregate over complete ('X') events, sorted by total
    duration descending."""
    agg: dict[str, dict] = {}
    for e in events:
        dur_ms = float(e.get("dur", 0.0)) / 1e3
        a = agg.setdefault(e["name"], {
            "name": e["name"], "count": 0, "total_ms": 0.0,
            "max_ms": 0.0})
        a["count"] += 1
        a["total_ms"] += dur_ms
        a["max_ms"] = max(a["max_ms"], dur_ms)
    rows = sorted(agg.values(), key=lambda r: -r["total_ms"])
    for r in rows:
        r["mean_ms"] = r["total_ms"] / max(r["count"], 1)
    return rows


def parse_prometheus(path: str) -> dict[str, float]:
    """Flat {series: value} from Prometheus text exposition."""
    out: dict[str, float] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                series, value = line.rsplit(None, 1)
                out[series] = float(value)
            except ValueError:
                continue
    return out


def parse_metrics_json(path: str) -> dict[str, float]:
    """Flat {series: value} from the registry's JSON snapshot (scalar
    metrics + histogram count/sum/mean)."""
    with open(path) as f:
        snap = json.load(f)
    out: dict[str, float] = {}
    for name, meta in snap.items():
        for entry in meta.get("values", []):
            labels = entry.get("labels") or {}
            suffix = "".join(f"/{k}={v}" for k, v in sorted(labels.items()))
            if meta.get("type") == "histogram":
                out[f"{name}{suffix}_count"] = entry.get("count", 0)
                out[f"{name}{suffix}_sum"] = entry.get("sum", 0.0)
                out[f"{name}{suffix}_mean"] = entry.get("mean", 0.0)
            else:
                out[f"{name}{suffix}"] = entry.get("value", 0.0)
    return out


def serving_summary(metrics: dict) -> dict:
    """Serving-focused rollup of the flat metrics: every
    ``ds_serving_*`` series (fused-decode efficiency, latency histogram
    aggregates, and the prefix-cache hit/miss/eviction counters +
    occupancy gauges), plus a derived block-level
    ``prefix_hit_rate_derived`` when the hit/miss counters are
    present."""
    out = {k: v for k, v in sorted(metrics.items())
           if "ds_serving_" in k}

    def total(stem: str):
        vals = [v for k, v in metrics.items() if stem in k
                and not k.endswith(("_mean",))]
        return sum(vals) if vals else None

    hits = total("ds_serving_prefix_hits_total")
    misses = total("ds_serving_prefix_misses_total")
    if hits is not None and misses is not None and hits + misses > 0:
        out["prefix_hit_rate_derived"] = round(hits / (hits + misses), 4)
    return out


def build_report(trace_path: str, metrics_path: str | None) -> dict:
    events = load_trace(trace_path)
    rows = span_table(events)
    report = {
        "trace": trace_path,
        "n_events": len(events),
        "span_names": len(rows),
        "spans": rows,
    }
    if metrics_path:
        if metrics_path.endswith(".json"):
            report["metrics"] = parse_metrics_json(metrics_path)
        else:
            report["metrics"] = parse_prometheus(metrics_path)
        report["serving"] = serving_summary(report["metrics"])
    return report


def print_report(report: dict) -> None:
    print(f"trace: {report['trace']} — {report['n_events']} events, "
          f"{report['span_names']} span names")
    print(f"{'span':<28}{'count':>8}{'total ms':>12}{'mean ms':>10}"
          f"{'max ms':>10}")
    for r in report["spans"]:
        print(f"{r['name'][:27]:<28}{r['count']:>8}"
              f"{r['total_ms']:>12.2f}{r['mean_ms']:>10.2f}"
              f"{r['max_ms']:>10.2f}")
    metrics = report.get("metrics")
    if metrics:
        print()
        print(f"{'metric':<64}{'value':>14}")
        for series in sorted(metrics):
            v = metrics[series]
            sval = f"{v:.6g}" if isinstance(v, float) else str(v)
            print(f"{series[:63]:<64}{sval:>14}")
    serving = report.get("serving")
    if serving:
        print()
        print("serving summary (ds_serving_* incl. prefix cache):")
        print(f"{'series':<64}{'value':>14}")
        for series in sorted(serving):
            v = serving[series]
            sval = f"{v:.6g}" if isinstance(v, float) else str(v)
            print(f"{series[:63]:<64}{sval:>14}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="summarize a deepspeed_tpu telemetry dump")
    ap.add_argument("trace", help="Chrome-trace JSON "
                                  "(telemetry export_artifacts *.trace.json)")
    ap.add_argument("metrics", nargs="?", default=None,
                    help="optional *.prom (Prometheus text) or "
                         "*.metrics.json (registry snapshot)")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON object")
    args = ap.parse_args(argv)
    report = build_report(args.trace, args.metrics)
    if args.json:
        json.dump(report, sys.stdout)
        print()
    else:
        print_report(report)
    return 0


if __name__ == "__main__":
    sys.exit(main())
