import sys
from pathlib import Path
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
import time
import numpy as np
import jax, jax.numpy as jnp
import deepspeed_tpu as ds
from deepspeed_tpu.models import Llama, Mixtral

moe = Mixtral(hidden_size=1024, num_layers=12, num_heads=8, num_kv_heads=8,
              intermediate_size=2816, num_experts=8, moe_top_k=2,
              vocab_size=32000, max_seq_len=2048)
dense = Llama(hidden_size=1024, num_layers=12, num_heads=8, num_kv_heads=8,
              intermediate_size=2816, vocab_size=32000, max_seq_len=2048)
B, P, N = 16, 128, 64
rng = np.random.default_rng(0)
prompts = jnp.asarray(rng.integers(0, 32000, size=(B, P)))

def decode_tps(model, grouped=None):
    # the engine binds the dispatch mode at construction (per-engine
    # model copy): pass it through the config, never set it post-hoc
    e = ds.init_inference(model, dtype="bfloat16", max_out_tokens=512,
                          moe_grouped_dispatch=bool(grouped))
    np.asarray(e.generate(prompts, max_new_tokens=N))
    reps = 3
    t0 = time.perf_counter()
    for _ in range(reps):
        out = e.generate(prompts, max_new_tokens=N)
    np.asarray(out)
    return B * N / ((time.perf_counter() - t0) / reps)

m_grp = decode_tps(moe, grouped=True)        # opt-in grouped dispatch
m_ein = decode_tps(moe)                      # einsum path (default)
d = decode_tps(dense)
print("moe grouped tps", round(m_grp,1), "moe einsum tps", round(m_ein,1),
      "dense tps", round(d,1))
print("overhead grouped", round(d/m_grp,2), "einsum", round(d/m_ein,2))
