"""Dev runner for bench.serve7b_int8 on the real chip."""
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
import jax
jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")
import bench  # noqa: E402
import deepspeed_tpu as ds  # noqa: E402

print("devices:", jax.devices())
res = bench.serve7b_int8(ds, on_tpu=jax.devices()[0].platform != "cpu")
print(json.dumps(res))
