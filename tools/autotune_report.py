#!/usr/bin/env python
"""Render an autotuning plan artifact (ISSUE 7) as a ranked table.

The plan JSON comes from ``Plan.save()`` — ``bench.py`` writes one at
``artifacts/autotune_plan.json`` during the ``autotune`` stage, and
``Planner.plan()`` callers can write their own. Shows every ranked
candidate with its predicted (and, for the measured top-K, observed)
step time, the compiler-reported AOT peak HBM next to the memory
model's prediction, per-axis collective payload, and the chosen
config diff ``Plan.apply()`` replays.

Serving plans (ISSUE 19, ``ServingPlan.save()`` / the bench
``serve_autotune`` stage, ``artifacts/serving_plan.json``) carry
``kind: "serving"`` and render as the ranked traffic-model table
instead: predicted TTFT/ITL/queue-wait/goodput per candidate plus the
measured truth the bench stamped onto the chosen row.

Stdlib-only on purpose (like tools/graftlint.py): reading a plan must
not need jax.

    python tools/autotune_report.py artifacts/autotune_plan.json
    python tools/autotune_report.py artifacts/serving_plan.json
    python tools/autotune_report.py plan.json --json   # machine-readable
"""

from __future__ import annotations

import argparse
import json
import sys


def _human_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024.0:
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} PB"


def _fmt(v, nd: int = 2) -> str:
    return "-" if v is None else f"{v:.{nd}f}"


def candidate_rows(plan: dict) -> list[dict]:
    """Ranked candidates first (rank order), then compile errors, then
    pruned — the same order the planner emits."""
    return list(plan.get("candidates", []))


def print_serving_report(plan: dict) -> None:
    """Serving-plan rendering (ISSUE 19): the ranked ServingCandidate
    grid from ``ServingPlan.save()`` (``kind: "serving"``, written by
    the bench ``serve_autotune`` stage) — predicted TTFT/ITL/queue-wait
    /goodput per candidate, measured truth where the bench stamped it,
    and the config patch ``ServingPlan.apply()`` replays."""
    tr = plan.get("traffic", {})
    cal = plan.get("calibration", {})
    print(f"serving plan v{plan.get('version')} — "
          f"{tr.get('arrival_rate_rps', 0):g} req/s, "
          f"{tr.get('prompt_tokens', '?')} prompt + "
          f"{tr.get('output_tokens', '?')} output tok, "
          f"SLO ttft {tr.get('slo_ttft_ms', 0):g} ms / "
          f"itl {tr.get('slo_itl_ms', 0):g} ms")
    print(f"calibration: {cal.get('source', '?')}  "
          f"tick {cal.get('decode_tick_s', 0) * 1e3:.3f} ms  "
          f"dispatch RTT {cal.get('dispatch_overhead_s', 0) * 1e3:.3f}"
          f" ms  prefill {cal.get('prefill_tokens_per_s', 0):g} tok/s")
    print()
    hdr = (f"{'rank':>4} {'candidate':<28}{'ttft ms':>9}{'itl ms':>8}"
           f"{'q-wait ms':>10}{'rho':>7}{'shed%':>7}{'goodput':>9}"
           f"{'meas gp':>9}{'meas ttft':>10}")
    print(hdr)
    print("-" * len(hdr))
    for row in candidate_rows(plan):
        if row.get("pruned"):
            print(f"{'--':>4} {row['label']:<28}pruned: "
                  f"{row['pruned']}")
            continue
        rho = row.get("predicted_rho")
        shed = row.get("predicted_shed_frac")
        print(f"{row.get('rank', '?'):>4} {row['label']:<28}"
              f"{_fmt(row.get('predicted_ttft_ms')):>9}"
              f"{_fmt(row.get('predicted_itl_ms')):>8}"
              f"{_fmt(row.get('predicted_queue_wait_ms'), 1):>10}"
              f"{_fmt(rho):>7}"
              f"{('%d' % (shed * 100) if shed is not None else '-'):>7}"
              f"{_fmt(row.get('predicted_goodput_rps'), 1):>9}"
              f"{_fmt(row.get('measured_goodput_rps'), 1):>9}"
              f"{_fmt(row.get('measured_ttft_p99_ms'), 1):>10}")
    chosen_i = plan.get("chosen_index", -1)
    cands = plan.get("candidates", [])
    print()
    if 0 <= chosen_i < len(cands):
        print(f"chosen: {cands[chosen_i]['label']}")
        diff = plan.get("config_diff", {})
        if diff:
            print("config diff (base -> chosen; ServingPlan.apply() "
                  "replays this):")
            for path, (a, b) in sorted(diff.items()):
                print(f"  {path}: {a!r} -> {b!r}")
        else:
            print("config diff: none (the base config won)")
    else:
        print("chosen: none (no candidate ranked)")


def print_report(plan: dict) -> None:
    info = plan.get("model_info", {})
    cal = plan.get("calibration", {})
    print(f"autotune plan v{plan.get('version')} — "
          f"{info.get('model', '?')} "
          f"({info.get('num_params', 0):,} params) on "
          f"{plan.get('n_devices', '?')} device(s)")
    print(f"calibration: {cal.get('source', '?')}  "
          f"eff {cal.get('flops_per_s', 0) / 1e9:.1f} GFLOP/s  "
          f"overhead {cal.get('overhead_s', 0) * 1e3:.2f} ms  "
          f"overlap {cal.get('overlap_ratio', 0):.2f}")
    print()
    hdr = (f"{'rank':>4} {'candidate':<44}{'pred ms':>9}{'meas ms':>9}"
           f"{'err':>7}{'tok/s pred':>12}{'tok/s meas':>12}"
           f"{'peak HBM':>10}{'coll B':>10}")
    print(hdr)
    print("-" * len(hdr))
    for row in candidate_rows(plan):
        if row.get("pruned"):
            why = row["pruned"]
            print(f"{'--':>4} {row['label']:<44}"
                  f"{'pruned: modeled ':>20}"
                  f"{_human_bytes(why.get('modeled_bytes', 0))} > "
                  f"headroom {_human_bytes(why.get('headroom_bytes', 0))}")
            continue
        if row.get("error"):
            print(f"{'!!':>4} {row['label']:<44}error: "
                  f"{row['error'][:60]}")
            continue
        aot = row.get("aot", {})
        err = row.get("prediction_rel_err")
        coll = sum(aot.get("collective_bytes_by_axis", {}).values())
        print(f"{row.get('rank', '?'):>4} {row['label']:<44}"
              f"{_fmt(row.get('predicted_step_ms')):>9}"
              f"{_fmt(row.get('measured_step_ms')):>9}"
              f"{('%d%%' % (err * 100) if err is not None else '-'):>7}"
              f"{_fmt(row.get('predicted_tokens_per_sec'), 0):>12}"
              f"{_fmt(row.get('measured_tokens_per_sec'), 0):>12}"
              f"{_human_bytes(aot.get('peak_hbm_bytes', 0)):>10}"
              f"{_human_bytes(coll):>10}")
    chosen_i = plan.get("chosen_index", -1)
    cands = plan.get("candidates", [])
    print()
    if 0 <= chosen_i < len(cands):
        print(f"chosen: {cands[chosen_i]['label']}")
        diff = plan.get("config_diff", {})
        if diff:
            print("config diff (base -> chosen; Plan.apply() replays "
                  "this):")
            for path, (a, b) in sorted(diff.items()):
                print(f"  {path}: {a!r} -> {b!r}")
        else:
            print("config diff: none (the base config won)")
    else:
        print("chosen: none (no candidate ranked)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render a deepspeed_tpu autotuning plan artifact")
    ap.add_argument("plan", help="plan JSON (Plan.save() output, e.g. "
                                 "artifacts/autotune_plan.json)")
    ap.add_argument("--json", action="store_true",
                    help="emit {summary, chosen, config_diff} as JSON")
    args = ap.parse_args(argv)
    with open(args.plan) as f:
        plan = json.load(f)
    serving = plan.get("kind") == "serving"
    if args.json:
        cands = plan.get("candidates", [])
        ranked = [c for c in cands if "rank" in c]
        measured = [c for c in ranked
                    if c.get("measured_goodput_rps" if serving
                             else "measured_step_ms") is not None]
        errs = [c["prediction_rel_err"] for c in measured
                if c.get("prediction_rel_err") is not None]
        chosen_i = plan.get("chosen_index", -1)
        out = {
            "n_candidates": len(cands),
            "n_ranked": len(ranked),
            "n_measured": len(measured),
            "prediction_rel_err": max(errs) if errs else None,
            "chosen": (cands[chosen_i]
                       if 0 <= chosen_i < len(cands) else None),
            "config_diff": plan.get("config_diff", {}),
        }
        json.dump(out, sys.stdout)
        print()
    elif serving:
        print_serving_report(plan)
    else:
        print_report(plan)
    return 0


if __name__ == "__main__":
    sys.exit(main())
