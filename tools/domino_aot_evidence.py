"""Domino overlap: HLO-level evidence via AOT TPU compilation
(VERDICT r4 #10; reference: runtime/domino/transformer.py:19).

AOT-compiles the chunked tensor-parallel layer for a v5e-2x4 topology
(no hardware needed) and reports what the TPU compiler actually does
with the per-chunk all-reduces, with and without the async-collective
fusion flags. Findings this tool reproduces (r5):

- typical payloads (<32 MiB/chunk): XLA MERGES the per-chunk
  all-reduces into one per reduction point — the compiled comm pattern
  is identical to the unchunked layer, i.e. Domino's restructuring is
  SUBSUMED BY XLA's collective combiner;
- large payloads (>=32 MiB/chunk): per-chunk all-reduces survive and
  sit between the chunk GEMM fusions in the instruction schedule, but
  the textual TPU HLO exposes NO async all-reduce-start/done pairs
  (even with --xla_tpu_enable_async_collective_fusion*), so
  compute/comm overlap cannot be proven at the HLO level on this
  backend — it is the TPU runtime's decision.

Prints one JSON line with the all-reduce counts per configuration.
"""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from deepspeed_tpu.utils.jax_compat import shard_map  # noqa: E402
from jax.experimental import topologies  # noqa: E402
from jax.sharding import Mesh, PartitionSpec as P  # noqa: E402

from deepspeed_tpu.runtime.domino import DominoTransformerLayer  # noqa: E402

ASYNC_FLAGS = {
    "xla_tpu_enable_async_collective_fusion": "true",
    "xla_tpu_enable_async_collective_fusion_fuse_all_reduce": "true",
}


def compile_counts(rows: int, n_micro: int = 4, d: int = 4096,
                   opts: dict | None = None) -> dict:
    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name="v5e:2x4")
    mesh = Mesh(np.array(topo.devices).reshape(8), ("tp",))

    def attn_fn(p, xc):   # col-parallel then row-parallel: reduce pending
        return (xc @ p["a_in"]) @ p["a_out"]

    def mlp_fn(p, xc):
        return (xc @ p["m_in"]) @ p["m_out"]

    layer = DominoTransformerLayer(attn_fn, mlp_fn,
                                   lambda x: jax.lax.psum(x, "tp"),
                                   n_micro=n_micro)

    def step(p, x):
        return shard_map(
            lambda p, x: layer(p, x), mesh=mesh,
            in_specs=({"a_in": P(None, "tp"), "a_out": P("tp", None),
                       "m_in": P(None, "tp"), "m_out": P("tp", None)},
                      P()),
            out_specs=P(), check_vma=False)(p, x)

    pa = {k: jax.ShapeDtypeStruct((d, d), jnp.bfloat16)
          for k in ("a_in", "a_out", "m_in", "m_out")}
    xa = jax.ShapeDtypeStruct((rows, d), jnp.bfloat16)
    lowered = jax.jit(step).lower(pa, xa)
    compiled = (lowered.compile(compiler_options=opts) if opts
                else lowered.compile())
    hlo = compiled.as_text()
    chunk_mib = rows // n_micro * d * 2 / 2 ** 20
    return {
        "chunk_payload_mib": round(chunk_mib, 1),
        "logical_reduces": 2 * n_micro,
        "all_reduce": hlo.count("all-reduce("),
        "async_start": hlo.count("all-reduce-start"),
        "async_done": hlo.count("all-reduce-done"),
    }


def main() -> dict:
    small = compile_counts(rows=4096)
    big = compile_counts(rows=32768)
    big_async = compile_counts(rows=32768, opts=ASYNC_FLAGS)
    return {
        "metric": "domino_aot_hlo_evidence",
        "small_payload": small,
        "big_payload": big,
        "big_payload_async_flags": big_async,
        "merged_at_small": small["all_reduce"] < small["logical_reduces"],
        "chunked_at_big": big["all_reduce"] == big["logical_reduces"],
        "async_pairs_exposed": big_async["async_start"] > 0,
        "conclusion": (
            "subsumed-by-XLA at typical sizes (collective combiner "
            "restores the unchunked comm pattern); per-chunk reduces "
            "survive only at >=32MiB payloads and the TPU HLO never "
            "exposes async start/done pairs, so overlap is the "
            "runtime's call — Domino chunking is free but its overlap "
            "claim is closed as unverifiable-by-construction here"),
    }


if __name__ == "__main__":
    print(json.dumps(main()))
