"""int8-expert MoE decode vs dense at batch 16/64 (routing-overhead
floor sweep) on the real chip. Run from the repo root."""
import sys
from pathlib import Path
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
import time
import numpy as np
import jax, jax.numpy as jnp
import deepspeed_tpu as ds
from deepspeed_tpu.models import Llama, Mixtral

def decode_tps(model, B, P=128, N=64, **kw):
    e = ds.init_inference(model, dtype="bfloat16", max_out_tokens=512, **kw)
    rng = np.random.default_rng(0)
    prompts = jnp.asarray(rng.integers(0, 32000, size=(B, P)))
    np.asarray(e.generate(prompts, max_new_tokens=N))
    reps = 3
    t0 = time.perf_counter()
    for _ in range(reps):
        out = e.generate(prompts, max_new_tokens=N)
    np.asarray(out)
    return B * N / ((time.perf_counter() - t0) / reps)

kw = dict(hidden_size=1024, num_layers=12, num_heads=8, num_kv_heads=8,
          intermediate_size=2816, vocab_size=32000, max_seq_len=2048)
for B in (16, 64):
    moe = Mixtral(num_experts=8, moe_top_k=2, **kw)
    dense = Llama(**kw)
    mq = decode_tps(moe, B, quantize_moe_experts=True)
    mb = decode_tps(moe, B)
    d = decode_tps(dense, B)
    print(f"B={B} moe_int8 {round(mq,1)} moe_bf16 {round(mb,1)} "
          f"dense {round(d,1)} ratio_int8 {round(d/mq,2)} "
          f"ratio_bf16 {round(d/mb,2)}")
