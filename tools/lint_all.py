#!/usr/bin/env python
"""lint_all — the whole static gate in one stdlib-only command.

    python tools/lint_all.py            # everything, one exit code
    python tools/lint_all.py --json     # machine-readable section report

Runs, in order (ISSUE 15 satellite — one invocation, single exit code,
no jax import anywhere):

1. **graftlint** — all rules (GL001-GL073 incl. the shardlint SPMD
   group and the numlint numerics group) over ``deepspeed_tpu/``
   against ``.graftlint-baseline.json``;
2. **spmd group** — the GL060-family pass alone (same findings subset;
   kept as its own section so a CI lane can see the SPMD gate status
   at a glance — equivalent to ``graftlint.py --select spmd``), and
   the GL070-family **numerics group** the same way (ISSUE 18;
   equivalent to ``graftlint.py --select numerics``);
3. **host-only audits** — ``traced_roots`` over the packages whose
   contract forbids jit-reachable code: ``autotuning/`` (deterministic
   planner ranking, incl. the ISSUE 19 serving planner in
   ``autotuning/serving.py``), ``serving/`` (the async front end AND
   the ISSUE 19 feedback controller in ``serving/controller.py`` —
   control decisions are host arithmetic over telemetry, never
   traced) + ``telemetry/reqtrace.py`` (the
   request-trace recorder runs on the event loop) +
   ``telemetry/{timeseries,health,fleet}.py`` (the ISSUE 17 fleet
   health plane is stdlib-only host logic) +
   ``telemetry/steptrace.py`` (the ISSUE 20 per-step training trace
   is a stdlib shell on the train loop's host side), and
   ``analysis/numsan.py`` (the sanitizer shell is host-side state
   keeping; its in-graph probes live at the call sites).

Exit codes: 0 = every section clean; 1 = any section failed;
2 = usage/environment error. The tier-1 suite asserts this exits 0 at
HEAD (tests/test_shardlint.py), so builders get the same gate CI runs
from one command.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import types

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PACKAGE = os.path.join(_REPO, "deepspeed_tpu")


def _import_analysis():
    """Import deepspeed_tpu.analysis without executing
    deepspeed_tpu/__init__.py (which imports jax)."""
    if "deepspeed_tpu" not in sys.modules:
        stub = types.ModuleType("deepspeed_tpu")
        stub.__path__ = [_PACKAGE]
        sys.modules["deepspeed_tpu"] = stub
    sys.path.insert(0, _REPO)
    from deepspeed_tpu import analysis
    return analysis


def run_sections() -> list[dict]:
    """Each section: {name, ok, detail}."""
    analysis = _import_analysis()
    from deepspeed_tpu.analysis import linter
    from deepspeed_tpu.analysis.rules import RULE_GROUPS
    sections: list[dict] = []

    # 1. full graftlint vs the committed baseline
    result = linter.lint_paths([_PACKAGE], root=_REPO)
    baseline = os.path.join(_REPO, linter.BASELINE_DEFAULT)
    linter.apply_baseline(result, baseline
                          if os.path.exists(baseline) else None)
    sections.append({
        "name": "graftlint (all rules)",
        "ok": result.ok,
        "detail": (f"{result.files} files, {len(result.findings)} "
                   f"finding(s), {len(result.new)} new, "
                   f"{len(result.errors)} error(s)"),
        "new": [f.to_dict() for f in result.new],
        "errors": [f.to_dict() for f in result.errors],
    })

    # 2. the SPMD group status, filtered from the full run's findings
    # (same result set `graftlint.py --select spmd` produces, without
    # re-reading and re-parsing the whole package)
    spmd_ids = set(RULE_GROUPS["spmd"])
    spmd_all = [f for f in result.findings if f.rule in spmd_ids]
    spmd_new = [f for f in result.new if f.rule in spmd_ids]
    sections.append({
        "name": "spmd group (GL060-GL063)",
        "ok": not spmd_new and not result.errors,
        "detail": (f"{len(spmd_all)} finding(s), "
                   f"{len(spmd_new)} new"),
        "new": [f.to_dict() for f in spmd_new],
        "errors": [],
    })

    # 2b. the numerics group status (ISSUE 18 — equivalent to
    # ``graftlint.py --select numerics`` / ``--select NUM``), same
    # filter-from-the-full-run trick as the spmd section
    num_ids = set(RULE_GROUPS["numerics"])
    num_all = [f for f in result.findings if f.rule in num_ids]
    num_new = [f for f in result.new if f.rule in num_ids]
    sections.append({
        "name": "numerics group (GL070-GL073)",
        "ok": not num_new and not result.errors,
        "detail": (f"{len(num_all)} finding(s), "
                   f"{len(num_new)} new"),
        "new": [f.to_dict() for f in num_new],
        "errors": [],
    })

    # 3. host-only package audits (no jit-reachable code allowed)
    for label, paths in (
            # ISSUE 19: the serving planner (autotuning/serving.py)
            # and the online controller (serving/controller.py) ride
            # these whole-directory roots — both are host arithmetic
            ("host-only: autotuning",
             [os.path.join(_PACKAGE, "autotuning")]),
            ("host-only: serving + reqtrace + fleet plane",
             [os.path.join(_PACKAGE, "serving"),
              os.path.join(_PACKAGE, "telemetry", "reqtrace.py"),
              # ISSUE 17: the fleet health plane is host-side control
              # logic — stdlib-only, nothing jit-reachable
              os.path.join(_PACKAGE, "telemetry", "timeseries.py"),
              os.path.join(_PACKAGE, "telemetry", "health.py"),
              os.path.join(_PACKAGE, "telemetry", "fleet.py"),
              # ISSUE 20: the per-step training trace recorder is a
              # stdlib shell — ledger/timeseries arrive as accessors,
              # nothing jit-reachable
              os.path.join(_PACKAGE, "telemetry", "steptrace.py")]),
            # ISSUE 18: the numsan sanitizer shell is host-side state
            # keeping — the in-graph probes live at the call sites
            # (engine, ops/pallas/quantization.py), never here
            ("host-only: numsan module",
             [os.path.join(_PACKAGE, "analysis", "numsan.py")])):
        roots = analysis.traced_roots(paths, root=_REPO)
        sections.append({
            "name": label,
            "ok": not roots,
            "detail": (f"{len(roots)} traced function(s)"
                       if roots else "clean"),
            "traced": roots,
        })
    return sections


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="lint_all", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit a JSON section report on stdout")
    args = ap.parse_args(argv)

    sections = run_sections()
    ok = all(s["ok"] for s in sections)
    if args.as_json:
        print(json.dumps({"ok": ok, "sections": sections},
                         indent=1, sort_keys=True))
    else:
        for s in sections:
            mark = "PASS" if s["ok"] else "FAIL"
            print(f"[{mark}] {s['name']}: {s['detail']}")
            for f in s.get("new", []):
                print(f"    {f['path']}:{f['line']}: {f['rule']} "
                      f"{f['message']}")
            for f in s.get("errors", []):
                print(f"    {f['path']}:{f['line']}: {f['rule']} "
                      f"{f['message']}")
            for r in s.get("traced", []):
                print(f"    {r['path']}:{r['line']}: traced function "
                      f"'{r['name']}'")
        print("lint_all: " + ("all sections clean"
                              if ok else "FAILURES above"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
