"""Benchmark: GPT-2 125M training throughput on one TPU chip.

Prints ONE JSON line on stdout: {"metric", "value", "unit",
"vs_baseline"}. vs_baseline is MFU / 0.45 — the north-star MFU target
from BASELINE.md §9 (the reference's headline training-efficiency claim
class; e.g. Ulysses sustains 54% of peak on A100, BASELINE.md §3).

stderr carries '# '-prefixed tail lines recorded alongside: a
Llama-family training config (BASELINE configs 2-3 class, scaled to one
chip) and a kernel smoke section running every Pallas kernel family on
the real chip (quantize/dequant roundtrips, fused optimizers, norms,
flash attention, block-sparse attention) so interpret-mode-only test
coverage can't hide TPU-specific lowering bugs.

Stage control (BENCH_r05 ended rc=124 with no parseable output): every
stage runs under a SIGALRM budget (``--budget-s``, per-stage), a GLOBAL
deadline (``--total-budget-s``, env ``DS_BENCH_TOTAL_BUDGET_S``,
default 3300 s) skips whatever stages remain once it passes — so the
full matrix can never outlive the harness wall clock — stages can be
selected with ``--stage a,b`` (``--list-stages`` prints them), and the
stdout JSON line is emitted no matter what — after the headline stage,
on any stage timeout, at the global deadline, or from the SIGTERM
handler when the harness's ``timeout`` fires mid-stage — so the driver
always parses a result instead of null.
"""

import argparse
import json
import os
import signal
import sys
import threading
import time

import jax
import jax.numpy as jnp

# bf16 peak FLOPS by device kind (per chip)
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5": 459e12,        # v5p
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,   # trillium
    "cpu": 1e12,             # arbitrary floor for CPU smoke runs
}


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu")
    for k, v in PEAK_FLOPS.items():
        if kind.startswith(k):
            return v
    return 1e12



def _cpu_batch(per_dev: int = 2) -> int:
    """CPU-smoke batch: must divide the (possibly virtual) dp world."""
    return per_dev * len(jax.devices())


def _mean_ci95(xs):
    """(mean, t-distribution 95% half-width) over measurement windows.
    A comparison claim is honest only when the CI excludes zero
    (VERDICT r4 #7 — single best-of pairs swung with tunnel RTT)."""
    import math
    n = len(xs)
    m = sum(xs) / n
    if n < 2:
        return m, float("inf")
    var = sum((x - m) ** 2 for x in xs) / (n - 1)
    t = {2: 12.706, 3: 4.303, 4: 3.182, 5: 2.776, 6: 2.571, 7: 2.447,
         8: 2.365, 9: 2.306, 10: 2.262}.get(n, 2.0)
    return m, t * math.sqrt(var / n)


def _mfu_fields(tps: float, cfg, seq: int) -> dict:
    """Primary MFU is causal-physical accounting; the conventional
    full-attention figure rides along as mfu_noncausal for
    cross-framework comparison (VERDICT r2 weak #1). With --telemetry,
    the device-truth fields from the executable ledger (ISSUE 5) ride
    along: compiler-measured MFU and peak HBM of the compiled step."""
    peak = peak_flops(jax.devices()[0])
    return {"mfu": round(tps * cfg.flops_per_token(seq) / peak, 4),
            "mfu_noncausal": round(
                tps * cfg.flops_per_token(seq, causal=False) / peak, 4),
            **_ledger_truth_fields(peak), **_steptrace_fields()}


def _ledger_truth_fields(peak: float) -> dict:
    """{mfu_hlo, hbm_peak_bytes} from the telemetry executable ledger
    when it is live (bench --telemetry): MFU from the compiled step's
    own cost_analysis() FLOPs over the measured span window, and the
    largest registered executable's compiler-reported peak HBM. Empty
    when telemetry/ledger are off."""
    from deepspeed_tpu.utils.telemetry_probe import active_telemetry
    mod = active_telemetry()
    led = mod.get_ledger() if mod is not None else None
    if led is None or not len(led):
        return {}
    out: dict = {}
    peaks = led.peak_hbm_by_name()
    if peaks:
        out["hbm_peak_bytes"] = max(peaks.values())
    tracer = mod.get_tracer()
    if tracer is not None:
        mfu = led.mfu_by_name(tracer.totals_trimmed(), peak)
        if "compiled_step" in mfu:
            out["mfu_hlo"] = round(mfu["compiled_step"], 4)
    # per-axis collective payload + observed wire width (ISSUE 8):
    # train-stage artifacts carry the HLO-accounted bytes so the
    # `--gate comms` diff family can watch them across rounds, and the
    # wire width shows whether qwZ/qgZ int8 payloads carried the
    # traffic (~1.1 B/el) or the wire was fp32 (4.0)
    traffic = led.traffic()
    if traffic:
        by_axis: dict = {}
        for (axis, _op), row in traffic.items():
            by_axis[axis] = by_axis.get(axis, 0) + row["bytes"]
        out["wire_bytes_per_axis"] = by_axis
        from deepspeed_tpu.telemetry.collectives import axis_wire_width
        out["wire_bytes_per_el"] = {
            a: round(w, 3) for a, w in axis_wire_width(traffic).items()}
    return out


def _steptrace_fields() -> dict:
    """{goodput_fraction, badput_seconds, recon_max_rel_err} from the
    steptrace run ledger when it is live (bench --telemetry, ISSUE 20):
    the train stages' artifacts carry the goodput/badput breakdown and
    the telescoping reconciliation error so `--gate train` can watch
    goodput across rounds and the recon contract is checkable from the
    bench record alone. Empty when telemetry/steptrace are off or no
    step completed."""
    from deepspeed_tpu.utils.telemetry_probe import active_telemetry
    mod = active_telemetry()
    st = mod.get_step_recorder() if mod is not None else None
    if st is None or not st.steps_recorded:
        return {}
    s = st.goodput_summary()
    return {"goodput_fraction": round(s["goodput_fraction"], 4),
            "badput_seconds": {k: round(v, 4) for k, v in
                               s["badput_seconds"].items()},
            "recon_max_rel_err": s["recon_max_rel_err"]}


def _train_tput(ds, model, config_extra: dict, batch: int, seq: int,
                steps: int, windows: int = 1):
    """Shared throughput harness: build an engine, warm up, run best-of-
    `windows` timed loops with a device->host sync (float(loss)) per
    window. Returns (tokens/s, last loss). The engine is freed when this
    frame returns (main() gc.collect()s between sections)."""
    config = {
        "train_batch_size": batch,
        "optimizer": {"type": "FusedAdam",
                      "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
        "steps_per_print": 10 ** 9,
        **config_extra,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (batch, seq + 1), 0,
                                model.config.vocab_size)
    data = (tokens[:, :-1], tokens[:, 1:])
    float(engine.train_batch(data))
    dt = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(data)
        last = float(loss)  # device->host copy = reliable sync (tunnel)
        dt = min(dt, time.perf_counter() - t0)
    return steps * batch * seq / dt, last


def kernel_smoke() -> dict:
    """Run every Pallas kernel family once on the live backend; returns
    {check: max_abs_err} (floats) — a failure surfaces as an exception
    string instead of an error value."""
    results: dict = {}
    key = jax.random.PRNGKey(0)

    def check(name, fn):
        try:
            results[name] = round(float(fn()), 8)
        except Exception as e:   # noqa: BLE001 — report, don't die
            results[name] = f"FAIL: {type(e).__name__}: {str(e)[:100]}"

    x = jax.random.normal(key, (4096, 1024), jnp.float32)

    def int8_roundtrip():
        from deepspeed_tpu.ops.pallas.quantization import (dequantize_int8,
                                                           quantize_int8)
        q, s, meta = quantize_int8(x)
        return jnp.max(jnp.abs(dequantize_int8(q, s, meta) - x))

    def fp8_roundtrip():
        from deepspeed_tpu.ops.fp_quant import fp_dequantize, fp_quantize
        c, s = fp_quantize(x, q_bits=8, mantissa_bits=3)
        return jnp.max(jnp.abs(
            fp_dequantize(c, s, q_bits=8, mantissa_bits=3, shape=x.shape)
            - x))

    def fp6_roundtrip():
        from deepspeed_tpu.ops.fp_quant import fp_dequantize, fp_quantize
        c, s = fp_quantize(x, q_bits=6, mantissa_bits=2)
        return jnp.max(jnp.abs(
            fp_dequantize(c, s, q_bits=6, mantissa_bits=2, shape=x.shape)
            - x))

    def norms_err():
        from deepspeed_tpu.ops import layers as L
        from deepspeed_tpu.ops.pallas import norms
        scale = jnp.ones((1024,)) * 1.5
        return jnp.max(jnp.abs(norms.rms_norm(x, scale)
                               - L.rms_norm(x, scale)))

    def fused_adam_err():
        import optax
        from deepspeed_tpu.ops.pallas.fused_optimizers import fused_adam
        p = {"w": x[:64]}
        g = {"w": jax.random.normal(jax.random.PRNGKey(1), (64, 1024))}
        tx, ref = fused_adam(1e-3), optax.adam(1e-3)
        up, _ = tx.update(g, tx.init(p), p)
        rup, _ = ref.update(g, ref.init(p), p)
        return jnp.max(jnp.abs(up["w"] - rup["w"]))

    def flash_err():
        from deepspeed_tpu.ops.layers import dot_product_attention
        from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
        ks = jax.random.split(key, 3)
        q = jax.random.normal(ks[0], (2, 512, 8, 64), jnp.float32)
        k = jax.random.normal(ks[1], (2, 512, 8, 64), jnp.float32)
        v = jax.random.normal(ks[2], (2, 512, 8, 64), jnp.float32)
        return jnp.max(jnp.abs(flash_attention(q, k, v, causal=True)
                               - dot_product_attention(q, k, v,
                                                       causal=True)))

    def sparse_err():
        import numpy as np
        from deepspeed_tpu.ops.sparse_attention import FixedSparsityConfig
        from deepspeed_tpu.ops.sparse_attention.kernels import \
            block_sparse_attention
        from deepspeed_tpu.ops.sparse_attention.sparse_self_attention \
            import layout_to_bias
        cfg = FixedSparsityConfig(num_heads=4, block=128)
        layout = cfg.make_layout(512)
        ks = jax.random.split(key, 3)
        q = jax.random.normal(ks[0], (2, 4, 512, 64), jnp.float32)
        k = jax.random.normal(ks[1], (2, 4, 512, 64), jnp.float32)
        v = jax.random.normal(ks[2], (2, 4, 512, 64), jnp.float32)
        bias = layout_to_bias(layout, 128)
        sc = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(64.0) + bias[None]
        ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(sc, -1), v)
        return jnp.max(jnp.abs(block_sparse_attention(q, k, v, layout)
                               - ref))

    def paged_err():
        # real-hardware parity of the paged-attention kernel vs the
        # exact gathered form (VERDICT r2 weak #7: the alignment-dispatch
        # seam was exercised interpret-mode only)
        import numpy as np
        from deepspeed_tpu.inference.v2.paged import (
            gather_pages, paged_attention, paged_attention_kernel,
            place_in_pages)
        B, SQ, H, D, NB, BS = 2, 16, 4, 64, 32, 16
        ks = jax.random.split(key, 5)
        q = jax.random.normal(ks[0], (B, SQ, H, D))
        k_new = jax.random.normal(ks[1], (B, SQ, H, D))
        v_new = jax.random.normal(ks[2], (B, SQ, H, D))
        k_pool = jax.random.normal(ks[3], (NB, BS, H, D))
        v_pool = jax.random.normal(ks[4], (NB, BS, H, D))
        tables = jnp.asarray(np.random.default_rng(2).permutation(NB)
                             [:B * 8].reshape(B, 8))
        pos0 = jnp.asarray([21, 0])
        true_len = jnp.asarray([SQ, 7])
        from deepspeed_tpu.ops.layers import alibi_slopes
        k_pages = place_in_pages(gather_pages(k_pool, tables), k_new,
                                 pos0, true_len)
        v_pages = place_in_pages(gather_pages(v_pool, tables), v_new,
                                 pos0, true_len)
        live = jnp.arange(SQ)[None, :, None, None] < true_len[:, None,
                                                             None, None]
        # BOTH kernel specializations get hardware parity: the default
        # path and the ALiBi (Bloom) path — worst error is reported
        err = 0.0
        for slopes in (None, alibi_slopes(H)):
            out_k = paged_attention_kernel(
                q, k_new, v_new, k_pool, v_pool, tables, pos0, true_len,
                alibi_slopes=slopes)
            ref = paged_attention(q, k_pages, v_pages, pos0,
                                  alibi_slopes=slopes)
            err = jnp.maximum(err, jnp.max(jnp.abs(
                jnp.where(live, out_k - ref, 0.0))))
        return err

    for name, fn in [("int8_roundtrip", int8_roundtrip),
                     ("fp8_roundtrip", fp8_roundtrip),
                     ("fp6_roundtrip", fp6_roundtrip),
                     ("norms", norms_err),
                     ("fused_adam", fused_adam_err),
                     ("flash_attention", flash_err),
                     ("block_sparse_attention", sparse_err),
                     ("paged_attention", paged_err)]:
        check(name, fn)
    return results


def llama_bench(ds, on_tpu: bool):
    """Llama-family training config (BASELINE configs 2-3 class, scaled
    to one chip): ~340M params, GQA d_head=128, RoPE/RMSNorm/SwiGLU,
    ZeRO-2 + fused Adam at seq 2048."""
    from deepspeed_tpu.models import Llama
    seq = 2048 if on_tpu else 128
    batch = 4 if on_tpu else _cpu_batch()
    model = (Llama(hidden_size=1024, num_layers=24, num_heads=8,
                   num_kv_heads=8, intermediate_size=2816,
                   vocab_size=32000, max_seq_len=seq,
                   remat_policy="segments", attn_impl="flash")
             if on_tpu else Llama(size="tiny", max_seq_len=seq))
    tps, _ = _train_tput(ds, model, {"gradient_clipping": 1.0}, batch,
                         seq, steps=10 if on_tpu else 2,
                         windows=2 if on_tpu else 1)
    return {"metric": "llama_340m_train_tokens_per_sec",
            "value": round(tps, 1), "unit": "tokens/s/chip",
            **_mfu_fields(tps, model.config, seq)}


def longctx_bench(ds, on_tpu: bool):
    """Long-context class (BASELINE config 4 / Ulysses-32k): 32k-token
    sequences on one chip (the sp>1 all-to-all path is exercised on the
    virtual mesh in dryrun_multichip; this measures the long-seq
    attention + remat engine path on real hardware)."""
    from deepspeed_tpu.models import Llama
    seq = 32768 if on_tpu else 256
    model = (Llama(hidden_size=1024, num_layers=12, num_heads=8,
                   num_kv_heads=8, intermediate_size=2816,
                   vocab_size=32000, max_seq_len=seq,
                   remat_policy="segments", attn_impl="flash",
                   loss_chunk=2048)
             if on_tpu else Llama(size="tiny", max_seq_len=seq))
    tps, _ = _train_tput(ds, model, {},
                         batch=1 if on_tpu else _cpu_batch(1),
                         seq=seq, steps=4 if on_tpu else 1)
    # the conventional full-attention figure is ~2x the causal-physical
    # one at 32k; _mfu_fields keeps causal primary
    return {"metric": "llama_32k_seq_train_tokens_per_sec",
            "value": round(tps, 1), "unit": "tokens/s/chip",
            **_mfu_fields(tps, model.config, seq)}


def moe_bench(ds, on_tpu: bool):
    """MoE class (BASELINE config 5 / Mixtral-EP): top-2 routed experts;
    ep>1 dispatch is exercised on the virtual mesh in dryrun_multichip —
    this measures the routed-expert compute path on real hardware."""
    from deepspeed_tpu.models import Mixtral
    seq = 1024 if on_tpu else 64
    batch = 8 if on_tpu else _cpu_batch()
    model = (Mixtral(hidden_size=512, num_layers=8, num_heads=8,
                     num_kv_heads=8, intermediate_size=1408,
                     num_experts=8, moe_top_k=2, vocab_size=32000,
                     max_seq_len=seq, remat_policy="segments",
                     attn_impl="flash")
             if on_tpu else Mixtral(size="tiny", max_seq_len=seq))
    tps, _ = _train_tput(ds, model, {}, batch, seq,
                         steps=8 if on_tpu else 1)
    return {"metric": "mixtral_8e_top2_train_tokens_per_sec",
            "value": round(tps, 1), "unit": "tokens/s/chip"}


def _decode_chain_setup(model, e2, uids, use_kernel: bool):
    """Shared scaffolding for the chain-differenced paged decode-step
    measurement: build the single-token decode operands for `uids` (the
    engine's own bucketing) and a make_chain(length) factory that scans
    the paged step inside ONE jit — a whole chain of decode steps costs
    one dispatch, so differencing two chain lengths cancels the
    harness's per-dispatch RTT."""
    import functools as _ft

    import numpy as np

    from deepspeed_tpu.inference.v2.engine_v2 import _batch_bucket, _bucket
    from deepspeed_tpu.inference.v2.paged import paged_forward

    mgr = e2.state_manager
    seqs = [mgr.seqs[u] for u in uids]
    bb = _batch_bucket(len(seqs))
    tok1 = np.zeros((bb, 1), np.int32)
    pos0_a = np.zeros((bb,), np.int32)
    tlen_a = np.zeros((bb,), np.int32)
    tabs = np.stack([mgr.block_table(s) for s in seqs]
                    + [mgr.block_table(seqs[0])] * (bb - len(seqs)))
    for i, sq_ in enumerate(seqs):
        tok1[i, 0] = 1
        pos0_a[i] = sq_.seen
        tlen_a[i] = 1
    live_blocks = -(-int((pos0_a + tlen_a).max()) // mgr.block_size)
    kb = min(_bucket(max(live_blocks, 1)), tabs.shape[1])
    tabs = tabs[:, :kb]
    fwd = _ft.partial(paged_forward, model, use_kernel=use_kernel)

    def make_chain(length):
        @jax.jit
        def chain(params, pools, tokens, pos0, tables, tlen):
            def body(pools, _):
                lg, pools = fwd(params, pools, tokens, pos0, tables, tlen)
                return pools, lg[0, 0]
            pools, lgs = jax.lax.scan(body, pools, None, length=length)
            return lgs, pools
        return chain

    args = (jnp.asarray(tok1), jnp.asarray(pos0_a), jnp.asarray(tabs),
            jnp.asarray(tlen_a))
    return make_chain, args


def _chain_pair_ms(chain_l, chain_s, params, pools, args,
                   long_n: int, short_n: int, reps: int = 3):
    """best-of-reps for each chain length, then differenced: one
    dispatch RTT (~0.1-0.5s through the dev tunnel) rides on each
    timing, so a single pair is noise-bound — min over reps recovers
    the device truth the differencing needs. Returns (ms/step, pools)."""
    dl = ds_ = float("inf")
    for _ in range(reps):
        t2 = time.perf_counter()
        lgs, pools = chain_l(params, pools, *args)
        float(jnp.sum(lgs))
        dl = min(dl, time.perf_counter() - t2)
        t2 = time.perf_counter()
        lgs, pools = chain_s(params, pools, *args)
        float(jnp.sum(lgs))
        ds_ = min(ds_, time.perf_counter() - t2)
    return max(dl - ds_, 1e-9) / (long_n - short_n) * 1e3, pools


def _tick_percentiles(one_tick, n: int):
    """(p50, p99) wall-clock over n host-in-loop scheduler ticks."""
    one_tick()                       # warm the decode bucket
    ticks = []
    for _ in range(n):
        t1 = time.perf_counter()
        one_tick()
        ticks.append((time.perf_counter() - t1) * 1e3)
    ticks.sort()
    return (ticks[len(ticks) // 2],
            ticks[min(len(ticks) - 1, int(len(ticks) * 0.99))])


def _fused_decode_metrics(e, prompts: list, k: int,
                          n_dispatches: int) -> dict:
    """Measure the fused multi-step decode loop (ISSUE 1 tentpole) on a
    v2 engine `e` with no live sequences: prefill `prompts`, then each
    timed host dispatch advances every sequence K tokens inside one
    compiled while_loop (in-graph sampling + KV writes + termination).
    Reported against the per-tick loop's 1 dispatch/token:
    ``fused_dispatches_per_token`` (~1/K) and ``fused_occupancy`` (live
    (row, step) slot fraction) come straight from the engine's serving
    counters, and ``fused_tick_p50_ms`` is the acceptance gate's figure
    — it should sit near K x decode_step_ms_compute, not K x
    host-RTT."""
    uids = list(range(len(prompts)))
    e.put(uids, prompts)
    # decode_fused consumes exactly one pending token per row (the last
    # sampled one); seed the chain with a fixed first token
    for u in uids:
        e.state_manager.extend(u, [1])
    e.reset_serving_metrics()
    # _tick_percentiles' warm (compile) dispatch lands inside the
    # counters but cancels out of the per-token ratios
    p50, p99 = _tick_percentiles(
        lambda: e.decode_fused(uids, k_steps=k), n_dispatches)
    m = e.serving_metrics()
    return {"fused_k": k,
            "fused_tick_p50_ms": round(p50, 2),
            "fused_tick_p99_ms": round(p99, 2),
            "fused_dispatches_per_token": round(
                m["dispatches_per_token"], 4),
            "fused_occupancy": round(m["fused_occupancy"], 3),
            "fused_tokens_per_sec": round(
                len(uids) * k * 1e3 / max(p50, 1e-9), 1)}


def _decode_step_probe(model, e, uids, use_kernel: bool, long_n: int,
                       short_n: int, reps: int) -> float:
    """Chain-differenced device-truth decode-step time (ms) for
    sequences already resident in engine ``e`` — the shared probe
    behind the serving stages' compute denominators. Never donates
    ``e.pools``, so the engine stays usable afterwards."""
    make_chain, args = _decode_chain_setup(model, e, uids,
                                           use_kernel=use_kernel)
    chain_l, chain_s = make_chain(long_n), make_chain(short_n)
    pools = e.pools
    for c in (chain_l, chain_s):                        # compile + warm
        lgs, pools = c(e.params, pools, *args)
        float(jnp.sum(lgs))
    ms, _ = _chain_pair_ms(chain_l, chain_s, e.params, pools, args,
                           long_n, short_n, reps=reps)
    return ms


def _chained_serve_metrics(e, prompts: list, k: int,
                           max_new: int) -> dict:
    """Drive the N-deep chained serving loop (ISSUE 6) over `prompts`
    and report the acceptance figures: per-decode-step wall time with
    the chain's host syncs amortized in (``tick_p50_ms`` over per-chain
    drains; the gate compares it against ``decode_step_ms_compute``)
    and host dispatches per decoded token at equal greedy outputs.
    Engine state is left flushed. Call once warm (compiles), once
    timed."""
    from deepspeed_tpu.inference.v2.serve_loop import FusedServeLoop
    e.reset_serving_metrics()
    loop = FusedServeLoop(e, k_steps=k, strict=True)
    for i, p in enumerate(prompts):
        loop.submit(p, max_new, uid=i)
    t0 = time.perf_counter()
    n_tok = 0
    while loop.has_work():
        for evt in loop.step():
            n_tok += len(evt.tokens)
    wall = time.perf_counter() - t0
    ticks = sorted(dt / s * 1e3 for dt, s in loop.drain_stats if s > 0)
    steps_total = sum(s for _, s in loop.drain_stats)
    m = e.serving_metrics()
    return {"tick_p50_ms": round(ticks[len(ticks) // 2], 2) if ticks
            else None,
            "tick_p99_ms": round(
                ticks[min(len(ticks) - 1, int(len(ticks) * 0.99))], 2)
            if ticks else None,
            "tick_mean_ms": round(wall * 1e3 / max(steps_total, 1), 2),
            "chained_tokens_per_sec": round(n_tok / max(wall, 1e-9), 1),
            "dispatches_per_token_chained": round(
                m["dispatches_per_token"], 4),
            "fused_occupancy_chained": round(m["fused_occupancy"], 3),
            "chain_depth": int(e._config.max_inflight_dispatches),
            "fused_admission": bool(e._config.fused_admission)}


def _bench_serving_slo():
    """ONE constructor for the bench's serving SLO targets (ISSUE 19
    satellite): the ``serving`` stage's ``tokens_per_sec_at_slo`` and
    the ``serve_openloop``/``serve_autotune`` goodput-under-SLO
    figures all gate against the SAME ``ServingConfig``-declared
    targets — no hard-coded SLA drifting from the config. ITL 50 ms is
    the FastGen-style >= 20 tok/s/user SLA."""
    from deepspeed_tpu.serving import ServingConfig
    return ServingConfig(slo_ttft_ms=1000.0, slo_itl_ms=50.0)


def _openloop_drive(e, scfg, prompts, arrivals, max_new):
    """Drive one open-loop Poisson trace against a fresh
    ``AsyncInferenceServer`` on ``e`` and score it under ``scfg``'s
    SLOs. Shared by the serve_openloop load-step phase and the
    serve_autotune measured comparison so both halves of ISSUE 19
    grade traffic identically. Returns client-side latencies, shed
    accounting (zero silent drops is asserted: every submit ends
    completed, shed or failed), goodput under SLO, and the server's
    final metrics."""
    import asyncio

    from deepspeed_tpu.serving import AsyncInferenceServer, RequestFailed

    out = {"ttft": [], "itl": [], "shed_lat": [], "completed": 0,
           "shed": 0, "failed": 0, "good": 0}
    t_wall = {}

    async def client(srv, i):
        await asyncio.sleep(float(arrivals[i]))
        t_sub = time.perf_counter()
        try:
            h = await srv.submit(prompts[i], max_new_tokens=max_new)
            t_first = t_last = None
            n = 0
            async for _tok in h:
                now = time.perf_counter()
                if t_first is None:
                    t_first = now
                t_last = now
                n += 1
        except RequestFailed as err:
            if "shed" in str(err):
                out["shed"] += 1
                out["shed_lat"].append(
                    (time.perf_counter() - t_sub) * 1e3)
            else:
                out["failed"] += 1
            return
        if t_first is None:
            out["failed"] += 1
            return
        out["completed"] += 1
        ttft_ms = (t_first - t_sub) * 1e3
        out["ttft"].append(ttft_ms)
        itl_ms = ((t_last - t_first) / (n - 1) * 1e3) if n > 1 else 0.0
        if n > 1:
            out["itl"].append(itl_ms)
        if ((not scfg.slo_ttft_ms or ttft_ms <= scfg.slo_ttft_ms)
                and (not scfg.slo_itl_ms or itl_ms <= scfg.slo_itl_ms)):
            out["good"] += 1

    async def run():
        async with AsyncInferenceServer(e, scfg) as srv:
            t_wall["t0"] = time.perf_counter()
            await asyncio.gather(*(client(srv, i)
                                   for i in range(len(prompts))))
            t_wall["t1"] = time.perf_counter()
            return srv.metrics()

    m = asyncio.run(run())
    n = len(prompts)
    accounted = out["completed"] + out["shed"] + out["failed"]
    assert accounted == n, (
        f"silent drop: {n - accounted} of {n} requests unaccounted")
    wall = max(t_wall["t1"] - t_wall["t0"], 1e-9)
    out["goodput_rps"] = out["good"] / wall
    out["wall_s"] = wall
    out["metrics"] = m
    return out


def serve_openloop_bench(ds, on_tpu: bool):
    """Open-loop Poisson traffic against the async continuous-batching
    server (ISSUE 6): synthetic clients arrive at a fixed rate, stream
    their tokens, and the stage reports the serving SLO histograms —
    TTFT p50/p99 (submit -> first streamed token, queueing included)
    and per-request mean inter-token latency p50/p99 — plus the
    tick-vs-compute ratio: p50 wall time per decode step through the
    chained serving loop over the chain-differenced device compute
    step (1.0 = the host adds nothing; the acceptance gate is <= 2).

    A second load-step phase (ISSUE 19) replays rate λ -> 3λ -> λ with
    the admission shed and feedback controller armed: goodput under
    the ServingConfig SLOs, shed counts (fast-failed, zero silent
    drops), controller adaptation events, and the controlled
    queue-wait p99 against the uncontrolled phase's (the >= 5x
    BENCH_r06 acceptance bar). Gate with ``--gate serving``."""
    import asyncio

    import numpy as np
    from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig)
    from deepspeed_tpu.models import Llama
    from deepspeed_tpu.serving import AsyncInferenceServer, ServingConfig

    if on_tpu:
        model = Llama(hidden_size=1024, num_layers=12, num_heads=8,
                      num_kv_heads=8, intermediate_size=2816,
                      vocab_size=32000, max_seq_len=2048)
        bs_kv, nb, chunk, B = 64, 256, 256, 16
        n_req, rate_rps, p_len, max_new, K, depth = 48, 6.0, 128, 48, 8, 4
    else:
        model = Llama(size="tiny", max_seq_len=256)
        bs_kv, nb, chunk, B = 8, 128, 16, 8
        n_req, rate_rps, p_len, max_new, K, depth = 10, 20.0, 12, 6, 4, 2
    e = InferenceEngineV2(model, RaggedInferenceEngineConfig(
        dtype="bfloat16" if on_tpu else "float32", kv_block_size=bs_kv,
        num_kv_blocks=nb, max_chunk_size=chunk,
        max_ragged_sequence_count=B, fused_decode_steps=K,
        max_inflight_dispatches=depth, fused_admission=True))
    rng = np.random.default_rng(0)
    vocab = model.config.vocab_size
    prompts = [rng.integers(0, vocab, p_len).tolist()
               for _ in range(n_req)]
    arrivals = np.cumsum(rng.exponential(1.0 / rate_rps, n_req))

    # device-truth decode step for the ratio denominator
    probe_uids = list(range(10 ** 6, 10 ** 6 + min(4, B)))
    e.put(probe_uids, [prompts[i % n_req] for i in range(len(probe_uids))])
    step_ms = _decode_step_probe(model, e, probe_uids, on_tpu,
                                 *((32, 8, 3) if on_tpu else (4, 2, 1)))
    e.flush(probe_uids)

    # warm the serving-loop executables (prefill buckets + the serve
    # ring loop) outside the measured traffic window — both the full
    # decode-batch bucket and the single-row bucket, so the measured
    # ticks mostly hit the executable cache
    for n_warm in (min(B, n_req), 1):
        _chained_serve_metrics(e, prompts[:n_warm], K,
                               max_new=min(max_new, 2 * K))
    # the gated efficiency counters must cover ONLY the measured
    # traffic window, not the warm-up drives
    e.reset_serving_metrics()
    # per-request tracing (ISSUE 10): with --telemetry the request
    # recorder is live — clear the warm-up traces so the component
    # percentiles and the access log cover only the measured window
    from deepspeed_tpu.utils.telemetry_probe import active_telemetry
    tel = active_telemetry()
    rec = tel.get_request_recorder() if tel is not None else None
    if rec is not None:
        rec.clear()

    results = {"ttft": [], "itl_req": [], "done": 0}

    async def client(srv, i):
        await asyncio.sleep(float(arrivals[i]))
        t_sub = time.perf_counter()
        h = await srv.submit(prompts[i], max_new_tokens=max_new)
        t_first = t_last = None
        n = 0
        async for _tok in h:
            now = time.perf_counter()
            if t_first is None:
                t_first = now
            t_last = now
            n += 1
        if t_first is None:
            return
        results["ttft"].append((t_first - t_sub) * 1e3)
        if n > 1:
            results["itl_req"].append((t_last - t_first) / (n - 1) * 1e3)
        results["done"] += 1

    async def run():
        async with AsyncInferenceServer(
                e, ServingConfig(k_steps=K)) as srv:
            await asyncio.gather(*(client(srv, i)
                                   for i in range(n_req)))
            return srv.session.drain_stats, srv.metrics()

    drains, m = asyncio.run(run())

    def pct(xs, q):
        if not xs:
            return None
        xs = sorted(xs)
        return round(xs[min(len(xs) - 1, int(len(xs) * q))], 2)

    ticks = [dt / s * 1e3 for dt, s in drains if s > 0]
    tick_p50 = pct(ticks, 0.5)
    # tail-latency attribution (ISSUE 10): per-request component
    # percentiles + the dominant p99-TTFT component + a reconciliation
    # figure (worst relative gap between a request's TTFT component sum
    # and its measured TTFT — the acceptance bound is 5%)
    breakdown: dict = {}
    if rec is not None:
        from deepspeed_tpu.telemetry.reqtrace import COMPONENT_KEYS
        pcts = rec.component_percentiles()
        for name in COMPONENT_KEYS:
            row = pcts.get(name)
            breakdown[f"{name}_p50_ms"] = (
                round(row["p50"] * 1e3, 3) if row else None)
            breakdown[f"{name}_p99_ms"] = (
                round(row["p99"] * 1e3, 3) if row else None)
        attr = rec.ttft_attribution()
        breakdown["ttft_dominant_component"] = attr.get(
            "dominant_component")
        recon = [abs((tr.queue_wait_s + tr.prefill_s + tr.migrate_s
                      + tr.first_drain_s) - tr.ttft_s) / tr.ttft_s
                 for tr in rec.completed() if tr.ttft_s]
        breakdown["access_log_requests"] = len(rec.completed())
        breakdown["ttft_recon_max_rel_err"] = (
            round(max(recon), 5) if recon else None)

    # ---- load-step phase (ISSUE 19): rate λ -> 3λ -> λ with the
    # admission shed + online feedback controller armed, against an
    # UNCONTROLLED run of the very same arrival trace (BENCH_r06:
    # unbounded admission put 11.2 s of queue_wait in an 11.5 s TTFT
    # p99) — the controller must hold ITL within budget and keep
    # queue_wait bounded by shedding fast-failed (counted) requests at
    # the 3λ peak.
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.serving import ControllerConfig
    slo = _bench_serving_slo()
    # size the step against MEASURED closed-loop capacity so the 3λ
    # peak genuinely saturates on every platform: λ at ~0.7x capacity
    # is healthy, 3λ overruns it ~2x and builds a real queue
    warm_full = _chained_serve_metrics(e, prompts[:min(B, n_req)], K,
                                       max_new=max_new)
    e.reset_serving_metrics()
    cap_est = max(warm_full["chained_tokens_per_sec"] / max_new, 1.0)
    lam = 0.7 * cap_est
    seg_n = 80
    rates = [lam, 3 * lam, lam]
    rng2 = np.random.default_rng(1)
    arr2, t_at = [], 0.0
    for r in rates:
        for g in rng2.exponential(1.0 / r, seg_n):
            t_at += g
            arr2.append(t_at)
    prompts2 = [rng2.integers(0, vocab, p_len).tolist() for _ in arr2]
    # the phase NEEDS the telemetry plane (request traces feed the
    # controller's queue-wait/burn signals and the queue_wait p99
    # comparison); own it for the phase when the harness did not pass
    # --telemetry (same discipline as the fleet stage)
    owned = not telemetry.is_active()
    if owned:
        telemetry.configure()
    tel2 = active_telemetry()
    rec2 = tel2.get_request_recorder() if tel2 is not None else None
    try:
        base2_cfg = ServingConfig(
            k_steps=K, slo_ttft_ms=slo.slo_ttft_ms,
            slo_itl_ms=slo.slo_itl_ms)
        # admission bound at the engine row count: an admitted request
        # goes straight toward a decode row instead of aging in the
        # mailbox — the queue the BENCH_r06 baseline let grow unbounded
        ctl_cfg = ServingConfig(
            k_steps=K, slo_ttft_ms=slo.slo_ttft_ms,
            slo_itl_ms=slo.slo_itl_ms, shed_queue_depth=B,
            controller=ControllerConfig(
                enabled=True, interval_s=0.5 if on_tpu else 0.1))
        # throwaway drive of the trace itself: the 3λ burst packs
        # chunked-prefill admission buckets no closed-loop warm
        # produces, and one cold compile mid-measurement reads as
        # seconds of queue_wait
        _openloop_drive(e, ctl_cfg, prompts2, arr2, max_new)

        def measured_loadstep(scfg):
            e.reset_serving_metrics()
            if rec2 is not None:
                rec2.clear()
            r = _openloop_drive(e, scfg, prompts2, arr2, max_new)
            qw = None
            if rec2 is not None:
                row = rec2.component_percentiles().get("queue_wait")
                if row and row.get("n"):
                    qw = round(row["p99"] * 1e3, 3)
            return r, qw

        base_run2, base_qw_ms = measured_loadstep(base2_cfg)
        step_out, ctl_qw_ms = measured_loadstep(ctl_cfg)
    finally:
        if owned:
            telemetry.shutdown()
    m2 = step_out["metrics"]
    ctl_actions = m2.get("controller_actions", {})
    base_ttft_p99 = pct(base_run2["ttft"], 0.99)
    ctl_ttft_p99 = pct(step_out["ttft"], 0.99)
    loadstep = {
        "load_step_rates_rps": [round(r, 1) for r in rates],
        "load_step_requests": len(arr2),
        "goodput_under_slo_rps": round(step_out["goodput_rps"], 3),
        # the same trace, unbounded admission, controller off —
        # the BENCH_r06 baseline (qw field named so the serving
        # gate's queue_wait_p99 row matches only the controlled run)
        "uncontrolled_goodput_rps": round(base_run2["goodput_rps"], 3),
        "uncontrolled_ttft_p99_ms": base_ttft_p99,
        "uncontrolled_qw_p99_ms": base_qw_ms,
        "ctl_completed": step_out["completed"],
        "ctl_shed": step_out["shed"],
        "ctl_failed": step_out["failed"],
        "ctl_adaptations": int(sum(ctl_actions.values())),
        "ctl_actions": ctl_actions,
        "ctl_ttft_p99_ms": ctl_ttft_p99,
        "ctl_itl_p99_ms": pct(step_out["itl"], 0.99),
        # shed requests must fail FAST (the whole point vs aging in
        # the mailbox): client-observed submit -> RequestFailed p99
        "shed_fail_fast_p99_ms": pct(step_out["shed_lat"], 0.99),
        "ctl_queue_wait_p99_ms": ctl_qw_ms,
        # >= 5x vs the uncontrolled phase is the acceptance bar; the
        # TTFT ratio is the telemetry-free proxy (BENCH_r06: TTFT p99
        # is queue_wait-dominated uncontrolled)
        "ctl_queue_speedup_x": (
            round(base_qw_ms / max(ctl_qw_ms, 1e-3), 1)
            if base_qw_ms is not None and ctl_qw_ms is not None
            else None),
        "ctl_ttft_speedup_x": (
            round(base_ttft_p99 / max(ctl_ttft_p99, 1e-3), 1)
            if base_ttft_p99 and ctl_ttft_p99 else None),
    }
    return {"metric": "serve_openloop_ttft_p50_ms",
            "value": pct(results["ttft"], 0.5), "unit": "ms",
            "requests": n_req, "completed": results["done"],
            "arrival_rate_rps": rate_rps, "prompt_tokens": p_len,
            "max_new_tokens": max_new,
            "ttft_p99_ms": pct(results["ttft"], 0.99),
            "itl_p50_ms": pct(results["itl_req"], 0.5),
            "itl_p99_ms": pct(results["itl_req"], 0.99),
            "tick_p50_ms": tick_p50,
            "tick_p99_ms": pct(ticks, 0.99),
            "decode_step_ms_compute": round(step_ms, 3),
            "tick_vs_compute_ratio": (
                round(tick_p50 / max(step_ms, 1e-3), 2)
                if tick_p50 else None),
            "dispatches_per_token": round(m["dispatches_per_token"], 4),
            "fused_occupancy": round(m["fused_occupancy"], 3),
            "preemptions": m["preemptions"],
            "chain_depth": depth, "fused_k": K,
            "fused_admission": True, **breakdown, **loadstep}


def serve_autotune_bench(ds, on_tpu: bool):
    """Serving planner stage (ISSUE 19, offline half): calibrate the
    serving cost model on the live engine (fused decode tick + host
    dispatch RTT, solved from an amortized and an unamortized drive),
    AOT-rank the ServingCandidate grid against the open-loop traffic
    model, write artifacts/serving_plan.json, then MEASURE the chosen
    config against the hand-tuned serve_openloop baseline on identical
    Poisson traffic. Acceptance: plan goodput-under-SLO >= baseline
    (``serving_plan_vs_baseline`` >= 1). Render the plan with
    tools/autotune_report.py; gate with ``--gate serving``."""
    import gc

    import numpy as np
    from deepspeed_tpu.autotuning import (AutotuningConfig,
                                          ServingCalibration,
                                          ServingCandidate,
                                          ServingCostModel,
                                          ServingPlanner, TrafficModel,
                                          summarize_serving)
    from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig)
    from deepspeed_tpu.inference.v2.serve_loop import FusedServeLoop
    from deepspeed_tpu.models import Llama

    if on_tpu:
        model = Llama(hidden_size=1024, num_layers=12, num_heads=8,
                      num_kv_heads=8, intermediate_size=2816,
                      vocab_size=32000, max_seq_len=2048)
        bs_kv, nb, chunk, B = 64, 256, 256, 16
        n_req, rate_rps, p_len, max_new, K, depth = 192, 6.0, 128, 48, 8, 4
    else:
        model = Llama(size="tiny", max_seq_len=256)
        bs_kv, nb, chunk, B = 8, 128, 16, 8
        n_req, rate_rps, p_len, max_new, K, depth = 128, 20.0, 12, 6, 4, 2
    # the hand-tuned serve_openloop config IS the baseline (and a grid
    # point, so the plan can never rank below it under its own model)
    base_engine = {"dtype": "bfloat16" if on_tpu else "float32",
                   "kv_block_size": bs_kv, "num_kv_blocks": nb,
                   "max_chunk_size": chunk,
                   "max_ragged_sequence_count": B,
                   "fused_decode_steps": K,
                   "max_inflight_dispatches": depth,
                   "fused_admission": True}
    slo = _bench_serving_slo()
    e = InferenceEngineV2(model, RaggedInferenceEngineConfig(
        **base_engine))
    rng = np.random.default_rng(0)
    vocab = model.config.vocab_size
    prompts = [rng.integers(0, vocab, p_len).tolist()
               for _ in range(n_req)]

    def drive_ticks(k_steps, chain_depth, n_tok):
        """Closed-loop drive; returns mean wall seconds per decode
        tick (chain host syncs amortized in — the calibration's
        observable)."""
        loop = FusedServeLoop(e, k_steps=k_steps, temperature=0.0)
        loop.set_chain_depth(chain_depth)
        for i in range(min(4, B)):
            loop.submit(prompts[i % n_req], max_new_tokens=n_tok)
        while loop.has_work():
            loop.step()
        ticks = [dt / s for dt, s in loop.drain_stats if s > 0]
        loop.close()
        return sum(ticks) / max(len(ticks), 1)

    # calibration: t(k=1, d=1) exposes the full host RTT per tick;
    # t(K, depth) amortizes it over the chain span. Two warm drives
    # each (first compiles), best-of-two per point.
    span = K * depth
    t1 = min(drive_ticks(1, 1, 2 * K) for _ in range(2))
    tkd = min(drive_ticks(K, depth, 4 * K) for _ in range(2))
    overhead = max(0.0, (t1 - tkd) * span / max(span - 1, 1))
    tick = max(t1 - overhead, 1e-6)
    cal = ServingCalibration(
        decode_tick_s=round(tick, 6),
        dispatch_overhead_s=round(overhead, 6), source="measured")

    def mk_traffic(rps):
        return TrafficModel(
            arrival_rate_rps=rps, prompt_tokens=p_len,
            output_tokens=max_new, slo_ttft_ms=slo.slo_ttft_ms,
            slo_itl_ms=slo.slo_itl_ms,
            # random-token prompts: prompt-lookup drafts never accept,
            # and the traffic model must say so or the planner buys
            # verify compute that pays nothing on THIS traffic
            draft_acceptance=0.0)

    # saturate: offer 4x the hand-tuned config's calibrated capacity
    # (platform-adaptive). Under this load an unbounded-admission
    # candidate's queue diverges (rho >= 1 -> goodput 0) and the
    # planner must discover admission control — the BENCH_r06 failure
    # mode — rather than win on a tie at idle.
    probe = ServingCostModel(cal, max_rows=B, kv_block_size=bs_kv,
                             base_kv_blocks=nb)
    base_cap = probe.predict(
        ServingCandidate(k_steps=K, chain_depth=depth, ring=True),
        mk_traffic(rate_rps))["capacity_rps"]
    rate_rps = max(rate_rps, round(4.0 * base_cap, 1))
    arrivals = np.cumsum(rng.exponential(1.0 / rate_rps, n_req))
    traffic = mk_traffic(rate_rps)
    cfg = AutotuningConfig(
        enabled=True,
        serving_k_steps=[K // 2, K], serving_chain_depths=[1, 2, 4],
        # ring admission only: open-loop arrivals admit at every
        # rowset size, and plain-chain mode compiles one executable
        # bucket per size (a cold-compile storm inside the measured
        # window) — the same reason the hand-tuned baseline runs ring
        serving_ring_modes=[True],
        serving_draft_lens=[0, 3], serving_shed_depths=[0, 2 * B])
    planner = ServingPlanner(
        cfg, cal, traffic, base_engine_config=base_engine,
        base_serving_config={"k_steps": K}, max_rows=B,
        kv_block_size=bs_kv, base_kv_blocks=nb)
    plan = planner.plan()
    os.makedirs("artifacts", exist_ok=True)
    path = plan.save(os.path.join("artifacts", "serving_plan.json"))
    out = summarize_serving(plan)
    out["metric"] = "serving_plan_vs_baseline"
    out["unit"] = "x"
    out["plan_path"] = path
    out["calibration_tick_ms"] = round(tick * 1e3, 4)
    out["calibration_overhead_ms"] = round(overhead * 1e3, 4)

    # measured comparison on identical traffic: hand-tuned baseline
    # first (this engine), then the chosen config (fresh engine built
    # from plan.apply() — the artifact's reproduction contract)
    from deepspeed_tpu.serving import ServingConfig

    def warm(engine, scfg):
        # warm EVERY executable bucket outside the measured traffic
        # window: closed-loop sweeps over admission row counts 1..B,
        # then one throwaway drive of the measured arrival trace
        # itself (saturated admission packs chunked-prefill batches —
        # e.g. 16-chunk ragged buckets — that no closed-loop sweep
        # produces). One cold compile mid-measurement reads as seconds
        # of TTFT and would grade the CONFIG for the compiler's sins.
        k = scfg.k_steps or K
        for n_warm in range(min(B, n_req), 0, -1):
            _chained_serve_metrics(engine, prompts[:n_warm], k,
                                   max_new=min(max_new, 2 * k))
        _openloop_drive(engine, scfg, prompts, arrivals, max_new)
        engine.reset_serving_metrics()

    base_scfg = ServingConfig(k_steps=K, slo_ttft_ms=slo.slo_ttft_ms,
                              slo_itl_ms=slo.slo_itl_ms)
    warm(e, base_scfg)
    base_run = _openloop_drive(e, base_scfg, prompts, arrivals, max_new)
    del e
    gc.collect()
    e2 = InferenceEngineV2(model, plan.engine_config())
    srv_dict = plan.apply().get("serving", {})
    plan_scfg = ServingConfig(**{**srv_dict,
                                 "slo_ttft_ms": slo.slo_ttft_ms,
                                 "slo_itl_ms": slo.slo_itl_ms})
    warm(e2, plan_scfg)
    plan_run = _openloop_drive(e2, plan_scfg, prompts, arrivals,
                               max_new)
    del e2
    gc.collect()

    def pct(xs, q):
        if not xs:
            return None
        xs = sorted(xs)
        return round(xs[min(len(xs) - 1, int(len(xs) * q))], 2)

    out["baseline_goodput_rps"] = round(base_run["goodput_rps"], 3)
    out["plan_goodput_rps"] = round(plan_run["goodput_rps"], 3)
    out["value"] = out["serving_plan_vs_baseline"] = round(
        plan_run["goodput_rps"] / max(base_run["goodput_rps"], 1e-9), 4)
    out["baseline_ttft_p99_ms"] = pct(base_run["ttft"], 0.99)
    out["plan_ttft_p99_ms"] = pct(plan_run["ttft"], 0.99)
    out["baseline_itl_p99_ms"] = pct(base_run["itl"], 0.99)
    out["plan_itl_p99_ms"] = pct(plan_run["itl"], 0.99)
    out["plan_shed"] = plan_run["shed"]
    # stamp the measured truth onto the chosen row and re-save, so
    # tools/autotune_report.py renders predicted vs measured
    chosen = plan.chosen
    if chosen is not None:
        chosen["measured_goodput_rps"] = out["plan_goodput_rps"]
        chosen["measured_ttft_p99_ms"] = out["plan_ttft_p99_ms"]
        chosen["measured_itl_p99_ms"] = out["plan_itl_p99_ms"]
        plan.save(path)
        out["chosen_patch"] = plan.chosen_patch
    del planner, plan
    gc.collect()
    return out


def disagg_bench(ds, on_tpu: bool):
    """Disaggregated serving (ISSUE 13): two acceptance figures.

    (A) Decode-ITL flatness under long-prompt pressure — mixed chat +
    long-prompt traffic, measured twice as the long prompts grow 10x:
    against a single co-located engine (long-prompt chunked prefill
    steals decode ticks at every dispatch boundary, so chat ITL p99
    degrades) and against the prefill/decode split (long prompts
    prefill on the dedicated engine and migrate in as KV block sets —
    decode ticks undisturbed, ITL p99 flat).

    (B) N-replica scaling behind the prefix-affinity router —
    aggregate tokens/s on N=2 replicas at the same per-replica offered
    load vs the single-replica figure (`replica_scaling_x`, acceptance
    >= 0.8), with per-replica placements and prefix hit rates (the
    shared-system-prompt wave lands on the replica holding the chain
    warm)."""
    import asyncio

    import numpy as np
    from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig)
    from deepspeed_tpu.models import Llama
    from deepspeed_tpu.serving import (AsyncInferenceServer,
                                       InferenceRouter, PrefillEngine,
                                       RouterConfig, ServingConfig)

    if on_tpu:
        model = Llama(hidden_size=1024, num_layers=12, num_heads=8,
                      num_kv_heads=8, intermediate_size=2816,
                      vocab_size=32000, max_seq_len=4096)
        bs_kv, nb, chunk, B, K = 64, 384, 256, 16, 8
        chat_len, chat_new, n_chat = 64, 64, 12
        long_lens, long_new, n_long, long_gap = (256, 2560), 8, 4, 0.2
        scale_req, scale_new, scale_k, scale_rps = 24, 64, 8, 4.0
    else:
        # big enough that prefill is real COMPUTE (a 320-token prompt's
        # chunked prefill stalls decode for many chain gaps), small
        # enough that the stall windows stay short relative to the run
        # — on this 2-core rig the prefill "mesh" shares silicon with
        # decode, so an oversized model turns the A comparison into a
        # pure CPU-contention measurement (a TPU deployment puts the
        # prefill engine on its own chips)
        model = Llama(size="tiny", hidden_size=128, num_layers=3,
                      num_heads=4, num_kv_heads=4,
                      intermediate_size=344, vocab_size=2048,
                      max_seq_len=512)
        bs_kv, nb, chunk, B, K = 8, 192, 32, 8, 4
        chat_len, chat_new, n_chat = 16, 16, 6
        long_lens, long_new, n_long, long_gap = (32, 320), 4, 3, 0.2
        # deeper fused K for the scaling runs: host work per token is
        # the 2-core rig's scaling ceiling, and K amortizes it
        scale_req, scale_new, scale_k, scale_rps = 12, 32, 16, 2.5
    dtype = "bfloat16" if on_tpu else "float32"

    def mk(params=None):
        return InferenceEngineV2(model, RaggedInferenceEngineConfig(
            dtype=dtype, kv_block_size=bs_kv, num_kv_blocks=nb,
            max_chunk_size=chunk, max_ragged_sequence_count=B,
            fused_decode_steps=K, prefix_cache={"enabled": True}),
            params=params)

    e_single = mk()
    params = e_single.params
    e_pre, e_d0, e_d1 = mk(params), mk(params), mk(params)
    rng = np.random.default_rng(0)
    vocab = model.config.vocab_size

    def prompts(n, length):
        return [rng.integers(0, vocab, length).tolist()
                for _ in range(n)]

    # ---- (A) chat ITL p99 vs long-prompt length, single vs disagg ----
    chat_prompts = prompts(n_chat, chat_len)

    async def mixed_run(router, long_len):
        itls: list[float] = []
        longs = prompts(n_long, long_len)

        async def chat(i):
            h = await router.submit(chat_prompts[i],
                                    max_new_tokens=chat_new)
            prev = None
            async for _t in h:
                now = time.perf_counter()
                if prev is not None:
                    itls.append((now - prev) * 1e3)
                prev = now

        async def long_stream():
            for p in longs:
                await asyncio.sleep(long_gap)
                h = await router.submit(p, max_new_tokens=long_new)
                await h.tokens()

        async with router:
            await asyncio.gather(long_stream(),
                                 *(chat(i) for i in range(n_chat)))
        return itls

    def pct(xs, q):
        if not xs:
            return None
        xs = sorted(xs)
        return round(xs[min(len(xs) - 1, int(len(xs) * q))], 2)

    def single_router():
        return InferenceRouter(
            [AsyncInferenceServer(e_single, ServingConfig(k_steps=K))],
            RouterConfig())

    def disagg_router():
        return InferenceRouter(
            [AsyncInferenceServer(e_d0, ServingConfig(k_steps=K))],
            RouterConfig(disaggregation={
                "enabled": True,
                # chat stays co-located; long prompts migrate
                "prefill_threshold_tokens": chat_len + 1}),
            prefill=PrefillEngine(e_pre, name="prefill0"))

    itl: dict[str, dict[int, float]] = {"single": {}, "disagg": {}}
    migrate_bytes = migrate_blocks = handoffs = 0
    for mode, mk_router in (("single", single_router),
                            ("disagg", disagg_router)):
        # warm pass (compiles prefill buckets + the serve loop) at the
        # SHORT length, outside every measured window
        asyncio.run(mixed_run(mk_router(), long_lens[0]))
        for L in long_lens:
            # best-of-2 windows per point (noisy-rig discipline)
            best = None
            for _ in range(2):
                router = mk_router()
                p99 = pct(asyncio.run(mixed_run(router, L)), 0.99)
                best = p99 if best is None else min(best, p99)
                if mode == "disagg":
                    pm = router.prefill.metrics()
                    migrate_bytes += pm["exported_bytes"]
                    migrate_blocks += pm["exported_blocks"]
                    handoffs += pm["prefills"]
            itl[mode][L] = best
    l0, l1 = long_lens
    single_drift = itl["single"][l1] / max(itl["single"][l0], 1e-6)
    disagg_drift = itl["disagg"][l1] / max(itl["disagg"][l0], 1e-6)
    # migration byte economics: the hand-off moves KV blocks in their
    # storage format — bytes/token rides kv_bytes_per_token exactly
    # (quantized engines migrate quantized; no dequantize leg)
    migrate_bpt = (migrate_bytes / max(migrate_blocks * bs_kv, 1)
                   if migrate_blocks else None)

    # ---- (B) N-replica scaling + per-replica prefix hit rates --------
    shared = rng.integers(0, vocab, 2 * bs_kv).tolist()

    def scale_prompts(n):
        # half shared-system-prompt traffic (the affinity key), half
        # unique chat
        out = []
        for i in range(n):
            if i % 2 == 0:
                out.append(shared
                           + rng.integers(0, vocab, 4).tolist())
            else:
                out.append(rng.integers(0, vocab, chat_len).tolist())
        return out

    async def scale_run(engines, rounds=2):
        """Open-loop Poisson traffic (the serve_openloop discipline)
        at ``scale_rps`` requests/s PER REPLICA: N replicas face N x
        the single-replica offered load, and sustained aggregate
        tokens/s is the scaling figure — best-of-``rounds`` windows
        after one closed-loop warm wave (compiles + prefix-cache
        seed), TTFT p99 reported so 'sustained' is checkable (a
        saturated config shows up as queue growth there first)."""
        servers = [AsyncInferenceServer(
            e, ServingConfig(k_steps=scale_k)) for e in engines]
        router = InferenceRouter(servers, RouterConfig())
        n = scale_req * len(engines)
        rate = scale_rps * len(engines)

        async def warm():
            hs = [await router.submit(p, max_new_tokens=scale_new)
                  for p in scale_prompts(n)]
            for h in hs:
                await h.tokens()

        async def openloop_window():
            reqs = scale_prompts(n)
            arrivals = np.cumsum(rng.exponential(1.0 / rate, n))
            ttfts: list[float] = []

            async def client(i):
                await asyncio.sleep(float(arrivals[i]))
                t_sub = time.perf_counter()
                h = await router.submit(reqs[i],
                                        max_new_tokens=scale_new)
                toks = []
                async for t in h:
                    if not toks:
                        ttfts.append((time.perf_counter() - t_sub)
                                     * 1e3)
                    toks.append(t)
                return len(toks)

            for e in engines:
                e.reset_serving_metrics()
            t0 = time.perf_counter()
            counts = await asyncio.gather(*(client(i)
                                            for i in range(n)))
            wall = time.perf_counter() - t0
            return (sum(counts) / max(wall, 1e-9),
                    pct(sorted(ttfts), 0.99))

        async with router:
            await warm()
            best, ttft = 0.0, None
            for _ in range(rounds):
                tps, t99 = await openloop_window()
                if tps > best:
                    best, ttft = tps, t99
            return best, ttft, router.metrics()

    # single replica on the SAME warmed engine the 2-replica run uses,
    # so the comparison is compile-free on both sides
    t1, ttft1, m1 = asyncio.run(scale_run([e_d0]))
    tn, ttftn, mn = asyncio.run(scale_run([e_d0, e_d1]))
    n_rep = 2
    scaling = tn / max(n_rep * t1, 1e-9)
    per_replica = {
        name: {"decoded_tokens": row["decoded_tokens"],
               "placed": row["placed"],
               "prefix_hit_rate": round(row["prefix_hit_rate"], 3)}
        for name, row in mn["replicas"].items()}

    return {"metric": "disagg_chat_itl_p99_ms_at_10x",
            "value": itl["disagg"][l1], "unit": "ms",
            "chat_itl_p99_ms": {m: {str(L): v for L, v in d.items()}
                                for m, d in itl.items()},
            "long_prompt_lens": list(long_lens),
            "single_itl_p99_drift_x10_ratio": round(single_drift, 3),
            "disagg_itl_p99_drift_x10_ratio": round(disagg_drift, 3),
            "itl_flat_under_10x": bool(disagg_drift <= 1.15),
            "prefill_handoffs": handoffs,
            "migrate_bytes_per_token": (round(migrate_bpt, 3)
                                        if migrate_bpt else None),
            "kv_bytes_per_token": round(e_pre.kv_bytes_per_token(), 3),
            "single_replica_tokens_per_sec": round(t1, 1),
            "aggregate_tokens_per_sec_2rep": round(tn, 1),
            "openloop_rps_per_replica": scale_rps,
            "scale_ttft_p99_ms": {"1rep": ttft1, "2rep": ttftn},
            "replica_scaling_x": round(scaling, 3),
            "replicas": n_rep, "per_replica": per_replica,
            "fused_k": K, "requests_per_replica": scale_req}


def fleet_bench(ds, on_tpu: bool):
    """Fleet health plane (ISSUE 17): kill one replica under open-loop
    Poisson load and measure the detection -> reroute incident
    response. Two replicas behind the health-gated router take Poisson
    traffic; mid-window the victim replica's serving loop is killed
    through the supported fault-injection path (``server.kill()`` — a
    real worker death, not a monkeypatch). The stage reports:

    - ``detection_ms`` — kill to the phi-accrual detector marking the
      victim suspect/dead (heartbeat silence, no failure RPC);
    - ``detection_to_reroute_ms`` — kill until BOTH the detector
      tripped and the router rerouted the victim's in-flight requests
      (the drain-and-reroute contract);
    - ``dropped_requests`` — client-visible failures (the acceptance
      bar is ZERO: every in-flight request completes elsewhere);
    - multi-window ``slo_burn_rate_*`` from the time-series ring
      (breaches per request over the fast/slow burn windows spanning
      the incident).

    Gated by ``telemetry_report --gate fleet``. Directly pre-stages
    ROADMAP item 1's acceptance figure."""
    import asyncio

    import numpy as np
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig)
    from deepspeed_tpu.models import Llama
    from deepspeed_tpu.serving import (AsyncInferenceServer,
                                       InferenceRouter, RouterConfig,
                                       ServingConfig)

    # the stage NEEDS the telemetry plane (detector + ring); own it for
    # the stage when the harness did not pass --telemetry
    owned = not telemetry.is_active()
    if owned:
        telemetry.configure()

    if on_tpu:
        model = Llama(hidden_size=1024, num_layers=12, num_heads=8,
                      num_kv_heads=8, intermediate_size=2816,
                      vocab_size=32000, max_seq_len=2048)
        bs_kv, nb, chunk, B, K = 64, 256, 256, 16, 8
        n_req, rate_rps, p_len, max_new = 32, 8.0, 64, 32
        slo_ttft_ms = 500.0
    else:
        model = Llama(size="tiny", hidden_size=128, num_layers=3,
                      num_heads=4, num_kv_heads=4,
                      intermediate_size=344, vocab_size=2048,
                      max_seq_len=512)
        bs_kv, nb, chunk, B, K = 8, 128, 16, 8, 4
        n_req, rate_rps, p_len, max_new = 32, 8.0, 12, 8
        slo_ttft_ms = 50.0
    dtype = "bfloat16" if on_tpu else "float32"

    def mk(params=None):
        return InferenceEngineV2(model, RaggedInferenceEngineConfig(
            dtype=dtype, kv_block_size=bs_kv, num_kv_blocks=nb,
            max_chunk_size=chunk, max_ragged_sequence_count=B,
            fused_decode_steps=K), params=params)

    e0 = mk()
    e1 = mk(e0.params)
    rng = np.random.default_rng(0)
    vocab = model.config.vocab_size
    prompts = [rng.integers(0, vocab, p_len).tolist()
               for _ in range(n_req)]
    arrivals = np.cumsum(rng.exponential(1.0 / rate_rps, n_req))
    # floor: the post-clear detector needs min_heartbeats intervals of
    # in-round cadence before silence can read as suspicion
    kill_at = max(float(arrivals[n_req // 3]), 2.5)
    tel = telemetry  # active by construction above
    incident = {"t_kill": None, "t_detect": None, "t_reroute": None,
                "detect_state": None, "victim_open_at_kill": None}

    def mk_router():
        servers = [AsyncInferenceServer(e, ServingConfig(
            k_steps=K, slo_ttft_ms=slo_ttft_ms)) for e in (e0, e1)]
        # tighter-than-default phi thresholds: the bench WANTS an
        # aggressive detector (it measures incident response, and a
        # false trip would show up as health_skips + a flapping state,
        # both reported)
        return servers, InferenceRouter(servers, RouterConfig(
            health={"phi_suspect": 2.0, "phi_dead": 5.0}))

    async def run(servers, router, kill: bool):
        results = {"done": 0, "dropped": 0, "tokens": 0}
        victim = servers[0].config.replica
        hm = tel.get_health_monitor()

        async def client(i):
            await asyncio.sleep(float(arrivals[i]))
            try:
                h = await router.submit(prompts[i],
                                        max_new_tokens=max_new)
                results["tokens"] += len(await h.tokens())
                results["done"] += 1
            except Exception:   # noqa: BLE001 — a drop IS the figure
                results["dropped"] += 1

        async def killer():
            await asyncio.sleep(kill_at)
            incident["t_kill"] = time.perf_counter()
            victim_open = servers[0].open_requests
            incident["victim_open_at_kill"] = victim_open
            servers[0].kill()
            deadline = incident["t_kill"] + 60.0
            # detection: heartbeat silence alone must trip the
            # detector (no failure notification is consulted)
            while hm.state(victim) not in ("suspect", "dead") \
                    and time.perf_counter() < deadline:
                await asyncio.sleep(0.002)
            if hm.state(victim) in ("suspect", "dead"):
                incident["t_detect"] = time.perf_counter()
                incident["detect_state"] = hm.state(victim)
            # reroute: the victim's in-flight requests resubmitted
            # elsewhere (drain-and-reroute); nothing to wait for if
            # the victim happened to be empty at the kill
            while victim_open and router.stats["reroutes"] == 0 \
                    and time.perf_counter() < deadline:
                await asyncio.sleep(0.002)
            if not victim_open or router.stats["reroutes"]:
                incident["t_reroute"] = time.perf_counter()

        async with router:
            t0 = time.perf_counter()
            jobs = [client(i) for i in range(n_req)]
            if kill:
                jobs.append(killer())
            await asyncio.gather(*jobs)
            wall = time.perf_counter() - t0
            return results, wall, router.metrics()

    try:
        # warm wave (compiles + detector cadence history), no kill
        servers, router = mk_router()
        asyncio.run(run(servers, router, kill=False))
        rt = tel.get_request_recorder()
        if rt is not None:
            rt.clear()
        ts = tel.get_timeseries()
        if ts is not None:
            ts.clear()
        # fresh detector cadence for the measured round: the warm
        # round's replicas answered to the same names, and the
        # inter-round setup gap would poison their interval history
        # (an inflated mean interval inflates detection latency)
        tel.get_health_monitor().clear()

        servers, router = mk_router()
        results, wall, m = asyncio.run(run(servers, router, kill=True))

        burn = {}
        if ts is not None:
            for win, rate in ts.multi_window_burn(
                    "ds_serving_slo_",
                    "ds_serving_requests_total").items():
                burn[f"slo_burn_rate_{win}"] = round(rate, 4)
        t_kill = incident["t_kill"]
        detection_ms = (
            round((incident["t_detect"] - t_kill) * 1e3, 1)
            if incident["t_detect"] else None)
        reroute_ms = (
            round((max(incident["t_reroute"], incident["t_detect"])
                   - t_kill) * 1e3, 1)
            if incident["t_reroute"] and incident["t_detect"] else None)
        survivors = [n for n, s in m.get("health", {}).items()
                     if s not in ("suspect", "dead")]
        placed = [m["replicas"][n]["placed"] for n in survivors
                  if n in m.get("replicas", {})]
        skew = (round(max(placed) / (sum(placed) / len(placed)), 3)
                if placed else None)
        return {"metric": "fleet_detection_to_reroute_ms",
                "value": reroute_ms, "unit": "ms",
                "detection_ms": detection_ms,
                "detection_state": incident["detect_state"],
                "requests": n_req, "completed": results["done"],
                "victim_open_at_kill": incident["victim_open_at_kill"],
                "dropped_requests": results["dropped"],
                "zero_drops": bool(results["dropped"] == 0),
                "reroutes": m["reroutes"],
                "health_skips": m["health_skips"],
                "replica_skew": skew,
                "health_states": m.get("health", {}),
                "tokens_per_sec": round(results["tokens"]
                                        / max(wall, 1e-9), 1),
                "arrival_rate_rps": rate_rps,
                "slo_ttft_ms_target": slo_ttft_ms, **burn}
    finally:
        if owned:
            telemetry.shutdown()


def serving_bench(ds, on_tpu: bool):
    """Serving class (BASELINE configs 1-2 / FastGen): greedy batch
    decode on the Llama-340M-class model. Reports the v1 engine's
    compiled decode loop (the CUDA-graph analogue — one dispatch per
    batch); the v2 per-tick scheduler is dispatch-bound through this
    harness's remote tunnel (~100ms RTT per tick), so its wall-clock
    here reflects the tunnel, not the engine — its tick RTT is reported
    for the record."""
    import numpy as np
    from deepspeed_tpu.models import Llama
    if on_tpu:
        model = Llama(hidden_size=1024, num_layers=12, num_heads=8,
                      num_kv_heads=8, intermediate_size=2816,
                      vocab_size=32000, max_seq_len=2048)
        B, P, N = 24, 256, 64
    else:
        model = Llama(size="tiny", max_seq_len=256)
        B, P, N = 2, 16, 4
    e = ds.init_inference(model, dtype="bfloat16" if on_tpu else "float32",
                          max_out_tokens=1024 if on_tpu else 64)
    rng = np.random.default_rng(0)
    prompts = jnp.asarray(rng.integers(0, model.config.vocab_size,
                                       size=(B, P)))
    np.asarray(e.generate(prompts, max_new_tokens=N))   # warmup/compile
    np.asarray(e.generate(prompts, max_new_tokens=1))   # warm 1-token

    def v1_pair(reps):
        """(full-decode, one-token) wall times, best-of-reps each —
        their difference isolates (N-1) compiled decode steps."""
        dt_ = dt1_ = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            out = e.generate(prompts, max_new_tokens=N)
            np.asarray(out)
            dt_ = min(dt_, time.perf_counter() - t0)
            t0 = time.perf_counter()
            out1 = e.generate(prompts, max_new_tokens=1)
            np.asarray(out1)
            dt1_ = min(dt1_, time.perf_counter() - t0)
        return dt_, dt1_

    dt, dt1 = v1_pair(3 if on_tpu else 1)
    # v2 scheduler tick RTT (one bucketed decode tick through put())
    from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig)
    e2 = InferenceEngineV2(model, RaggedInferenceEngineConfig(
        dtype="bfloat16" if on_tpu else "float32", kv_block_size=64,
        num_kv_blocks=256, max_chunk_size=256))
    n = min(24, B)
    uids = list(range(n))
    # same prompt length as the v1 decode measurement so the two
    # per-step figures compare at matched context
    e2.put(uids, [prompts[i].tolist() for i in range(n)])

    def one_tick():
        e2.schedule(uids, [[1]] * n, do_checks=False)
        res = e2.tick()
        # decode ticks finish every sequence's single pending token, so
        # res is non-empty; the float() forces a device->host sync
        # (block_until_ready can return early under the remote tunnel)
        float(jnp.sum(next(iter(res.values()))))

    p50, p99 = _tick_percentiles(one_tick, 24 if on_tpu else 4)
    # compute-basis per-token step time from the COMPILED decode loop:
    # marginal cost of (N-1) extra decode steps, so prefill + dispatch
    # are subtracted out. This is the device truth the v2 tick would see
    # on a local host; the host-in-loop v2 tick p50/p99 above
    # additionally pays this harness's ~100 ms client<->TPU tunnel RTT
    # per tick — a property of the measurement path, not the engine.
    decode_step_ms = max(dt - dt1, 1e-9) / max(N - 1, 1) * 1e3

    # v2 paged-step device time (the paged kernel reads only LIVE
    # pages, vs the v1 static cache scanning all max_out_tokens slots —
    # the FastGen memory-read advantage at realistic context lengths)
    make_chain, args = _decode_chain_setup(model, e2, uids,
                                           use_kernel=on_tpu)
    long_n, short_n = (64, 8) if on_tpu else (4, 2)
    chain_l, chain_s = make_chain(long_n), make_chain(short_n)
    pools = e2.pools
    for c in (chain_l, chain_s):                       # compile + warm
        lgs, pools = c(e2.params, pools, *args)
        float(jnp.sum(lgs))

    def chain_pair_ms(params, pools, args, reps=3):
        return _chain_pair_ms(chain_l, chain_s, params, pools, args,
                              long_n, short_n, reps)

    # paired windows: each window measures the v1 step AND the paged
    # step back-to-back, so tunnel-RTT drift hits both sides alike;
    # the per-window delta distribution carries the claim (CI95 must
    # exclude zero — VERDICT r4 #7)
    n_windows = 5 if on_tpu else 2
    v1_steps, v2_steps = [], []
    for _ in range(n_windows):
        w_dt, w_dt1 = v1_pair(2 if on_tpu else 1)
        v1_steps.append(max(w_dt - w_dt1, 1e-9) / max(N - 1, 1) * 1e3)
        ms, pools = chain_pair_ms(e2.params, pools, args,
                                  reps=2 if on_tpu else 1)
        v2_steps.append(ms)
    deltas = [a - b for a, b in zip(v1_steps, v2_steps)]
    d_mean, d_ci = _mean_ci95(deltas)
    v2_step_ms = sorted(v2_steps)[len(v2_steps) // 2]   # median window

    # short-context check (paged must also still win where it already
    # did): same differencing at ~32-token contexts
    short = {}
    if on_tpu:
        e3 = InferenceEngineV2(model, RaggedInferenceEngineConfig(
            dtype="bfloat16", kv_block_size=64, num_kv_blocks=256,
            max_chunk_size=256))
        e3.put(uids, [prompts[i, :32].tolist() for i in range(n)])
        _, args3 = _decode_chain_setup(model, e3, uids, use_kernel=True)
        pools3 = e3.pools
        for c in (chain_l, chain_s):
            lgs, pools3 = c(e3.params, pools3, *args3)
            float(jnp.sum(lgs))
        ms3, pools3 = chain_pair_ms(e3.params, pools3, args3)
        short["v2_paged_step_ms_32ctx"] = round(ms3, 2)

    # fused multi-step decode (ISSUE 1): K ticks per host dispatch with
    # in-graph sampling + termination — the tick RTT is paid once per K
    # tokens, so the per-token figure collapses toward the compute
    # floor. e2 is reused (flush releases the tick-grown sequences);
    # the chain measurements above never donate e2.pools
    e2.flush(uids)
    fused = _fused_decode_metrics(
        e2, [prompts[i].tolist() for i in range(n)],
        k=8 if on_tpu else 4, n_dispatches=12 if on_tpu else 3)

    # the SLA comes from ServingConfig (ISSUE 19 satellite: the gate
    # and the config must agree), not a literal in this stage
    slo_ms = _bench_serving_slo().slo_itl_ms
    return {"metric": "serving_decode_tokens_per_sec",
            **short, **fused,
            "value": round(B * N / dt, 1), "unit": "tokens/s/chip",
            "batch": B, "with_prefill": round(B * (N + P) / dt, 1),
            "decode_step_ms_compute": round(decode_step_ms, 2),
            "v1_step_ms_windows": [round(x, 2) for x in v1_steps],
            "v2_step_ms_windows": [round(x, 2) for x in v2_steps],
            "v1_minus_paged_delta_ms": round(d_mean, 3),
            "paged_delta_ci95_ms": round(d_ci, 3),
            # claimed only when the paired-window CI excludes zero
            "paged_wins": bool(d_mean - d_ci > 0),
            "v2_paged_step_ms_compute": round(v2_step_ms, 2),
            "v2_paged_tokens_per_sec_compute": round(
                n * 1e3 / v2_step_ms, 1),
            "v2_tick_p50_ms": round(p50, 1),
            "v2_tick_p99_ms": round(p99, 1),
            "slo_ms": slo_ms,
            "tokens_per_sec_at_slo": round(
                B * 1e3 / max(decode_step_ms, slo_ms), 1)}


def prefix_bench(ds, on_tpu: bool):
    """Automatic prefix caching (ISSUE 4): shared-system-prompt serving.

    N requests share a long system prefix and differ only in a short
    unique tail. Served sequentially against (a) a cache-disabled
    engine and (b) a prefix-cache engine whose first request warms the
    chain, the cached path must cut prefill tokens >=50% and TTFT with
    it. TTFT here is the put() wall time — prefill through first-token
    logits — the exact cost prefix reuse removes. The warm engine's
    ``max_cached_blocks`` is sized so unique tail blocks churn through
    the LRU, exercising (and reporting) eviction."""
    import numpy as np
    from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig)
    from deepspeed_tpu.models import Llama
    if on_tpu:
        model = Llama(hidden_size=1024, num_layers=12, num_heads=8,
                      num_kv_heads=8, intermediate_size=2816,
                      vocab_size=32000, max_seq_len=2048)
        bs, nb, chunk = 64, 256, 256
        shared_len, uniq_len, n_req = 1024, 64, 8
    else:
        model = Llama(size="tiny", max_seq_len=256)
        bs, nb, chunk = 8, 128, 16
        shared_len, uniq_len, n_req = 64, 8, 6
    shared_blocks = shared_len // bs
    rng = np.random.default_rng(0)
    vocab = model.config.vocab_size
    shared = rng.integers(0, vocab, shared_len).tolist()
    prompts = [shared + rng.integers(0, vocab, uniq_len).tolist()
               for _ in range(n_req)]

    def serve(enabled):
        e = InferenceEngineV2(model, RaggedInferenceEngineConfig(
            dtype="bfloat16" if on_tpu else "float32",
            kv_block_size=bs, num_kv_blocks=nb, max_chunk_size=chunk,
            prefix_cache={"enabled": enabled, "min_match_blocks": 1,
                          "max_cached_blocks": shared_blocks + 4}))
        # warming request: compiles the prefill buckets on both engines
        # and (cache on) seeds the shared chain — excluded from timing
        e.put([10 ** 6], [prompts[0]])
        e.flush(10 ** 6)
        e.reset_serving_metrics()
        ttfts = []
        for i, p in enumerate(prompts):
            t0 = time.perf_counter()
            lg = e.put([i], [p])
            float(jnp.max(lg))           # force the device->host sync
            ttfts.append((time.perf_counter() - t0) * 1e3)
            e.flush(i)
        ttfts.sort()
        p50 = ttfts[len(ttfts) // 2]
        p99 = ttfts[min(len(ttfts) - 1, int(len(ttfts) * 0.99))]
        return p50, p99, e.serving_metrics()

    cold_p50, cold_p99, cold_m = serve(False)
    warm_p50, warm_p99, warm_m = serve(True)
    # mirror the cache counters into the telemetry registry (the put()
    # prefill path has no fused dispatch to flush them) so the stage's
    # --telemetry artifacts carry ds_serving_prefix_* series
    from deepspeed_tpu.utils.telemetry_probe import active_telemetry
    tel = active_telemetry()
    reg = tel.get_registry() if tel is not None else None
    if reg is not None:
        tel.bridges.collect_serving(reg, warm_m)
    total_prompt_tokens = sum(len(p) for p in prompts)
    reduction = warm_m["prefill_tokens_saved"] / total_prompt_tokens
    return {"metric": "prefix_cache_warm_ttft_p50_ms",
            "value": round(warm_p50, 2), "unit": "ms",
            "ttft_cold_p50_ms": round(cold_p50, 2),
            "ttft_cold_p99_ms": round(cold_p99, 2),
            "ttft_warm_p99_ms": round(warm_p99, 2),
            "ttft_speedup_p50": round(cold_p50 / max(warm_p50, 1e-9), 2),
            "prefill_token_reduction": round(reduction, 3),
            "prefill_tokens_saved": warm_m["prefill_tokens_saved"],
            "prompt_tokens_total": total_prompt_tokens,
            "prefix_hits": warm_m["prefix_hits"],
            "prefix_misses": warm_m["prefix_misses"],
            "prefix_hit_rate": round(warm_m["prefix_hit_rate"], 3),
            "prefix_evictions": warm_m["prefix_evictions"],
            "prefix_cached_blocks": warm_m["prefix_cached_blocks"],
            "shared_prefix_tokens": shared_len, "requests": n_req}


def spec_bench(ds, on_tpu: bool):
    """Speculative decoding (ISSUE 9): prompt-lookup drafting + the
    in-graph 1+draft_len verify on a repetitive decode workload.

    The workload decodes LONG greedy continuations: past a short
    burn-in, greedy decode settles into a repeating cycle — the extreme
    form of the agentic/templated traffic PLD targets (tool-call
    loops, JSON scaffolds, copied context), where the continuation is
    predictable from the row's own recent history. Spec-on and
    spec-off runs share the model/engine config and greedy sampling,
    and the stage asserts BIT-PARITY of outputs before reporting any
    number — speculation may only change how many tokens land per
    forward, never which tokens.

    Gated via ``telemetry_report --diff --gate serving``:
    ``spec_tokens_per_sec`` / ``tokens_per_sec_spec_off`` (+1),
    ``acceptance_rate`` (+1), ``tokens_per_dispatch`` — mean tokens
    COMMITTED per scheduled (row, tick) slot, the >1.5 acceptance
    figure — (+1), and ``spec_overhead_ms`` (-1): p50 per-dispatch
    wall of a spec-ON engine on a SHORT non-repetitive workload where
    drafts essentially never land, i.e. the full price of drafting +
    the widened verify forward with no speculation win to hide it
    (``spec_overhead_delta_ms``, the difference vs spec-off on the
    same workload, rides along un-gated — on a compute-bound CPU rig
    it is real and positive; dispatch-bound TPU serving is where it
    vanishes)."""
    import numpy as np
    from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig)
    from deepspeed_tpu.models import Llama
    if on_tpu:
        model = Llama(hidden_size=1024, num_layers=12, num_heads=8,
                      num_kv_heads=8, intermediate_size=2816,
                      vocab_size=32000, max_seq_len=2048)
        bs, nb, chunk = 64, 512, 256
        B, P, N = 8, 64, 768
    else:
        # long horizon on purpose: the tiny random-weight model needs a
        # burn-in (~150 ticks here) before its greedy continuation
        # settles into the cycle the drafter feeds on, and the stage
        # must measure mostly steady state (a production agentic
        # workload is repetitive from the first tool echo, not after a
        # burn-in)
        model = Llama(size="tiny", max_seq_len=768)
        bs, nb, chunk = 8, 512, 32
        B, P, N = 4, 16, 720
    K, L = 4, 6
    spec_cfg = {"enabled": True, "draft_len": L, "min_ngram": 2,
                "history_window": 64}
    rng = np.random.default_rng(0)
    vocab = model.config.vocab_size
    prompts = [rng.integers(0, vocab, P).tolist() for _ in range(B)]

    def eng(spec_on):
        return InferenceEngineV2(model, RaggedInferenceEngineConfig(
            dtype="bfloat16" if on_tpu else "float32",
            kv_block_size=bs, num_kv_blocks=nb, max_chunk_size=chunk,
            speculative={**spec_cfg, "enabled": spec_on}))

    def run(spec_on):
        e = eng(spec_on)
        e.generate_fused(prompts, max_new_tokens=2 * K,
                         k_steps=K)                  # compile the path
        e.reset_serving_metrics()
        t0 = time.perf_counter()
        out = e.generate_fused(prompts, max_new_tokens=N, k_steps=K)
        wall = time.perf_counter() - t0
        return out, wall, e.serving_metrics()

    out_off, wall_off, m_off = run(False)
    out_on, wall_on, m_on = run(True)
    assert out_on == out_off, "speculative greedy output diverged"
    n_tok = sum(len(o) for o in out_on)
    tps_on = n_tok / max(wall_on, 1e-9)
    tps_off = n_tok / max(wall_off, 1e-9)

    # draft-miss overhead probe: SHORT random continuations (burn-in
    # regime, no cycle for the n-gram index to hit) through the raw
    # fused-decode dispatch, spec-on vs spec-off
    ov_on = _fused_decode_metrics(eng(True), prompts, k=K,
                                  n_dispatches=6)
    ov_off = _fused_decode_metrics(eng(False), prompts, k=K,
                                   n_dispatches=6)

    # mirror the serving counters into the live registry so the
    # stage's --telemetry artifacts carry the ds_serving_spec_* series
    from deepspeed_tpu.utils.telemetry_probe import active_telemetry
    tel = active_telemetry()
    reg = tel.get_registry() if tel is not None else None
    if reg is not None:
        tel.bridges.collect_serving(reg, m_on)
    return {"metric": "spec_decode_tokens_per_sec",
            "value": round(tps_on, 1), "unit": "tokens/s/chip",
            "spec_tokens_per_sec": round(tps_on, 1),
            "tokens_per_sec_spec_off": round(tps_off, 1),
            "speedup_vs_spec_off": round(tps_on / max(tps_off, 1e-9),
                                         2),
            "greedy_parity": True,
            "acceptance_rate": round(m_on["spec_acceptance_rate"], 3),
            "tokens_per_dispatch": round(m_on["tokens_per_dispatch"],
                                         3),
            "spec_proposed_tokens": m_on["spec_proposed_tokens"],
            "spec_accepted_tokens": m_on["spec_accepted_tokens"],
            "spec_hit_slots": m_on["spec_hit_slots"],
            "spec_overhead_ms": ov_on["fused_tick_p50_ms"],
            "spec_overhead_delta_ms": round(
                ov_on["fused_tick_p50_ms"]
                - ov_off["fused_tick_p50_ms"], 2),
            "draft_len": L, "min_ngram": 2, "k_steps": K,
            "batch": B, "new_tokens": N,
            "decoded_tokens": n_tok}


def kvquant_bench(ds, on_tpu: bool):
    """Quantized KV cache (ISSUE 12): int8 pools with per-vector
    scales, dequant fused into the paged-decode attention.

    Four figures, each against an UNQUANTIZED engine of the same model
    and compute dtype:

    - ``max_resident_batch`` (gated +1): concurrent (prompt + budget)
      requests the pool admits at EQUAL KV pool bytes — the quantized
      allocator is sized in quantized bytes, so the same HBM budget
      holds proportionally more blocks (the 2-4x resident-requests
      headline; exact ratio = full-precision over quantized
      bytes/token, reported as ``resident_batch_ratio``).
    - ``kv_bytes_per_token`` (gated -1): storage cost per cached token
      in the active format (deterministic layout arithmetic).
    - ``tokens_per_sec_int8`` vs ``tokens_per_sec_fp`` (equal pool
      bytes) and ``tokens_per_sec_fp_equal_blocks`` (a full-precision
      pool with the SAME block count the quantized pool holds, i.e.
      what matching the quantized engine's resident capacity costs
      unquantized): greedy fused decode at matched batch. CAVEAT (CPU
      rig): interpret-mode Pallas pays a pool-BYTES-proportional
      emulation cost per dispatch plus emulated dequant multiplies, so
      int8-vs-fp at equal bytes reads SLOWER here — the honest CPU
      figure is the equal-blocks one (same resident capacity: the
      int8 pool is ~2x faster AND 3-4x smaller). On TPU the dequant
      is an in-register VPU multiply against halved-to-quartered pool
      HBM traffic; re-baseline there.
    - accuracy: ``greedy_parity_horizon`` — tokens until the first
      greedy divergence vs the fp pool (min over the batch; the
      horizon the ISSUE pins) — and ``spec_acceptance_delta``: the
      prompt-lookup acceptance rate must move <2% absolute when the
      verify forward reads quantized KV (speculation reads the same
      pool as plain decode, so the drafter/acceptance machinery sees
      quantization only through the logits)."""
    import numpy as np
    from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig)
    from deepspeed_tpu.models import Llama
    if on_tpu:
        model = Llama(hidden_size=1024, num_layers=12, num_heads=8,
                      num_kv_heads=8, intermediate_size=2816,
                      vocab_size=32000, max_seq_len=2048)
        bs, nb, chunk = 64, 128, 256
        B, P, N, K = 8, 128, 64, 8
        n_spec = 512
    else:
        model = Llama(size="tiny", max_seq_len=768)
        bs, nb, chunk = 8, 128, 32
        B, P, N, K = 4, 16, 32, 4
        n_spec = 320
    dtype = "bfloat16" if on_tpu else "float32"
    rng = np.random.default_rng(0)
    vocab = model.config.vocab_size
    prompts = [rng.integers(0, vocab, P).tolist() for _ in range(B)]

    def eng(quant, grow=True, **over):
        kv = ({"enabled": True, "dtype": "int8", "grow_pool": grow}
              if quant else {"enabled": False})
        kw = dict(dtype=dtype, kv_block_size=bs, num_kv_blocks=nb,
                  max_chunk_size=chunk, max_ragged_sequence_count=64,
                  kv_cache=kv)
        kw.update(over)
        return InferenceEngineV2(model,
                                 RaggedInferenceEngineConfig(**kw))

    e_fp = eng(False)
    e_q = eng(True)
    # equal-budget accounting: the quantized pool must not exceed the
    # fp pool's bytes while holding more blocks
    assert e_q.kv_pool_bytes() <= e_fp.kv_pool_bytes(), \
        (e_q.kv_pool_bytes(), e_fp.kv_pool_bytes())
    bpr = -(-(P + N) // bs)          # blocks one resident request pins
    resident_fp = e_fp.num_kv_blocks // bpr
    resident_q = e_q.num_kv_blocks // bpr
    ratio = resident_q / max(resident_fp, 1)
    if dtype == "float32":
        # CPU rig: fp32 -> int8(+scales) is >= 2x by construction; a
        # regression here means the scale layout grew
        assert ratio >= 2.0, (resident_q, resident_fp)

    def timed_decode(e):
        """Greedy fused decode at MATCHED batch (both engines hold >= B
        requests): best-of-3 tokens/s over warmed drives."""
        e.generate_fused(prompts, max_new_tokens=2 * K,
                         k_steps=K)                  # compile the path
        e.reset_serving_metrics()
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            out = e.generate_fused(prompts, max_new_tokens=N, k_steps=K)
            wall = time.perf_counter() - t0
            best = max(best, sum(len(o) for o in out) / max(wall, 1e-9))
        return out, best

    out_fp, tps_fp = timed_decode(e_fp)
    out_q, tps_q = timed_decode(e_q)
    # equal-RESIDENT-CAPACITY comparison: a full-precision pool sized
    # to the quantized pool's block count (3-4x the bytes)
    _, tps_fp_big = timed_decode(
        eng(False, num_kv_blocks=e_q.num_kv_blocks)) \
        if e_q.num_kv_blocks != e_fp.num_kv_blocks else (out_fp, tps_fp)
    horizon = min(
        next((i for i, (a, b) in enumerate(zip(of, oq)) if a != b),
             len(of))
        for of, oq in zip(out_fp, out_q))

    # spec acceptance under quantized KV: the spec stage's repetitive
    # long-horizon workload (greedy cycles past burn-in), fp vs int8
    # pools. MANY streams on purpose: per-stream steady-state
    # acceptance depends on which cycle the (slightly different) token
    # stream settles into, so the comparable figure is the average —
    # 12+ streams holds the fp-vs-int8 delta under the 2% acceptance
    # bound (4 streams showed 4% of pure cycle-assignment noise).
    # grow_pool=False: equal block COUNT, so both sides run the same
    # admission schedule and the int8 pool's smaller bytes keep the
    # interpret-mode dispatch affordable.
    b_s, n_s = (8, 384) if on_tpu else (12, n_spec)
    sp_prompts = [rng.integers(0, vocab, P).tolist() for _ in range(b_s)]
    nb_s = -(-(P + n_s) // bs) * b_s

    def spec_accept(quant):
        e = eng(quant, grow=False, num_kv_blocks=nb_s,
                speculative={"enabled": True, "draft_len": 4,
                             "min_ngram": 2})
        e.generate_fused(sp_prompts, max_new_tokens=2 * K, k_steps=K)
        e.reset_serving_metrics()
        e.generate_fused(sp_prompts, max_new_tokens=n_s, k_steps=K)
        return e.serving_metrics()["spec_acceptance_rate"]

    acc_fp = spec_accept(False)
    acc_q = spec_accept(True)

    # mirror the kv gauges into the stage's --telemetry artifacts
    from deepspeed_tpu.utils.telemetry_probe import active_telemetry
    tel = active_telemetry()
    reg = tel.get_registry() if tel is not None else None
    if reg is not None:
        tel.bridges.collect_serving(reg, e_q.serving_metrics())
    return {"metric": "kvquant_max_resident_batch", "value": resident_q,
            "unit": "requests", "kv_dtype": e_q.kv_dtype,
            "max_resident_batch": resident_q,
            "resident_batch_fp": resident_fp,
            "resident_batch_ratio": round(ratio, 2),
            "kv_bytes_per_token": round(e_q.kv_bytes_per_token(), 2),
            "kv_bytes_per_token_fp": round(e_fp.kv_bytes_per_token(), 2),
            "kv_pool_bytes": e_q.kv_pool_bytes(),
            "kv_pool_bytes_fp": e_fp.kv_pool_bytes(),
            "kv_num_blocks": e_q.num_kv_blocks,
            "kv_num_blocks_fp": e_fp.num_kv_blocks,
            "tokens_per_sec_int8": round(tps_q, 1),
            "tokens_per_sec_fp": round(tps_fp, 1),
            "tokens_per_sec_fp_equal_blocks": round(tps_fp_big, 1),
            "greedy_parity_horizon": horizon,
            "decode_horizon": N,
            "spec_acceptance_int8": round(acc_q, 3),
            "spec_acceptance_fp": round(acc_fp, 3),
            "spec_acceptance_delta": round(abs(acc_q - acc_fp), 4),
            "batch": B, "prompt_tokens": P, "k_steps": K}


def moe_serving_bench(ds, on_tpu: bool):
    """MoE serving (reference: inference/v2 cutlass_ops moe_gemm +
    mixed_gemm). Decode MoE is EXPERT-WEIGHT-READ bound: every live
    expert's weights stream from HBM for a handful of tokens, so the
    routing overhead vs a dense model has a floor set by BYTES — for
    this config (8 experts, top-2) the expert tier reads ~8x the dense
    MLP weights, giving a computed bf16 floor ~1.9x at batch 16, which
    is exactly what r3 measured (1.93). The lever that moves the floor
    is weight-only int8 expert quantization (quantize_moe_experts;
    XLA fuses the dequant into the expert GEMM) — both rows are
    measured here. The sort-by-expert grouped dispatch
    (moe_ffn_grouped) exists for reference parity but measured SLOWER
    than the einsum on v5e decode (ragged_dot lowering), so the einsum
    stays the serving default."""
    import numpy as np
    from deepspeed_tpu.models import Llama, Mixtral
    if on_tpu:
        kw = dict(hidden_size=1024, num_layers=12, num_heads=8,
                  num_kv_heads=8, intermediate_size=2816,
                  vocab_size=32000, max_seq_len=2048)
        moe = Mixtral(num_experts=8, moe_top_k=2, **kw)
        dense = Llama(**kw)
        B, P, N = 16, 128, 64
    else:
        moe = Mixtral(size="tiny", max_seq_len=256)
        dense = Llama(size="tiny", max_seq_len=256)
        B, P, N = 2, 16, 4
    rng = np.random.default_rng(0)
    prompts = jnp.asarray(rng.integers(0, moe.config.vocab_size,
                                       size=(B, P)))

    def make_engine(model, **ikw):
        e = ds.init_inference(model,
                              dtype="bfloat16" if on_tpu else "float32",
                              max_out_tokens=512 if on_tpu else 64,
                              **ikw)
        np.asarray(e.generate(prompts, max_new_tokens=N))  # warm
        return e

    def timed(e, reps):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            out = e.generate(prompts, max_new_tokens=N)
            np.asarray(out)
            best = min(best, time.perf_counter() - t0)
        return best

    e_bf16 = make_engine(moe)
    e_int8 = make_engine(moe, quantize_moe_experts=True)
    e_dense = make_engine(dense)
    # paired windows (bf16 vs int8 back-to-back; the int8 claim is made
    # only when the per-window delta's CI95 excludes zero — r4 #7)
    n_windows = 5 if on_tpu else 2
    t_bf16, t_int8 = [], []
    for _ in range(n_windows):
        t_bf16.append(timed(e_bf16, 2 if on_tpu else 1))
        t_int8.append(timed(e_int8, 2 if on_tpu else 1))
    dense_t = timed(e_dense, 3 if on_tpu else 1)
    deltas = [a - b for a, b in zip(t_bf16, t_int8)]
    d_mean, d_ci = _mean_ci95(deltas)
    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    moe_tps = B * N / med(t_bf16)
    moe_q_tps = B * N / med(t_int8)
    dense_tps = B * N / dense_t
    return {"metric": "mixtral_serving_decode_tokens_per_sec",
            "value": round(moe_q_tps, 1), "unit": "tokens/s/chip",
            "batch": B, "dense_equiv_tokens_per_sec": round(dense_tps, 1),
            "routing_overhead": round(dense_tps / max(moe_q_tps, 1e-9), 2),
            "experts_int8": True,
            "bf16_tokens_per_sec": round(moe_tps, 1),
            "bf16_routing_overhead": round(
                dense_tps / max(moe_tps, 1e-9), 2),
            "bf16_s_windows": [round(x, 3) for x in t_bf16],
            "int8_s_windows": [round(x, 3) for x in t_int8],
            "bf16_minus_int8_delta_s": round(d_mean, 4),
            "int8_delta_ci95_s": round(d_ci, 4),
            "int8_wins": bool(d_mean - d_ci > 0)}


def _moe_dispatch_bytes(traffic: dict) -> dict:
    """{axis: bytes} of the MoE dispatch exchange in a FORWARD-only
    trace: all-to-all + reduce-scatter on the token (dp/fsdp/zps) axes.
    Forward-only keeps the figure clean — no grad-transpose collectives
    and (under ZeRO-3) the param gathers are all-gathers, excluded by
    op. The combine all-gather is excluded the same way (its wire stays
    float; the int8 protocol covers dispatched activations only)."""
    out: dict = {}
    for (axis, op), row in traffic.items():
        if op not in ("all_to_all", "reduce_scatter"):
            continue
        if not set(axis.split("+")) <= {"dp", "fsdp", "zps"}:
            continue
        out[axis] = out.get(axis, 0) + row["bytes"]
    return out


def moe_train_bench(ds, on_tpu: bool):
    """Ep-sharded MoE training (ISSUE 16): the Mixtral `ref` config on
    an ep×zps×fsdp mesh with the explicit dispatch/combine exchange
    (runtime/comm/moe_alltoall.py) engaged, meshsan contract in raise
    mode. Reports MFU on ACTIVE-params accounting vs an
    equal-active-params dense model, the HLO-accounted per-axis
    dispatch bytes for the fp32 vs int8 a2a wire (the slow-link cut is
    the acceptance figure, >= 2x at <= 1e-2 loss rel err), and the
    loss trajectory gap between wires.

    Needs >= 8 devices (ep=2 x zps=2 x fsdp=2); smaller hosts
    self-provision a virtual 8-device CPU mesh in a subprocess (the
    zeropp recipe) and relay the child's record."""
    if len(jax.devices()) < 8:
        if os.environ.get("DS_TPU_MOE_TRAIN_CHILD"):
            return {"metric": "moe_train_mfu",
                    "skipped": "virtual mesh provisioning failed"}
        import subprocess
        env = dict(os.environ)
        env["DS_TPU_MOE_TRAIN_CHILD"] = "1"
        env.pop("JAX_PLATFORM_NAME", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8")
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--stage", "moe_train"],
            capture_output=True, text=True, timeout=600, env=env)
        for line in proc.stderr.splitlines():
            if line.startswith("# moe_train {"):
                return json.loads(line[len("# moe_train "):])
        raise RuntimeError(
            f"moe_train child produced no record (rc={proc.returncode}): "
            + proc.stderr[-400:])

    from deepspeed_tpu.models import Llama, Mixtral
    from deepspeed_tpu.parallel import mesh as mesh_mod
    from deepspeed_tpu.profiling.flops_profiler.profiler import \
        lower_compiled
    from deepspeed_tpu.telemetry import collectives as coll
    seq = 512 if on_tpu else 64
    batch = 8
    steps = 3

    def run(wire: str):
        mesh_mod.reset_topology()
        engine, _, _, _ = ds.initialize(
            model=Mixtral(size="ref", max_seq_len=seq),
            config={"train_batch_size": batch,
                    "optimizer": {"type": "AdamW",
                                  "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 3},
                    "mesh": {"fsdp": -1, "zps": 2, "ep": 2},
                    "moe": {"wire_dtype": wire},
                    "telemetry": {"enabled": True,
                                  "executable_ledger": True},
                    "meshsan": {"enabled": True, "mode": "raise"},
                    "steps_per_print": 10 ** 9})
        assert engine._moe_dispatcher is not None, \
            "ep-sharded dispatcher did not engage"
        tokens = jax.random.randint(
            jax.random.PRNGKey(0), (batch, seq + 1), 0,
            engine.module.config.vocab_size)
        data = (tokens[:, :-1], tokens[:, 1:])
        # forward-only HLO walk: the dispatch exchange without the
        # grad-transpose collectives riding the same axes
        compiled = lower_compiled(engine._eval_loss,
                                  engine.state["params"], data)
        disp = _moe_dispatch_bytes(coll.traffic_matrix(
            coll.analyze_hlo(compiled.as_text(), mesh=engine.mesh)))
        losses = [float(engine.train_batch(data)) for _ in range(steps)]
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(data)
        float(loss)
        tps = steps * batch * seq / (time.perf_counter() - t0)
        cfg = engine.module.config
        mesh_mod.reset_topology()
        return disp, losses, tps, cfg

    fp_disp, fp_losses, fp_tps, moe_cfg = run("fp32")
    q_disp, q_losses, _q_tps, _ = run("int8")
    # slow-link = the dispatch payload NOT on the fast (zps) hop
    slow = lambda d: sum(b for a, b in d.items()  # noqa: E731
                         if set(a.split("+")) != {"zps"})
    fp_slow, q_slow = slow(fp_disp), slow(q_disp)
    wire_cut = fp_slow / q_slow if q_slow else 0.0
    loss_rel = max(abs(a - b) / max(abs(b), 1e-9)
                   for a, b in zip(q_losses, fp_losses))

    # equal-ACTIVE-params dense baseline: top-2 of 8 swiglu experts
    # run per token, so a dense MLP of 2x the expert width matches the
    # active FFN params exactly (router + parked experts excluded)
    c = moe_cfg
    dense = Llama(hidden_size=c.hidden_size, num_layers=c.num_layers,
                  num_heads=c.num_heads, num_kv_heads=c.num_kv_heads,
                  intermediate_size=c.moe_top_k * c.intermediate_size,
                  vocab_size=c.vocab_size, max_seq_len=seq,
                  tie_embeddings=False)
    mesh_mod.reset_topology()
    dense_tps, _ = _train_tput(
        ds, dense, {"zero_optimization": {"stage": 3},
                    "mesh": {"fsdp": -1, "zps": 2}},
        batch, seq, steps=steps)
    mesh_mod.reset_topology()

    moe_mfu = _mfu_fields(fp_tps, moe_cfg, seq)
    dense_mfu = _mfu_fields(dense_tps, dense.config, seq)
    return {
        "metric": "moe_train_mfu", "value": moe_mfu["mfu"], "unit": "MFU"
                  " (active-params accounting)",
        "moe_mfu": moe_mfu["mfu"],
        "dense_mfu": dense_mfu["mfu"],
        "mfu_vs_dense": round(
            moe_mfu["mfu"] / max(dense_mfu["mfu"], 1e-9), 3),
        "tokens_per_sec": round(fp_tps, 1),
        "dense_tokens_per_sec": round(dense_tps, 1),
        "active_params": moe_cfg.num_active_params(),
        "dense_params": dense.config.num_params(),
        "dispatch_bytes_per_axis": {k: int(v) for k, v in fp_disp.items()},
        "dispatch_bytes_per_axis_int8": {k: int(v)
                                         for k, v in q_disp.items()},
        "dispatch_slow_bytes_fp32": int(fp_slow),
        "dispatch_slow_bytes_int8": int(q_slow),
        "dispatch_wire_cut_slow": round(wire_cut, 2),
        "loss_rel_err_int8_wire": round(loss_rel, 5),
        "losses": [round(x, 5) for x in fp_losses],
        "losses_int8_wire": [round(x, 5) for x in q_losses],
        "meshsan": "green (raise mode)",
    }


def moe_serve_bench(ds, on_tpu: bool):
    """Expert-sharded fused MoE decode (ISSUE 16): the Mixtral `ref`
    config through the v2 paged FUSED decode loop with the grouped
    expert GEMM (moe_ffn_grouped — exact top-k, no capacity padding)
    and weight-only int8 experts, vs (a) the per-tick decode loop of
    the SAME engine (greedy bit-parity is the correctness figure) and
    (b) an equal-ACTIVE-size dense model on the identical rig (the
    throughput step-up figure: int8 experts cut the expert-weight-read
    floor that routing pays). CAVEAT (CPU rig): moe_vs_dense reads < 1
    here — the honest CPU story is that top-2-of-8 experts stream ~4x
    the FFN weight bytes of the equal-active dense twin and interpret-
    mode ragged_dot adds routing overhead that XLA:CPU cannot fuse
    away; int8 experts halving those bytes plus the fused grouped GEMM
    are exactly the TPU levers (MoE per-token FLOPs stay a fraction of
    dense at equal quality), so the step-up figure re-baselines on TPU
    like serve7b. Greedy parity and the int8-expert path are the
    rig-independent claims this stage gates."""
    import numpy as np
    from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig)
    from deepspeed_tpu.models import Llama, Mixtral
    if on_tpu:
        moe = Mixtral(size="ref", max_seq_len=2048)
        B, P, N, K = 8, 128, 64, 8
        bs, nb, chunk = 64, 128, 256
    else:
        moe = Mixtral(size="ref", max_seq_len=512)
        B, P, N, K = 2, 16, 24, 4
        bs, nb, chunk = 16, 96, 32
    c = moe.config
    dense = Llama(hidden_size=c.hidden_size, num_layers=c.num_layers,
                  num_heads=c.num_heads, num_kv_heads=c.num_kv_heads,
                  intermediate_size=c.moe_top_k * c.intermediate_size,
                  vocab_size=c.vocab_size, max_seq_len=c.max_seq_len,
                  tie_embeddings=False)
    dtype = "bfloat16" if on_tpu else "float32"
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, c.vocab_size, P).tolist()
               for _ in range(B)]

    def eng(model, **over):
        kw = dict(dtype=dtype, kv_block_size=bs, num_kv_blocks=nb,
                  max_chunk_size=chunk, max_ragged_sequence_count=16)
        kw.update(over)
        return InferenceEngineV2(model,
                                 RaggedInferenceEngineConfig(**kw))

    e_moe = eng(moe, moe_grouped_dispatch=True,
                quantize_moe_experts=True)
    assert e_moe.model.moe_serving_dispatch is True
    assert "w_up_q" in e_moe.params["layers"]["experts"]
    e_dense = eng(dense)

    def timed_fused(e):
        e.generate_fused(prompts, max_new_tokens=2 * K, k_steps=K)
        best = 0.0
        out = None
        for _ in range(3):
            t0 = time.perf_counter()
            out = e.generate_fused(prompts, max_new_tokens=N, k_steps=K)
            wall = time.perf_counter() - t0
            best = max(best, sum(len(o) for o in out) / max(wall, 1e-9))
        return out, best

    out_fused, moe_tps = timed_fused(e_moe)
    _, dense_tps = timed_fused(e_dense)
    # greedy bit-parity: the fused in-graph loop vs the per-tick
    # scheduler driving the same engine (same model copy, same pools)
    out_tick = e_moe.generate(prompts, max_new_tokens=N)
    horizon = min(
        next((i for i, (a, b) in enumerate(zip(of, ot)) if a != b),
             len(of))
        for of, ot in zip(out_fused, out_tick))
    parity = all(list(of) == list(ot)
                 for of, ot in zip(out_fused, out_tick))
    return {
        "metric": "moe_serve_fused_tokens_per_sec",
        "value": round(moe_tps, 1), "unit": "tokens/s/chip",
        "tokens_per_sec": round(moe_tps, 1),
        "dense_tokens_per_sec": round(dense_tps, 1),
        "moe_vs_dense": round(moe_tps / max(dense_tps, 1e-9), 3),
        "greedy_parity": bool(parity),
        "greedy_parity_horizon": int(horizon),
        "decode_horizon": N,
        "experts_int8": True, "grouped_dispatch": True,
        "batch": B, "prompt_tokens": P, "k_steps": K,
        "active_params": c.num_active_params(),
        "dense_params": dense.config.num_params(),
    }


def serve7b_int8(ds, on_tpu: bool):
    """Serve a 7B on ONE 16 GiB v5e (VERDICT r4 #5; reference serving
    headline: FastGen Llama-2-70B on 4xA100, blogs/deepspeed-fastgen/
    README.md:139, and the ZeRO-Inference weight-quantization recipe).

    Weight-only int8 (linear/quantization.py quantize_dense_params)
    puts the 6.74B-param dense tree at ~6.6 GiB beside a 2 GiB paged
    KV pool. Weights are INITIALIZED ON DEVICE in bf16 and quantized
    leaf-by-leaf with donation (peak HBM ~= bf16 tree + one leaf), so
    nothing model-scale crosses the harness tunnel. Reported: decode
    tokens/s from the chain-differenced paged step (device truth) +
    host-in-loop tick p50/p99 (which ride the dev tunnel's RTT)."""
    if not on_tpu:
        return {"metric": "serve7b_int8", "skipped": "cpu rig"}
    import functools as _ft

    import numpy as np
    from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                            RaggedInferenceEngineConfig)
    from deepspeed_tpu.models import Llama
    import jax.numpy as jnp

    model = Llama(hidden_size=4096, num_layers=32, num_heads=32,
                  num_kv_heads=32, intermediate_size=11008,
                  vocab_size=32000, max_seq_len=2048, tie_embeddings=False,
                  param_dtype=jnp.bfloat16)
    # generate each leaf ALREADY quantized on device: the full-size
    # bf16 tree never exists in HBM (13.4 GiB + temps + int8 would
    # exceed the 16 GiB chip)
    from deepspeed_tpu.linear.quantization import _q_leaf
    abstract = jax.eval_shape(model.init, jax.random.PRNGKey(0))

    @_ft.partial(jax.jit, static_argnums=(1,))
    def _rand_q(key, shape):
        w = jax.random.normal(key, shape, jnp.bfloat16) * 0.02
        return _q_leaf(w, jnp.bfloat16)

    @_ft.partial(jax.jit, static_argnums=(1, 2))
    def _rand(key, shape, dtype):
        return jax.random.normal(key, shape, dtype) * 0.02

    from deepspeed_tpu.linear.quantization import quantizable_leaf

    def build(tree, path=()):
        import zlib
        out = {}
        for k, v in tree.items():
            if isinstance(v, dict):
                out[k] = build(v, path + (k,))
                continue
            key = jax.random.fold_in(            # stable across runs
                jax.random.PRNGKey(7),
                zlib.crc32("/".join(path + (k,)).encode()))
            if ("embed" not in path and v.ndim >= 2
                    and quantizable_leaf(v.shape, v.ndim, path)):
                q, s = _rand_q(key, v.shape)
                out[k + "_q"], out[k + "_s"] = q, s
            else:
                out[k] = _rand(key, v.shape, v.dtype)
        return out

    params = build(abstract)
    # decode is WEIGHT-READ bound at this scale (step time ~flat in
    # batch: 19.5 ms at B=8, 18.6 ms at B=12), so batch rides free
    # until the KV pool + weights hit HBM (B=16/88 blocks OOMs).
    # SplitFuse chunk 64: the blocked-flash kernel carries ALL heads per
    # grid block, and 32 heads x 256-token chunks overflow the 16 MiB
    # VMEM scoped allocation (head-split grids are the follow-up)
    B, P = 12, 256
    e2 = InferenceEngineV2(model, RaggedInferenceEngineConfig(
        dtype="bfloat16", kv_block_size=64, num_kv_blocks=64,
        max_chunk_size=64, max_ragged_sequence_count=B), params=params)
    int8_gib = sum(l.size for l in jax.tree.leaves(e2.params)
                   if l.dtype == jnp.int8) / 2 ** 30
    uids = list(range(B))
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 32000, P).tolist() for _ in range(B)]
    e2.put(uids, prompts)

    def one_tick():
        e2.schedule(uids, [[1]] * B, do_checks=False)
        res = e2.tick()
        float(jnp.sum(next(iter(res.values()))))

    p50, p99 = _tick_percentiles(one_tick, 16)

    # device-truth decode step: chain-differenced (shared probe)
    step_ms = _decode_step_probe(model, e2, uids, True, 32, 8, 3)

    # fused multi-step decode (ISSUE 1 acceptance): the per-tick p50
    # above rides one tunnel RTT PER TOKEN; the fused loop pays it once
    # per K tokens. Fresh KV state — the tick phase grew the sequences,
    # and the 64-block pool is sized to the fused horizon at context P.
    e2.flush(uids)
    K = 8
    fused = _fused_decode_metrics(e2, prompts, k=K, n_dispatches=6)

    # ISSUE 6 acceptance: N-deep chained serving with in-graph
    # admission + one host read per chain. decode_fused above blocks on
    # every dispatch (RTT per K tokens); the chained loop pays the RTT
    # once per chain of `depth` dispatches, so its per-step tick should
    # sit within 2x decode_step_ms_compute — and its host dispatches
    # per token at equal greedy outputs strictly below the PR 1 figure.
    e2.flush(list(range(B)))
    e2._config.max_inflight_dispatches = 4
    e2._config.fused_admission = True
    _chained_serve_metrics(e2, prompts, K, max_new=64)   # warm/compile
    chained = _chained_serve_metrics(e2, prompts, K, max_new=64)
    # ISSUE 9: the same chained/ring serving pass with speculative
    # decoding on (prompt-lookup drafting + in-graph verify) — reported
    # NEXT TO the chained-tick numbers so the spec-on delta is read at
    # matched batch/context/depth. Random-weight greedy decode cycles
    # in steady state, so the drafter has real hits here; acceptance on
    # genuine weights is workload-dependent (see docs/serving.md).
    from deepspeed_tpu.inference.v2.engine_v2 import SpeculativeConfig
    e2._config.speculative = SpeculativeConfig(
        enabled=True, draft_len=4, min_ngram=2)
    _chained_serve_metrics(e2, prompts, K, max_new=64)   # warm spec fns
    spec_ch = _chained_serve_metrics(e2, prompts, K, max_new=64)
    spec_m = e2.serving_metrics()
    spec = {f"spec_{k}": v for k, v in spec_ch.items()
            if k not in ("chain_depth", "fused_admission")}
    spec["spec_acceptance_rate"] = round(
        spec_m["spec_acceptance_rate"], 3)
    spec["spec_tokens_per_dispatch"] = round(
        spec_m["tokens_per_dispatch"], 3)
    return {"metric": "serve7b_int8_decode_tokens_per_sec",
            **spec,
            "value": round(B * 1e3 / step_ms, 1), "unit": "tokens/s/chip",
            "batch": B, "params_b": round(
                model.config.num_params() / 1e9, 2),
            "weights_int8_gib": round(int8_gib, 2),
            "context_tokens": P,
            "decode_step_ms_compute": round(step_ms, 2),
            # host-in-loop per-tick scheduler (the BENCH_r05 "tick_p50"
            # baseline: one RTT per token); the serving tick_p50_ms now
            # comes from the chained loop below
            "per_tick_p50_ms": round(p50, 1),
            "per_tick_p99_ms": round(p99, 1),
            **fused,
            "fused_step_ms": round(fused["fused_tick_p50_ms"] / K, 2),
            **chained,
            "tick_note": "per-tick rides one tunnel RTT per token; "
                         "decode_fused pays it once per K tokens; the "
                         "chained serving loop (tick_p50_ms) once per "
                         "chain of depth dispatches"}


def llama7b_streamed(ds, on_tpu: bool):
    """ZeRO-Infinity tier (BASELINE config 2 / north-star capability):
    a Llama-7B-parity model trains on ONE chip with all layer matrices +
    Adam state resident in pinned_host (~81 GiB), streamed per layer
    through HBM inside the compiled step (runtime/infinity.py; reference
    stage3.py:1926 + swap_tensor/). Host residency is asserted from the
    live arrays. Transfer-bound by design: the step rides PCIe, so MFU
    is reported honestly alongside tokens/s."""
    from deepspeed_tpu.models import Llama
    if on_tpu:
        # loss_chunk=256 (fused chunked cross-entropy) keeps the [B,S,V]
        # logits slab out of HBM — that is what unlocks micro=12 (r4's
        # micro=12 spilled activations at 0.042 MFU with full logits;
        # micro=14 still OOMs). Per-token cost at ga-saturation is the
        # per-micro weight stream, so micro 8 -> 12 is a direct 1.25x.
        model = Llama(hidden_size=4096, num_layers=32, num_heads=32,
                      num_kv_heads=32, intermediate_size=11008,
                      vocab_size=32000, max_seq_len=2048,
                      remat_policy="segments", attn_impl="flash",
                      loss_chunk=256, tie_embeddings=False)
        # ga=24 amortizes the fixed master+moments stream further
        # (runs once per step). stream_dtype stays "master": the bf16
        # stream stack's +12 GiB pinned (60.3 GiB total) reproducibly
        # KILLS the dev tunnel ("connection dropped 8 times") — this
        # host's stable pinned envelope ends just above the 48.2 GiB
        # master+moments footprint (r5, twice; r4 measured the same
        # config net-negative before the cliff).
        # Measured r5 ladder (ga, micro): (16,8) 0.309 -> (16,10)
        # 0.345 -> (16,12)+loss_chunk 0.388 -> (24,12) 0.395 MFU.
        micro, ga, seq, steps = 12, 24, 2048, 1
        batch = micro * ga
    else:
        model = Llama(size="tiny", max_seq_len=128, tie_embeddings=False)
        micro, ga, seq, steps = 2, 1, 128, 2
        batch = micro * ga
    engine, _, _, _ = ds.initialize(model=model, config={
        "train_batch_size": batch,
        "train_micro_batch_size_per_gpu": micro,
        "bf16": {"enabled": True},
        "optimizer": {"type": "FusedAdam",
                      "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "gradient_clipping": 1.0,
        "zero_optimization": {
            "stage": 3,
            "offload_param": {"device": "cpu",
                              **({} if on_tpu else {"stream": True})},
            "offload_optimizer": {"device": "cpu",
                                  "moment_dtype": "bfloat16"}},
        "steps_per_print": 10 ** 9})
    from deepspeed_tpu.runtime.infinity import StreamedZeroEngine
    assert isinstance(engine, StreamedZeroEngine), type(engine)
    rpt = engine.host_memory_report()
    if on_tpu:
        assert rpt["host_fraction"] > 0.85, rpt
    tokens = jax.random.randint(jax.random.PRNGKey(0),
                                (batch, seq + 1), 0,
                                model.config.vocab_size)
    data = (tokens[:, :-1], tokens[:, 1:])
    loss = float(engine.train_batch(data))      # compile + step 1
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = float(engine.train_batch(data))
    dt = (time.perf_counter() - t0) / steps
    tps = batch * seq / dt
    return {"metric": "llama7b_streamed_train_tokens_per_sec",
            "value": round(tps, 1), "unit": "tokens/s/chip",
            "params_b": round(model.config.num_params() / 1e9, 2),
            "host_state_gib": round(rpt["pinned_host"] / 2 ** 30, 1),
            "host_fraction": round(rpt["host_fraction"], 3),
            "grad_accumulation": ga,
            "step_s": round(dt, 2), "loss": round(loss, 4),
            **_mfu_fields(tps, model.config, seq)}


def nvme_streamed(ds, on_tpu: bool):
    """ZeRO-Infinity NVMe tier (VERDICT r3 missing #1; reference:
    swap_tensor/partitioned_param_swapper.py + stage3.py:1926): master
    weights and Adam moments live on DISK (12 bytes/param), paged per
    layer through the native AIO op into the C++ CPU Adam, so model
    size is bounded by NVMe capacity — not host RAM (the one
    capability row where the reference could train something the r3
    repo could not). Host RAM holds only the bf16 stream stack phase A
    reads (2 bytes/param) + a transient grad stack. Measured at ~0.9B
    params; the same path scales to any size the disk holds.

    Measurement path (VERDICT r4 #4): the trajectory runs HOST-SIDE in
    a subprocess on the local CPU backend — compute, pinned staging and
    the AIO swap files all on one machine, exactly like a production
    TPU host, with none of this dev harness's client<->chip tunnel in
    the loop (through the tunnel every model-scale byte crosses a
    WAN-class link, which benchmarks the tunnel, not the engine). The
    config is >=1B parameters with >90% of optimizer state paged from
    disk; a 20-step decreasing-loss run of the same tool is committed
    at artifacts/nvme_1b_trajectory.json."""
    import json as _json
    import os
    import subprocess
    import sys as _sys
    steps = 4 if on_tpu else 2
    env = dict(os.environ)
    env.pop("JAX_PLATFORM_NAME", None)
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "nvme_1b_trajectory.py")
    if not on_tpu:   # CPU smoke: the tiny in-process path is covered by
        env["DS_NVME_TRAJ_TINY"] = "1"   # tests; keep the row cheap
    try:
        proc = subprocess.run([_sys.executable, tool, str(steps)],
                              capture_output=True, text=True, env=env,
                              timeout=3600)
    except subprocess.TimeoutExpired as e:
        return {"metric": "nvme_streamed_train_tokens_per_sec",
                "error": f"host-side trajectory timed out after 3600s "
                         f"({(e.stderr or '')[-200:]})"}
    if proc.returncode != 0:
        return {"metric": "nvme_streamed_train_tokens_per_sec",
                "error": proc.stderr[-500:]}
    res = _json.loads(proc.stdout.strip().splitlines()[-1])
    out = {"metric": "nvme_streamed_train_tokens_per_sec",
           "value": res["tokens_per_sec"], "unit": "tokens/s (host-side)",
           **{k: res[k] for k in (
               "params_b", "offloaded_fraction", "nvme_state_gib",
               "host_state_gib", "nvme_read_gib_per_step",
               "nvme_written_gib_per_step", "step_s", "loss_first",
               "loss_last", "steps", "platform")}}
    art = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "artifacts", "nvme_1b_trajectory.json")
    if os.path.exists(art):
        with open(art) as f:
            traj = _json.load(f)
        out["trajectory_20step"] = {k: traj[k] for k in (
            "steps", "loss_first", "loss_last", "decreasing")
            if k in traj}
    return out


def domino_bench(ds, on_tpu: bool):
    """Domino overlap evidence on real hardware (VERDICT r3 weak #5).

    One chip cannot time a tp all-reduce over ICI, so the claim 'XLA
    overlaps chunk i's collective with chunk i+1's compute'
    (runtime/domino.py) is evidenced with the resource that IS
    observable single-chip: a pinned_host DMA round trip as the
    pending-reduction proxy. Like an ICI collective, the DMA rides a
    non-MXU resource, so IF the latency-hiding scheduler interleaves
    chunks, chunked wall time approaches max(compute, transfer) rather
    than their sum. overlap_ratio < 1 is the measured evidence;
    single-chip limits are documented in COVERAGE.md."""
    import functools

    import jax.numpy as jnp
    from jax.sharding import SingleDeviceSharding

    if not on_tpu:
        return {"metric": "domino_overlap_ratio", "skipped": "cpu rig"}
    dev = jax.devices()[0]
    dev_sh = SingleDeviceSharding(dev)
    host_sh = SingleDeviceSharding(dev, memory_kind="pinned_host")
    # shapes picked so per-chunk compute ~= per-chunk transfer (~7 ms
    # each): overlap is only visible when neither resource dominates
    d, rows, n_micro, k_gemm = 4096, 2048, 4, 16
    w = jax.random.normal(jax.random.PRNGKey(0), (d, d), jnp.bfloat16)
    x = jax.random.normal(jax.random.PRNGKey(1), (rows, d), jnp.bfloat16)

    def attn_like(p, xc):
        for _ in range(k_gemm):
            xc = xc @ p
        return xc

    def dma_reduce(y):
        # chunk's pending tp-reduction proxy: D2H + H2D round trip
        return jax.device_put(jax.device_put(y, host_sh), dev_sh)

    def run(n, x):
        def step(xc, _):
            chunks = jnp.split(xc, n, axis=0)
            outs = [dma_reduce(attn_like(w, c)) for c in chunks]
            y = jnp.concatenate(outs, axis=0)
            # data dependency between scan steps: no dead-code elision
            return y / (1 + jnp.max(jnp.abs(y))), ()
        y, _ = jax.lax.scan(step, x, None, length=8)
        return y

    times = {}
    for n in (1, n_micro):
        f = jax.jit(functools.partial(run, n))
        float(jnp.sum(f(x)))             # warm compile incl. the sum
        t0 = time.perf_counter()
        float(jnp.sum(f(x)))             # forced device->host sync
        times[n] = time.perf_counter() - t0
    ratio = times[n_micro] / times[1]
    return {"metric": "domino_overlap_ratio", "value": round(ratio, 3),
            "unit": "chunked/unchunked wall time (<1 = overlap)",
            "unchunked_ms": round(times[1] * 1e3, 1),
            "chunked_ms": round(times[n_micro] * 1e3, 1),
            "n_micro": n_micro, "proxy": "pinned_host DMA round trip"}


def _aot_wire_bytes(engine, batch):
    """{axis: collective payload bytes} + {axis: wire bytes/element} of
    the engine's compiled train step, from the AOT HLO walk (no
    dispatch; the compile lands in jax's executable cache so the
    subsequent measured steps reuse it)."""
    from deepspeed_tpu.profiling.flops_profiler.profiler import \
        lower_compiled
    from deepspeed_tpu.telemetry import collectives as coll
    compiled = lower_compiled(engine._train_step, engine.state, batch)
    traffic = coll.traffic_matrix(
        coll.analyze_hlo(compiled.as_text(), mesh=engine.mesh))
    by_axis: dict = {}
    for (axis, _op), row in traffic.items():
        by_axis[axis] = by_axis.get(axis, 0) + row["bytes"]
    return by_axis, coll.axis_wire_width(traffic)


def _sharded_dp_bytes(by_axis: dict) -> int:
    """Payload on the sharded-DP axes (fsdp/zps and combinations) —
    the traffic the ZeRO++ wire protocol quantizes."""
    return sum(b for axis, b in by_axis.items()
               if set(axis.split("+")) <= {"fsdp", "zps"})


def zeropp_bench(ds, on_tpu: bool):
    """ZeRO++ wire-protocol stage (ISSUE 8): the same fsdp×zps ZeRO-3
    training config compiled with the fp32 wire vs the quantized +
    hierarchical wire (qwZ + qgZ int8, stochastic rounding, two-hop
    gathers), reporting per-axis HLO-accounted collective bytes, the
    sharded-DP byte reduction, tokens/s, and the loss trajectory gap.
    The ``--gate comms`` family of ``telemetry_report --diff`` watches
    these fields across rounds (collective bytes must not regress,
    tokens/s ±5%).

    Needs >=4 devices for a real zps split; on a smaller host the
    stage self-provisions a virtual 8-device CPU mesh in a subprocess
    (the dryrun_multichip recipe) and relays the child's record."""
    if len(jax.devices()) < 4:
        if os.environ.get("DS_TPU_ZEROPP_CHILD"):
            return {"metric": "zeropp_wire_reduction",
                    "skipped": "virtual mesh provisioning failed"}
        import subprocess
        env = dict(os.environ)
        env["DS_TPU_ZEROPP_CHILD"] = "1"
        env.pop("JAX_PLATFORM_NAME", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8")
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--stage", "zeropp"],
            capture_output=True, text=True, timeout=600, env=env)
        for line in proc.stderr.splitlines():
            if line.startswith("# zeropp {"):
                return json.loads(line[len("# zeropp "):])
        raise RuntimeError(
            f"zeropp child produced no record (rc={proc.returncode}): "
            + proc.stderr[-400:])

    from deepspeed_tpu.models import GPT2
    from deepspeed_tpu.parallel import mesh as mesh_mod
    n = len(jax.devices())
    seq = 256 if on_tpu else 64
    batch = 2 * n
    steps = 3

    def run(quantized: bool):
        mesh_mod.reset_topology()
        zero = {"stage": 3}
        if quantized:
            zero.update({"zero_quantized_weights": True,
                         "zero_quantized_gradients": True,
                         "zero_quantized_dtype": "int8",
                         "zero_quantized_rounding": "stochastic",
                         "zero_hierarchical_allgather": True})
        engine, _, _, _ = ds.initialize(
            model=GPT2(size="tiny", max_seq_len=seq),
            config={"train_batch_size": batch,
                    "gradient_accumulation_steps": 1,
                    "optimizer": {"type": "AdamW",
                                  "params": {"lr": 1e-3}},
                    "gradient_clipping": 1.0,
                    "zero_optimization": zero,
                    "mesh": {"fsdp": -1, "zps": 2},
                    "steps_per_print": 10 ** 9})
        tokens = jax.random.randint(
            jax.random.PRNGKey(0), (batch, seq + 1), 0,
            engine.module.config.vocab_size)
        data = (tokens[:, :-1], tokens[:, 1:])
        by_axis, width = _aot_wire_bytes(engine, data)
        losses = [float(engine.train_batch(data)) for _ in range(steps)]
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(data)
        float(loss)
        tps = steps * batch * seq / (time.perf_counter() - t0)
        mesh_mod.reset_topology()
        return by_axis, width, losses, tps

    fp32_axis, fp32_width, fp32_losses, fp32_tps = run(quantized=False)
    q_axis, q_width, q_losses, q_tps = run(quantized=True)
    fp32_dp = _sharded_dp_bytes(fp32_axis)
    q_dp = _sharded_dp_bytes(q_axis)
    reduction = (1.0 - q_dp / fp32_dp) if fp32_dp else 0.0
    loss_rel = max(abs(a - b) / max(abs(b), 1e-9)
                   for a, b in zip(q_losses, fp32_losses))
    return {
        "metric": "zeropp_wire_reduction_sharded_dp",
        "value": round(reduction, 4),
        # the gate-visible name: --gate comms matches flattened numeric
        # KEYS, and the "metric" string leaf is dropped by the
        # flattener, so the acceptance figure must be a field name
        "wire_reduction": round(reduction, 4),
        "unit": "1 - quantized/fp32 collective bytes (fsdp+zps axes)",
        "wire_bytes_per_axis": {k: int(v) for k, v in q_axis.items()},
        "wire_bytes_per_axis_fp32": {k: int(v)
                                     for k, v in fp32_axis.items()},
        "wire_bytes_sharded_dp": int(q_dp),
        "wire_bytes_sharded_dp_fp32": int(fp32_dp),
        "wire_bytes_per_el": {k: round(v, 3) for k, v in q_width.items()},
        "tokens_per_sec": round(q_tps, 1),
        "tokens_per_sec_fp32_wire": round(fp32_tps, 1),
        "loss_rel_err_vs_fp32_wire": round(loss_rel, 5),
        "losses": [round(x, 5) for x in q_losses],
        "losses_fp32_wire": [round(x, 5) for x in fp32_losses],
    }


def numsan_bench(ds, on_tpu: bool):
    """numsan overhead stage (ISSUE 18): the same training config run
    three ways — no numsan block at all, the block present but
    disabled, and armed in warn mode (per-leaf grad stats folded into
    the compiled step + the deferred host check) — reporting

    - ``numsan_overhead_pct``: armed-vs-off tokens/s delta (the ≤3%
      acceptance figure; the armed step adds one fused per-leaf
      count/max reduction and a deferred-by-one-dispatch host check);
    - ``extra_executables``: backend-compile events of the
      disabled-block run minus the no-block run — MUST be 0 (the
      disabled path traces byte-identical graphs; the ``--gate
      numerics`` family zero-tolerates this field);
    - the sanitizer's own counters from the armed run (checked steps,
      violations — a healthy run reports 0 violations).
    """
    from deepspeed_tpu.models import GPT2
    from deepspeed_tpu.telemetry import bridges
    bridges.install_jax_compile_listener()
    seq = 1024 if on_tpu else 64
    batch = 8 if on_tpu else _cpu_batch()
    steps = 10 if on_tpu else 3
    model_kw = dict(max_seq_len=seq)

    # run 1 also warms every process-level jit cache (module-level
    # helpers compile once per process, not per engine) so the later
    # compile-count comparison sees per-engine executables only
    off_tps, _ = _train_tput(ds, GPT2(size="tiny", **model_kw), {},
                             batch, seq, steps,
                             windows=2 if on_tpu else 1)
    # executable-count parity check (warm vs warm): a second no-block
    # run vs a numsan-key-present-but-disabled run must compile the
    # SAME number of executables — the disabled path is byte-identical
    c0 = bridges.compile_event_count()
    _train_tput(ds, GPT2(size="tiny", **model_kw), {}, batch, seq, 1)
    c1 = bridges.compile_event_count()
    _train_tput(ds, GPT2(size="tiny", **model_kw),
                {"numsan": {"enabled": False}}, batch, seq, 1)
    c2 = bridges.compile_event_count()

    on_tps, _ = _train_tput(ds, GPT2(size="tiny", **model_kw),
                            {"numsan": {"enabled": True, "mode": "warn"}},
                            batch, seq, steps,
                            windows=2 if on_tpu else 1)
    from deepspeed_tpu.analysis.numsan import get_numsan
    san = get_numsan()
    counters = dict(san.counters) if san is not None else {}
    overhead = (off_tps - on_tps) / off_tps * 100.0 if off_tps else 0.0
    return {
        "metric": "numsan_overhead_pct",
        "value": round(overhead, 2),
        "unit": "% tokens/s lost with the sanitizer armed (warn mode)",
        "tokens_per_sec": round(on_tps, 1),
        "tokens_per_sec_numsan_off": round(off_tps, 1),
        "extra_executables": int((c2 - c1) - (c1 - c0)),
        "numsan_checked_steps": int(counters.get("checked_steps", 0)),
        "numsan_violations": int(counters.get("violations", 0)),
    }


def offload_smoke(ds, on_tpu: bool):
    """ZeRO-Offload tier on real hardware. Sweeps the Twin-Flow
    `ratio` (reference offload_config.py:93): 1.0 = everything in
    pinned_host, 0.5 = largest half of the optimizer-tier bytes on host,
    0.0 = all-HBM baseline. Host residency is ASSERTED from the live
    arrays (engine.host_memory_report) — a silently-degraded placement
    fails the bench instead of reporting fiction (VERDICT r2 weak #3)."""
    import gc
    from deepspeed_tpu.models import GPT2
    model = (GPT2(size="125m", vocab_size=50304, max_seq_len=256)
             if on_tpu else GPT2(size="tiny", max_seq_len=256))
    batch = 4 if on_tpu else _cpu_batch(1)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (batch, 257), 0,
                                model.config.vocab_size)
    data = (tokens[:, :-1], tokens[:, 1:])
    out = {"metric": "zero_offload_cpu_step_ms", "unit": "ms"}
    for ratio in (1.0, 0.5, 0.0):
        config = {
            "train_batch_size": batch,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "bf16": {"enabled": True},
            "zero_optimization": {
                "stage": 2,
                "offload_optimizer": {"device": "cpu", "ratio": ratio}},
            "steps_per_print": 10 ** 9,
        }
        engine, _, _, _ = ds.initialize(model=model, config=config)
        float(engine.train_batch(data))
        rpt = engine.host_memory_report()
        if on_tpu:
            # placement must actually hold on real hardware
            assert rpt["host_fraction"] >= 0.9 * min(ratio, 0.99), rpt
            assert ratio > 0.0 or rpt["host_fraction"] == 0.0, rpt
        t0 = time.perf_counter()
        for _ in range(3):
            loss = engine.train_batch(data)
        float(loss)
        key = {1.0: "value", 0.5: "ratio05_ms", 0.0: "in_hbm_ms"}[ratio]
        out[key] = round((time.perf_counter() - t0) / 3 * 1e3, 1)
        out[{1.0: "host_frac", 0.5: "ratio05_host_frac",
             0.0: "in_hbm_host_frac"}[ratio]] = round(
                 rpt["host_fraction"], 3)
        del engine
        gc.collect()
    return out


def autotune_bench(ds, on_tpu: bool):
    """Planner stage (ISSUE 7): run the ledger-driven autotuner on the
    headline training config — calibrate effective FLOPs/s on the
    hand-tuned base, AOT-rank the mesh x microbatch x ZeRO x remat grid
    without dispatching a step, measure the top-3, and report the
    chosen plan next to its prediction error and the baseline
    throughput. Plan artifact: artifacts/autotune_plan.json (render
    with tools/autotune_report.py); gate with
    ``telemetry_report --diff --gate autotune``."""
    import gc

    from deepspeed_tpu.autotuning import (AutotuningConfig, Planner,
                                          summarize)
    from deepspeed_tpu.models import GPT2

    seq = 1024 if on_tpu else 64
    mb = 8 if on_tpu else 2
    model = (GPT2(size="125m", vocab_size=50304,
                  remat_policy="segments", attn_impl="flash")
             if on_tpu else GPT2(size="tiny", max_seq_len=seq))
    # the hand-tuned headline-stage config is the baseline the chosen
    # plan must beat (or match: it is itself a grid point)
    base = {
        "train_micro_batch_size_per_gpu": mb,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "FusedAdam",
                      "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
        "gradient_clipping": 1.0,
        "steps_per_print": 10 ** 9,
    }

    def make_batch(total):
        tokens = jax.random.randint(jax.random.PRNGKey(0),
                                    (total, seq + 1), 0,
                                    model.config.vocab_size)
        return tokens[:, :-1], tokens[:, 1:]

    cfg = AutotuningConfig(
        enabled=True,
        min_train_micro_batch_size_per_gpu=mb,
        num_tuning_micro_batch_sizes=3,
        zero_stages=[0, 1, 2, 3],
        calibration_steps=4 if on_tpu else 3,
        start_step=2, end_step=5,
        measure_windows=3,
        measure_top_k=3)
    planner = Planner(model, base, cfg, make_batch=make_batch)
    plan = planner.plan()
    os.makedirs("artifacts", exist_ok=True)
    path = plan.save(os.path.join("artifacts", "autotune_plan.json"))
    out = summarize(plan)
    # the acceptance metric is prediction error over the measured
    # TOP-K; the base candidate is also measured (for the baseline
    # ratio below) but its short mb-2 steps are the noisiest — keep
    # its error in the _all figure, not the gated one
    errs_top = [c["prediction_rel_err"] for c in plan.ranked()
                if c.get("prediction_rel_err") is not None
                and c.get("rank", 99) <= cfg.measure_top_k]
    if errs_top:
        if "prediction_rel_err" in out:
            out["prediction_rel_err_all"] = out["prediction_rel_err"]
        out["prediction_rel_err"] = round(max(errs_top), 4)
    out["plan_path"] = path
    out["calibration_flops_per_s"] = round(
        plan.calibration.get("flops_per_s", 0.0), 1)
    # calibration point 1 IS the hand-tuned base config: its measured
    # throughput is the baseline the chosen plan is compared against
    log = planner.trial_log
    if log:
        out["baseline_tokens_per_sec"] = round(log[0]["tokens_per_sec"],
                                               1)
        if out.get("plan_tokens_per_sec"):
            out["plan_vs_baseline"] = round(
                out["plan_tokens_per_sec"]
                / out["baseline_tokens_per_sec"], 4)
    out["config_diff"] = {k: v for k, v in plan.diff().items()
                          if not k.startswith("train_batch_size")}
    del planner, plan
    gc.collect()
    return out


def headline_bench(ds, on_tpu: bool):
    """The stdout-JSON stage: GPT-2 125M training throughput."""
    from deepspeed_tpu.models import GPT2
    seq = 1024 if on_tpu else 128
    batch = 24 if on_tpu else _cpu_batch()
    size = "125m" if on_tpu else "tiny"

    # vocab padded to a multiple of 128 lanes: GPT-2's 50257 fragments the
    # MXU tiling on the logits matmul (worth ~2x step time at 125M).
    # flash attention (in-repo one-pass-backward kernel) + segment remat
    # (attention outside jax.checkpoint so its residuals are kept — no
    # flash fwd rerun in backward): 31% -> 38% -> 46% MFU on v5e across
    # rounds vs full remat + unfused attention.
    model = (GPT2(size=size, vocab_size=50304,
                  remat_policy="segments", attn_impl="flash")
             if on_tpu else GPT2(size=size, max_seq_len=seq))
    # best-of-3 windows: the remote-tunnel backend occasionally serves a
    # cold/slow first window (observed 2.7x on otherwise identical runs);
    # min over windows reports steady-state device throughput
    tokens_per_sec, loss = _train_tput(
        ds, model,
        {"gradient_clipping": 1.0, "gradient_accumulation_steps": 1},
        batch, seq, steps=10 if on_tpu else 3,
        windows=3 if on_tpu else 1)
    dt_steps = batch * seq / tokens_per_sec      # seconds per step
    m = _mfu_fields(tokens_per_sec, model.config, seq)
    print(f"# mfu={m['mfu']:.3f} mfu_noncausal={m['mfu_noncausal']:.3f} "
          f"loss={loss:.4f} step_ms={dt_steps * 1e3:.1f}", file=sys.stderr)
    return {
        "metric": "gpt2_125m_train_tokens_per_sec" if on_tpu
                  else "gpt2_tiny_cpu_smoke_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        # the 0.45 north-star target (BASELINE.md §9) is a conventional-
        # accounting claim, so the ratio compares like accounting with
        # like; the primary (causal) MFU rides alongside
        "vs_baseline": round(m["mfu_noncausal"] / 0.45, 4),
        "mfu": m["mfu"],
    }


# the one stdout JSON line the driver parses; filled by the headline
# stage (or with skip/error context when it can't run) and emitted
# exactly once — including from the SIGTERM handler, so a harness-level
# timeout (rc=124) still leaves parseable output behind
_FINAL: dict = {}
_FINAL_LOCK = threading.Lock()
_FINAL_DONE = threading.Event()


def _emit_final() -> None:
    if _FINAL_DONE.is_set():
        return
    # mask SIGTERM while holding the (non-reentrant) lock: the handler
    # also calls _emit_final, and a signal landing inside the critical
    # section would self-deadlock the main thread
    try:
        old = signal.pthread_sigmask(signal.SIG_BLOCK, {signal.SIGTERM})
    except (ValueError, OSError):   # non-main thread on some platforms
        old = None
    try:
        with _FINAL_LOCK:
            if _FINAL_DONE.is_set():
                return
            if "metric" not in _FINAL:
                # whatever the exit path (SIGTERM/watchdog/fall-through),
                # the one stdout line always carries metric/value keys
                _FINAL.setdefault("error", "headline stage did not run")
                _FINAL.update({"metric": "bench_headline", "value": None})
            print(json.dumps(_FINAL), flush=True)
            _FINAL_DONE.set()
    finally:
        if old is not None:
            signal.pthread_sigmask(signal.SIG_SETMASK, old)


_BENCH_DONE = threading.Event()


def _arm_total_watchdog(total_s: float, grace_s: float = 30.0) -> None:
    """Hard global deadline (BENCH_r05 rc=124): if the stage matrix is
    still running ``grace_s`` seconds past the ``total_s`` budget —
    e.g. a stage wedged inside a C++ XLA compile where SIGALRM never
    fires — emit the stdout JSON and exit 0 from a daemon thread, so
    the driver parses a result instead of a timeout kill. Messages
    report the configured budget, not the budget+grace wait."""
    def run():
        if not _BENCH_DONE.wait(total_s + grace_s):
            _FINAL.setdefault(
                "interrupted",
                f"total budget {total_s:.0f}s exhausted mid-stage")
            # forensics BEFORE the exit (ISSUE 5): when telemetry's
            # flight recorder is live, leave a hang dump (recent
            # dispatches, open spans, ledger, thread stacks) so an
            # rc=124-class wedge is diagnosable post-mortem
            try:
                from deepspeed_tpu.utils.telemetry_probe import \
                    active_telemetry
                mod = active_telemetry()
                if mod is not None:
                    path = mod.dump_flight_record(
                        f"bench total budget {total_s:.0f}s exhausted")
                    if path:
                        print(f"# flight-recorder dump: {path}",
                              file=sys.stderr)
            except Exception:   # noqa: BLE001 - never mask the exit
                pass
            print(f"# total budget {total_s:.0f}s exhausted; exiting "
                  "with the stages completed so far", file=sys.stderr)
            _emit_final()
            sys.stdout.flush()
            sys.stderr.flush()
            os._exit(0)
    threading.Thread(target=run, daemon=True,
                     name="bench-total-watchdog").start()


def _arm_watchdog(deadline_s: float) -> None:
    """Emit the stdout JSON from a daemon thread if the headline stage
    hasn't produced it by ``deadline_s``. SIGALRM/SIGTERM handlers only
    run between Python bytecodes — a stage stuck inside one long C++
    XLA compile (the BENCH_r05 rc=124 failure) never returns to the
    interpreter, the harness escalates to SIGKILL, and no JSON lands.
    Threads keep running during C++ calls, so this fires regardless."""
    def run():
        if not _FINAL_DONE.wait(deadline_s):
            _FINAL.setdefault(
                "interrupted",
                f"watchdog: headline not done after {deadline_s:.0f}s "
                "(stage unresponsive to signals, e.g. mid-compile)")
            _emit_final()
    threading.Thread(target=run, daemon=True, name="bench-watchdog").start()


class _StageTimeout(BaseException):
    """BaseException so the SIGALRM raise punches through the broad
    `except Exception` blocks inside stages (e.g. kernel_smoke's
    per-kernel check) instead of being recorded as a kernel FAIL with
    the stage running on unbudgeted."""


def _install_signal_handlers() -> None:
    def on_alarm(signum, frame):
        raise _StageTimeout()

    def on_term(signum, frame):
        _FINAL.setdefault("interrupted", "SIGTERM mid-stage")
        _emit_final()
        sys.stdout.flush()
        os._exit(124)

    signal.signal(signal.SIGALRM, on_alarm)
    signal.signal(signal.SIGTERM, on_term)


def steptrace_bench(ds, on_tpu):
    """Seeded-regression micro-phase (ISSUE 20): drive a fake-clock
    StepTraceRecorder through a healthy plateau, then inject a slow
    collective (excess over the calibrated device baseline on a
    comm-carrying executable) and assert the online changepoint
    finding names the injected component AND its owning executable.
    Pure host arithmetic — runs in milliseconds on any rig; the
    assertions make a detector regression a stage failure, not a
    silent artifact drift."""
    from deepspeed_tpu.telemetry.steptrace import StepTraceRecorder

    class _Clock:
        t = 1000.0

        def __call__(self):
            return self.t

    class _Led:
        compile_seconds: dict = {}

        def collective_bytes_by_axis(self, name):
            return {"dp": 4.2e6}

    clk = _Clock()
    rec = StepTraceRecorder(capacity=256, clock=clk,
                            ledger=lambda: _Led(),
                            regression_window=8,
                            regression_threshold=0.3)
    inject_at, detect_at = 24, None
    for i in range(64):
        rec.step_begin(i + 1)
        clk.t += 0.002
        rec.data_ready()
        clk.t += 0.001
        rec.h2d_done()
        # healthy device window 10 ms; the fault adds 4 ms of exposed
        # comm on the same executable from step `inject_at` on
        clk.t += 0.010 if i < inject_at else 0.014
        rec.dispatch_done("compiled_step")
        clk.t += 0.0005
        rec.step_end()
        if detect_at is None and any(
                f["component"] == "exposed_comm"
                for f in rec.regressions()):
            detect_at = i + 1
    findings = rec.regressions()
    assert findings, "seeded slow-comm fault produced no finding"
    hit = next(f for f in findings if f["component"] == "exposed_comm")
    assert hit["executable"] == "compiled_step", hit
    assert rec.recon_max_rel_err <= 1e-6, rec.recon_max_rel_err
    s = rec.goodput_summary()
    return {"seeded_component": "exposed_comm",
            "finding_component": hit["component"],
            "finding_executable": hit["executable"],
            "finding_step": hit["step"],
            "detect_latency_steps": detect_at - inject_at,
            "recon_max_rel_err": rec.recon_max_rel_err,
            "goodput_fraction": round(s["goodput_fraction"], 4)}


# headline first (its JSON goes out as soon as it lands), kernel_smoke
# BEFORE the slow 7B sections so a harness-level timeout can only cost
# the capability rows, not the kernel evidence
STAGES = [("headline", headline_bench),
          ("llama", llama_bench), ("longctx", longctx_bench),
          ("moe", moe_bench), ("serving", serving_bench),
          ("prefix", prefix_bench),
          ("spec", spec_bench),
          ("kvquant", kvquant_bench),
          ("serve_openloop", serve_openloop_bench),
          ("serve_autotune", serve_autotune_bench),
          ("disagg", disagg_bench),
          ("fleet", fleet_bench),
          ("moe_serving", moe_serving_bench),
          ("moe_train", moe_train_bench),
          ("moe_serve", moe_serve_bench),
          ("offload", offload_smoke),
          ("autotune", autotune_bench),
          ("zeropp", zeropp_bench),
          ("numsan", numsan_bench),
          ("steptrace", steptrace_bench),
          ("domino", domino_bench),
          ("kernel_smoke", lambda *_: kernel_smoke()),
          ("serve7b", serve7b_int8),
          ("llama7b", llama7b_streamed),
          ("nvme", nvme_streamed)]


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="deepspeed_tpu benchmark (one JSON line on stdout; "
                    "'# '-prefixed stage records on stderr)")
    ap.add_argument("--stage", default="",
                    help="comma-separated subset of stages to run "
                         "(default: all; see --list-stages)")
    ap.add_argument("--budget-s", type=int, default=0,
                    help="per-stage wall-clock budget in seconds, "
                         "enforced with SIGALRM (0 = platform default: "
                         "600 on TPU, 240 on CPU)")
    ap.add_argument("--total-budget-s", type=int, default=-1,
                    help="global wall-clock deadline for the whole "
                         "stage matrix: remaining stages are skipped "
                         "(recorded on stderr) once it is reached and "
                         "the final JSON line is always emitted. "
                         "-1 = $DS_BENCH_TOTAL_BUDGET_S or 3300; "
                         "0 disables")
    ap.add_argument("--telemetry", metavar="DIR", default="",
                    help="activate the telemetry subsystem (ISSUE 2) and "
                         "write per-stage artifacts into DIR: "
                         "<stage>.trace.json (Perfetto), <stage>.prom "
                         "(Prometheus text), <stage>.metrics.json")
    ap.add_argument("--list-stages", action="store_true",
                    help="print stage names and exit")
    args = ap.parse_args(argv)
    if args.list_stages:
        print(" ".join(name for name, _ in STAGES))
        return

    import gc

    import deepspeed_tpu as ds

    if args.telemetry:
        from deepspeed_tpu import telemetry
        # full device-truth stack (ISSUE 5): executable ledger for
        # mfu_hlo/hbm_peak_bytes stage fields, flight recorder so the
        # total-budget watchdog can leave forensics behind
        telemetry.configure(executable_ledger=True,
                            flight_recorder=True,
                            watchdog_artifact_dir=args.telemetry)

    on_tpu = jax.devices()[0].platform != "cpu"
    budget = args.budget_s or (600 if on_tpu else 240)
    total_budget = args.total_budget_s
    if total_budget < 0:
        total_budget = int(os.environ.get("DS_BENCH_TOTAL_BUDGET_S",
                                          "3300"))
    deadline = (time.monotonic() + total_budget) if total_budget > 0 \
        else None
    selected = {s.strip() for s in args.stage.split(",") if s.strip()}
    unknown = selected - {name for name, _ in STAGES}
    if unknown:
        ap.error(f"unknown stage(s): {sorted(unknown)} "
                 f"(choose from: {' '.join(n for n, _ in STAGES)})")
    _install_signal_handlers()
    # headline runs first (or emits its skip record immediately), so if
    # the JSON hasn't landed one grace period past the stage budget the
    # signal path is wedged — let the watchdog thread put it out
    _arm_watchdog(budget * 1.25 + 60)
    if deadline is not None:
        # backstop for a stage unresponsive even to SIGALRM: emit the
        # JSON and exit 0 shortly after the deadline passes
        _arm_total_watchdog(total_budget)
    try:
        for name, fn in STAGES:
            if selected and name not in selected:
                if name == "headline":
                    _FINAL.update({"metric": "bench_headline",
                                   "value": None,
                                   "skipped": "not in --stage"})
                    _emit_final()
                continue
            remaining = (deadline - time.monotonic()
                         if deadline is not None else budget)
            if remaining <= 5:
                info = {"skipped": f"total budget {total_budget}s "
                                   "exhausted"}
                if name == "headline":
                    _FINAL.update({"metric": "bench_headline",
                                   "value": None, **info})
                    _emit_final()
                print(f"# {name} " + json.dumps(info), file=sys.stderr)
                continue
            signal.alarm(max(1, min(budget, int(remaining))))
            t0 = time.perf_counter()
            try:
                res = fn(ds, on_tpu)
                # disarm before recording: a budget expiring right as
                # fn() returns must not raise mid-emit (double stdout
                # line) or misreport the completed stage as skipped
                signal.alarm(0)
                if name == "headline":
                    _FINAL.update(res)
                    _emit_final()
                else:
                    print(f"# {name} " + json.dumps(res), file=sys.stderr)
            except _StageTimeout:
                info = {"skipped": f"stage budget {budget}s exceeded"}
                if name == "headline":
                    _FINAL.update({"metric": "bench_headline",
                                   "value": None, **info})
                    _emit_final()
                print(f"# {name} " + json.dumps(info), file=sys.stderr)
            except Exception as e:   # noqa: BLE001
                if name == "headline":
                    _FINAL.update({"metric": "bench_headline",
                                   "value": None,
                                   "error": f"{type(e).__name__}: "
                                            f"{str(e)[:160]}"})
                    _emit_final()
                print(f"# {name} FAIL: {type(e).__name__}: "
                      f"{str(e)[:160]}", file=sys.stderr)
            finally:
                signal.alarm(0)
                if args.telemetry:
                    # per-stage artifacts, then a clean slate for the
                    # next stage (written even when the stage timed out
                    # or failed — partial telemetry is still evidence)
                    from deepspeed_tpu import telemetry
                    paths = telemetry.export_artifacts(args.telemetry,
                                                       prefix=name)
                    if paths:
                        print(f"# {name} telemetry: {paths['trace']} "
                              f"{paths['prometheus']}", file=sys.stderr)
                    telemetry.clear()
                    # keep the comms tallies paired with the cleared
                    # span window (log_summary's bandwidth bound)
                    lg = ds.comm.get_comms_logger()
                    if lg is not None:
                        lg.reset()
            print(f"# {name} took {time.perf_counter() - t0:.1f}s",
                  file=sys.stderr)
            gc.collect()
    finally:
        _emit_final()
        _BENCH_DONE.set()


if __name__ == "__main__":
    main()
