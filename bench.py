"""Benchmark: GPT-2 125M training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is MFU / 0.45 — the north-star MFU target from BASELINE.md §9
(the reference's headline training-efficiency claim class; e.g. Ulysses
sustains 54% of peak on A100, BASELINE.md §3).
"""

import json
import sys
import time

import jax
import jax.numpy as jnp

# bf16 peak FLOPS by device kind (per chip)
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5": 459e12,        # v5p
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,   # trillium
    "cpu": 1e12,             # arbitrary floor for CPU smoke runs
}


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu")
    for k, v in PEAK_FLOPS.items():
        if kind.startswith(k):
            return v
    return 1e12


def main():
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import GPT2

    on_tpu = jax.devices()[0].platform != "cpu"
    seq = 1024 if on_tpu else 128
    batch = 24 if on_tpu else 2
    size = "125m" if on_tpu else "tiny"

    # vocab padded to a multiple of 128 lanes: GPT-2's 50257 fragments the
    # MXU tiling on the logits matmul (worth ~2x step time at 125M).
    # flash attention (in-repo one-pass-backward kernel) + segment remat
    # (attention outside jax.checkpoint so its residuals are kept — no
    # flash fwd rerun in backward): 31% -> 38% -> 46% MFU on v5e across
    # rounds vs full remat + unfused attention.
    model = (GPT2(size=size, vocab_size=50304,
                  remat_policy="segments", attn_impl="flash")
             if on_tpu else GPT2(size=size, max_seq_len=seq))
    config = {
        "train_batch_size": batch,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "FusedAdam",
                      "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "gradient_clipping": 1.0,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
        "steps_per_print": 10 ** 9,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)

    key = jax.random.PRNGKey(0)
    tokens = jax.random.randint(key, (batch, seq + 1), 0,
                                model.config.vocab_size)
    data = (tokens[:, :-1], tokens[:, 1:])

    # warmup/compile (float() forces a device->host sync; plain
    # block_until_ready can return early under the remote-tunnel backend)
    float(engine.train_batch(data))

    # best-of-3 windows: the remote-tunnel backend occasionally serves a
    # cold/slow first window (observed 2.7x on otherwise identical runs);
    # min over windows reports steady-state device throughput
    steps = 10 if on_tpu else 3
    windows = 3 if on_tpu else 1
    dt = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(data)
        loss = float(loss)  # device->host copy = reliable sync
        dt = min(dt, time.perf_counter() - t0)

    tokens_per_sec = steps * batch * seq / dt
    flops_per_token = model.config.flops_per_token(seq)
    achieved = tokens_per_sec * flops_per_token
    mfu = achieved / peak_flops(jax.devices()[0])
    print(json.dumps({
        "metric": "gpt2_125m_train_tokens_per_sec" if on_tpu
                  else "gpt2_tiny_cpu_smoke_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.45, 4),
    }))
    print(f"# mfu={mfu:.3f} loss={float(loss):.4f} step_ms={dt / steps * 1e3:.1f}",
          file=sys.stderr)


if __name__ == "__main__":
    main()
