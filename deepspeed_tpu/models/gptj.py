"""GPT-J family (reference: module_inject/containers/gptj.py — partial
rotary (rotary_dim=64 of head_dim 256), parallel attention+MLP sharing
one LayerNorm, unbiased attention but biased MLP, untied head)."""

from __future__ import annotations

from .base import ModelConfig, register_model
from .transformer import DecoderLM


def gptj_config(size: str = "6b", **overrides) -> ModelConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     intermediate_size=256, vocab_size=512,
                     max_seq_len=128, rotary_pct=0.5),
        "6b": dict(hidden_size=4096, num_layers=28, num_heads=16,
                   intermediate_size=16384, vocab_size=50400,
                   max_seq_len=2048, rotary_pct=0.25),  # rotary_dim 64
    }
    base = dict(norm_type="layernorm", activation="gelu",
                position_embedding="rope", use_bias=False, mlp_bias=True,
                parallel_residual=True, tie_embeddings=False)
    base.update(presets[size])
    base.update(overrides)
    return ModelConfig(**base)


@register_model("gptj")
class GPTJ(DecoderLM):
    def __init__(self, config: ModelConfig | None = None,
                 size: str | None = None, **overrides):
        if config is not None and (size is not None or overrides):
            raise ValueError(
                "pass either an explicit config or size/overrides, not both")
        super().__init__(config or gptj_config(size or "6b", **overrides))
