"""Bloom family (reference: module_inject/containers/bloom.py +
inference/v2 — ALiBi positional bias, LayerNorm after word embeddings,
full biases, tied embeddings)."""

from __future__ import annotations

from .base import ModelConfig, register_model
from .transformer import DecoderLM


def bloom_config(size: str = "560m", **overrides) -> ModelConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     intermediate_size=256, vocab_size=512,
                     max_seq_len=128),
        "560m": dict(hidden_size=1024, num_layers=24, num_heads=16,
                     intermediate_size=4096, vocab_size=250880,
                     max_seq_len=2048),
        "7b1": dict(hidden_size=4096, num_layers=30, num_heads=32,
                    intermediate_size=16384, vocab_size=250880,
                    max_seq_len=2048),
        "176b": dict(hidden_size=14336, num_layers=70, num_heads=112,
                     intermediate_size=57344, vocab_size=250880,
                     max_seq_len=2048),
    }
    base = dict(norm_type="layernorm", activation="gelu",
                position_embedding="alibi", use_bias=True,
                embed_layernorm=True, tie_embeddings=True)
    base.update(presets[size])
    base.update(overrides)
    return ModelConfig(**base)


@register_model("bloom")
class Bloom(DecoderLM):
    def __init__(self, config: ModelConfig | None = None,
                 size: str | None = None, **overrides):
        if config is not None and (size is not None or overrides):
            raise ValueError(
                "pass either an explicit config or size/overrides, not both")
        super().__init__(config or bloom_config(size or "560m", **overrides))
