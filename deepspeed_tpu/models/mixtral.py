"""Mixtral family: MoE decoder (BASELINE.md config 5: Mixtral-8x7B EP+ZeRO-3).

A DecoderLM whose FFN is a top-k routed mixture of experts. Expert weights
are stacked ``[L, E, ...]``: the ``ep`` mesh axis shards E (expert
parallelism), fsdp/tp still shard the inner dims — the composition the
reference builds with expert-parallel groups
(deepspeed/moe/layer.py:89, utils/groups.py:117).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..moe.sharded_moe import moe_ffn
from .base import ModelConfig, register_model
from .transformer import DecoderLM, _dense_init


def mixtral_config(size: str = "8x7b", **overrides) -> ModelConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     num_kv_heads=2, intermediate_size=128, vocab_size=512,
                     max_seq_len=128, num_experts=4, moe_top_k=2),
        # MoE reference config (ISSUE 16): big enough that routing,
        # ep sharding and the dispatch wire dominate like a real MoE
        # block (8 experts top-2 -> 4x total/active param ratio in the
        # FFN), small enough for the bench rig and slow tests
        "ref": dict(hidden_size=256, num_layers=4, num_heads=4,
                    num_kv_heads=4, intermediate_size=512,
                    vocab_size=4096, max_seq_len=512, num_experts=8,
                    moe_top_k=2, capacity_factor=1.25),
        "8x7b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                     num_kv_heads=8, intermediate_size=14336,
                     vocab_size=32000, max_seq_len=4096, num_experts=8,
                     moe_top_k=2, rope_theta=1e6),
    }
    base = dict(norm_type="rmsnorm", activation="swiglu",
                position_embedding="rope", use_bias=False,
                tie_embeddings=False)
    base.update(presets[size])
    base.update(overrides)
    return ModelConfig(**base)


@register_model("mixtral")
class Mixtral(DecoderLM):
    def __init__(self, config: ModelConfig | None = None,
                 size: str | None = None, **overrides):
        if config is not None and (size is not None or overrides):
            raise ValueError(
                "pass either an explicit config or size/overrides, not both")
        config = config or mixtral_config(size or "8x7b", **overrides)
        if config.num_experts <= 0:
            raise ValueError("Mixtral requires num_experts > 0")
        super().__init__(config)

    def init(self, rng: jax.Array):
        params = super().init(rng)
        c = self.config
        dt = c.param_dtype
        d, f, L, E = (c.hidden_size, c.intermediate_size, c.num_layers,
                      c.num_experts)
        std = 0.02
        resid_std = std / (2 * L) ** 0.5
        keys = jax.random.split(jax.random.fold_in(rng, 17), 4)
        layers = params["layers"]
        # replace dense FFN with routed experts + gate
        for name in ("w_up", "w_down", "w_gate", "w_up_b", "w_down_b",
                     "w_gate_b"):
            layers.pop(name, None)
        layers["router"] = _dense_init(keys[0], (L, d, E), std, dt)
        layers["experts"] = {
            "w_up": _dense_init(keys[1], (L, E, d, f), std, dt),
            "w_gate": _dense_init(keys[2], (L, E, d, f), std, dt),
            "w_down": _dense_init(keys[3], (L, E, f, d), resid_std, dt),
        }
        return params

    # set True by init_inference: decode batches route through the
    # sort-by-expert grouped GEMM (exact top-k, no capacity padding or
    # drops) instead of the training path's [N, E, C] capacity einsum
    # (reference: inference v2 moe_gemm/moe_gather/moe_scatter vs
    # training sharded_moe dispatch)
    moe_serving_dispatch = False

    # set by the training engine (runtime/engine.py, ISSUE 16): the
    # ep-sharded explicit dispatch/combine exchange, routing overrides
    # from the moe config block (None = this config's values), and the
    # router-telemetry opt-in. Class attrs so plain model use (tests,
    # serving) keeps the implicit einsum collectives.
    moe_dispatcher = None
    moe_capacity_factor = None
    moe_min_capacity = None
    moe_router_telemetry = False

    def _mlp(self, p, h):
        c = self.config
        from ..moe.sharded_moe import dequantize_experts
        experts = dequantize_experts(p["experts"], h.dtype)
        norm = c.moe_norm_topk
        if self.moe_serving_dispatch:
            from ..moe.sharded_moe import moe_ffn_grouped
            return moe_ffn_grouped(h, p["router"], experts,
                                   k=c.moe_top_k,
                                   activation=c.activation,
                                   normalize_topk=norm)
        hook = None
        if self.moe_router_telemetry:
            from ..moe.dispatch import publish_router_metrics
            hook = publish_router_metrics
        cf = self.moe_capacity_factor
        mc = self.moe_min_capacity
        return moe_ffn(
            h, p["router"], experts, k=c.moe_top_k,
            capacity_factor=c.capacity_factor if cf is None else cf,
            min_capacity=c.min_capacity if mc is None else mc,
            activation=c.activation, normalize_topk=norm,
            dispatcher=self.moe_dispatcher, metrics_hook=hook)

    def partition_rules(self):
        rules = [r for r in super().partition_rules()
                 if "w_up" not in r[0] and "w_down" not in r[0]
                 and "w_gate" not in r[0]]
        return rules + [
            (r"layers/router", P()),
            (r"layers/experts/(w_up|w_gate)$", P(None, "ep", None, "tp")),
            (r"layers/experts/w_down$", P(None, "ep", "tp", None)),
        ]
