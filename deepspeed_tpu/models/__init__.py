from .base import Model, ModelConfig, get_model_class, register_model  # noqa: F401
from .bert import Bert, bert_config  # noqa: F401
from .bloom import Bloom, bloom_config  # noqa: F401
from .falcon import Falcon, falcon_config  # noqa: F401
from .gpt2 import GPT2, gpt2_config  # noqa: F401
from .gptj import GPTJ, gptj_config  # noqa: F401
from .gptneox import GPTNeoX, gptneox_config  # noqa: F401
from .internlm import InternLM, internlm_config  # noqa: F401
from .llama import Llama, llama_config  # noqa: F401
from .mistral import Mistral, mistral_config  # noqa: F401
from .mixtral import Mixtral, mixtral_config  # noqa: F401
from .opt import OPT, opt_config  # noqa: F401
from .phi import Phi, Phi3, phi3_config, phi_config  # noqa: F401
from .qwen import (Qwen, Qwen2, Qwen2MoE, qwen2_config,  # noqa: F401
                   qwen2_moe_config, qwen_config)
from .transformer import DecoderLM  # noqa: F401


def from_pretrained(model_path: str, **config_overrides):
    """(model, params) from a local HF checkpoint directory — see
    checkpoint/huggingface.py (reference: inference/v2/checkpoint/
    huggingface_engine.py)."""
    from ..checkpoint.huggingface import from_pretrained as _fp
    return _fp(model_path, **config_overrides)
