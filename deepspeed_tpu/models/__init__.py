from .base import Model, ModelConfig, get_model_class, register_model  # noqa: F401
from .gpt2 import GPT2, gpt2_config  # noqa: F401
from .llama import Llama, llama_config  # noqa: F401
from .mixtral import Mixtral, mixtral_config  # noqa: F401
from .transformer import DecoderLM  # noqa: F401
