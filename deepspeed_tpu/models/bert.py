"""BERT-style encoder, end-to-end trainable (reference:
module_inject/containers/bert.py + the training transformer kernel
ops/transformer/transformer.py it was built for — DeepSpeed's original
headline workload was BERT pre-training).

Wraps ops/transformer.py's DeepSpeedTransformerLayer (the encoder-layer
API mirroring the reference kernel config) into a Model-protocol MLM:
embeddings (token + learned position, LayerNorm), stacked layers via
lax.scan, and the standard BERT MLM head (transform + tied decoder).
Trainable through ds.initialize like any decoder family.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from ..ops import layers as L
from ..ops.transformer import (DeepSpeedTransformerConfig,
                               DeepSpeedTransformerLayer)
from .base import register_model

PyTree = Any


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 512
    norm_eps: float = 1e-12
    pre_layer_norm: bool = False     # post-LN = original BERT
    param_dtype: Any = None

    def __post_init__(self):
        if self.param_dtype is None:
            self.param_dtype = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    def num_params(self) -> int:
        d, f, v, Lr = (self.hidden_size, self.intermediate_size,
                       self.vocab_size, self.num_layers)
        per_layer = (d * 3 * d + 3 * d) + (d * d + d) + 2 * d \
            + (d * f + f) + (f * d + d) + 2 * d
        embed = v * d + self.max_seq_len * d + 2 * d
        head = d * d + d + 2 * d + v   # transform + LN + decoder bias
        return embed + Lr * per_layer + head

    def flops_per_token(self, seq_len: int, causal: bool = False) -> float:
        # encoders attend bidirectionally; `causal` kept for API parity
        n = self.num_params()
        return 6 * n + 12 * self.num_layers * self.hidden_size * seq_len


def bert_config(size: str = "base", **overrides) -> BertConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     intermediate_size=256, vocab_size=512,
                     max_seq_len=128),
        "base": dict(hidden_size=768, num_layers=12, num_heads=12,
                     intermediate_size=3072),
        "large": dict(hidden_size=1024, num_layers=24, num_heads=16,
                      intermediate_size=4096),
        # reference containers/distil_bert.py: 6-layer distilled BERT
        "distil": dict(hidden_size=768, num_layers=6, num_heads=12,
                       intermediate_size=3072),
    }
    base = dict(presets[size])
    base.update(overrides)
    return BertConfig(**base)


@register_model("bert")
class Bert:
    """Model-protocol encoder: init / apply (MLM logits) / loss."""

    def __init__(self, config: BertConfig | None = None,
                 size: str | None = None, **overrides):
        if config is not None and (size is not None or overrides):
            raise ValueError(
                "pass either an explicit config or size/overrides, not both")
        self.config = config or bert_config(size or "base", **overrides)
        c = self.config
        self._layer = DeepSpeedTransformerLayer(DeepSpeedTransformerConfig(
            hidden_size=c.hidden_size, intermediate_size=c.intermediate_size,
            heads=c.num_heads, num_hidden_layers=c.num_layers,
            attn_dropout_ratio=0.0, hidden_dropout_ratio=0.0,
            pre_layer_norm=c.pre_layer_norm, layer_norm_eps=c.norm_eps,
            training=True))

    # ------------------------------------------------------------- init
    def init(self, rng: jax.Array) -> PyTree:
        c = self.config
        dt = c.param_dtype
        d, v = c.hidden_size, c.vocab_size
        ks = jax.random.split(rng, c.num_layers + 3)
        layer_trees = [self._layer.init(k) for k in ks[:c.num_layers]]
        # the kernel-layer init only knows fp16/fp32; honor param_dtype
        layers = jax.tree.map(lambda *xs: jnp.stack(xs).astype(dt),
                              *layer_trees)
        std = 0.02
        return {
            "embed": {
                "tokens": (jax.random.normal(ks[-3], (v, d)) * std
                           ).astype(dt),
                "positions": (jax.random.normal(ks[-2], (c.max_seq_len, d))
                              * std).astype(dt),
                "ln_scale": jnp.ones((d,), dt),
                "ln_bias": jnp.zeros((d,), dt),
            },
            "layers": layers,
            "mlm_head": {
                "transform_w": (jax.random.normal(ks[-1], (d, d)) * std
                                ).astype(dt),
                "transform_b": jnp.zeros((d,), dt),
                "ln_scale": jnp.ones((d,), dt),
                "ln_bias": jnp.zeros((d,), dt),
                "decoder_b": jnp.zeros((v,), dt),
            },
        }

    # ------------------------------------------------------------ apply
    def apply(self, params: PyTree, tokens: jax.Array,
              attention_mask: jax.Array | None = None) -> jax.Array:
        """MLM logits [B, S, V]. ``attention_mask``: [B, S] 1=real
        0=padding (HF convention) -> additive bias."""
        c = self.config
        if tokens.shape[-1] > c.max_seq_len:
            raise ValueError(
                f"sequence length {tokens.shape[-1]} exceeds max_seq_len "
                f"{c.max_seq_len}")
        e = params["embed"]
        x = jnp.take(e["tokens"], tokens, axis=0)
        x = x + e["positions"][: tokens.shape[-1]][None]
        x = L.layer_norm(x, e["ln_scale"], e["ln_bias"], c.norm_eps)
        bias = None
        if attention_mask is not None:
            bias = jnp.where(attention_mask[:, None, None, :] > 0,
                             0.0, -1e30).astype(jnp.float32)

        def body(h, lp):
            return self._layer.apply(lp, h, attention_mask=bias), None

        x, _ = jax.lax.scan(body, x, params["layers"])
        h = params["mlm_head"]
        x = L.gelu(x @ h["transform_w"] + h["transform_b"])
        x = L.layer_norm(x, h["ln_scale"], h["ln_bias"], c.norm_eps)
        return x @ e["tokens"].T + h["decoder_b"]

    # ------------------------------------------------------------- loss
    def loss(self, params: PyTree, batch: Any, **_kw) -> jax.Array:
        """Masked-LM loss: batch = (tokens, targets[, attention_mask]);
        targets use -100 at unmasked positions (HF convention)."""
        if isinstance(batch, dict):
            tokens, targets = batch["input_ids"], batch["labels"]
            mask = batch.get("attention_mask")
        else:
            tokens, targets = batch[0], batch[1]
            mask = batch[2] if len(batch) > 2 else None
        logits = self.apply(params, tokens, attention_mask=mask)
        return L.cross_entropy_loss(logits, targets)

    def partition_rules(self):
        from jax.sharding import PartitionSpec as P
        return [
            (r"embed/tokens", P("tp", None)),
            (r"layers/(qkv_w|inter_w)", P(None, None, "tp")),
            (r"layers/(attn_ow|output_w)", P(None, "tp", None)),
            (r"mlm_head/transform_w", P()),
        ]
