"""GPT-2 family (BASELINE.md config 1: GPT-2 125M ZeRO-1)."""

from __future__ import annotations

from .base import ModelConfig, register_model
from .transformer import DecoderLM


def gpt2_config(size: str = "125m", **overrides) -> ModelConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     intermediate_size=256, vocab_size=512, max_seq_len=128),
        "125m": dict(hidden_size=768, num_layers=12, num_heads=12,
                     intermediate_size=3072, vocab_size=50257,
                     max_seq_len=1024),
        "350m": dict(hidden_size=1024, num_layers=24, num_heads=16,
                     intermediate_size=4096, vocab_size=50257,
                     max_seq_len=1024),
        "1.3b": dict(hidden_size=2048, num_layers=24, num_heads=32,
                     intermediate_size=8192, vocab_size=50257,
                     max_seq_len=1024),
    }
    base = dict(norm_type="layernorm", activation="gelu",
                position_embedding="learned", use_bias=True,
                tie_embeddings=True)
    base.update(presets[size])
    base.update(overrides)
    return ModelConfig(**base)


@register_model("gpt2")
class GPT2(DecoderLM):
    def __init__(self, config: ModelConfig | None = None,
                 size: str | None = None, **overrides):
        if config is not None and (size is not None or overrides):
            raise ValueError(
                "pass either an explicit config or size/overrides, not both")
        super().__init__(config or gpt2_config(size or "125m", **overrides))
