"""GPT-NeoX family (reference: module_inject/containers/gptneox.py —
partial rotary, use_parallel_residual with SEPARATE LayerNorms for
attention and MLP, full biases, untied head)."""

from __future__ import annotations

from .base import ModelConfig, register_model
from .transformer import DecoderLM


def gptneox_config(size: str = "20b", **overrides) -> ModelConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     intermediate_size=256, vocab_size=512,
                     max_seq_len=128, rotary_pct=0.5),
        "pythia-1.4b": dict(hidden_size=2048, num_layers=24, num_heads=16,
                            intermediate_size=8192, vocab_size=50304,
                            max_seq_len=2048, rotary_pct=0.25),
        "20b": dict(hidden_size=6144, num_layers=44, num_heads=64,
                    intermediate_size=24576, vocab_size=50432,
                    max_seq_len=2048, rotary_pct=0.25),
    }
    base = dict(norm_type="layernorm", activation="gelu",
                position_embedding="rope", use_bias=True,
                parallel_residual=True, parallel_dual_norm=True,
                tie_embeddings=False)
    base.update(presets[size])
    base.update(overrides)
    return ModelConfig(**base)


@register_model("gptneox")
class GPTNeoX(DecoderLM):
    def __init__(self, config: ModelConfig | None = None,
                 size: str | None = None, **overrides):
        if config is not None and (size is not None or overrides):
            raise ValueError(
                "pass either an explicit config or size/overrides, not both")
        super().__init__(config or gptneox_config(size or "20b",
                                                  **overrides))
