"""Model interface for the TPU runtime.

The reference wraps user-provided ``torch.nn.Module``s; the TPU-native
equivalent is a functional model: a pytree of parameters plus pure
``init``/``apply``/``loss`` functions. The engine only relies on this
protocol, so users can bring flax/haiku modules via thin adapters
(models/adapters.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Protocol

import jax
from jax.sharding import PartitionSpec

PyTree = Any
Rules = list[tuple[str, PartitionSpec]]


@dataclasses.dataclass
class ModelConfig:
    vocab_size: int = 32000
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: int | None = None  # None -> MHA
    max_seq_len: int = 1024
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    tie_embeddings: bool = True
    lm_head_bias: bool = False      # Phi / GPT-J biased vocab projection
    # architecture switches
    norm_type: str = "layernorm"        # layernorm | rmsnorm
    activation: str = "gelu"            # gelu | relu | swiglu
    position_embedding: str = "learned"  # learned | rope | alibi (Bloom)
    use_bias: bool = True
    attn_qkv_bias: bool = False     # qkv biases even when use_bias=False
    #                                 (Qwen-style)
    mlp_bias: bool | None = None    # None -> use_bias; GPT-J: attn
    #                                 unbiased but fc_in/fc_out biased
    parallel_residual: bool = False  # Falcon/Phi-2: x + attn(h) + mlp(h)
    #                                  with a single input norm (no ln2)
    parallel_dual_norm: bool = False  # GPT-NeoX: parallel residual but
    #                                   attn/mlp each get their own norm
    embed_layernorm: bool = False   # Bloom: LayerNorm after word embed
    rotary_pct: float = 1.0         # partial rotary (GPT-NeoX/Phi-2)
    sliding_window: int | None = None  # Mistral windowed attention
    # MoE (0 experts = dense; reference: deepspeed/moe)
    num_experts: int = 0
    moe_num_shared_experts: int = 0  # Qwen2-MoE always-on experts
    moe_top_k: int = 2
    moe_norm_topk: bool = True      # renormalize top-k probs (Mixtral
    #                                 yes, Qwen2-MoE norm_topk_prob)
    capacity_factor: float = 1.25
    min_capacity: int = 4
    router_aux_loss_coef: float = 0.01
    # numerics
    param_dtype: Any = None   # set to jnp dtype in __post_init__
    loss_chunk: int = 0       # >0: fused chunked cross-entropy (tokens per
    #                           chunk) — never materializes [B,S,V] logits
    remat: bool = True
    # jax.checkpoint_policies name; "nothing_saveable" = full recompute
    remat_policy: str = "nothing_saveable"
    attn_impl: str = "reference"  # reference | flash

    def __post_init__(self):
        import jax.numpy as jnp
        if self.param_dtype is None:
            self.param_dtype = jnp.float32
        if self.num_kv_heads is None:
            self.num_kv_heads = self.num_heads

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def effective_mlp_bias(self) -> bool:
        """mlp_bias falls back to use_bias — the single source of truth
        for init / forward / num_params (GPT-J splits them)."""
        return self.use_bias if self.mlp_bias is None else self.mlp_bias

    def num_params(self) -> int:
        """Analytic parameter count (embedding + layers + final norm),
        matching the trees DecoderLM.init builds exactly."""
        d, f, v, L = (self.hidden_size, self.intermediate_size,
                      self.vocab_size, self.num_layers)
        nh_d = self.num_heads * self.head_dim
        kv = self.num_kv_heads * self.head_dim
        attn = d * nh_d + 2 * d * kv + nh_d * d  # wq, wk, wv, wo
        mlp = 3 * d * f if self.activation == "swiglu" else 2 * d * f
        if self.num_experts > 0:
            mlp = mlp * self.num_experts + d * self.num_experts  # + gate
            if self.moe_num_shared_experts > 0:
                # shared experts fused into one n-times-wider swiglu MLP
                # plus the sigmoid gate proj (d -> 1)
                mlp += 3 * d * f * self.moe_num_shared_experts + d
        n_norms = (1 if self.parallel_residual
                   and not self.parallel_dual_norm else 2)
        mlp_bias = self.effective_mlp_bias
        per_layer = attn + mlp + n_norms * d  # + ln scales
        if self.use_bias or self.attn_qkv_bias:
            per_layer += nh_d + 2 * kv      # qkv biases
        if self.use_bias:
            per_layer += d                  # wo bias
        if mlp_bias:
            per_layer += f + d              # w_up_b, w_down_b
            if self.activation == "swiglu":
                per_layer += f              # w_gate_b
        if self.norm_type == "layernorm":
            per_layer += n_norms * d        # ln biases
        embed = v * d + (0 if self.tie_embeddings else v * d)
        if not self.tie_embeddings and self.lm_head_bias:
            embed += v
        if self.embed_layernorm:
            embed += 2 * d
        pos = self.max_seq_len * d if self.position_embedding == "learned" else 0
        final_norm = d + (d if self.norm_type == "layernorm" else 0)
        return embed + pos + L * per_layer + final_norm

    def num_active_params(self) -> int:
        """Parameters a token actually computes with: dense models run
        everything; an MoE token runs only its top-k routed experts (the
        router projection and any shared experts always run). This is
        the MFU denominator — counting parked experts would credit the
        model with FLOPs it never executed."""
        n = self.num_params()
        if self.num_experts <= 0:
            return n
        d, f = self.hidden_size, self.intermediate_size
        per_expert = 3 * d * f if self.activation == "swiglu" else 2 * d * f
        inactive = max(self.num_experts - self.moe_top_k, 0)
        return n - self.num_layers * inactive * per_expert

    def flops_per_token(self, seq_len: int, causal: bool = True) -> float:
        """Training FLOPs/token (fwd+bwd ~= 6*N_active + attention
        term), the standard MFU accounting (BASELINE.md §9). For MoE
        models N is :meth:`num_active_params` — top-k experts per
        token, not the full expert bank.

        ``causal=True`` (default — the PRIMARY number for every reported
        MFU) counts only the attention work a causal model performs: the
        average attended context is (s+1)/2, or bounded by the sliding
        window when one is configured. ``causal=False`` is the
        conventional full-attention accounting some frameworks report;
        at long sequence it flatters MFU ~2x and is kept only as a
        secondary figure.
        """
        n = self.num_active_params()
        s = seq_len
        if causal:
            w = self.sliding_window
            if w and w < s:
                # mean_i min(i+1, w): first w positions grow linearly,
                # the rest are window-bounded
                ctx = (w * (w + 1) / 2 + (s - w) * w) / s
            else:
                ctx = (s + 1) / 2
        else:
            ctx = s
        attn_flops = 12 * self.num_layers * self.hidden_size * ctx
        return 6 * n + attn_flops


class Model(Protocol):
    config: ModelConfig

    def init(self, rng: jax.Array) -> PyTree: ...

    def apply(self, params: PyTree, tokens: jax.Array, **kw) -> jax.Array: ...

    def loss(self, params: PyTree, batch: Any, **kw) -> jax.Array: ...

    def partition_rules(self) -> Rules: ...


_MODEL_REGISTRY: dict[str, Callable[..., Any]] = {}


def register_model(name: str):
    def deco(cls):
        _MODEL_REGISTRY[name] = cls
        return cls
    return deco


def get_model_class(name: str):
    if name not in _MODEL_REGISTRY:
        raise KeyError(
            f"unknown model {name!r}; available: {sorted(_MODEL_REGISTRY)}")
    return _MODEL_REGISTRY[name]
