"""Phi family (reference: inference/v2/model_implementations/phi/ and
phi3/). Phi-2: parallel residual with a single LayerNorm and partial
rotary embeddings; Phi-3: llama-style RMSNorm + SwiGLU."""

from __future__ import annotations

from .base import ModelConfig, register_model
from .transformer import DecoderLM


def phi_config(size: str = "2", **overrides) -> ModelConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     intermediate_size=256, vocab_size=512,
                     max_seq_len=128, rotary_pct=0.5),
        "2": dict(hidden_size=2560, num_layers=32, num_heads=32,
                  intermediate_size=10240, vocab_size=51200,
                  max_seq_len=2048, rotary_pct=0.4),
    }
    base = dict(norm_type="layernorm", activation="gelu",
                position_embedding="rope", use_bias=True,
                parallel_residual=True, tie_embeddings=False)
    base.update(presets[size])
    base.update(overrides)
    return ModelConfig(**base)


def phi3_config(size: str = "mini", **overrides) -> ModelConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     num_kv_heads=2, intermediate_size=128, vocab_size=512,
                     max_seq_len=128),
        "mini": dict(hidden_size=3072, num_layers=32, num_heads=32,
                     num_kv_heads=32, intermediate_size=8192,
                     vocab_size=32064, max_seq_len=4096),
    }
    base = dict(norm_type="rmsnorm", activation="swiglu",
                position_embedding="rope", use_bias=False,
                tie_embeddings=False)
    base.update(presets[size])
    base.update(overrides)
    return ModelConfig(**base)


@register_model("phi")
class Phi(DecoderLM):
    def __init__(self, config: ModelConfig | None = None,
                 size: str | None = None, **overrides):
        if config is not None and (size is not None or overrides):
            raise ValueError(
                "pass either an explicit config or size/overrides, not both")
        super().__init__(config or phi_config(size or "2", **overrides))


@register_model("phi3")
class Phi3(DecoderLM):
    def __init__(self, config: ModelConfig | None = None,
                 size: str | None = None, **overrides):
        if config is not None and (size is not None or overrides):
            raise ValueError(
                "pass either an explicit config or size/overrides, not both")
        super().__init__(config or phi3_config(size or "mini", **overrides))
