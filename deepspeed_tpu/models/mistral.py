"""Mistral family (reference: inference/v2/model_implementations/mistral/
— llama-style GQA decoder with sliding-window attention)."""

from __future__ import annotations

from .base import ModelConfig, register_model
from .transformer import DecoderLM


def mistral_config(size: str = "7b", **overrides) -> ModelConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     num_kv_heads=2, intermediate_size=128, vocab_size=512,
                     max_seq_len=128, sliding_window=32),
        "7b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                   num_kv_heads=8, intermediate_size=14336,
                   vocab_size=32000, max_seq_len=8192,
                   sliding_window=4096),
    }
    base = dict(norm_type="rmsnorm", activation="swiglu",
                position_embedding="rope", use_bias=False,
                tie_embeddings=False, rope_theta=10000.0)
    base.update(presets[size])
    base.update(overrides)
    return ModelConfig(**base)


@register_model("mistral")
class Mistral(DecoderLM):
    def __init__(self, config: ModelConfig | None = None,
                 size: str | None = None, **overrides):
        if config is not None and (size is not None or overrides):
            raise ValueError(
                "pass either an explicit config or size/overrides, not both")
        super().__init__(config or mistral_config(size or "7b", **overrides))
