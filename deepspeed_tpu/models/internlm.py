"""InternLM family (reference: module_inject/containers/internlm.py).

Llama architecture; the 7B generation carries biases on ALL attention
projections (q/k/v AND o_proj, which the reference container loads as
self_attn.o_proj.bias) while the MLP stays bias-free; InternLM-20B
dropped the biases entirely (plain Llama layout)."""

from __future__ import annotations

from .base import ModelConfig, register_model
from .transformer import DecoderLM


def internlm_config(size: str = "7b", **overrides) -> ModelConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     num_kv_heads=4, intermediate_size=128,
                     vocab_size=512, max_seq_len=128),
        "7b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                   num_kv_heads=32, intermediate_size=11008,
                   vocab_size=103168, max_seq_len=2048),
        "20b": dict(hidden_size=5120, num_layers=60, num_heads=40,
                    num_kv_heads=40, intermediate_size=13824,
                    vocab_size=103168, max_seq_len=4096,
                    use_bias=False, mlp_bias=None),  # 20B is bias-free
    }
    # 7B layout: q/k/v/o biased (use_bias) but the MLP unbiased
    # (mlp_bias=False) — the InternLM delta vs Llama
    base = dict(norm_type="rmsnorm", activation="swiglu",
                position_embedding="rope", use_bias=True,
                mlp_bias=False, tie_embeddings=False)
    base.update(presets[size])
    base.update(overrides)
    return ModelConfig(**base)


@register_model("internlm")
class InternLM(DecoderLM):
    def __init__(self, config: ModelConfig | None = None,
                 size: str | None = None, **overrides):
        if config is not None and (size is not None or overrides):
            raise ValueError(
                "pass either an explicit config or size/overrides, not both")
        super().__init__(config or internlm_config(size or "7b",
                                                   **overrides))
