"""Qwen family (reference: inference/v2/model_implementations/{qwen,
qwen_v2,qwen_v2_moe}/ — llama-style decoders with qkv biases; the MoE
variant adds routed experts plus an always-on shared expert)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..moe.sharded_moe import moe_ffn
from ..ops import layers as L
from .base import ModelConfig, register_model
from .mixtral import Mixtral
from .transformer import DecoderLM, _dense_init


def qwen_config(size: str = "7b", **overrides) -> ModelConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     num_kv_heads=2, intermediate_size=128, vocab_size=512,
                     max_seq_len=128),
        "7b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                   num_kv_heads=32, intermediate_size=11008,
                   vocab_size=151936, max_seq_len=8192),
        "72b": dict(hidden_size=8192, num_layers=80, num_heads=64,
                    num_kv_heads=64, intermediate_size=24576,
                    vocab_size=152064, max_seq_len=32768,
                    rope_theta=1e6),
    }
    base = dict(norm_type="rmsnorm", activation="swiglu",
                position_embedding="rope", use_bias=False,
                attn_qkv_bias=True, tie_embeddings=False)
    base.update(presets[size])
    base.update(overrides)
    return ModelConfig(**base)


def qwen2_config(size: str = "7b", **overrides) -> ModelConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     num_kv_heads=2, intermediate_size=128, vocab_size=512,
                     max_seq_len=128),
        "7b": dict(hidden_size=3584, num_layers=28, num_heads=28,
                   num_kv_heads=4, intermediate_size=18944,
                   vocab_size=152064, max_seq_len=32768, rope_theta=1e6),
    }
    base = dict(norm_type="rmsnorm", activation="swiglu",
                position_embedding="rope", use_bias=False,
                attn_qkv_bias=True, tie_embeddings=False)
    base.update(presets[size])
    base.update(overrides)
    return ModelConfig(**base)


def qwen2_moe_config(size: str = "a2.7b", **overrides) -> ModelConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     num_kv_heads=2, intermediate_size=128, vocab_size=512,
                     max_seq_len=128, num_experts=4, moe_top_k=2,
                     moe_num_shared_experts=1),
        "a2.7b": dict(hidden_size=2048, num_layers=24, num_heads=16,
                      num_kv_heads=16, intermediate_size=1408,
                      vocab_size=151936, max_seq_len=8192,
                      num_experts=60, moe_top_k=4, rope_theta=1e6,
                      moe_num_shared_experts=1),
    }
    base = dict(norm_type="rmsnorm", activation="swiglu",
                position_embedding="rope", use_bias=False,
                attn_qkv_bias=True, tie_embeddings=False,
                # HF Qwen2-MoE norm_topk_prob defaults False: raw
                # softmax probs combine the top-k experts
                moe_norm_topk=False)
    base.update(presets[size])
    base.update(overrides)
    return ModelConfig(**base)


@register_model("qwen")
class Qwen(DecoderLM):
    def __init__(self, config: ModelConfig | None = None,
                 size: str | None = None, **overrides):
        if config is not None and (size is not None or overrides):
            raise ValueError(
                "pass either an explicit config or size/overrides, not both")
        super().__init__(config or qwen_config(size or "7b", **overrides))


@register_model("qwen2")
class Qwen2(DecoderLM):
    def __init__(self, config: ModelConfig | None = None,
                 size: str | None = None, **overrides):
        if config is not None and (size is not None or overrides):
            raise ValueError(
                "pass either an explicit config or size/overrides, not both")
        super().__init__(config or qwen2_config(size or "7b", **overrides))


@register_model("qwen2_moe")
class Qwen2MoE(Mixtral):
    """Routed experts + a shared expert whose output is added through a
    sigmoid gate (reference: qwen_v2_moe modules)."""

    def __init__(self, config: ModelConfig | None = None,
                 size: str | None = None, **overrides):
        if config is None:
            config = qwen2_moe_config(size or "a2.7b", **overrides)
        elif size is not None or overrides:
            raise ValueError(
                "pass either an explicit config or size/overrides, not both")
        super().__init__(config)

    def init(self, rng: jax.Array):
        params = super().init(rng)
        c = self.config
        if c.moe_num_shared_experts <= 0:
            return params
        dt = c.param_dtype
        d, Ln = c.hidden_size, c.num_layers
        # n shared experts fuse into one n-times-wider swiglu MLP
        fs = c.intermediate_size * c.moe_num_shared_experts
        keys = jax.random.split(jax.random.fold_in(rng, 23), 4)
        std = 0.02
        params["layers"]["shared"] = {
            "w_gate": _dense_init(keys[0], (Ln, d, fs), std, dt),
            "w_up": _dense_init(keys[1], (Ln, d, fs), std, dt),
            "w_down": _dense_init(keys[2], (Ln, fs, d),
                                  std / (2 * Ln) ** 0.5, dt),
            "gate_proj": _dense_init(keys[3], (Ln, d, 1), std, dt),
        }
        return params

    def _mlp(self, p, h):
        out, aux = super()._mlp(p, h)
        if "shared" not in p:
            return out, aux
        # weight-only int8 serving (quantize_dense_params) quantizes the
        # shared-expert matrices like any other layer-stacked leaves;
        # dequantize inline at the use site (XLA fuses into the GEMMs),
        # mirroring how the routed experts dict handles its own dequant
        from ..linear.quantization import dequantize_dense
        sh = dequantize_dense(p["shared"], h.dtype)
        shared = (L.silu(h @ sh["w_gate"]) * (h @ sh["w_up"])) @ sh["w_down"]
        gate = jax.nn.sigmoid(h @ sh["gate_proj"])
        return out + gate * shared, aux

    def partition_rules(self):
        return super().partition_rules() + [
            (r"layers/shared/(w_gate|w_up)$", P(None, None, "tp")),
            (r"layers/shared/w_down$", P(None, "tp", None)),
            (r"layers/shared/gate_proj$", P()),
        ]
