"""Adapters: bring non-native models into the Model protocol.

The reference accepts any torch.nn.Module; here we accept flax linen
modules and plain (init, apply, loss) function triples.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ..ops.layers import cross_entropy_loss
from .base import ModelConfig


class FunctionalModel:
    """Wrap (init_fn, apply_fn[, loss_fn]) into the Model protocol."""

    def __init__(self, init_fn: Callable, apply_fn: Callable,
                 loss_fn: Optional[Callable] = None, partition_rules=None,
                 config: ModelConfig | None = None):
        self._init = init_fn
        self._apply = apply_fn
        self._loss = loss_fn
        self._rules = partition_rules or []
        self.config = config

    def init(self, rng):
        return self._init(rng)

    def apply(self, params, *args, **kw):
        return self._apply(params, *args, **kw)

    def loss(self, params, batch, **kw):
        if self._loss is not None:
            return self._loss(params, batch)
        tokens, targets = batch if not isinstance(batch, dict) \
            else (batch["tokens"], batch["targets"])
        logits = self._apply(params, tokens)
        return cross_entropy_loss(logits, targets)

    def partition_rules(self):
        return self._rules


class FlaxModel(FunctionalModel):
    """Wrap a flax.linen.Module. The module's __call__ must map tokens to
    logits; loss defaults to next-token cross entropy."""

    def __init__(self, module, example_tokens=None, loss_fn=None,
                 partition_rules=None, config=None):
        self.flax_module = module
        example = example_tokens if example_tokens is not None \
            else jnp.zeros((1, 8), jnp.int32)

        def init_fn(rng):
            return module.init(rng, example)["params"]

        def apply_fn(params, tokens, **kw):
            return module.apply({"params": params}, tokens, **kw)

        super().__init__(init_fn, apply_fn, loss_fn, partition_rules, config)


def wrap_model(model):
    try:
        import flax.linen as nn
        if isinstance(model, nn.Module):
            return FlaxModel(model)
    except ImportError:
        pass
    if isinstance(model, (tuple, list)) and len(model) in (2, 3):
        return FunctionalModel(*model)
    raise TypeError(
        f"cannot adapt {type(model)!r} into the Model protocol; provide an "
        "object with init/apply/loss/partition_rules, a flax Module, or an "
        "(init, apply[, loss]) tuple")
