"""OPT family (reference: inference/v2/model_implementations/opt/ —
GPT-style learned positions, LayerNorm, ReLU-family MLP, biases)."""

from __future__ import annotations

from .base import ModelConfig, register_model
from .transformer import DecoderLM


def opt_config(size: str = "1.3b", **overrides) -> ModelConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     intermediate_size=256, vocab_size=512,
                     max_seq_len=128),
        "125m": dict(hidden_size=768, num_layers=12, num_heads=12,
                     intermediate_size=3072, vocab_size=50272,
                     max_seq_len=2048),
        "1.3b": dict(hidden_size=2048, num_layers=24, num_heads=32,
                     intermediate_size=8192, vocab_size=50272,
                     max_seq_len=2048),
        "13b": dict(hidden_size=5120, num_layers=40, num_heads=40,
                    intermediate_size=20480, vocab_size=50272,
                    max_seq_len=2048),
        "66b": dict(hidden_size=9216, num_layers=64, num_heads=72,
                    intermediate_size=36864, vocab_size=50272,
                    max_seq_len=2048),
    }
    # OPT's FFN activation is ReLU (HF OPTConfig activation_function
    # default; caught by the HF logits-parity suite — gelu diverged)
    base = dict(norm_type="layernorm", activation="relu",
                position_embedding="learned", use_bias=True,
                tie_embeddings=True)
    base.update(presets[size])
    base.update(overrides)
    return ModelConfig(**base)


@register_model("opt")
class OPT(DecoderLM):
    def __init__(self, config: ModelConfig | None = None,
                 size: str | None = None, **overrides):
        if config is not None and (size is not None or overrides):
            raise ValueError(
                "pass either an explicit config or size/overrides, not both")
        super().__init__(config or opt_config(size or "1.3b", **overrides))
