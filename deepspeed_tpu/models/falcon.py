"""Falcon family (reference: inference/v2/model_implementations/falcon/
— parallel attention+MLP blocks sharing one input LayerNorm, rope,
multi-query attention on 7B)."""

from __future__ import annotations

from .base import ModelConfig, register_model
from .transformer import DecoderLM


def falcon_config(size: str = "7b", **overrides) -> ModelConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     num_kv_heads=1, intermediate_size=256, vocab_size=512,
                     max_seq_len=128),
        "7b": dict(hidden_size=4544, num_layers=32, num_heads=71,
                   num_kv_heads=1, intermediate_size=4544 * 4,
                   vocab_size=65024, max_seq_len=2048),
        "40b": dict(hidden_size=8192, num_layers=60, num_heads=128,
                    num_kv_heads=8, intermediate_size=8192 * 4,
                    vocab_size=65024, max_seq_len=2048),
    }
    base = dict(norm_type="layernorm", activation="gelu",
                position_embedding="rope", use_bias=False,
                parallel_residual=True, tie_embeddings=True)
    base.update(presets[size])
    base.update(overrides)
    return ModelConfig(**base)


@register_model("falcon")
class Falcon(DecoderLM):
    def __init__(self, config: ModelConfig | None = None,
                 size: str | None = None, **overrides):
        if config is not None and (size is not None or overrides):
            raise ValueError(
                "pass either an explicit config or size/overrides, not both")
        super().__init__(config or falcon_config(size or "7b", **overrides))
