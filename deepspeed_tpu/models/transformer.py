"""Decoder-only transformer core, TPU-first.

One parameterized implementation serves GPT-2 (learned positions, LayerNorm,
GELU, biases) and Llama (RoPE, RMSNorm, SwiGLU, GQA, no biases) — the
architecture switches live in ``ModelConfig``. Design choices that matter
on TPU:

- **Stacked layer parameters** ``[L, ...]`` + ``lax.scan`` over layers: one
  compiled block regardless of depth, and ZeRO-3-style parameter sharding
  becomes "all-gather one layer slice per scan step" which XLA pipelines
  against compute — the static-schedule translation of the reference's
  trace-based prefetch coordinator
  (``runtime/zero/partitioned_param_coordinator.py:276``).
- **Pluggable attention** (``attn_fn``): the Ulysses/ring sequence-parallel
  wrappers (deepspeed_tpu/sequence/) and the Pallas flash kernel drop in
  without touching the model, mirroring how ``DistributedAttention`` wraps
  any local attention (``deepspeed/sequence/layer.py:271``).
- **Exposed embed/block/unembed** pieces so the pipeline engine
  (runtime/pipe/) can place stage boundaries without re-deriving the model.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops import layers as L
from .base import Model, ModelConfig, Rules

PyTree = Any
AttnFn = Callable[..., jax.Array]


def _dense_init(key, shape, scale, dtype):
    return (jax.random.normal(key, shape) * scale).astype(dtype)


class DecoderLM:
    """Functional decoder-only LM over a parameter pytree."""

    def __init__(self, config: ModelConfig):
        self.config = config
        if config.position_embedding == "rope":
            # partial rotary (rotary_pct < 1): rope covers only the first
            # rot_dim channels of each head (GPT-NeoX/Phi-2 style)
            self._rot_dim = max(2, int(config.head_dim
                                       * config.rotary_pct) // 2 * 2)
            self._rope = L.rotary_embedding(
                config.max_seq_len, self._rot_dim, config.rope_theta)
        else:
            self._rot_dim = 0
            self._rope = None
        self._alibi_slopes = (L.alibi_slopes(config.num_heads)
                              if config.position_embedding == "alibi"
                              else None)
        if self._alibi_slopes is not None and config.attn_impl == "flash":
            raise ValueError(
                "attn_impl='flash' does not support ALiBi yet — the "
                "kernel has no per-head additive-bias path; use the "
                "default attention (O(S^2) bias) or rope/learned "
                "positions with flash")

    # ---------------- init ----------------
    def init(self, rng: jax.Array) -> PyTree:
        c = self.config
        dt = c.param_dtype
        d, f, v = c.hidden_size, c.intermediate_size, c.vocab_size
        nh, nkv, hd = c.num_heads, c.num_kv_heads, c.head_dim
        keys = jax.random.split(rng, 8)
        std = 0.02
        resid_std = std / (2 * c.num_layers) ** 0.5

        def layer_stack(key, shape, scale):
            return _dense_init(key, (c.num_layers, *shape), scale, dt)

        lk = jax.random.split(keys[0], 12)
        layers = {
            "ln1_scale": jnp.ones((c.num_layers, d), dt),
            "wq": layer_stack(lk[0], (d, nh * hd), std),
            "wk": layer_stack(lk[1], (d, nkv * hd), std),
            "wv": layer_stack(lk[2], (d, nkv * hd), std),
            "wo": layer_stack(lk[3], (nh * hd, d), resid_std),
            "w_up": layer_stack(lk[4], (d, f), std),
            "w_down": layer_stack(lk[5], (f, d), resid_std),
        }
        has_ln2 = not c.parallel_residual or c.parallel_dual_norm
        if has_ln2:  # single-norm parallel blocks share ln1
            layers["ln2_scale"] = jnp.ones((c.num_layers, d), dt)
        if c.activation == "swiglu":
            layers["w_gate"] = layer_stack(lk[6], (d, f), std)
        if c.norm_type == "layernorm":
            layers["ln1_bias"] = jnp.zeros((c.num_layers, d), dt)
            if has_ln2:
                layers["ln2_bias"] = jnp.zeros((c.num_layers, d), dt)
        if c.use_bias or c.attn_qkv_bias:
            layers.update({
                "wq_b": jnp.zeros((c.num_layers, nh * hd), dt),
                "wk_b": jnp.zeros((c.num_layers, nkv * hd), dt),
                "wv_b": jnp.zeros((c.num_layers, nkv * hd), dt),
            })
        if c.use_bias:
            layers["wo_b"] = jnp.zeros((c.num_layers, d), dt)
        if c.effective_mlp_bias:
            layers.update({
                "w_up_b": jnp.zeros((c.num_layers, f), dt),
                "w_down_b": jnp.zeros((c.num_layers, d), dt),
            })
            if c.activation == "swiglu":
                layers["w_gate_b"] = jnp.zeros((c.num_layers, f), dt)
        params: dict[str, Any] = {
            "embed": {"tokens": _dense_init(keys[1], (v, d), std, dt)},
            "layers": layers,
            "final_norm": {"scale": jnp.ones((d,), dt)},
        }
        if c.position_embedding == "learned":
            params["embed"]["positions"] = _dense_init(
                keys[2], (c.max_seq_len, d), std, dt)
        if c.embed_layernorm:   # Bloom: LayerNorm after word embeddings
            params["embed"]["ln_scale"] = jnp.ones((d,), dt)
            params["embed"]["ln_bias"] = jnp.zeros((d,), dt)
        if c.norm_type == "layernorm":
            params["final_norm"]["bias"] = jnp.zeros((d,), dt)
        if not c.tie_embeddings:
            params["lm_head"] = _dense_init(keys[3], (d, v), std, dt)
            if c.lm_head_bias:  # Phi / GPT-J biased vocab projection
                params["lm_head_b"] = jnp.zeros((v,), dt)
        return params

    # ---------------- pieces (reused by pipeline/inference) --------------
    def _maybe_dequant(self, p: PyTree, dtype) -> PyTree:
        """Inline per-layer dequant of weight-only int8 serving trees
        (linear/quantization.py quantize_dense_params): inside the layer
        scan, at most ONE layer's bf16 weights ever exist and XLA fuses
        the convert+scale into the consuming GEMM (reference:
        ZeRO-Inference weight quantization / cutlass mixed_gemm)."""
        from ..linear.quantization import dequantize_dense
        return dequantize_dense(p, dtype)

    def _norm(self, x, scale, bias=None):
        if self.config.norm_type == "rmsnorm":
            return L.rms_norm(x, scale, self.config.norm_eps)
        return L.layer_norm(x, scale, bias, self.config.norm_eps)

    def embed(self, params: PyTree, tokens: jax.Array,
              positions: jax.Array | None = None) -> jax.Array:
        c = self.config
        if tokens.shape[-1] > c.max_seq_len:
            raise ValueError(
                f"sequence length {tokens.shape[-1]} exceeds max_seq_len "
                f"{c.max_seq_len}")
        x = jnp.take(params["embed"]["tokens"], tokens, axis=0)
        if c.position_embedding == "learned":
            if positions is None:
                positions = jnp.arange(tokens.shape[-1])[None, :]
            x = x + jnp.take(params["embed"]["positions"], positions, axis=0)
        if c.embed_layernorm:
            x = L.layer_norm(x, params["embed"]["ln_scale"],
                             params["embed"]["ln_bias"], c.norm_eps)
        return x

    def _qkv(self, p: PyTree, h: jax.Array,
             positions: jax.Array | None = None):
        """Shared q/k/v projection (+bias, head reshape, rope)."""
        c = self.config
        b, s, _ = h.shape
        nh, nkv, hd = c.num_heads, c.num_kv_heads, c.head_dim
        q = h @ p["wq"]
        k = h @ p["wk"]
        v = h @ p["wv"]
        if c.use_bias or c.attn_qkv_bias:
            q, k, v = q + p["wq_b"], k + p["wk_b"], v + p["wv_b"]
        q = q.reshape(b, s, nh, hd)
        k = k.reshape(b, s, nkv, hd)
        v = v.reshape(b, s, nkv, hd)
        if self._rope is not None:
            cos, sin = self._rope
            if self._rot_dim < hd:   # partial rotary: rotate a prefix
                q = jnp.concatenate(
                    [L.apply_rotary(q[..., :self._rot_dim], cos, sin,
                                    positions), q[..., self._rot_dim:]],
                    axis=-1)
                k = jnp.concatenate(
                    [L.apply_rotary(k[..., :self._rot_dim], cos, sin,
                                    positions), k[..., self._rot_dim:]],
                    axis=-1)
            else:
                q = L.apply_rotary(q, cos, sin, positions)
                k = L.apply_rotary(k, cos, sin, positions)
        from jax.ad_checkpoint import checkpoint_name
        return (checkpoint_name(q, "qkv"), checkpoint_name(k, "qkv"),
                checkpoint_name(v, "qkv"))

    def _attn_out(self, p: PyTree, a: jax.Array) -> jax.Array:
        b, s = a.shape[:2]
        out = a.reshape(b, s, -1) @ p["wo"]
        if self.config.use_bias:
            out = out + p["wo_b"]
        return out

    def _mlp_residual(self, p: PyTree, x: jax.Array):
        h = self._norm(x, p["ln2_scale"], p.get("ln2_bias"))
        m, aux = self._mlp(p, h)
        return x + m, aux

    def _parallel_mlp_input(self, p: PyTree, x: jax.Array, h: jax.Array):
        """MLP input for parallel-residual blocks — THE single place for
        the dual-norm switch (GPT-NeoX norms the raw residual with ln2;
        Falcon/GPT-J share ln1's output). apply/flash/decode/paged all
        route through here so the paths can't drift (a past bug: decode
        and v2 serving fed ln1's output to a dual-norm MLP)."""
        if self.config.parallel_dual_norm:
            return self._norm(x, p["ln2_scale"], p.get("ln2_bias"))
        return h

    def block(self, layer_params: PyTree, x: jax.Array, *,
              attn_fn: AttnFn | None = None,
              positions: jax.Array | None = None) -> jax.Array:
        """One transformer block. layer_params carries per-layer slices
        (no leading L dim)."""
        c = self.config
        p = self._maybe_dequant(layer_params, x.dtype)
        if attn_fn is not None and c.sliding_window is not None:
            from ..utils.logging import warning_once
            warning_once(
                "sliding_window is set but a custom attn_fn (e.g. the "
                "sequence-parallel wrapper) is in use; the window mask is "
                "NOT applied by the wrapper — attention is full-causal")
        if attn_fn is not None and c.position_embedding == "alibi":
            from ..utils.logging import warning_once
            warning_once(
                "position_embedding='alibi' but a custom attn_fn (e.g. the "
                "sequence-parallel wrapper) is in use; the ALiBi bias is "
                "NOT applied by the wrapper — the model runs with no "
                "positional encoding")
        if attn_fn is None:
            if c.position_embedding == "alibi":
                # ALiBi rides the exact path as a per-head additive bias
                # (Bloom; reference bloom containers add it in-kernel)
                import functools
                attn_fn = functools.partial(
                    L.dot_product_attention,
                    bias=L.alibi_bias(self._alibi_slopes, x.shape[1]))
            elif c.attn_impl == "flash":
                import functools

                from ..ops.pallas.flash_attention import flash_attention
                attn_fn = (functools.partial(flash_attention,
                                             window=c.sliding_window)
                           if c.sliding_window is not None
                           else flash_attention)
            elif c.sliding_window is not None:
                import functools
                attn_fn = functools.partial(
                    L.dot_product_attention,
                    bias=self._window_bias(x.shape[1]))
            else:
                attn_fn = L.dot_product_attention

        if c.remat and c.remat_policy == "segments":
            return self._block_segmented(p, x, attn_fn, positions)

        h = self._norm(x, p["ln1_scale"], p.get("ln1_bias"))
        q, k, v = self._qkv(p, h, positions)
        a = attn_fn(q, k, v, causal=True)
        if c.parallel_residual:
            m, aux = self._mlp(p, self._parallel_mlp_input(p, x, h))
            return x + self._attn_out(p, a) + m, aux
        x = x + self._attn_out(p, a)
        return self._mlp_residual(p, x)

    def _block_segmented(self, p, x, attn_fn, positions):
        """Segment remat: attention sits OUTSIDE any jax.checkpoint, so
        its custom-VJP residuals (q, k, v, o, lse) are stored and the
        backward never re-runs the forward flash kernel (custom_vjp under
        remat re-executes its fwd rule — measured ~2ms/layer on v5e at
        GPT-2 shapes). The projections around it are rematted in two
        segments:

        - seg_qkv (norm + qkv projection): saves nothing internally; its
          outputs q/k/v are boundary values (= the flash residuals).
        - seg_out (output proj + MLP): saves the mid-residual and the
          pre-activation ffn tensors, so backward recomputes only norms
          and the activation function — no matmul re-runs.

        Net per-layer saves at [B=24, S=1024, D=768]: ~378MB vs ~302MB
        for "save_attn_ffn", in exchange for skipping the flash rerun and
        the attn-proj + up-matmul recomputes (~3.5ms/layer on v5e).
        """
        c = self.config
        from jax.ad_checkpoint import checkpoint_name

        def seg_qkv(p, x):
            h = self._norm(x, p["ln1_scale"], p.get("ln1_bias"))
            q, k, v = self._qkv(p, h, positions)
            return q, k, v, (h if c.parallel_residual else None)

        q, k, v, h = jax.checkpoint(seg_qkv, prevent_cse=False)(p, x)
        a = attn_fn(q, k, v, causal=True)

        def seg_out(p, x, a, h):
            if c.parallel_residual:
                m, aux = self._mlp(p, self._parallel_mlp_input(p, x, h))
                return x + self._attn_out(p, a) + m, aux
            x2 = x + self._attn_out(p, a)
            x2 = checkpoint_name(x2, "resid_mid")
            return self._mlp_residual(p, x2)

        pol = jax.checkpoint_policies.save_only_these_names(
            "resid_mid", "ffn_pre")
        return jax.checkpoint(seg_out, prevent_cse=False, policy=pol)(
            p, x, a, h)

    def _window_bias(self, seq_len: int) -> jax.Array:
        return L.window_bias(seq_len, self.config.sliding_window)

    def _mlp(self, p: PyTree, h: jax.Array):
        """Dense FFN. Returns (out, aux_loss) — MoE subclasses override
        (aux carries the router load-balancing loss)."""
        from jax.ad_checkpoint import checkpoint_name
        c = self.config
        mlp_bias = c.effective_mlp_bias
        if c.activation == "swiglu":
            gate = checkpoint_name(h @ p["w_gate"], "ffn_pre")
            up = checkpoint_name(h @ p["w_up"], "ffn_pre")
            if mlp_bias:
                gate = gate + p["w_gate_b"]
                up = up + p["w_up_b"]
            m = L.silu(gate) * up
        else:
            up = checkpoint_name(h @ p["w_up"], "ffn_pre")
            if mlp_bias:
                up = up + p["w_up_b"]
            m = jax.nn.relu(up) if c.activation == "relu" else L.gelu(up)
        m = checkpoint_name(m, "ffn")
        m = m @ p["w_down"]
        if mlp_bias:
            m = m + p["w_down_b"]
        return m, jnp.zeros((), jnp.float32)

    # ---------------- KV-cache decode (inference engine) -----------------
    def init_cache(self, batch_size: int, max_len: int,
                   dtype=None) -> PyTree:
        """Static-shape KV cache (reference: inference_context.h KV buffer
        allocation). [L, B, S_max, H_kv, D] per k/v."""
        c = self.config
        dt = dtype or c.param_dtype
        shape = (c.num_layers, batch_size, max_len, c.num_kv_heads,
                 c.head_dim)
        return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt),
                "index": jnp.zeros((), jnp.int32)}

    def block_decode(self, layer_params: PyTree, x: jax.Array,
                     k_cache: jax.Array, v_cache: jax.Array,
                     index: jax.Array):
        """One block over new tokens with cache read/write. x: [B, S_new,
        D]; caches [B, S_max, H_kv, D]. Returns (x, new_k, new_v)."""
        p = self._maybe_dequant(layer_params, x.dtype)
        b, s, _ = x.shape
        positions = (index + jnp.arange(s))[None, :].repeat(b, axis=0)

        h = self._norm(x, p["ln1_scale"], p.get("ln1_bias"))
        q, k, v = self._qkv(p, h, positions)
        k_cache = jax.lax.dynamic_update_slice_in_dim(
            k_cache, k.astype(k_cache.dtype), index, axis=1)
        v_cache = jax.lax.dynamic_update_slice_in_dim(
            v_cache, v.astype(v_cache.dtype), index, axis=1)
        a = L.cached_attention(q, k_cache, v_cache, index,
                               window=self.config.sliding_window,
                               alibi_slopes=self._alibi_slopes)
        if self.config.parallel_residual:
            m, _ = self._mlp(p, self._parallel_mlp_input(p, x, h))
            return x + self._attn_out(p, a) + m, k_cache, v_cache
        x = x + self._attn_out(p, a)
        x, _ = self._mlp_residual(p, x)
        return x, k_cache, v_cache

    def decode(self, params: PyTree, tokens: jax.Array, cache: PyTree):
        """Prefill or incremental decode: run `tokens` (appended at
        cache["index"]) through all layers, updating the cache. Returns
        (logits [B, S_new, V], new_cache)."""
        index = cache["index"]
        b, s = tokens.shape
        positions = (index + jnp.arange(s))[None, :].repeat(b, axis=0)
        x = self.embed(params, tokens, positions=positions)

        def body(x, xs):
            layer_params, k_l, v_l = xs
            x, new_k, new_v = self.block_decode(layer_params, x, k_l, v_l,
                                                index)
            return x, (new_k, new_v)

        x, (new_k, new_v) = jax.lax.scan(
            body, x, (params["layers"], cache["k"], cache["v"]))
        logits = self.unembed(params, x)
        return logits, {"k": new_k, "v": new_v, "index": index + s}

    def unembed(self, params: PyTree, x: jax.Array) -> jax.Array:
        x = self._norm(x, params["final_norm"]["scale"],
                       params["final_norm"].get("bias"))
        return self._project_vocab(params, x)

    # ---------------- apply / loss ----------------
    def apply(self, params: PyTree, tokens: jax.Array, *,
              attn_fn: AttnFn | None = None,
              positions: jax.Array | None = None,
              return_aux: bool = False, act_sharding=None):
        x, aux = self._final_hidden(params, tokens, attn_fn=attn_fn,
                                    positions=positions,
                                    act_sharding=act_sharding)
        logits = self._project_vocab(params, x)
        return (logits, aux) if return_aux else logits

    def loss(self, params: PyTree, batch: Any, *,
             attn_fn: AttnFn | None = None,
             act_sharding=None) -> jax.Array:
        tokens, targets = _unpack_batch(batch)
        if self.config.loss_chunk > 0:
            return self._chunked_loss(params, tokens, targets,
                                      attn_fn=attn_fn,
                                      act_sharding=act_sharding)
        logits, aux = self.apply(params, tokens, attn_fn=attn_fn,
                                 return_aux=True,
                                 act_sharding=act_sharding)
        ce = L.cross_entropy_loss(logits, targets)
        return ce + self.aux_loss_coef() * aux

    def _chunked_loss(self, params: PyTree, tokens, targets, *,
                      attn_fn=None, act_sharding=None) -> jax.Array:
        """Fused chunked cross-entropy: the [B, S, V] logits tensor is
        never materialized — the unembed matmul + logsumexp run per
        sequence chunk under remat, so peak HBM holds one
        [B, loss_chunk, V] slab and the backward recomputes it per chunk.
        The HBM-traffic role of the reference's fused logits kernels
        (csrc/transformer/inference logits_gather + fused softmax)."""
        c = self.config
        x, aux = self._final_hidden(params, tokens, attn_fn=attn_fn,
                                    act_sharding=act_sharding)
        W = (params["embed"]["tokens"].T if c.tie_embeddings
             else params["lm_head"])
        b, s, d = x.shape
        chunk = min(c.loss_chunk, s)
        if s % chunk != 0:
            raise ValueError(
                f"loss_chunk {c.loss_chunk} (effective {chunk}) must "
                f"divide sequence length {s}")
        n = s // chunk
        xc = x.reshape(b, n, chunk, d).swapaxes(0, 1)
        tc = targets.reshape(b, n, chunk).swapaxes(0, 1)

        bias = params.get("lm_head_b")

        @jax.checkpoint
        def chunk_nll(x_c, t_c):
            logits = (x_c @ W.astype(x_c.dtype)).astype(jnp.float32)
            if bias is not None:
                logits = logits + bias.astype(jnp.float32)
            lse = jax.scipy.special.logsumexp(logits, axis=-1)
            # same masking contract as ops.layers.cross_entropy_loss
            valid = t_c != -100
            safe = jnp.where(valid, t_c, 0)
            tl = jnp.take_along_axis(logits, safe[..., None],
                                     axis=-1)[..., 0]
            return jnp.sum(jnp.where(valid, lse - tl, 0.0)), \
                jnp.sum(valid)

        def body(acc, xs):
            x_c, t_c = xs
            nll, cnt = chunk_nll(x_c, t_c)
            return (acc[0] + nll, acc[1] + cnt), None

        (nll, cnt), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)),
            (xc, tc))
        ce = nll / jnp.maximum(cnt, 1)
        return ce + self.aux_loss_coef() * aux

    def _final_hidden(self, params: PyTree, tokens, *, attn_fn=None,
                      positions=None, act_sharding=None):
        """Final-normed hidden states [B, S, D] + router aux loss.

        ``act_sharding`` (a NamedSharding for [B, S, D]) pins the
        layer-scan carry to one canonical layout. Without it, a
        sequence-parallel attn_fn (shard_map manual over sp) plus
        fsdp-sharded stacked weights leaves GSPMD free to flip
        activation/weight layouts between scan iterations — on the ring
        config that produced 'Involuntary full rematerialization'
        resharding of the embed gradient scatter-add (VERDICT r4 #2)."""
        c = self.config
        x = self.embed(params, tokens, positions)
        if act_sharding is not None:
            x = jax.lax.with_sharding_constraint(x, act_sharding)

        def body(carry, layer_params):
            x, aux = carry
            x, layer_aux = self.block(layer_params, x, attn_fn=attn_fn,
                                      positions=positions)
            if act_sharding is not None:
                x = jax.lax.with_sharding_constraint(x, act_sharding)
            return (x, aux + layer_aux), None

        if c.remat and c.remat_policy != "segments":
            # "segments" applies selective checkpoints INSIDE block()
            # (attention outside remat); wrapping the whole body here
            # would re-introduce the flash fwd rerun it exists to avoid
            body = jax.checkpoint(body, prevent_cse=False,
                                  policy=_remat_policy(c.remat_policy))
        (x, aux), _ = jax.lax.scan(
            body, (x, jnp.zeros((), jnp.float32)), params["layers"])
        x = self._norm(x, params["final_norm"]["scale"],
                       params["final_norm"].get("bias"))
        return x, aux

    def _project_vocab(self, params: PyTree, x: jax.Array) -> jax.Array:
        """Vocab projection of already-final-normed hidden states."""
        if self.config.tie_embeddings:
            return x @ params["embed"]["tokens"].T
        if "lm_head_q" in params:   # weight-only int8 serving
            W = (params["lm_head_q"].astype(x.dtype)
                 * params["lm_head_s"].astype(x.dtype))
        else:
            W = params["lm_head"]
        out = x @ W
        if "lm_head_b" in params:   # Phi / GPT-J biased head
            out = out + params["lm_head_b"]
        return out

    def aux_loss_coef(self) -> float:
        return getattr(self.config, "router_aux_loss_coef", 0.0)

    # ---------------- sharding ----------------
    def partition_rules(self) -> Rules:
        """Megatron-style TP rules; the engine overlays fsdp sharding
        (reference TP analogue: module_inject/auto_tp.py row/col split)."""
        return [
            (r"embed/tokens", P("tp", None)),
            (r"embed/positions", P()),
            (r"layers/(wq|wk|wv|w_up|w_gate)$", P(None, None, "tp")),
            (r"layers/(wq_b|wk_b|wv_b|w_up_b|w_gate_b)$", P(None, "tp")),
            (r"layers/(wo|w_down)$", P(None, "tp", None)),
            (r"layers/(wo_b|w_down_b)$", P()),
            (r"layers/ln\d_(scale|bias)", P()),
            (r"final_norm", P()),
            (r"lm_head$", P(None, "tp")),
            (r"lm_head_b$", P("tp")),
        ]


def _remat_policy(name: str):
    """Map a config policy name to a jax.checkpoint policy. Besides the
    stock jax.checkpoint_policies names, ``save_attn_ffn`` saves the
    O(S)-sized per-layer tensors named "qkv"/"attn_out"/"ffn" (both the
    reference attention and the flash wrapper name their outputs) —
    backward then recomputes only norms and the O(S^2) attention scores,
    usually the best single-chip throughput point."""
    if name == "nothing_saveable":
        return None
    if name == "save_attn_ffn":
        # save the O(S)-sized per-layer tensors (qkv, attention output,
        # ffn hidden); backward recomputes only norms and the O(S^2)
        # attention scores — the usual best single-chip throughput point
        return jax.checkpoint_policies.save_only_these_names(
            "qkv", "attn_out", "ffn")
    return getattr(jax.checkpoint_policies, name)


def _unpack_batch(batch):
    if isinstance(batch, dict):
        return batch["tokens"], batch["targets"]
    tokens, targets = batch
    return tokens, targets
