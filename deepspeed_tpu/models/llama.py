"""Llama-2 family (BASELINE.md configs 2/3: 7B ZeRO-2, 70B ZeRO-3)."""

from __future__ import annotations

from .base import ModelConfig, register_model
from .transformer import DecoderLM


def llama_config(size: str = "7b", **overrides) -> ModelConfig:
    presets = {
        "tiny": dict(hidden_size=64, num_layers=2, num_heads=4,
                     num_kv_heads=2, intermediate_size=128, vocab_size=512,
                     max_seq_len=128),
        "7b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                   num_kv_heads=32, intermediate_size=11008,
                   vocab_size=32000, max_seq_len=4096),
        "13b": dict(hidden_size=5120, num_layers=40, num_heads=40,
                    num_kv_heads=40, intermediate_size=13824,
                    vocab_size=32000, max_seq_len=4096),
        "70b": dict(hidden_size=8192, num_layers=80, num_heads=64,
                    num_kv_heads=8, intermediate_size=28672,
                    vocab_size=32000, max_seq_len=4096),
        # Llama-3 generation: GQA everywhere, 128k vocab, theta 500k
        "3-8b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                     num_kv_heads=8, intermediate_size=14336,
                     vocab_size=128256, max_seq_len=8192,
                     rope_theta=500000.0),
        "3-70b": dict(hidden_size=8192, num_layers=80, num_heads=64,
                      num_kv_heads=8, intermediate_size=28672,
                      vocab_size=128256, max_seq_len=8192,
                      rope_theta=500000.0),
    }
    base = dict(norm_type="rmsnorm", activation="swiglu",
                position_embedding="rope", use_bias=False,
                tie_embeddings=False, norm_eps=1e-5)
    base.update(presets[size])
    base.update(overrides)
    return ModelConfig(**base)


@register_model("llama")
class Llama(DecoderLM):
    def __init__(self, config: ModelConfig | None = None,
                 size: str | None = None, **overrides):
        if config is not None and (size is not None or overrides):
            raise ValueError(
                "pass either an explicit config or size/overrides, not both")
        super().__init__(config or llama_config(size or "7b", **overrides))
