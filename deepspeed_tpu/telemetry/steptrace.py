"""Per-step training trace + goodput/badput ledger + online regression
attribution (ISSUE 20 tentpole) — the training-side mirror of
:mod:`.reqtrace`.

The training loop's telemetry so far is aggregate: spans time
``train_batch`` and the ledger knows what a compiled step *costs*, but
nothing reconciles one step's wall time into named components, nothing
accounts goodput vs badput across a run, and a step-time regression is
still diagnosed by hand. This module records one host-side record per
``engine.train_batch`` and derives an EXACT telescoping decomposition::

    step_wall = data_wait + h2d + dispatch_overhead + device_compute
              + exposed_comm + optimizer + checkpoint + restart
              + recompile + residual

where ``step_wall`` spans from the PREVIOUS step's end (so checkpoint
saves and data stalls between steps are inside the telescoping, not
lost), ``device_compute`` is the per-executable calibration baseline
(the running minimum of the cleaned dispatch window — the PR 7
cost-model convention: the baseline already contains overlapped comm),
``exposed_comm`` is the excess over that baseline when the ledger says
the executable carries collectives (excess on a collective-free
executable is host jitter and lands in ``dispatch_overhead``),
``recompile`` is charged from the jax compile-event listener's
per-phase seconds (via the executable ledger), and ``residual`` closes
the telescoping exactly — ``recon_max_rel_err`` (float-associativity
noise, <= 1e-6 by construction) is exported so the contract is
checkable from artifacts alone.

On top of the per-step records:

- a run-level **goodput/badput ledger**: goodput fraction = productive
  device seconds / wall, badput bucketed into ``compile`` (compile
  seconds accrued since the run's first step — pre-run AOT/serving
  builds never charge the training wall), ``overflow`` (skipped steps
  via ``ds_overflow_steps_total``), ``checkpoint``, ``data_wait``,
  ``straggler`` (cross-rank skew samples) and
  ``restart`` (checkpoint loads), exported as
  ``ds_train_goodput_fraction`` + ``ds_train_badput_seconds{bucket}``;
- a JSONL **step log** with the stable :data:`STEP_LOG_KEYS` schema
  (one line per step; ``telemetry_report --diff`` aggregates it as a
  numeric source);
- per-step **Perfetto tracks** composable with ``--merge``;
- an online **regression detector**: sliding-window mean-shift
  changepoints per component that emit findings NAMING the moved
  component, the owning executable, and the step index, bumping
  ``ds_steptrace_regressions_total{component}`` and riding the
  hang-watchdog dump.

Host-only, stdlib-only (graftlint host-only package audit applies);
zero-import when telemetry is disabled — the engine resolves the
recorder through the telemetry probe and guards every call. The ledger
and timeseries ring are handed in as zero-arg accessors so this module
imports nothing outside the stdlib.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Callable, Optional

# the telescoping components, in telescoped order (a step's Perfetto
# component track lays them out sequentially in exactly this order)
COMPONENT_KEYS = ("data_wait", "h2d", "dispatch_overhead",
                  "device_compute", "exposed_comm", "optimizer",
                  "checkpoint", "restart", "recompile", "residual")

# one JSONL step-log line per finalized step — the stable schema
# consumers (and the schema test) hold on to. *_ms components
# telescope: their sum equals step_wall_ms exactly (residual included);
# straggler_skew_ms is an attribution overlay (the skew overlaps the
# dispatch wait), NOT a tenth telescoping term.
STEP_LOG_KEYS = ("step", "unix_s", "executable", "step_wall_ms",
                 "data_wait_ms", "h2d_ms", "dispatch_overhead_ms",
                 "device_compute_ms", "exposed_comm_ms", "optimizer_ms",
                 "checkpoint_ms", "restart_ms", "recompile_ms",
                 "residual_ms", "straggler_skew_ms", "recon_rel_err")

# run-level badput buckets (seconds) — see goodput_summary()
BADPUT_BUCKETS = ("compile", "overflow", "checkpoint", "data_wait",
                  "straggler", "restart")

# components owned by the compiled executable (regression findings on
# these name the executable; the rest are host-side)
_DEVICE_COMPONENTS = frozenset(
    ("device_compute", "exposed_comm", "recompile", "optimizer",
     "dispatch_overhead"))

_FINDINGS_CAP = 128


class StepRecord:
    """One finalized training step. Timestamps are recorder-clock
    (default ``time.perf_counter``) seconds, the span tracer's clock
    family, so the Chrome export shares the host-span timebase."""

    __slots__ = ("step", "unix_s", "executable", "t_end", "step_wall",
                 "components", "straggler_s", "recon_rel_err")

    def __init__(self, step: int, unix_s: float, executable: str,
                 t_end: float, step_wall: float, components: dict,
                 straggler_s: float, recon_rel_err: float):
        self.step = step
        self.unix_s = unix_s
        self.executable = executable
        self.t_end = t_end
        self.step_wall = step_wall
        self.components = components
        self.straggler_s = straggler_s
        self.recon_rel_err = recon_rel_err

    def log_row(self) -> dict:
        def ms(v: float) -> float:
            return round(v * 1e3, 6)

        row = {"step": self.step, "unix_s": round(self.unix_s, 6),
               "executable": self.executable,
               "step_wall_ms": ms(self.step_wall)}
        for name in COMPONENT_KEYS:
            row[f"{name}_ms"] = ms(self.components[name])
        row["straggler_skew_ms"] = ms(self.straggler_s)
        row["recon_rel_err"] = self.recon_rel_err
        return row


class _Pending:
    """The step currently being traced (between step_begin and
    step_end)."""

    __slots__ = ("step", "t_begin", "t_data", "t_h2d", "t_disp",
                 "executable", "compile_at_begin", "offload_s",
                 "straggler_s", "unix_s")

    def __init__(self, step: int, now: float, unix_s: float,
                 compile_at_begin: float):
        self.step = step
        self.t_begin = now
        self.t_data = now
        self.t_h2d = now
        self.t_disp = now
        self.executable = "compiled_step"
        self.compile_at_begin = compile_at_begin
        self.offload_s = 0.0
        self.straggler_s = 0.0
        self.unix_s = unix_s


class StepTraceRecorder:
    """Bounded recorder of per-train-step telescoping records plus the
    run-level goodput/badput ledger and the online regression detector.
    All methods are host-only and O(1)-ish per step (the detector is
    O(components x window) of float means); registry work happens at
    :meth:`collect` (export boundaries) except the regressions counter,
    bumped once per finding."""

    def __init__(self, capacity: int = 2048, registry=None,
                 ledger: Optional[Callable] = None,
                 timeseries: Optional[Callable] = None,
                 clock: Callable[[], float] = time.perf_counter,
                 regression_window: int = 32,
                 regression_threshold: float = 0.5,
                 regression_min_shift_s: float = 1e-4):
        self.capacity = max(int(capacity), 8)
        self._done: deque[StepRecord] = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._clock = clock
        self._registry = registry
        # zero-arg accessors (stdlib shell: never imports siblings)
        self._ledger_fn = ledger
        self._timeseries_fn = timeseries
        self._cur: Optional[_Pending] = None
        self._prev_end: Optional[float] = None
        self._run_start: Optional[float] = None
        # per-executable calibration baseline: running min of the
        # cleaned dispatch window (recompile/optimizer removed) — the
        # "no interference" device seconds the excess is measured over
        self._baseline: dict[str, float] = {}
        self._has_comm: dict[str, bool] = {}
        # charges accumulated between/inside steps
        self._pending_ckpt = 0.0
        self._pending_restart = 0.0
        # compile seconds already on the listener's books when the
        # run's first step began — the badput `compile` bucket charges
        # the delta since, so pre-run AOT/eval/serving builds never
        # count against the training wall
        self._compile_at_run_start = 0.0
        # run-level accounting (survives ring eviction)
        self._n_steps = 0
        self._wall_s_total = 0.0
        self._device_s_total = 0.0
        self._data_wait_s_total = 0.0
        self._ckpt_s_total = 0.0
        self._restart_s_total = 0.0
        self._straggler_s_total = 0.0
        self._recompile_s_total = 0.0
        self._overflow_total = 0
        self.recon_max_rel_err = 0.0
        # regression detector state
        self.regression_window = max(int(regression_window), 2)
        self.regression_threshold = float(regression_threshold)
        self.regression_min_shift_s = float(regression_min_shift_s)
        self._history: dict[str, deque] = {}
        self._findings: deque[dict] = deque(maxlen=_FINDINGS_CAP)

    # -- configuration -------------------------------------------------
    def set_registry(self, registry) -> None:
        self._registry = registry

    def _ledger(self):
        fn = self._ledger_fn
        if fn is None:
            return None
        return fn() if callable(fn) else fn

    def _compile_total(self) -> float:
        """Process-wide compile seconds so far (every phase), from the
        jax.monitoring listener via the executable ledger; 0.0 when the
        ledger is off (the listener's plain tallies carry counts, not
        seconds)."""
        led = self._ledger()
        if led is None:
            return 0.0
        try:
            return float(sum(led.compile_seconds.values()))
        except Exception:   # noqa: BLE001 - telemetry never raises
            return 0.0

    def _executable_has_comm(self, name: str) -> bool:
        """Does this executable carry collectives (per the ledger's HLO
        accounting)? Sticky-cached once true — collective content is a
        compile-time property of the executable."""
        if self._has_comm.get(name):
            return True
        led = self._ledger()
        if led is None:
            return False
        try:
            has = bool(led.collective_bytes_by_axis(name))
        except Exception:   # noqa: BLE001
            return False
        if has:
            self._has_comm[name] = True
        return has

    # -- per-step lifecycle (engine call sites, probe-guarded) ---------
    def step_begin(self, step: int) -> None:
        """``train_batch`` entered (before the data fetch)."""
        now = self._clock()
        with self._lock:
            compile_now = self._compile_total()
            if self._run_start is None:
                self._run_start = now
                self._compile_at_run_start = compile_now
            self._cur = _Pending(int(step), now, time.time(),
                                 compile_now)

    def data_ready(self) -> None:
        """The batch is in hand (``next(data_iter)`` returned / the
        caller passed one)."""
        with self._lock:
            if self._cur is not None:
                self._cur.t_data = self._clock()

    def h2d_done(self) -> None:
        """Batch staged on device (curriculum slicing + transfer)."""
        with self._lock:
            if self._cur is not None:
                self._cur.t_h2d = self._clock()

    def dispatch_done(self, executable: str = "compiled_step") -> None:
        """The step dispatch returned to the host (with donated state
        the window tracks true per-step device wall in steady state)."""
        with self._lock:
            if self._cur is not None:
                self._cur.t_disp = self._clock()
                self._cur.executable = str(executable)

    def note_checkpoint(self, seconds: float, kind: str = "save") -> None:
        """A checkpoint save/load took ``seconds``. Saves charge the
        ``checkpoint`` telescoping component of the NEXT step (the stall
        sits in the inter-step gap) and the ``checkpoint`` badput
        bucket; loads charge the ``restart`` telescoping component and
        badput bucket (a load mid-run IS the restart cost elasticity
        pays) — save and restart stalls never conflate, so the train
        gate's checkpoint stems only see saves."""
        s = max(float(seconds), 0.0)
        with self._lock:
            if kind == "load":
                self._restart_s_total += s
                self._pending_restart += s
            else:
                self._ckpt_s_total += s
                self._pending_ckpt += s

    def note_offload(self, seconds: float) -> None:
        """Host-side optimizer/offload work inside the current step's
        dispatch window (the NVMe-tier ``nvme_opt_step``)."""
        with self._lock:
            if self._cur is not None:
                self._cur.offload_s += max(float(seconds), 0.0)

    def note_straggler(self, skew_s: float) -> None:
        """A cross-rank skew sample landed for the current step (the
        rate-limited per-step ``record_straggler_skew`` cadence)."""
        s = max(float(skew_s), 0.0)
        with self._lock:
            self._straggler_s_total += s
            if self._cur is not None:
                self._cur.straggler_s += s

    def note_overflow_total(self, n: int) -> None:
        """Latest device-truth overflow-step count (the engine reads
        ``overflow_steps`` at flush boundaries where the sync is
        already paid — mirrors ``ds_overflow_steps_total``)."""
        with self._lock:
            self._overflow_total = max(self._overflow_total, int(n))

    def step_end(self) -> Optional[StepRecord]:
        """Finalize the current step: derive the exact telescoping
        decomposition, update the run ledger and calibration baseline,
        run the regression detector, and append the record."""
        now = self._clock()
        with self._lock:
            cur, self._cur = self._cur, None
            if cur is None:
                return None
            rec = self._finalize(cur, now)
            self._done.append(rec)
            # detection mutates _history/_findings, which clear()
            # resets under this same lock — keep it inside (it is
            # O(components x window) on floats, cheap)
            self._detect(rec)
        ts_fn = self._timeseries_fn
        ring = ts_fn() if callable(ts_fn) else None
        if ring is not None:
            try:
                ring.maybe_sample(self._registry)
            except Exception:   # noqa: BLE001
                pass
        return rec

    def _finalize(self, cur: _Pending, now: float) -> StepRecord:
        prev_end = self._prev_end
        self._prev_end = now
        gap = (max(cur.t_begin - prev_end, 0.0)
               if prev_end is not None else 0.0)
        fetch = max(cur.t_data - cur.t_begin, 0.0)
        h2d = max(cur.t_h2d - cur.t_data, 0.0)
        window = max(cur.t_disp - cur.t_h2d, 0.0)
        tail = max(now - cur.t_disp, 0.0)
        step_wall = gap + fetch + h2d + window + tail

        # inter-step gap: the checkpoint (save) stall first, then the
        # restart (load) stall, data wait takes the rest (plus the
        # in-step fetch)
        ckpt = min(self._pending_ckpt, gap)
        self._pending_ckpt = max(self._pending_ckpt - ckpt, 0.0)
        restart = min(self._pending_restart, gap - ckpt)
        self._pending_restart = max(self._pending_restart - restart, 0.0)
        data_wait = (gap - ckpt - restart) + fetch

        # dispatch window: compile charge (the listener's per-phase
        # seconds delta across the step — first-sight ledger AOT
        # registration included), then host optimizer/offload, then
        # the calibrated device baseline; the excess over the baseline
        # is exposed comm on a collective-carrying executable, host
        # jitter otherwise
        recompile = min(max(self._compile_total() - cur.compile_at_begin,
                            0.0), window)
        optimizer = min(cur.offload_s, window - recompile)
        cleaned = window - recompile - optimizer
        base = self._baseline.get(cur.executable)
        if recompile <= 0.0:
            # only compile-free steps calibrate: a compiling step's
            # cleaned window is whatever scraps the build left over,
            # not a device measurement — as a running-min seed it
            # would zero device_compute for the whole run
            base = cleaned if base is None else min(base, cleaned)
            self._baseline[cur.executable] = base
        device_compute = cleaned if base is None else min(base, cleaned)
        excess = cleaned - device_compute
        if self._executable_has_comm(cur.executable):
            exposed_comm = excess
            dispatch_overhead = tail
        else:
            exposed_comm = 0.0
            dispatch_overhead = tail + excess

        components = {
            "data_wait": data_wait, "h2d": h2d,
            "dispatch_overhead": dispatch_overhead,
            "device_compute": device_compute,
            "exposed_comm": exposed_comm, "optimizer": optimizer,
            "checkpoint": ckpt, "restart": restart,
            "recompile": recompile}
        components["residual"] = step_wall - sum(components.values())
        recon = (abs(step_wall - sum(components.values()))
                 / max(step_wall, 1e-12))
        self.recon_max_rel_err = max(self.recon_max_rel_err, recon)

        self._n_steps += 1
        self._wall_s_total += step_wall
        self._device_s_total += device_compute
        self._data_wait_s_total += data_wait
        self._recompile_s_total += recompile
        return StepRecord(cur.step, cur.unix_s, cur.executable, now,
                          step_wall, components, cur.straggler_s, recon)

    # -- regression detector -------------------------------------------
    def _detect(self, rec: StepRecord) -> None:
        """Sliding-window mean-shift changepoint per component: the
        mean of the last W steps against the mean of the W before
        them. The warmup step (first record — XLA compile) never
        enters the history."""
        if self._n_steps <= 1:
            return
        w = self.regression_window
        series = dict(rec.components)
        series["step_wall"] = rec.step_wall
        for name, value in series.items():
            hist = self._history.setdefault(name, deque(maxlen=2 * w))
            hist.append(value)
            if len(hist) < 2 * w:
                continue
            vals = list(hist)
            base = sum(vals[:w]) / w
            recent = sum(vals[w:]) / w
            shift = recent - base
            if (shift < self.regression_min_shift_s
                    or recent <= base * (1.0 + self.regression_threshold)):
                continue
            owner = (rec.executable if name in _DEVICE_COMPONENTS
                     or name == "step_wall" else "host")
            finding = {"step": rec.step, "component": name,
                       "executable": owner,
                       "base_mean_s": round(base, 6),
                       "recent_mean_s": round(recent, 6),
                       "shift_s": round(shift, 6),
                       "ratio": round(recent / max(base, 1e-12), 4)}
            self._findings.append(finding)
            hist.clear()    # re-baseline: one finding per shift
            reg = self._registry
            if reg is not None:
                reg.counter(
                    "ds_steptrace_regressions_total",
                    "mean-shift changepoints detected in the per-step "
                    "component series (the finding names the moved "
                    "component, its owning executable, and the step)"
                ).inc(component=name)

    # -- run-level goodput/badput ledger -------------------------------
    def goodput_summary(self, now: Optional[float] = None) -> dict:
        """Run-level ledger: goodput fraction = productive device
        seconds / wall since the first step; badput bucketed per
        :data:`BADPUT_BUCKETS`. The ``compile`` bucket is the compile
        seconds accrued SINCE the run's first step (delta over the
        listener's books at run start — pre-run AOT/eval/serving
        builds never charge the training wall). The ``overflow``
        bucket charges the skipped-step count
        (``ds_overflow_steps_total``) at the mean step wall — the
        whole step was spent to apply nothing."""
        with self._lock:
            n = self._n_steps
            if n == 0 or self._run_start is None:
                return {"steps": 0, "goodput_fraction": 0.0,
                        "productive_device_s": 0.0, "wall_s": 0.0,
                        "recon_max_rel_err": self.recon_max_rel_err,
                        "badput_seconds": dict.fromkeys(BADPUT_BUCKETS,
                                                        0.0)}
            t = self._clock() if now is None else float(now)
            wall = max(t - self._run_start, 1e-12)
            mean_wall = self._wall_s_total / n
            mean_dev = self._device_s_total / n
            overflow_s = self._overflow_total * mean_wall
            productive = max(self._device_s_total
                             - self._overflow_total * mean_dev, 0.0)
            badput = {
                "compile": max(self._compile_total()
                               - self._compile_at_run_start, 0.0),
                "overflow": overflow_s,
                "checkpoint": self._ckpt_s_total,
                "data_wait": self._data_wait_s_total,
                "straggler": self._straggler_s_total,
                "restart": self._restart_s_total}
            return {"steps": n,
                    "goodput_fraction": min(productive / wall, 1.0),
                    "productive_device_s": productive, "wall_s": wall,
                    "overflow_steps": self._overflow_total,
                    "recon_max_rel_err": self.recon_max_rel_err,
                    "badput_seconds": badput}

    # -- registry export -----------------------------------------------
    def collect(self, reg=None, now: Optional[float] = None) -> None:
        """Goodput/badput/recon gauges + component p50/p99 gauges from
        the step ring (export boundaries only)."""
        reg = reg if reg is not None else self._registry
        if reg is None:
            return
        s = self.goodput_summary(now=now)
        if not s["steps"]:
            return
        reg.gauge("ds_train_goodput_fraction",
                  "productive device seconds / run wall seconds "
                  "(steptrace run ledger)").set(
            round(s["goodput_fraction"], 6))
        bad = reg.gauge(
            "ds_train_badput_seconds",
            "run seconds lost per badput bucket: compile, overflow-"
            "skipped steps, checkpoint saves, data wait, straggler "
            "skew, restart (checkpoint loads)")
        for bucket, v in s["badput_seconds"].items():
            bad.set(round(v, 6), bucket=bucket)
        reg.gauge("ds_steptrace_recon_max_rel_err",
                  "worst per-step telescoping reconciliation error "
                  "(|sum(components) - step_wall| / step_wall; float "
                  "noise only — the decomposition is exact by "
                  "construction)").set(self.recon_max_rel_err)
        reg.gauge("ds_steptrace_steps",
                  "training steps the steptrace recorder finalized"
                  ).set(s["steps"])
        pcts = self.component_percentiles()
        if pcts:
            p50 = reg.gauge("ds_train_step_component_p50_seconds",
                            "median per-step telescoping component "
                            "over the step-record ring")
            p99 = reg.gauge("ds_train_step_component_p99_seconds",
                            "p99 per-step telescoping component over "
                            "the step-record ring")
            for name, row in pcts.items():
                p50.set(round(row["p50"], 6), component=name)
                p99.set(round(row["p99"], 6), component=name)

    # -- readers -------------------------------------------------------
    def completed(self) -> list[StepRecord]:
        with self._lock:
            return list(self._done)

    @property
    def steps_recorded(self) -> int:
        return self._n_steps

    def component_percentiles(self) -> dict[str, dict]:
        """{component: {p50, p99, mean, n}} seconds over the step ring
        (``step_wall`` rides along as a pseudo-component)."""
        rows = self.completed()
        if not rows:
            return {}
        out = {}
        for name in COMPONENT_KEYS + ("step_wall",):
            vals = sorted((r.step_wall if name == "step_wall"
                           else r.components[name]) for r in rows)
            out[name] = {"p50": vals[len(vals) // 2],
                         "p99": vals[min(len(vals) - 1,
                                         int(len(vals) * 0.99))],
                         "mean": sum(vals) / len(vals), "n": len(vals)}
        return out

    def regressions(self) -> list[dict]:
        return list(self._findings)

    def last_steps(self, n: int = 16) -> list[dict]:
        """The last ``n`` step-log rows — the hang-watchdog dump's
        'what were the recent steps doing' section."""
        rows = self.completed()[-max(int(n), 1):]
        return [r.log_row() for r in rows]

    # -- artifact export -----------------------------------------------
    def write_step_log(self, path: str) -> Optional[str]:
        """JSONL, one :data:`STEP_LOG_KEYS` line per finalized step.
        Returns the path, or None when no step completed."""
        rows = self.completed()
        if not rows:
            return None
        with open(path, "w") as f:
            for r in rows:
                f.write(json.dumps(r.log_row(), sort_keys=True) + "\n")
        return path

    def chrome_events(self, pid: int, epoch_ns: int) -> list[dict]:
        """Two named tracks for the Chrome-trace export: one slice per
        step, and the telescoped components laid out sequentially
        inside each step's window (exact by construction, so the
        component track tiles the step track with no gaps). ``epoch_ns``
        is the span tracer's epoch so the tracks share the host-span
        timebase; tids sit clear of real thread ids AND the reqtrace
        request tracks (0x52xxxx)."""
        rows = self.completed()
        if not rows:
            return []
        tid_steps, tid_comp = 0x570000, 0x570001
        events: list[dict] = [
            {"name": "thread_name", "ph": "M", "pid": pid,
             "tid": tid_steps, "args": {"name": "train steps"}},
            {"name": "thread_name", "ph": "M", "pid": pid,
             "tid": tid_comp, "args": {"name": "train step components"}},
        ]

        def us(t: float) -> float:
            return round((t * 1e9 - epoch_ns) / 1e3, 3)

        for r in rows:
            t0 = r.t_end - r.step_wall
            events.append({
                "name": f"step {r.step}", "ph": "X", "ts": us(t0),
                "dur": round(r.step_wall * 1e6, 3), "pid": pid,
                "tid": tid_steps, "cat": "steptrace",
                "args": {"step": r.step, "executable": r.executable,
                         "recon_rel_err": r.recon_rel_err}})
            cur = t0
            for name in COMPONENT_KEYS:
                v = r.components[name]
                if v <= 0:
                    continue
                events.append({
                    "name": f"step/{name}", "ph": "X", "ts": us(cur),
                    "dur": round(v * 1e6, 3), "pid": pid,
                    "tid": tid_comp, "cat": "steptrace",
                    "args": {"step": r.step}})
                cur += v
        return events

    def clear(self) -> None:
        with self._lock:
            self._done.clear()
            self._cur = None
            self._prev_end = None
            self._run_start = None
            self._baseline.clear()
            self._has_comm.clear()
            self._pending_ckpt = 0.0
            self._pending_restart = 0.0
            self._compile_at_run_start = 0.0
            self._n_steps = 0
            self._wall_s_total = 0.0
            self._device_s_total = 0.0
            self._data_wait_s_total = 0.0
            self._ckpt_s_total = 0.0
            self._restart_s_total = 0.0
            self._straggler_s_total = 0.0
            self._recompile_s_total = 0.0
            self._overflow_total = 0
            self.recon_max_rel_err = 0.0
            self._history.clear()
            self._findings.clear()


# --- module-level current recorder (wired by telemetry.configure) --------

_RECORDER: Optional[StepTraceRecorder] = None


def get_step_recorder() -> Optional[StepTraceRecorder]:
    return _RECORDER


def set_step_recorder(rec: Optional[StepTraceRecorder]) -> None:
    global _RECORDER
    _RECORDER = rec
