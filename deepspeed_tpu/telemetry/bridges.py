"""Bridges from existing signal sources into the telemetry registry
(ISSUE 2 tentpole part 2b).

Each collector reads one legacy/framework surface and mirrors it into
Prometheus-style metrics:

- ``install_jax_compile_listener`` — ``jax.monitoring`` duration events
  (jit trace / lowering / backend compile) -> compile count + seconds.
- ``collect_memory`` — /proc/self/status VmRSS+VmHWM and PJRT device
  ``memory_stats()`` -> host/device memory gauges.
- ``collect_comms`` — ``CommsLogger`` per-op call/byte tallies ->
  ``ds_comm_*_total`` counters.
- ``collect_serving`` — ``InferenceEngineV2.serving_metrics()`` ->
  serving counters + efficiency gauges.
- ``collect_throughput`` — ``ThroughputTimer`` -> samples/s + TFLOPS.
- ``flush_to_monitor`` — registry snapshot -> ``MonitorMaster`` events,
  so CSV/TensorBoard/W&B see everything the registry holds.

All collectors are cheap, idempotent, and safe to call at flush
boundaries only — never per token.
"""

from __future__ import annotations

from typing import Optional

from . import ledger as _ledger_mod, registry as _registry_mod
from .registry import MetricsRegistry

_JAX_LISTENER_INSTALLED = False

# plain process-wide compile-event tallies, independent of the registry
# lifecycle: the analysis/sentinels.py recompile sentinel reads these, so
# it works with telemetry configured OR shut down (the registry mirror
# below additionally feeds ds_jax_compile_total when active)
_COMPILE_EVENTS: dict[str, int] = {}


def compile_event_count(phase: str = "backend_compile") -> int:
    """Monotonic count of jax compile-path events seen by this process's
    listener. ``backend_compile`` fires exactly once per executable
    built (trace/lowering phases can fire more) — the signal the
    recompile sentinel watches. Returns 0 until the listener is
    installed."""
    return _COMPILE_EVENTS.get(phase, 0)


def install_jax_compile_listener() -> None:
    """Capture jit compile count/time via ``jax.monitoring``. Installed
    once per process; the registry half reads the live registry on each
    event, so it no-ops after ``telemetry.shutdown()`` (jax offers no
    per-listener removal) while the plain tallies keep counting for the
    sentinels."""
    global _JAX_LISTENER_INSTALLED
    if _JAX_LISTENER_INSTALLED:
        return
    import jax

    def _on_duration(name: str, dur_s: float, **kw) -> None:
        if "/compile/" not in name:
            return
        phase = name.rsplit("/", 1)[-1]
        if phase.endswith("_duration"):
            phase = phase[: -len("_duration")]
        _COMPILE_EVENTS[phase] = _COMPILE_EVENTS.get(phase, 0) + 1
        # the executable ledger tracks process-wide compile time per
        # phase (every newly compiled executable announces itself
        # here, whether or not a call site ever observe()s it)
        led = _ledger_mod.get_ledger()
        if led is not None:
            led.on_compile_event(phase, dur_s)
        reg = _registry_mod.get_registry()
        if reg is None:
            return
        reg.counter("ds_jax_compile_total",
                    "jax compile-path events by phase").inc(phase=phase)
        reg.counter("ds_jax_compile_seconds_total",
                    "cumulative seconds in jax compile phases").inc(
            dur_s, phase=phase)

    jax.monitoring.register_event_duration_secs_listener(_on_duration)
    _JAX_LISTENER_INSTALLED = True


def collect_memory(reg: MetricsRegistry) -> None:
    """Host VmRSS/VmHWM + device memory stats as gauges."""
    host = reg.gauge("ds_host_memory_bytes",
                     "host process memory from /proc/self/status")
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    host.set(int(line.split()[1]) * 1024, kind="rss")
                elif line.startswith("VmHWM:"):
                    host.set(int(line.split()[1]) * 1024, kind="hwm")
    except OSError:
        pass  # no procfs (VmHWM is also absent on some sandboxed kernels)
    from ..utils.memory import device_memory_stats
    stats = device_memory_stats()
    if stats:
        dev = reg.gauge("ds_device_memory_bytes",
                        "PJRT device memory stats (device 0)")
        for key, kind in (("bytes_in_use", "in_use"),
                          ("peak_bytes_in_use", "peak"),
                          ("bytes_limit", "limit")):
            if key in stats:
                dev.set(float(stats[key]), kind=kind)


def collect_comms(reg: MetricsRegistry, comms_logger=None) -> None:
    """CommsLogger per-op tallies -> counters (absolute mirror)."""
    if comms_logger is None:
        from .. import comm as dist
        comms_logger = dist.get_comms_logger()
    if comms_logger is None:
        return
    calls = reg.counter("ds_comm_calls_total",
                        "collective calls recorded at trace time")
    byts = reg.counter("ds_comm_bytes_total",
                       "collective payload bytes recorded at trace time")
    for op, sizes in comms_logger.comms_dict.items():
        n = sum(sizes.values())
        b = sum(cnt * sz for sz, cnt in sizes.items())
        calls.set_total(n, op=op)
        byts.set_total(b, op=op)


# serving counters mirrored 1:1 from InferenceEngineV2.serving_stats,
# plus the prefix-cache counters (schema shared with ragged.py's
# PREFIX_STAT_KEYS so the key set cannot drift from what
# serving_metrics() emits). Resolved lazily: importing the inference
# package here would pull jax + the model zoo into every telemetry
# process, serving or not.
_SERVING_COUNTERS_BASE = ("decoded_tokens", "host_dispatches",
                          "fused_dispatches", "fused_steps",
                          "spec_proposed_tokens",
                          "spec_accepted_tokens", "spec_hit_slots")
_SERVING_GAUGES = ("dispatches_per_token", "fused_occupancy",
                   "max_inflight_dispatches",
                   "tokens_per_dispatch", "spec_acceptance_rate",
                   "prefix_hit_rate", "prefix_cached_blocks",
                   "prefix_evictable_blocks")


def _serving_counter_keys() -> tuple:
    import sys
    ragged = sys.modules.get("deepspeed_tpu.inference.v2.ragged")
    if ragged is None:
        # no engine loaded -> nothing beyond the base counters can be
        # present in the metrics dict anyway
        return _SERVING_COUNTERS_BASE
    return _SERVING_COUNTERS_BASE + ragged.PREFIX_STAT_KEYS


def collect_serving(reg: MetricsRegistry, serving_metrics: dict,
                    engine_label: str = "v2") -> None:
    """``InferenceEngineV2.serving_metrics()`` -> registry."""
    for key in _serving_counter_keys():
        if key in serving_metrics:
            reg.counter(f"ds_serving_{key}_total",
                        f"serving counter {key}").set_total(
                serving_metrics[key], engine=engine_label)
    for key in _SERVING_GAUGES:
        if key in serving_metrics:
            reg.gauge(f"ds_serving_{key}",
                      f"decode-loop efficiency ratio {key}").set(
                serving_metrics[key], engine=engine_label)
    # quantized KV cache (ISSUE 12): pool footprint gauges carry the
    # storage format as a label so fp16/int8/fp8 pools chart as
    # distinct series at one glance
    if "kv_pool_bytes" in serving_metrics:
        kv_dtype = str(serving_metrics.get("kv_dtype", "unknown"))
        reg.gauge("ds_kv_pool_bytes",
                  "HBM bytes of the paged KV pools (payload + scale "
                  "slabs)").set(serving_metrics["kv_pool_bytes"],
                                dtype=kv_dtype, engine=engine_label)
        reg.gauge("ds_kv_bytes_per_token",
                  "KV bytes one cached token costs across all layers "
                  "(k+v, scales included)").set(
            serving_metrics.get("kv_bytes_per_token", 0.0),
            dtype=kv_dtype, engine=engine_label)
        reg.gauge("ds_kv_num_blocks",
                  "blocks in the paged KV pool (grown past "
                  "num_kv_blocks when the quantized pool fills the "
                  "full-precision HBM budget)").set(
            serving_metrics.get("kv_num_blocks", 0),
            dtype=kv_dtype, engine=engine_label)


def collect_ledger(reg: MetricsRegistry, peak_flops: float = 0.0) -> None:
    """Executable-ledger state -> registry (ISSUE 5): per-jit-name MFU
    from ledger FLOPs x span seconds, peak HBM per executable name,
    HBM headroom against the device limit, and the per-(mesh axis, op)
    HLO collective traffic counters. No-op (zero allocations) when the
    ledger is off."""
    led = _ledger_mod.get_ledger()
    if led is None:
        return
    from . import spans as _spans_mod
    reg.gauge("ds_ledger_executables",
              "compiled executables registered in the cost ledger"
              ).set(len(led))
    peak = _ledger_mod.device_peak_flops(peak_flops)
    tracer = _spans_mod.get_tracer()
    if tracer is not None:
        mfu = reg.gauge(
            "ds_mfu", "model FLOPs utilization per instrumented jit "
            "name: ledger FLOPs x dispatches / measured span seconds "
            "/ device peak (steady-state: the warmup span, which "
            "includes the XLA compile, is trimmed; still a lower "
            "bound — span time includes host overhead around the "
            "device work)")
        for name, value in led.mfu_by_name(tracer.totals_trimmed(),
                                           peak).items():
            mfu.set(value, name=name)
    flops_total = reg.counter(
        "ds_ledger_dispatched_flops_total",
        "FLOPs dispatched per jit name (executable FLOPs x calls)")
    for name, flops in led.dispatched_flops().items():
        flops_total.set_total(flops, name=name)
    hbm = reg.gauge("ds_ledger_peak_hbm_bytes",
                    "compiler-reported peak HBM per executable name "
                    "(max over live shape signatures)")
    max_peak = 0
    for name, peak_bytes in led.peak_hbm_by_name().items():
        hbm.set(peak_bytes, name=name)
        max_peak = max(max_peak, peak_bytes)
    from ..utils.memory import device_memory_stats
    limit = float(device_memory_stats().get("bytes_limit", 0) or 0)
    if limit > 0 and max_peak > 0:
        reg.gauge("ds_hbm_headroom_bytes",
                  "device memory limit minus the largest registered "
                  "executable's peak HBM").set(limit - max_peak)
    traffic = led.traffic()
    if traffic:
        byts = reg.counter(
            "ds_hlo_collective_bytes_total",
            "collective payload bytes from HLO accounting, dispatch-"
            "weighted, attributed to mesh axes")
        sites = reg.counter(
            "ds_hlo_collective_sites_total",
            "collective instruction sites in registered executables")
        for (axis, op), row in traffic.items():
            byts.set_total(row["bytes"], axis=axis, op=op)
            sites.set_total(row["sites"], axis=axis, op=op)
        wire = reg.gauge(
            "ds_hlo_wire_bytes_per_el",
            "observed collective wire width per mesh axis "
            "(bytes/element; ~1.1 when ZeRO++ qwZ/qgZ int8 payloads "
            "+ fp32 block scales carry the traffic, 4.0 at fp32)")
        from .collectives import axis_wire_width
        for axis, width in axis_wire_width(traffic).items():
            wire.set(round(width, 4), axis=axis)


def collect_throughput(reg: MetricsRegistry, tput_timer) -> None:
    """``ThroughputTimer`` -> samples/s (+ TFLOPS when configured)."""
    sps = tput_timer.avg_samples_per_sec()
    reg.gauge("ds_train_samples_per_second",
              "training throughput (ThroughputTimer)").set(sps)
    if getattr(tput_timer, "flops_per_sample", None):
        reg.gauge("ds_train_tflops",
                  "estimated training TFLOPS").set(tput_timer.tflops())


def record_train_step(reg: MetricsRegistry, engine, metrics) -> None:
    """Engine step-boundary metrics (called at steps_per_print
    boundaries, where the device sync is already paid)."""
    reg.counter("ds_train_steps_total",
                "engine steps taken").set_total(engine.global_steps)
    reg.counter("ds_train_samples_total",
                "samples consumed").set_total(engine.global_samples)
    reg.counter("ds_train_skipped_steps_total",
                "overflow-skipped optimizer steps").set_total(
        engine.skipped_steps)
    # device-truth overflow count (ISSUE 18): global_steps minus the
    # on-device applied-step counter — covers the compiled path, which
    # never tallies skipped_steps on the host
    ov = getattr(engine, "overflow_steps", None)
    if ov is not None:
        reg.counter("ds_overflow_steps_total",
                    "fp16 overflow steps (optimizer update skipped, "
                    "loss scale backed off) — derived from the "
                    "on-device applied-step counter").set_total(int(ov))
    if metrics:
        if "loss" in metrics:
            reg.gauge("ds_train_loss", "last reported loss").set(
                float(metrics["loss"]))
        if "grad_norm" in metrics:
            reg.gauge("ds_train_grad_norm",
                      "last reported global gradient norm").set(
                float(metrics["grad_norm"]))
        if "loss_scale" in metrics:
            reg.gauge("ds_train_loss_scale", "live fp16 loss scale").set(
                float(metrics["loss_scale"]))
    tput = getattr(engine, "tput_timer", None)
    if tput is not None:
        collect_throughput(reg, tput)
    collect_memory(reg)
    collect_comms(reg)
    collect_ledger(reg)


def flush_to_monitor(monitor, step: int,
                     reg: Optional[MetricsRegistry] = None,
                     prefix: str = "Telemetry") -> int:
    """Write the registry's scalar view through MonitorMaster so the
    CSV/TensorBoard/W&B backends chart it. Returns event count."""
    reg = reg if reg is not None else _registry_mod.get_registry()
    if reg is None or monitor is None or not getattr(monitor, "enabled",
                                                     False):
        return 0
    events = reg.events_for_monitor(step, prefix=prefix)
    if events:
        monitor.write_events(events)
    return len(events)
