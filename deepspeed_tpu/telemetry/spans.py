"""Host-side span tracer (ISSUE 2 tentpole part 1).

A span is a named host-wall-clock interval with tags (step, dispatch_id,
request_id, ...). Spans nest (per-thread depth counter), land in a
bounded per-rank ring buffer, and — when ``profiler_annotations`` is on —
simultaneously open a ``jax.profiler.TraceAnnotation`` so the same range
appears in XLA's XPlane trace next to the device timeline.

Export is Chrome-trace-event JSON (``ph:"X"`` complete events with
``ts``/``dur`` in microseconds), loadable directly in Perfetto
(https://ui.perfetto.dev) or chrome://tracing.

Overhead contract: this module is only imported once telemetry is
configured; call sites in the hot loops (runtime/engine.py,
inference/v2/engine_v2.py) probe ``sys.modules`` instead of importing,
so the disabled path allocates nothing and pays one dict lookup.
"""

from __future__ import annotations

import functools
import json
import threading
import time
from collections import deque
from typing import Any, Callable, Optional


class Span:
    """One recorded interval. ``ts_us`` is microseconds since the
    tracer's epoch; ``dur_us`` the measured duration."""

    __slots__ = ("name", "ts_us", "dur_us", "depth", "tid", "args")

    def __init__(self, name: str, ts_us: float, dur_us: float,
                 depth: int, tid: int, args: Optional[dict]):
        self.name = name
        self.ts_us = ts_us
        self.dur_us = dur_us
        self.depth = depth
        self.tid = tid
        self.args = args

    def to_chrome(self, pid: int) -> dict:
        ev = {"name": self.name, "ph": "X", "ts": round(self.ts_us, 3),
              "dur": round(self.dur_us, 3), "pid": pid, "tid": self.tid,
              "cat": "host"}
        if self.args:
            ev["args"] = dict(self.args)
        return ev


class _NullContext:
    """Shared no-op context manager — what ``span()`` hands out when
    tracing is off, so disabled call sites allocate nothing."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


NULL_CONTEXT = _NullContext()


class _SpanContext:
    __slots__ = ("_tracer", "_name", "_args", "_t0", "_ann", "_depth")

    def __init__(self, tracer: "SpanTracer", name: str, args: dict):
        self._tracer = tracer
        self._name = name
        self._args = args
        self._ann = None

    def __enter__(self):
        tls = self._tracer._tls
        self._depth = getattr(tls, "depth", 0)
        tls.depth = self._depth + 1
        if self._tracer.profiler_annotations:
            import jax
            self._ann = jax.profiler.TraceAnnotation(self._name)
            self._ann.__enter__()
        self._t0 = time.perf_counter_ns()
        # open-span stack for the hang watchdog's dump: each thread
        # appends/pops only its own list, so no lock is needed
        self._tracer._open.setdefault(
            threading.get_ident(), []).append((self._name, self._t0))
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter_ns()
        if self._ann is not None:
            self._ann.__exit__(exc_type, exc, tb)
        stack = self._tracer._open.get(threading.get_ident())
        if stack:
            stack.pop()
        self._tracer._tls.depth = self._depth
        self._tracer._record(
            self._name,
            (self._t0 - self._tracer._epoch_ns) / 1e3,
            (t1 - self._t0) / 1e3,
            self._depth, threading.get_ident() & 0xFFFFFFFF, self._args)
        return False


class SpanTracer:
    """Per-process span recorder with a bounded ring buffer.

    The ring (``capacity`` spans, oldest dropped first) bounds memory on
    long runs; cumulative per-name totals survive ring eviction, so
    breakdown reporting and the comms-bandwidth window stay exact even
    when individual events have rotated out.
    """

    def __init__(self, capacity: int = 8192,
                 profiler_annotations: bool = True):
        self.capacity = int(capacity)
        self.profiler_annotations = bool(profiler_annotations)
        self._epoch_ns = time.perf_counter_ns()
        self.epoch_unix = time.time()
        self._buf: deque[Span] = deque(maxlen=self.capacity)
        self._tls = threading.local()
        # thread ident -> stack of (name, t0_ns) for spans currently
        # ENTERED but not exited — what a hang dump reports the host
        # was inside when the loop stalled
        self._open: dict[int, list] = {}
        self._lock = threading.Lock()
        # name -> [total_seconds, count]; never evicted (bounded by the
        # number of distinct span names, not the number of events)
        self._totals: dict[str, list] = {}
        # name -> max single-span seconds (survives eviction); lets
        # steady-state consumers (MFU) trim the warmup outlier
        self._maxes: dict[str, float] = {}
        # drain marks: consumer key -> {name: [seconds, count]} snapshot
        self._marks: dict[str, dict[str, tuple]] = {}
        # depth-0 seconds only (survives ring eviction); kept separate
        # from _totals so a name recorded at BOTH top level and nested
        # (e.g. v2/dispatch standalone vs under v2/prefill) never
        # double-counts in window_seconds()
        self._depth0_seconds = 0.0
        self.recorded = 0

    # ------------------------------------------------------------------
    def span(self, name: str, **args: Any) -> _SpanContext:
        """Context manager recording one span; kwargs become Chrome
        ``args`` tags (step / dispatch_id / request_id / ...)."""
        return _SpanContext(self, name, args or None)

    def trace(self, func: Optional[Callable] = None, *,
              name: Optional[str] = None):
        """Decorator form: ``@tracer.trace`` or ``@tracer.trace(name=...)``."""
        def wrap(f):
            label = name or f.__qualname__

            @functools.wraps(f)
            def inner(*a, **kw):
                with self.span(label):
                    return f(*a, **kw)
            return inner
        return wrap(func) if func is not None else wrap

    def _record(self, name, ts_us, dur_us, depth, tid, args):
        with self._lock:
            self._buf.append(Span(name, ts_us, dur_us, depth, tid, args))
            tot = self._totals.setdefault(name, [0.0, 0])
            tot[0] += dur_us / 1e6
            tot[1] += 1
            if dur_us / 1e6 > self._maxes.get(name, 0.0):
                self._maxes[name] = dur_us / 1e6
            if depth == 0:
                self._depth0_seconds += dur_us / 1e6
            self.recorded += 1

    @property
    def epoch_ns(self) -> int:
        """The tracer's time origin (``perf_counter_ns`` at
        construction/clear) — exporters producing events on the same
        timeline (the per-request tracks) convert through this."""
        return self._epoch_ns

    # ------------------------------------------------------------------
    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._buf)

    def totals(self) -> dict[str, tuple[float, int]]:
        """Cumulative {name: (seconds, count)} since construction/clear."""
        with self._lock:
            return {k: (v[0], v[1]) for k, v in self._totals.items()}

    def totals_trimmed(self) -> dict[str, tuple[float, int]]:
        """Cumulative {name: (seconds, count)} with each name's single
        LONGEST span removed when it has more than one — steady-state
        accounting that excludes the warmup occurrence (whose duration
        includes trace + XLA compile). Names with one span pass
        through untrimmed."""
        with self._lock:
            out = {}
            for name, (sec, cnt) in ((k, (v[0], v[1]))
                                     for k, v in self._totals.items()):
                if cnt > 1:
                    out[name] = (sec - self._maxes.get(name, 0.0),
                                 cnt - 1)
                else:
                    out[name] = (sec, cnt)
            return out

    def drain_totals(self, consumer: str = "default") \
            -> dict[str, tuple[float, int]]:
        """Per-name (seconds, count) accumulated since this consumer's
        previous drain. Independent consumers (monitor flush, comms
        window) each get their own mark, so one reader cannot starve
        another."""
        with self._lock:
            mark = self._marks.get(consumer, {})
            out = {}
            for name, (sec, cnt) in ((k, v) for k, v in
                                     self._totals.items()):
                psec, pcnt = mark.get(name, (0.0, 0))
                if cnt > pcnt:
                    out[name] = (sec - psec, cnt - pcnt)
            self._marks[consumer] = {k: (v[0], v[1])
                                     for k, v in self._totals.items()}
            return out

    def open_spans(self) -> list[dict]:
        """Spans currently entered and not yet exited, innermost last
        per thread — the hang watchdog's 'where was the host stuck'
        view. Reads other threads' stacks without a lock (each entry
        is an immutable tuple; a torn read worst-case misses one
        in-flight span)."""
        now = time.perf_counter_ns()
        out = []
        for tid, stack in list(self._open.items()):
            for depth, item in enumerate(list(stack)):
                name, t0 = item
                out.append({"tid": tid & 0xFFFFFFFF, "name": name,
                            "depth": depth,
                            "elapsed_s": (now - t0) / 1e9})
        return out

    def window_seconds(self) -> float:
        """Total measured wall time of top-level (depth-0) spans. The
        comms logger uses this as the measured window over which
        collective bytes moved — a lower bound on bandwidth, since XLA
        overlaps collectives with compute inside the window. Only
        depth-0 durations count, so nested occurrences (even of a name
        that also appears at top level) are never double counted."""
        with self._lock:
            return self._depth0_seconds

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self._open.clear()
            self._totals.clear()
            self._maxes.clear()
            self._marks.clear()
            self._depth0_seconds = 0.0
            self.recorded = 0
            self._epoch_ns = time.perf_counter_ns()
            self.epoch_unix = time.time()

    # ------------------------------------------------------------------
    def chrome_trace(self) -> dict:
        """Chrome-trace-event JSON object (Perfetto-loadable)."""
        import jax
        pid = jax.process_index()
        events: list[dict] = [
            {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": f"deepspeed_tpu rank {pid} (host)"}},
        ]
        for s in self.spans():
            events.append(s.to_chrome(pid))
        return {"traceEvents": events,
                "displayTimeUnit": "ms",
                "otherData": {
                    "epoch_unix_s": self.epoch_unix,
                    "recorded_spans": self.recorded,
                    "ring_capacity": self.capacity,
                }}

    def export_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path


# --- module-level current tracer (wired by telemetry.configure) ---------

_TRACER: Optional[SpanTracer] = None


def get_tracer() -> Optional[SpanTracer]:
    return _TRACER


def set_tracer(tracer: Optional[SpanTracer]) -> None:
    global _TRACER
    _TRACER = tracer


def span(name: str, **args: Any):
    """Record a span under the current tracer; no-op (shared null
    context, zero allocation) when tracing is off."""
    t = _TRACER
    return t.span(name, **args) if t is not None else NULL_CONTEXT
