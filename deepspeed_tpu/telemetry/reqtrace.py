"""Per-request lifecycle tracing + tail-latency attribution for the
serving stack (ISSUE 10 tentpole).

The serving telemetry so far is aggregate: the TTFT/ITL histograms say
p99 is slow without saying WHICH request was slow or WHY (queue wait?
cold prefill? a preemption park? a chain-boundary drain gap?). This
module records one host-side trace per request driven through
:class:`~...inference.v2.serve_loop.FusedServeLoop` (closed-loop
``generate_fused``, the per-tick ``generate`` driver, and the async
``deepspeed_tpu.serving`` front end all ride it): every lifecycle
event — enqueue, admission (priority, queue depth at entry,
prefix-cache blocks hit), prefill, fused dispatches participated in,
token drains, preemption park/restore, cancel, completion — lands in a
bounded per-request event list, and at completion the recorder derives
an EXACT latency decomposition:

- ``TTFT = queue_wait + prefill + first_drain`` (telescoping event
  timestamps, so the components reconcile with the measured TTFT by
  construction);
- decode time (first token -> last token) splits into
  ``decode_active`` (inside a dispatch-chain window: device compute +
  dispatch RTT), ``boundary_gap`` (between chains: the host doing
  admission/prefill/housekeeping for OTHER requests), and
  ``preempt_stall`` (parked by a higher-priority arrival until the
  next token after restore).

Three export surfaces (all flush-boundary, never per token):

- per-request async tracks appended to the Chrome-trace/Perfetto
  export (one named track per request; composable with
  ``telemetry_report --merge``);
- a structured JSONL access log, one line per completed request
  (:data:`ACCESS_LOG_KEYS`);
- registry metrics: ``ds_serving_component_seconds{component}``
  histograms and ``ds_serving_request_ttft_seconds`` carrying
  OpenMetrics trace-id EXEMPLARS (a p99 bucket links to a concrete
  trace), component p50/p99 gauges, and SLO burn counters
  (``ds_serving_slo_{ttft,itl}_breaches_total`` against the
  ``ServingConfig`` targets).

Host-only, stdlib-only (graftlint host-only package audit applies);
zero-import when telemetry is disabled — call sites resolve the
recorder through the telemetry probe and guard every call.
"""

from __future__ import annotations

import heapq
import itertools
import json
import threading
import time
from collections import deque
from typing import Callable, Optional

# one JSONL access-log line per completed request — the stable schema
# consumers (and the schema test) hold on to. *_ms components telescope:
# queue_wait + prefill + first_drain == ttft_ms and decode_active +
# boundary_gap + preempt_stall == total_ms - ttft_ms, exactly.
ACCESS_LOG_KEYS = (
    "trace_id", "uid", "priority", "prompt_tokens", "output_tokens",
    "max_new_tokens", "cached_blocks", "cached_tokens",
    "queue_depth_at_admit", "preemptions", "drains", "dispatches",
    "spec_tokens_extra", "replica", "migrate_bytes", "outcome",
    "error", "enqueue_unix_s", "ttft_ms", "itl_mean_ms", "total_ms",
    "queue_wait_ms", "prefill_ms", "migrate_ms", "first_drain_ms",
    "decode_active_ms", "boundary_gap_ms", "preempt_stall_ms")

# the latency components the percentile gauges / bench breakdown
# report. "migrate" (ISSUE 13) is the cross-mesh KV hand-off leg of a
# disaggregated request — export, wire, import, and the importing
# replica's admission queueing; zero for co-located requests, so the
# TTFT telescoping TTFT = queue_wait + prefill + migrate + first_drain
# stays exact either way.
COMPONENT_KEYS = ("queue_wait", "prefill", "migrate", "first_drain",
                  "decode_active", "boundary_gap", "preempt_stall")

_EVENT_CAP = 256            # per-request event-list bound
_PARK_CAP = 32              # per-request parked-interval bound


class RequestTrace:
    """One request's lifecycle. Timestamps are ``time.perf_counter()``
    seconds (same clock family as the span tracer's epoch, so the
    Chrome export lines up with the host spans)."""

    __slots__ = (
        "uid", "trace_id", "priority", "prompt_tokens",
        "max_new_tokens", "t_enqueue", "enqueue_unix",
        "t_admit", "t_prefill_done", "t_migrate_done", "t_first",
        "t_last", "t_finish", "queue_depth_at_admit", "cached_tokens",
        "cached_blocks", "preemptions", "tokens", "drains",
        "dispatches", "spec_tokens_extra", "replica", "migrate_bytes",
        "migrate_blocks", "decode_active_s", "boundary_gap_s",
        "preempt_stall_s", "park_open_t", "parks", "events",
        "outcome", "error", "_t_prev_token", "_state")

    def __init__(self, uid: int, trace_id: str, priority: int,
                 prompt_tokens: int, max_new_tokens: int,
                 now: float):
        self.uid = uid
        self.trace_id = trace_id
        self.priority = priority
        self.prompt_tokens = prompt_tokens
        self.max_new_tokens = max_new_tokens
        self.t_enqueue = now
        self.enqueue_unix = time.time()
        self.t_admit: Optional[float] = None        # first admission
        self.t_prefill_done: Optional[float] = None
        self.t_migrate_done: Optional[float] = None  # KV import landed
        self.t_first: Optional[float] = None
        self.t_last: Optional[float] = None
        self.t_finish: Optional[float] = None
        self.queue_depth_at_admit = 0
        self.cached_tokens = 0
        self.cached_blocks = 0
        self.preemptions = 0
        self.tokens = 0
        self.drains = 0
        self.dispatches = 0
        self.spec_tokens_extra = 0
        self.replica = ""
        self.migrate_bytes = 0
        self.migrate_blocks = 0
        self.decode_active_s = 0.0
        self.boundary_gap_s = 0.0
        self.preempt_stall_s = 0.0
        self.park_open_t: Optional[float] = None
        self.parks: list[tuple[float, float]] = []
        self.events: deque = deque(maxlen=_EVENT_CAP)
        self.outcome: Optional[str] = None
        self.error: Optional[str] = None
        self._t_prev_token: Optional[float] = None
        self._state = "queued"

    # -- derived components (seconds) ---------------------------------
    @property
    def ttft_s(self) -> Optional[float]:
        if self.t_first is None:
            return None
        return self.t_first - self.t_enqueue

    @property
    def queue_wait_s(self) -> float:
        if self.t_admit is None:
            end = self.t_finish if self.t_finish is not None \
                else self.t_enqueue
            return end - self.t_enqueue
        return self.t_admit - self.t_enqueue

    @property
    def prefill_s(self) -> float:
        if self.t_admit is None or self.t_prefill_done is None:
            return 0.0
        return self.t_prefill_done - self.t_admit

    @property
    def migrate_s(self) -> float:
        """Cross-mesh hand-off leg (ISSUE 13): prefill done (or
        admission, when the prefill ran on another process) -> KV
        import landed on the serving replica. 0 for co-located
        requests, keeping the TTFT telescoping exact either way."""
        if self.t_migrate_done is None:
            return 0.0
        start = self.t_prefill_done if self.t_prefill_done is not None \
            else self.t_admit
        if start is None:
            return 0.0
        return self.t_migrate_done - start

    @property
    def first_drain_s(self) -> float:
        if self.t_first is None:
            return 0.0
        start = self.t_migrate_done if self.t_migrate_done is not None \
            else self.t_prefill_done
        if start is None:
            return 0.0
        return self.t_first - start

    @property
    def itl_mean_s(self) -> Optional[float]:
        if self.t_first is None or self.t_last is None or self.tokens < 2:
            return None
        return (self.t_last - self.t_first) / (self.tokens - 1)

    def components(self) -> dict[str, float]:
        return {"queue_wait": self.queue_wait_s,
                "prefill": self.prefill_s,
                "migrate": self.migrate_s,
                "first_drain": self.first_drain_s,
                "decode_active": self.decode_active_s,
                "boundary_gap": self.boundary_gap_s,
                "preempt_stall": self.preempt_stall_s}

    def access_log_row(self) -> dict:
        ttft = self.ttft_s
        itl = self.itl_mean_s
        total = ((self.t_finish - self.t_enqueue)
                 if self.t_finish is not None else None)

        def ms(v):
            return round(v * 1e3, 3) if v is not None else None

        return {"trace_id": self.trace_id, "uid": self.uid,
                "priority": self.priority,
                "prompt_tokens": self.prompt_tokens,
                "output_tokens": self.tokens,
                "max_new_tokens": self.max_new_tokens,
                "cached_blocks": self.cached_blocks,
                "cached_tokens": self.cached_tokens,
                "queue_depth_at_admit": self.queue_depth_at_admit,
                "preemptions": self.preemptions,
                "drains": self.drains, "dispatches": self.dispatches,
                "spec_tokens_extra": self.spec_tokens_extra,
                "replica": self.replica,
                "migrate_bytes": self.migrate_bytes,
                "outcome": self.outcome, "error": self.error,
                "enqueue_unix_s": round(self.enqueue_unix, 6),
                "ttft_ms": ms(ttft), "itl_mean_ms": ms(itl),
                "total_ms": ms(total),
                "queue_wait_ms": ms(self.queue_wait_s),
                "prefill_ms": ms(self.prefill_s),
                "migrate_ms": ms(self.migrate_s),
                "first_drain_ms": ms(self.first_drain_s),
                "decode_active_ms": ms(self.decode_active_s),
                "boundary_gap_ms": ms(self.boundary_gap_s),
                "preempt_stall_ms": ms(self.preempt_stall_s)}


class RequestTraceRecorder:
    """Bounded recorder: an ``active`` map of in-flight traces plus a
    ring (``capacity``) of completed ones. All methods are host-only
    and O(1) per event; the registry work (histograms + exemplars +
    SLO counters) happens once per request at completion, percentile
    gauges once per :meth:`collect` (export boundaries)."""

    def __init__(self, capacity: int = 1024, registry=None,
                 slo_ttft_s: Optional[float] = None,
                 slo_itl_s: Optional[float] = None,
                 clock: Callable[[], float] = time.perf_counter):
        self.capacity = max(int(capacity), 8)
        self._active: dict[int, RequestTrace] = {}
        self._done: deque[RequestTrace] = deque(maxlen=self.capacity)
        self._seq = itertools.count()
        self._lock = threading.Lock()
        self._clock = clock
        self._registry = registry
        self.slo_ttft_s = slo_ttft_s
        self.slo_itl_s = slo_itl_s

    # -- configuration -------------------------------------------------
    def set_registry(self, registry) -> None:
        self._registry = registry

    def set_slo(self, ttft_s: Optional[float],
                itl_s: Optional[float]) -> None:
        """SLO targets (seconds; None/0 disables the burn counter)."""
        self.slo_ttft_s = ttft_s if ttft_s else None
        self.slo_itl_s = itl_s if itl_s else None

    # -- lifecycle events ----------------------------------------------
    def enqueue(self, uid: int, priority: int = 1,
                prompt_tokens: int = 0,
                max_new_tokens: int = 0) -> Optional[str]:
        """Request submitted. Idempotent per in-flight uid (the async
        server records the true submit time; the serve loop's own
        submit() then no-ops). Returns the trace id."""
        now = self._clock()
        with self._lock:
            tr = self._active.get(uid)
            if tr is not None:
                return tr.trace_id
            trace_id = f"req{next(self._seq):06d}-u{uid}"
            tr = RequestTrace(uid, trace_id, int(priority),
                              int(prompt_tokens), int(max_new_tokens),
                              now)
            tr.events.append((now, "enqueue", None))
            self._active[uid] = tr
            return trace_id

    def admitted(self, uid: int, queue_depth: int = 0,
                 cached_tokens: int = 0, cached_blocks: int = 0,
                 restore: bool = False, replica: str = "") -> None:
        now = self._clock()
        with self._lock:
            tr = self._active.get(uid)
            if tr is None:
                return
            tr.events.append((now, "restore" if restore else "admit",
                              {"queue_depth": queue_depth,
                               "cached_blocks": cached_blocks}))
            tr._state = "live"
            if replica:
                # the access log names the replica that SERVED the
                # request: last admission wins (a preempted request
                # may restore elsewhere after a drain-and-reroute)
                tr.replica = str(replica)
            if tr.t_admit is None:
                tr.t_admit = now
                tr.queue_depth_at_admit = int(queue_depth)
                tr.cached_tokens = int(cached_tokens)
                tr.cached_blocks = int(cached_blocks)

    def migrated(self, uid: int, *, replica: str = "", nbytes: int = 0,
                 blocks: int = 0, source: str = "") -> None:
        """Cross-mesh KV hand-off landed (ISSUE 13): the migrated
        block set was imported into ``replica``'s pool. Closes the
        ``migrate`` leg of the TTFT telescoping — but ONLY when the
        import gated the first token (first one wins; the event list
        records every hop). A hand-off whose first token was streamed
        EARLY by the router (before the import landed) charges the
        hand-off wait to the inter-token gap accounting instead —
        setting ``t_migrate_done`` after ``t_first`` would drive
        ``first_drain``/``prefill`` negative."""
        now = self._clock()
        with self._lock:
            tr = self._active.get(uid)
            if tr is None:
                return
            tr.events.append((now, "migrate",
                              {"replica": replica, "bytes": int(nbytes),
                               "blocks": int(blocks),
                               "source": source}))
            if replica:
                tr.replica = str(replica)
            tr.migrate_bytes = tr.migrate_bytes or int(nbytes)
            tr.migrate_blocks = tr.migrate_blocks or int(blocks)
            if tr.t_migrate_done is None and tr.t_first is None:
                tr.t_migrate_done = now

    def handoff(self, uid: int, *, source: str = "",
                target: str = "") -> None:
        """The EXPORT side of a hand-off (the prefill engine or a
        draining replica serialized the request's KV) — event-list
        only; the timing lands in ``migrate`` when the import
        completes."""
        now = self._clock()
        with self._lock:
            tr = self._active.get(uid)
            if tr is not None:
                tr.events.append((now, "handoff",
                                  {"source": source, "target": target}))

    def prefill_done(self, uids) -> None:
        now = self._clock()
        with self._lock:
            for uid in uids:
                tr = self._active.get(uid)
                if tr is not None:
                    tr.events.append((now, "prefill_done", None))
                    if tr.t_prefill_done is None:
                        tr.t_prefill_done = now

    def dispatched(self, uids, dispatch_id: int, k: int = 0) -> None:
        """One fused dispatch enqueued with these uids in its rowset
        (row/epoch attribution comes from the drain side)."""
        now = self._clock()
        with self._lock:
            for uid in uids:
                tr = self._active.get(uid)
                if tr is not None:
                    tr.dispatches += 1
                    tr.events.append((now, "dispatch",
                                      {"dispatch_id": dispatch_id,
                                       "k": k}))

    def tokens_landed(self, uid: int, n: int, *,
                      window_start: Optional[float] = None,
                      steps: int = 0, row: Optional[int] = None,
                      epoch: Optional[int] = None) -> None:
        """``n`` tokens for ``uid`` reached the host. ``window_start``
        is the dispatch-chain window this drain closes (everything in
        the gap since the request's previous token that falls inside
        the window is decode_active; parked time is preempt_stall; the
        remainder is boundary_gap). Prefill-sampled first tokens pass
        no window."""
        if n <= 0:
            return
        now = self._clock()
        with self._lock:
            tr = self._active.get(uid)
            if tr is None:
                return
            meta = {"tokens": n}
            if steps:
                meta["steps"] = steps
            if row is not None:
                meta["row"] = row
            if epoch:
                meta["epoch"] = epoch
            tr.events.append((now, "drain", meta))
            tr.tokens += n
            if steps:
                tr.drains += 1
                # tokens beyond one per executed tick: verified
                # speculative drafts (ISSUE 9) landing in this drain
                tr.spec_tokens_extra += max(0, n - steps)
            if tr.t_first is None:
                if tr.t_prefill_done is None \
                        and tr.t_migrate_done is None:
                    # driver never reported prefill separately (the
                    # per-tick generate path): fold it into prefill so
                    # the TTFT components still telescope exactly. A
                    # migrated request without a local prefill event
                    # instead charges admit -> import to `migrate`.
                    tr.t_prefill_done = now
                tr.t_first = now
            else:
                prev = tr._t_prev_token if tr._t_prev_token is not None \
                    else tr.t_first
                gap = max(now - prev, 0.0)
                parked = 0.0
                if tr.park_open_t is not None:
                    # the preemption stall ends at the first token
                    # after restore (re-queue + re-prefill included:
                    # from the client's seat that whole gap is the
                    # preemption's price)
                    parked = min(max(now - tr.park_open_t, 0.0), gap)
                    tr.parks.append((tr.park_open_t, now))
                    del tr.parks[:-_PARK_CAP]
                    tr.park_open_t = None
                active = 0.0
                if window_start is not None:
                    active = min(max(now - max(prev, window_start), 0.0),
                                 gap - parked)
                tr.preempt_stall_s += parked
                tr.decode_active_s += active
                tr.boundary_gap_s += max(gap - parked - active, 0.0)
            tr._t_prev_token = now
            tr.t_last = now
            tr._state = "live"

    def parked(self, uid: int) -> None:
        """Preemption swap-out: the request left the decode batch."""
        now = self._clock()
        with self._lock:
            tr = self._active.get(uid)
            if tr is None:
                return
            tr.preemptions += 1
            tr.park_open_t = now
            tr._state = "parked"
            tr.events.append((now, "park", None))

    def finished(self, uid: int, outcome: str = "completed",
                 error: Optional[str] = None) -> None:
        now = self._clock()
        with self._lock:
            tr = self._active.pop(uid, None)
            if tr is None:
                return
            if tr.t_first is not None:
                # attribute the last-token -> finish tail so the decode
                # decomposition telescopes exactly: decode_active +
                # boundary_gap + preempt_stall == total - ttft
                prev = tr._t_prev_token if tr._t_prev_token is not None \
                    else tr.t_first
                gap = max(now - prev, 0.0)
                parked = 0.0
                if tr.park_open_t is not None:
                    parked = min(max(now - tr.park_open_t, 0.0), gap)
                    tr.parks.append((tr.park_open_t, now))
                    del tr.parks[:-_PARK_CAP]
                    tr.park_open_t = None
                tr.preempt_stall_s += parked
                tr.boundary_gap_s += gap - parked
            elif tr.park_open_t is not None:
                # parked before any token and finished there
                # (cancel/abort): close the stall
                tr.preempt_stall_s += max(now - tr.park_open_t, 0.0)
                tr.parks.append((tr.park_open_t, now))
                tr.park_open_t = None
            tr.t_finish = now
            tr.outcome = outcome
            tr.error = error
            tr._state = outcome
            tr.events.append((now, "finish", {"outcome": outcome}))
            self._done.append(tr)
        self._observe_finished(tr)

    # -- registry export -----------------------------------------------
    def _observe_finished(self, tr: RequestTrace) -> None:
        reg = self._registry
        if reg is None:
            return
        reg.counter(
            "ds_serving_requests_total",
            "completed serving requests by outcome").inc(
            outcome=tr.outcome or "completed")
        comp = reg.histogram(
            "ds_serving_component_seconds",
            "per-request latency decomposition: TTFT = queue_wait + "
            "prefill + first_drain; decode = decode_active (in a "
            "dispatch-chain window) + boundary_gap (between chains) + "
            "preempt_stall (parked)")
        for name, v in tr.components().items():
            comp.observe(v, exemplar=tr.trace_id, component=name)
        ttft = tr.ttft_s
        if ttft is not None:
            reg.histogram(
                "ds_serving_request_ttft_seconds",
                "submit -> first token per request (queueing "
                "included; exemplars link buckets to trace ids)"
            ).observe(ttft, exemplar=tr.trace_id)
            if self.slo_ttft_s is not None and ttft > self.slo_ttft_s:
                reg.counter(
                    "ds_serving_slo_ttft_breaches_total",
                    "requests whose TTFT exceeded the ServingConfig "
                    "target (SLO burn)").inc()
        itl = tr.itl_mean_s
        if itl is not None:
            reg.histogram(
                "ds_serving_request_itl_seconds",
                "per-request mean inter-token latency (exemplars "
                "link buckets to trace ids)").observe(
                itl, exemplar=tr.trace_id)
            if self.slo_itl_s is not None and itl > self.slo_itl_s:
                reg.counter(
                    "ds_serving_slo_itl_breaches_total",
                    "requests whose mean ITL exceeded the "
                    "ServingConfig target (SLO burn)").inc()

    def collect(self, reg=None) -> None:
        """Component p50/p99 gauges from the completed ring (export
        boundaries only — sorts the ring per component)."""
        reg = reg if reg is not None else self._registry
        if reg is None:
            return
        pcts = self.component_percentiles()
        if not pcts:
            return
        p50 = reg.gauge("ds_serving_component_p50_seconds",
                        "median per-request latency component over the "
                        "completed-trace ring")
        p99 = reg.gauge("ds_serving_component_p99_seconds",
                        "p99 per-request latency component over the "
                        "completed-trace ring")
        for name, row in pcts.items():
            p50.set(row["p50"], component=name)
            p99.set(row["p99"], component=name)

    # -- readers ---------------------------------------------------------
    def completed(self) -> list[RequestTrace]:
        with self._lock:
            return list(self._done)

    def in_flight(self) -> list[dict]:
        """[{uid, trace_id, state, age_s, tokens, priority}] for the
        flight-recorder heartbeat and the hang-watchdog dump: a wedged
        serving loop names its stuck requests, not just the stalled
        thread."""
        now = self._clock()
        with self._lock:
            return [{"uid": tr.uid, "trace_id": tr.trace_id,
                     "state": tr._state,
                     "age_s": round(now - tr.t_enqueue, 4),
                     "tokens": tr.tokens, "priority": tr.priority}
                    for tr in self._active.values()]

    def inflight_count(self) -> int:
        """O(1) live-request count (the per-step heartbeat's fast
        path — no scan, no row building)."""
        with self._lock:
            return len(self._active)

    def heartbeat_meta(self, cap: int = 8) -> dict:
        """Compact in-flight summary for a flight-recorder progress
        event: live count plus the ``cap`` oldest uids (one partial
        heap pass, no full sort / per-row dicts — this runs on the
        serving loop's step path)."""
        now = self._clock()
        with self._lock:
            n = len(self._active)
            if not n:
                return {"inflight": 0}
            oldest = heapq.nsmallest(cap, self._active.values(),
                                     key=lambda tr: tr.t_enqueue)
        return {"inflight": n,
                "uids": [tr.uid for tr in oldest],
                "oldest_age_s": round(now - oldest[0].t_enqueue, 4),
                "oldest_uid": oldest[0].uid}

    def component_percentiles(self) -> dict[str, dict]:
        """{component: {p50, p99, mean, n}} seconds over completed
        requests that produced at least one token."""
        rows = [tr for tr in self.completed() if tr.t_first is not None]
        if not rows:
            return {}
        out = {}
        for name in COMPONENT_KEYS:
            vals = sorted(tr.components()[name] for tr in rows)
            out[name] = {
                "p50": vals[len(vals) // 2],
                "p99": vals[min(len(vals) - 1,
                               int(len(vals) * 0.99))],
                "mean": sum(vals) / len(vals), "n": len(vals)}
        return out

    def ttft_attribution(self) -> dict:
        """Which component dominates the TTFT tail: over the requests
        at/above the TTFT p99, the mean of each TTFT component and the
        name of the largest — 'what made the slowest requests slow'."""
        rows = [tr for tr in self.completed() if tr.ttft_s is not None]
        if not rows:
            return {}
        ttfts = sorted(tr.ttft_s for tr in rows)
        p99 = ttfts[min(len(ttfts) - 1, int(len(ttfts) * 0.99))]
        tail = [tr for tr in rows if tr.ttft_s >= p99] or rows
        comps = {}
        for name in ("queue_wait", "prefill", "migrate", "first_drain"):
            comps[name] = (sum(tr.components()[name] for tr in tail)
                           / len(tail))
        dominant = max(comps, key=comps.get)
        return {"ttft_p99_s": p99, "tail_requests": len(tail),
                "dominant_component": dominant,
                "tail_mean_components_s": comps}

    # -- artifact export -------------------------------------------------
    def write_access_log(self, path: str) -> Optional[str]:
        """JSONL, one line per completed request, enqueue order.
        Returns the path, or None when nothing completed."""
        rows = self.completed()
        if not rows:
            return None
        with open(path, "w") as f:
            for tr in rows:
                f.write(json.dumps(tr.access_log_row(),
                                   sort_keys=True) + "\n")
        return path

    def chrome_events(self, pid: int, epoch_ns: int) -> list[dict]:
        """Per-request tracks for the Chrome-trace export: each request
        gets its own named tid under the host process, with one X slice
        per lifecycle phase (+ parked intervals), so Perfetto shows a
        swimlane per request next to the host spans. ``epoch_ns`` is
        the span tracer's epoch (``perf_counter_ns`` at configure), so
        both track families share a timebase."""
        events: list[dict] = []

        def us(t: float) -> float:
            return round((t * 1e9 - epoch_ns) / 1e3, 3)

        def slice_(tid, name, t0, t1, args):
            if t0 is None or t1 is None or t1 < t0:
                return
            events.append({"name": name, "ph": "X", "ts": us(t0),
                           "dur": round((t1 - t0) * 1e6, 3),
                           "pid": pid, "tid": tid, "cat": "request",
                           "args": args})

        for i, tr in enumerate(self.completed()):
            tid = 0x520000 + i          # clear of real thread ids
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid,
                "tid": tid,
                "args": {"name": f"req {tr.trace_id} "
                                 f"(prio {tr.priority})"}})
            base = {"trace_id": tr.trace_id, "uid": tr.uid}
            slice_(tid, "req/queue_wait", tr.t_enqueue,
                   tr.t_admit if tr.t_admit is not None else tr.t_finish,
                   {**base, "queue_depth": tr.queue_depth_at_admit})
            slice_(tid, "req/prefill", tr.t_admit, tr.t_prefill_done,
                   {**base, "cached_blocks": tr.cached_blocks,
                    "prompt_tokens": tr.prompt_tokens})
            if tr.t_migrate_done is not None:
                slice_(tid, "req/migrate",
                       tr.t_prefill_done if tr.t_prefill_done
                       is not None else tr.t_admit,
                       tr.t_migrate_done,
                       {**base, "replica": tr.replica,
                        "bytes": tr.migrate_bytes,
                        "blocks": tr.migrate_blocks})
            slice_(tid, "req/first_drain",
                   tr.t_migrate_done if tr.t_migrate_done is not None
                   else tr.t_prefill_done, tr.t_first, dict(base))
            slice_(tid, "req/decode", tr.t_first, tr.t_last,
                   {**base, "tokens": tr.tokens,
                    "drains": tr.drains,
                    "dispatches": tr.dispatches,
                    "preemptions": tr.preemptions,
                    "outcome": tr.outcome})
            for t0, t1 in tr.parks:
                slice_(tid, "req/parked", t0, t1, dict(base))
        return events

    def clear(self) -> None:
        with self._lock:
            self._active.clear()
            self._done.clear()


# --- module-level current recorder (wired by telemetry.configure) --------

_RECORDER: Optional[RequestTraceRecorder] = None


def get_request_recorder() -> Optional[RequestTraceRecorder]:
    return _RECORDER


def set_request_recorder(rec: Optional[RequestTraceRecorder]) -> None:
    global _RECORDER
    _RECORDER = rec
