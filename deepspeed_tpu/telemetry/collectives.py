"""HLO collective accounting (ISSUE 5 tentpole part 2).

XLA fuses collectives into the compiled step, so per-op wall time is
unobservable from the host (comm/comm.py logs shapes at trace time and
leaves timing to the profiler). What IS knowable exactly is the
*static* collective content of each compiled executable: this module
walks the optimized HLO text of a registered executable
(``Compiled.as_text()``), finds every
all-reduce/all-gather/reduce-scatter/all-to-all/collective-permute
(sync or async ``-start`` form), decodes the payload bytes from the
result shapes, and attributes each op to the mesh axis (or axis
combination) whose device groups match the instruction's
``replica_groups`` — the T3-style per-axis traffic matrix the overlap
analysis needs.

Combined with the executable ledger's per-executable dispatch counts
and the span tracer's measured window, ``traffic_matrix()`` rows give
honest algbw/busbw LOWER bounds per (axis, op): every dispatched byte
moved somewhere inside the measured window.

Pure host-side text analysis: never imports the model, never runs
device code; one walk per *newly registered executable*, never per
dispatch.
"""

from __future__ import annotations

import itertools
import re
from typing import Optional

import numpy as np

# HLO primitive -> comm-facade op name (comms_logging.get_bw formulas)
HLO_TO_COMM_OP = {
    "all-reduce": "all_reduce",
    "all-gather": "all_gather",
    "reduce-scatter": "reduce_scatter",
    "all-to-all": "all_to_all",
    "ragged-all-to-all": "all_to_all",
    "collective-permute": "ppermute",
    "collective-broadcast": "broadcast",
}

_OP_RE = re.compile(
    r"=\s*(?P<shapes>[^=]*?)\s*"
    r"(?P<op>all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"ragged-all-to-all|collective-permute|collective-broadcast)"
    r"(?P<start>-start)?\(")
_SHAPE_RE = re.compile(r"\b([a-z]+[0-9]*(?:e[0-9a-z]+)?)\[([0-9,]*)\]")
_GROUPS_BRACE_RE = re.compile(r"replica_groups=\{(\{[0-9,{} ]*\})\}")
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?")
_PAIRS_RE = re.compile(r"source_target_pairs=\{([0-9,{} ]*)\}")

_DTYPE_BYTES = {
    "pred": 1, "s2": 1, "s4": 1, "u2": 1, "u4": 1,
    "s8": 1, "u8": 1, "s16": 2, "u16": 2, "s32": 4, "u32": 4,
    "s64": 8, "u64": 8, "bf16": 2, "f16": 2, "f32": 4, "f64": 8,
    "c64": 8, "c128": 16, "token": 0, "opaque": 0,
}


def _dtype_bytes(name: str) -> int:
    if name in _DTYPE_BYTES:
        return _DTYPE_BYTES[name]
    if name.startswith("f8") or name.startswith("e4") \
            or name.startswith("e5"):
        return 1
    return 4


def _shapes_bytes(text: str) -> tuple[int, int]:
    """(total bytes, total elements) of every ``dtype[dims]`` shape
    token in ``text`` (handles variadic tuple results). The ratio is
    the instruction's effective wire width — 1.x bytes/element once
    qwZ/qgZ put int8/fp8 payloads (plus fp32 block scales) on the
    wire, 4.0 for a plain fp32 collective."""
    total = 0
    elements = 0
    for dtype, dims in _SHAPE_RE.findall(text):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _dtype_bytes(dtype)
        elements += n
    return total, elements


def _parse_groups(line: str) -> Optional[list[list[int]]]:
    """Device-id groups from either HLO syntax: literal
    ``{{0,2},{1,3}}`` braces or the iota form
    ``[groups,size]<=[dims]T(perm)``."""
    m = _GROUPS_BRACE_RE.search(line)
    if m:
        groups = []
        for grp in re.findall(r"\{([0-9, ]*)\}", m.group(1)):
            ids = [int(x) for x in grp.replace(" ", "").split(",") if x]
            if ids:
                groups.append(ids)
        return groups or None
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        n_groups, group_size = int(m.group(1)), int(m.group(2))
        dims = [int(x) for x in m.group(3).split(",")]
        ids = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(4):
            perm = [int(x) for x in m.group(4).split(",")]
            ids = ids.transpose(perm)
        return [r.tolist() for r in ids.reshape(n_groups, group_size)]
    return None


def mesh_axis_groups(mesh) -> dict[frozenset, str]:
    """{partition-of-device-ids -> axis label} for every non-empty
    combination of the mesh's axes (size-1 groups excluded: they move
    no bytes). A collective whose ``replica_groups`` match one of
    these partitions ran along that axis (combinations label as
    ``"dp+tp"``). Best-effort: an exotic mesh yields fewer matches and
    the caller falls back to an ``"n<group_size>"`` label."""
    if mesh is None:
        return {}
    try:
        devices = np.asarray(mesh.devices)
        ids = np.vectorize(lambda d: int(d.id))(devices)
        axes = list(mesh.axis_names)
    except Exception:
        return {}
    table: dict[frozenset, str] = {}
    n = ids.ndim
    for r in range(1, n + 1):
        for subset in itertools.combinations(range(n), r):
            perm = ([i for i in range(n) if i not in subset]
                    + list(subset))
            grp = ids.transpose(perm).reshape(-1, int(np.prod(
                [ids.shape[i] for i in subset])))
            if grp.shape[1] <= 1:
                continue
            key = frozenset(frozenset(int(x) for x in row)
                            for row in grp)
            # r ascends, so a single axis wins over an equivalent
            # multi-axis flattening of size-1 axes
            table.setdefault(key, "+".join(axes[i] for i in subset))
    return table


def _permute_axis(pairs: list[tuple[int, int]], mesh) -> Optional[str]:
    """Mesh axis a collective-permute rotates along: every
    source->target pair differs in exactly that one mesh coordinate."""
    if mesh is None:
        return None
    try:
        ids = np.vectorize(lambda d: int(d.id))(np.asarray(mesh.devices))
        axes = list(mesh.axis_names)
        coord = {int(ids[idx]): idx for idx in np.ndindex(ids.shape)}
        moved: set[int] = set()
        for s, t in pairs:
            cs, ct = coord[s], coord[t]
            moved |= {i for i in range(len(cs)) if cs[i] != ct[i]}
        if len(moved) == 1:
            return axes[moved.pop()]
    except Exception:
        pass
    return None


def analyze_hlo(hlo_text: str, mesh=None,
                n_devices: Optional[int] = None) -> list[dict]:
    """Per-collective-instruction records
    ``{op, hlo_op, bytes, elements, wire_bytes_per_el, group_size,
    axis, groups}`` from optimized HLO text. ``bytes`` is the full
    logical payload per device group participant (the reference
    comms-logging convention get_bw expects: full tensor for
    all-reduce / gathered output for all-gather / full input for
    reduce-scatter), decoded from the actual result dtypes — an int8
    qwZ/qgZ payload counts 1 byte/element, so the quantized wire's win
    lands in ``ds_hlo_collective_bytes_total{axis,op}`` without any
    assumed element width. Async ``-start`` ops count once; their
    ``-done`` halves are ignored."""
    axis_table = mesh_axis_groups(mesh)
    records: list[dict] = []
    for line in hlo_text.splitlines():
        m = _OP_RE.search(line)
        if m is None or "-done" in line.split("=", 1)[0]:
            continue
        hlo_op = m.group("op")
        out_bytes, out_elements = _shapes_bytes(m.group("shapes"))
        groups = _parse_groups(line)
        axis = None
        if hlo_op == "collective-permute":
            pm = _PAIRS_RE.search(line)
            pairs = []
            if pm:
                pairs = [tuple(int(x) for x in p.replace(" ", "")
                               .split(","))
                         for p in re.findall(r"\{([0-9, ]+)\}",
                                             pm.group(1))]
            group_size = len({d for p in pairs for d in p}) or 2
            axis = _permute_axis(pairs, mesh)
        else:
            if groups:
                group_size = max(len(g) for g in groups)
                key = frozenset(frozenset(g) for g in groups
                                if len(g) > 1)
                axis = axis_table.get(key)
            else:
                group_size = n_devices or (
                    int(np.asarray(mesh.devices).size)
                    if mesh is not None else 0)
                axis = "world" if group_size else None
        if group_size <= 1:
            continue        # degenerate single-participant group
        payload = out_bytes
        elements = out_elements
        if hlo_op == "reduce-scatter":
            payload = out_bytes * group_size
            elements = out_elements * group_size
        records.append({
            "op": HLO_TO_COMM_OP[hlo_op],
            "hlo_op": hlo_op + ("-start" if m.group("start") else ""),
            "bytes": int(payload),
            "elements": int(elements),
            "wire_bytes_per_el": (payload / elements if elements
                                  else 0.0),
            "group_size": int(group_size),
            "axis": axis or f"n{group_size}",
            "groups": len(groups) if groups else 1,
        })
    return records


def traffic_matrix(records: list[dict], calls: int = 1) -> dict:
    """Aggregate per-instruction records into the per-(axis, op)
    traffic matrix: ``{(axis, op): {bytes, sites, group_size}}`` where
    ``bytes`` is per-execution payload x ``calls`` dispatches."""
    out: dict = {}
    for r in records:
        key = (r["axis"], r["op"])
        row = out.setdefault(key, {"bytes": 0, "elements": 0,
                                   "sites": 0,
                                   "group_size": r["group_size"]})
        row["bytes"] += r["bytes"] * calls
        row["elements"] += r.get("elements", 0) * calls
        row["sites"] += 1
        row["group_size"] = max(row["group_size"], r["group_size"])
    return out


def bandwidth_bounds(traffic: dict, window_s: float) -> dict:
    """Per-(axis, op) algorithm/bus bandwidth LOWER bounds over a
    measured window: ``{(axis, op): {bytes, group_size, algbw_bytes_
    per_s, busbw_bytes_per_s}}``. Every dispatched byte moved somewhere
    inside the window, so bytes/window is an honest floor; the busbw
    column applies the reference ``get_bw`` op factors. Empty window
    -> empty result (no invented bandwidth). Calibration query for the
    autotuning cost model (ISSUE 7)."""
    if window_s <= 0:
        return {}
    from ..utils.comms_logging import get_bw
    out: dict = {}
    for (axis, op), row in traffic.items():
        if row["bytes"] <= 0:
            continue
        algbw, busbw = get_bw(op, row["bytes"], window_s,
                              max(row["group_size"], 2))
        out[(axis, op)] = {"bytes": row["bytes"],
                           "group_size": row["group_size"],
                           "algbw_bytes_per_s": algbw * 1e9,
                           "busbw_bytes_per_s": busbw * 1e9}
    return out


def axis_bandwidth_bounds(traffic: dict, window_s: float) -> dict:
    """Per-axis fold of :func:`bandwidth_bounds`: total payload bytes
    on the axis over the window — the single-number algbw floor the
    cost model divides candidate traffic by."""
    if window_s <= 0:
        return {}
    out: dict = {}
    for (axis, _op), row in traffic.items():
        if row["bytes"] <= 0:
            continue
        dst = out.setdefault(axis, {"bytes": 0})
        dst["bytes"] += row["bytes"]
    for axis, dst in out.items():
        dst["algbw_bytes_per_s"] = dst["bytes"] / window_s
    return out


def merge_traffic(*matrices: dict) -> dict:
    """Fold several per-executable traffic matrices into one."""
    out: dict = {}
    for mat in matrices:
        for key, row in mat.items():
            dst = out.setdefault(key, {"bytes": 0, "elements": 0,
                                       "sites": 0,
                                       "group_size": row["group_size"]})
            dst["bytes"] += row["bytes"]
            dst["elements"] += row.get("elements", 0)
            dst["sites"] += row["sites"]
            dst["group_size"] = max(dst["group_size"],
                                    row["group_size"])
    return out


def axis_wire_width(traffic: dict) -> dict[str, float]:
    """Per-axis effective wire width (bytes/element) over a traffic
    matrix — the observed number the autotuning calibration records
    (``Calibration.axis_wire_bytes_per_el``): ~4.0 on an fp32 wire,
    ~1.1 once qwZ/qgZ carry int8 payloads + fp32 block scales. Axes
    with no element accounting are omitted."""
    agg: dict[str, list[float]] = {}
    for (axis, _op), row in traffic.items():
        if row.get("elements", 0) > 0:
            a = agg.setdefault(axis, [0.0, 0.0])
            a[0] += row["bytes"]
            a[1] += row["elements"]
    return {axis: b / e for axis, (b, e) in agg.items() if e > 0}
