"""Process-wide metrics registry (ISSUE 2 tentpole part 2).

Counter / Gauge / Histogram with labels, a ``snapshot()``/JSON dump for
programmatic readers, and Prometheus text exposition
(https://prometheus.io/docs/instrumenting/exposition_formats/) so a
node-local scraper can pull serving, comms, memory, and compile metrics
from a training or serving host.

Naming follows Prometheus conventions: ``_total`` counters,
``_seconds``/``_bytes`` units, e.g. ``ds_serving_decoded_tokens_total``,
``ds_jax_compile_seconds_total{phase="backend_compile"}``. The full
metric table is in docs/observability.md.
"""

from __future__ import annotations

import json
import math
import threading
from typing import Any, Iterable, Optional

LabelKey = tuple  # tuple of sorted (k, v) pairs

# default latency buckets: 0.5 ms .. 60 s, roughly log-spaced
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _label_key(labels: dict) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape(v: str) -> str:
    """Label-VALUE escaping per the text-format spec: backslash first
    (escaping the escapes), then quote and newline. Now that
    request-derived label values exist (trace ids, outcome strings,
    component names fed from serving state), every value goes through
    here — a stray quote or newline must not break a scrape."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    """HELP-text escaping: the spec escapes backslash and newline only
    (quotes are legal in help text — escaping them would corrupt it)."""
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(key: LabelKey, extra: Iterable[tuple[str, str]] = ()) -> str:
    pairs = list(key) + list(extra)
    if not pairs:
        return ""
    return "{" + ",".join(f'{k}="{_escape(v)}"' for k, v in pairs) + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._values: dict[LabelKey, Any] = {}

    def label_sets(self) -> list[dict]:
        with self._lock:
            return [dict(k) for k in self._values]


class Counter(_Metric):
    """Monotonically increasing value (per label set)."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative inc {amount}")
        k = _label_key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + amount

    def set_total(self, total: float, **labels) -> None:
        """Mirror an external monotonic counter (e.g. an engine's
        serving_stats entry): sets the exposed total directly, refusing
        to go backwards so scrapes never see a counter reset."""
        k = _label_key(labels)
        with self._lock:
            self._values[k] = max(self._values.get(k, 0.0), float(total))

    def value(self, **labels) -> float:
        return self._values.get(_label_key(labels), 0.0)


class Gauge(_Metric):
    """Point-in-time value (per label set)."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        self._values[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        k = _label_key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        return self._values.get(_label_key(labels), 0.0)


class _HistState:
    __slots__ = ("bucket_counts", "sum", "count", "exemplars")

    def __init__(self, n_buckets: int):
        self.bucket_counts = [0] * n_buckets  # non-cumulative per bucket
        self.sum = 0.0
        self.count = 0
        # bucket index -> (trace_id, value): the most recent exemplar
        # observed into that bucket (OpenMetrics exemplar semantics —
        # a p99 bucket links to a concrete request trace)
        self.exemplars: dict[int, tuple[str, float]] = {}


class Histogram(_Metric):
    """Bucketed distribution (per label set). Buckets are upper bounds;
    an implicit +Inf bucket catches the tail."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: tuple = DEFAULT_BUCKETS):
        super().__init__(name, help)
        self.buckets = tuple(sorted(float(b) for b in buckets))

    def observe(self, value: float, *, exemplar: Optional[str] = None,
                **labels) -> None:
        """Record one observation. ``exemplar`` attaches a trace id to
        the bucket the value lands in (most recent wins), emitted in
        OpenMetrics exemplar syntax by :meth:`MetricsRegistry.\
prometheus_text` so a tail bucket names a concrete trace."""
        value = float(value)
        k = _label_key(labels)
        with self._lock:
            st = self._values.get(k)
            if st is None:
                st = self._values[k] = _HistState(len(self.buckets) + 1)
            i = 0
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    break
            else:
                i = len(self.buckets)
            st.bucket_counts[i] += 1
            st.sum += value
            st.count += 1
            if exemplar is not None:
                st.exemplars[i] = (str(exemplar), value)

    def exemplars(self, **labels) -> dict:
        """{bucket upper bound (inf for the tail): (trace_id, value)}"""
        st = self._values.get(_label_key(labels))
        if st is None:
            return {}
        ubs = list(self.buckets) + [math.inf]
        return {ubs[i]: ex for i, ex in st.exemplars.items()}

    def summary(self, **labels) -> dict:
        """{count, sum, mean, buckets: {le: cumulative_count}}"""
        st = self._values.get(_label_key(labels))
        if st is None:
            return {"count": 0, "sum": 0.0, "mean": 0.0, "buckets": {}}
        cum, out = 0, {}
        for ub, c in zip(self.buckets, st.bucket_counts):
            cum += c
            out[ub] = cum
        out[math.inf] = st.count
        return {"count": st.count, "sum": st.sum,
                "mean": st.sum / max(st.count, 1), "buckets": out}


class MetricsRegistry:
    """Name -> metric map with typed, idempotent getters: asking twice
    for the same name returns the same object; asking with a different
    type raises (one name, one meaning)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _get(self, cls, name: str, help: str, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: tuple = DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        return self._metrics.get(name)

    def names(self) -> list[str]:
        return sorted(self._metrics)

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()

    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-friendly dump of every metric and label set."""
        out = {}
        for name in self.names():
            m = self._metrics[name]
            entries = []
            for labels in m.label_sets():
                if isinstance(m, Histogram):
                    s = m.summary(**labels)
                    entry = {
                        "labels": labels, "count": s["count"],
                        "sum": s["sum"], "mean": s["mean"],
                        "buckets": {("+Inf" if math.isinf(k) else k): v
                                    for k, v in s["buckets"].items()}}
                    exs = m.exemplars(**labels)
                    if exs:
                        entry["exemplars"] = {
                            ("+Inf" if math.isinf(k) else k):
                                {"trace_id": t, "value": v}
                            for k, (t, v) in exs.items()}
                    entries.append(entry)
                else:
                    entries.append({"labels": labels,
                                    "value": m.value(**labels)})
            out[name] = {"type": m.kind, "help": m.help,
                         "values": entries}
        return out

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.snapshot(), indent=indent)

    def dump_json(self, path: str, indent: int = 1) -> str:
        with open(path, "w") as f:
            f.write(self.to_json(indent=indent))
        return path

    def prometheus_text(self, exemplars: bool = True) -> str:
        """Prometheus text exposition. HELP text and label values are
        escaped per the 0.0.4 spec; with ``exemplars=True`` (default)
        histogram buckets holding one carry it in OPENMETRICS exemplar
        syntax (``... # {trace_id="..."} value``) so a tail bucket
        links to a concrete request trace. Exemplars are an
        OpenMetrics extension — strict 0.0.4 parsers reject mid-line
        ``#``, so pass ``exemplars=False`` when feeding one (the
        in-repo consumer, ``telemetry_report.parse_prometheus``,
        strips the suffix)."""
        lines: list[str] = []
        for name in self.names():
            m = self._metrics[name]
            if m.help:
                lines.append(f"# HELP {name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {name} {m.kind}")
            for labels in m.label_sets():
                key = _label_key(labels)
                if isinstance(m, Histogram):
                    s = m.summary(**labels)
                    exs = m.exemplars(**labels) if exemplars else {}
                    for ub, cum in s["buckets"].items():
                        le = "+Inf" if math.isinf(ub) else repr(ub)
                        line = (f"{name}_bucket"
                                f"{_fmt_labels(key, [('le', le)])} {cum}")
                        ex = exs.get(ub)
                        if ex is not None:
                            line += (f' # {{trace_id="{_escape(ex[0])}"}}'
                                     f" {ex[1]}")
                        lines.append(line)
                    lines.append(f"{name}_sum{_fmt_labels(key)} "
                                 f"{s['sum']}")
                    lines.append(f"{name}_count{_fmt_labels(key)} "
                                 f"{s['count']}")
                else:
                    v = m.value(**labels)
                    lines.append(f"{name}{_fmt_labels(key)} {v}")
        return "\n".join(lines) + "\n"

    def dump_prometheus(self, path: str) -> str:
        with open(path, "w") as f:
            f.write(self.prometheus_text())
        return path

    # ------------------------------------------------------------------
    def events_for_monitor(self, step: int, prefix: str = "Telemetry") \
            -> list[tuple[str, float, int]]:
        """Flatten scalar metrics into monitor event tuples so CSV /
        TensorBoard / W&B backends chart the registry. Histograms emit
        ``_count``/``_sum``/``_mean`` scalars; labeled metrics append
        ``/k=v`` segments to the event name."""
        events: list[tuple[str, float, int]] = []
        for name in self.names():
            m = self._metrics[name]
            for labels in m.label_sets():
                suffix = "".join(f"/{k}={v}"
                                 for k, v in sorted(labels.items()))
                base = f"{prefix}/{name}{suffix}"
                if isinstance(m, Histogram):
                    s = m.summary(**labels)
                    if s["count"]:
                        events += [(f"{base}_count", float(s["count"]),
                                    step),
                                   (f"{base}_sum", s["sum"], step),
                                   (f"{base}_mean", s["mean"], step)]
                else:
                    events.append((base, m.value(**labels), step))
        return events


# --- module-level current registry (wired by telemetry.configure) -------

_REGISTRY: Optional[MetricsRegistry] = None


def get_registry() -> Optional[MetricsRegistry]:
    return _REGISTRY


def set_registry(reg: Optional[MetricsRegistry]) -> None:
    global _REGISTRY
    _REGISTRY = reg
