"""Unified telemetry (ISSUE 2 tentpole): span tracing + metrics registry
+ Perfetto/Prometheus export across training and serving.

Three parts:

- :mod:`.spans` — host-side span tracer (context manager + decorator,
  nested, per-rank ring buffer) that mirrors each span into a
  ``jax.profiler.TraceAnnotation`` (XPlane) and exports
  Chrome-trace-event JSON loadable in Perfetto.
- :mod:`.registry` — process-wide Counter/Gauge/Histogram registry with
  ``snapshot()``, JSON dump, and Prometheus text exposition.
- :mod:`.bridges` — collectors from existing sources (jax compile
  events, ThroughputTimer, CommsLogger, serving_metrics, memory) and a
  registry -> MonitorMaster flush.

Activation::

    from deepspeed_tpu import telemetry
    telemetry.configure()                  # or via the engine's
                                           # {"telemetry": {"enabled": true}}
    ... run training / serving ...
    telemetry.export_artifacts("/tmp/tel", prefix="run1")

Overhead contract: nothing in this package is imported by the framework
until telemetry is activated; instrumented call sites probe
``sys.modules`` for this module instead of importing it, so a
telemetry-disabled run allocates no tracer/registry state and pays one
dict lookup per *dispatch* (never per token). See docs/observability.md.
"""

from __future__ import annotations

import os
from typing import Optional

from . import (bridges, collectives, flightrec as _flightrec_mod,  # noqa: F401
               fleet as _fleet_mod, health as _health_mod,
               ledger as _ledger_mod, registry as _registry_mod,
               reqtrace as _reqtrace_mod, spans as _spans_mod,
               steptrace as _steptrace_mod, timeseries as _timeseries_mod)
from .fleet import FleetScope, get_fleet  # noqa: F401
from .flightrec import (FlightRecorder, HangWatchdog,  # noqa: F401
                        get_flight_recorder, get_watchdog)
flightrec = _flightrec_mod   # public alias for instrumented call sites
from .health import HealthMonitor, get_health_monitor  # noqa: F401
from .ledger import ExecutableLedger, get_ledger  # noqa: F401
from .registry import (Counter, Gauge, Histogram,  # noqa: F401
                       MetricsRegistry, get_registry)
from .reqtrace import (RequestTraceRecorder,  # noqa: F401
                       get_request_recorder)
from .spans import NULL_CONTEXT, SpanTracer, get_tracer  # noqa: F401
from .steptrace import (StepTraceRecorder,  # noqa: F401
                        get_step_recorder)
from .timeseries import TimeSeriesRing, get_timeseries  # noqa: F401

_ACTIVE = False
_ARTIFACT_DIR = "telemetry_hangdump"
_BURN_WINDOWS_S = _timeseries_mod.DEFAULT_BURN_WINDOWS_S


def is_active() -> bool:
    """True iff ``configure()`` ran (and ``shutdown()`` has not)."""
    return _ACTIVE


def configure(config=None, *, span_buffer_size: Optional[int] = None,
              profiler_annotations: Optional[bool] = None,
              jax_compile_events: Optional[bool] = None,
              executable_ledger: Optional[bool] = None,
              hlo_collectives: Optional[bool] = None,
              flight_recorder: Optional[bool] = None,
              flight_recorder_size: Optional[int] = None,
              watchdog_deadline_s: Optional[float] = None,
              watchdog_artifact_dir: Optional[str] = None,
              watchdog_abort: Optional[bool] = None,
              request_traces: Optional[bool] = None,
              request_trace_size: Optional[int] = None,
              steptrace: Optional[bool] = None,
              steptrace_size: Optional[int] = None,
              steptrace_regression_window: Optional[int] = None,
              steptrace_regression_threshold: Optional[float] = None,
              fleet: Optional[bool] = None,
              fleet_replica: Optional[str] = None,
              timeseries_capacity: Optional[int] = None,
              timeseries_interval_s: Optional[float] = None,
              burn_windows_s=None) -> None:
    """Activate telemetry for this process. ``config`` may be the
    engine's ``TelemetryConfig`` block; keyword overrides win.
    Idempotent: re-configuring while active keeps the existing
    tracer/registry (so engine init cannot wipe a bench harness's
    already-collected spans).

    The device-truth layer (ISSUE 5) is opt-in on top: the executable
    ledger + HLO collective accounting (``executable_ledger``), and
    the flight recorder + hang watchdog (``flight_recorder`` /
    ``watchdog_deadline_s``)."""
    global _ACTIVE
    if _ACTIVE:
        return

    def pick(kw, attr, default):
        if kw is not None:
            return kw
        return getattr(config, attr, default) if config is not None \
            else default

    capacity = pick(span_buffer_size, "span_buffer_size", 8192)
    annotations = pick(profiler_annotations, "profiler_annotations", True)
    compile_events = pick(jax_compile_events, "jax_compile_events", True)
    ledger_on = pick(executable_ledger, "executable_ledger", False)
    hlo_coll = pick(hlo_collectives, "hlo_collectives", True)
    flight_on = pick(flight_recorder, "flight_recorder", False)
    flight_cap = pick(flight_recorder_size, "flight_recorder_size", 2048)
    deadline = pick(watchdog_deadline_s, "watchdog_deadline_s", 0.0)
    artifact_dir = pick(watchdog_artifact_dir, "watchdog_artifact_dir",
                        "telemetry_hangdump")
    abort = pick(watchdog_abort, "watchdog_abort", False)
    global _ARTIFACT_DIR
    _ARTIFACT_DIR = artifact_dir
    req_on = pick(request_traces, "request_traces", True)
    req_cap = pick(request_trace_size, "request_trace_size", 1024)
    _spans_mod.set_tracer(SpanTracer(
        capacity=capacity, profiler_annotations=annotations))
    _registry_mod.set_registry(MetricsRegistry())
    if req_on:
        # per-request serving traces (ISSUE 10): host-only ring; the
        # serving loops resolve it through the probe and guard every
        # call, so nothing is recorded until requests actually flow
        _reqtrace_mod.set_request_recorder(RequestTraceRecorder(
            capacity=req_cap, registry=_registry_mod.get_registry()))
    if ledger_on:
        _ledger_mod.set_ledger(ExecutableLedger(
            hlo_collectives=hlo_coll))
    if pick(steptrace, "steptrace", True):
        # per-step training traces (ISSUE 20): host-only ring like
        # reqtrace; the engine resolves it through the probe and guards
        # every call, so nothing is recorded until train_batch runs.
        # The ledger/timeseries hooks are zero-arg accessors — wiring
        # stays correct whether those layers are on, off, or re-wired.
        _steptrace_mod.set_step_recorder(StepTraceRecorder(
            capacity=pick(steptrace_size, "steptrace_size", 2048),
            registry=_registry_mod.get_registry(),
            ledger=_ledger_mod.get_ledger,
            timeseries=_timeseries_mod.get_timeseries,
            regression_window=pick(steptrace_regression_window,
                                   "steptrace_regression_window", 32),
            regression_threshold=pick(
                steptrace_regression_threshold,
                "steptrace_regression_threshold", 0.5)))
    if flight_on:
        rec = FlightRecorder(capacity=flight_cap)
        _flightrec_mod.set_flight_recorder(rec)
        if deadline and deadline > 0:
            dog = HangWatchdog(rec, deadline_s=deadline,
                               artifact_dir=artifact_dir, abort=abort)
            _flightrec_mod.set_watchdog(dog)
            dog.start()
    if compile_events:
        bridges.install_jax_compile_listener()
    _ACTIVE = True
    # fleet health plane (ISSUE 17): opt-in like the device-truth layer
    if pick(fleet, "fleet", False):
        configure_fleet(
            replica=pick(fleet_replica, "fleet_replica", ""),
            timeseries_capacity=pick(timeseries_capacity,
                                     "timeseries_capacity", 512),
            timeseries_interval_s=pick(timeseries_interval_s,
                                       "timeseries_interval_s", 0.25),
            burn_windows_s=pick(burn_windows_s, "burn_windows_s", None))


def configure_fleet(*, replica: str = "",
                    timeseries_capacity: int = 512,
                    timeseries_interval_s: float = 0.25,
                    burn_windows_s=None, **health_kw) -> None:
    """Install the fleet health plane (ISSUE 17): the time-series ring,
    the health monitor, and a :class:`FleetScope` with this process's
    registry registered as the local replica. Idempotent (a second
    caller — router after bench, say — keeps the existing components;
    its kwargs are ignored). Requires an active ``configure()`` —
    no-ops otherwise so disabled runs stay allocation-free.

    ``health_kw`` passes through to :class:`HealthMonitor`
    (``phi_suspect``, ``phi_dead``, ``heartbeat_window``, ...), which is
    how the router's ``RouterConfig.health`` block lands here."""
    if not _ACTIVE:
        return
    if _timeseries_mod.get_timeseries() is None:
        _timeseries_mod.set_timeseries(TimeSeriesRing(
            capacity=timeseries_capacity,
            interval_s=timeseries_interval_s))
    if burn_windows_s:
        global _BURN_WINDOWS_S
        _BURN_WINDOWS_S = tuple(float(w) for w in burn_windows_s)
    if _health_mod.get_health_monitor() is None:
        _health_mod.set_health_monitor(HealthMonitor(**health_kw))
    if _fleet_mod.get_fleet() is None:
        scope = FleetScope()
        reg = get_registry()
        if reg is not None:
            scope.add_replica(replica or f"proc{os.getpid()}", reg)
        _fleet_mod.set_fleet(scope)


def burn_windows() -> tuple:
    """The configured multi-window burn lookbacks (seconds)."""
    return _BURN_WINDOWS_S


def shutdown() -> None:
    """Deactivate and drop all telemetry state. The jax.monitoring
    listener stays registered (jax has no per-listener removal) but
    no-ops once the registry is gone."""
    global _ACTIVE, _BURN_WINDOWS_S
    _ACTIVE = False
    _fleet_mod.set_fleet(None)
    _health_mod.set_health_monitor(None)
    _timeseries_mod.set_timeseries(None)
    _BURN_WINDOWS_S = _timeseries_mod.DEFAULT_BURN_WINDOWS_S
    _flightrec_mod.set_watchdog(None)
    _flightrec_mod.set_flight_recorder(None)
    _flightrec_mod.reset_straggler_gate()
    _ledger_mod.set_ledger(None)
    _steptrace_mod.set_step_recorder(None)
    _reqtrace_mod.set_request_recorder(None)
    _spans_mod.set_tracer(None)
    _registry_mod.set_registry(None)


def clear() -> None:
    """Reset spans + metrics + device-truth state in place (e.g.
    between bench stages)."""
    t = get_tracer()
    if t is not None:
        t.clear()
    r = get_registry()
    if r is not None:
        r.clear()
    led = get_ledger()
    if led is not None:
        led.clear()
    fr = get_flight_recorder()
    if fr is not None:
        fr.clear()
    _flightrec_mod.reset_straggler_gate()
    rt = get_request_recorder()
    if rt is not None:
        rt.clear()
    st = get_step_recorder()
    if st is not None:
        st.clear()
    ts = get_timeseries()
    if ts is not None:
        ts.clear()
    hm = get_health_monitor()
    if hm is not None:
        hm.clear()


def span(name: str, **tags):
    """Module-level span helper; shared no-op context when inactive."""
    return _spans_mod.span(name, **tags)


def trace(func=None, *, name: Optional[str] = None):
    """Decorator recording a span per call; pass-through when inactive
    at call time (the check happens per call, not at decoration)."""
    import functools

    def wrap(f):
        label = name or f.__qualname__

        @functools.wraps(f)
        def inner(*a, **kw):
            with _spans_mod.span(label):
                return f(*a, **kw)
        return inner
    return wrap(func) if func is not None else wrap


def export_artifacts(out_dir: str, prefix: str = "telemetry",
                     serving_metrics: Optional[dict] = None) -> dict:
    """Write ``<prefix>.trace.json`` (Perfetto), ``<prefix>.prom``
    (Prometheus text) and ``<prefix>.metrics.json`` (snapshot) into
    ``out_dir``, refreshing the memory/comms collectors first. Returns
    the written paths (empty when telemetry is inactive)."""
    tracer, reg = get_tracer(), get_registry()
    if tracer is None or reg is None:
        return {}
    os.makedirs(out_dir, exist_ok=True)
    bridges.collect_memory(reg)
    bridges.collect_comms(reg)
    bridges.collect_ledger(reg)
    if serving_metrics is not None:
        bridges.collect_serving(reg, serving_metrics)
    rt = get_request_recorder()
    if rt is not None:
        rt.collect(reg)     # component p50/p99 gauges
    st = get_step_recorder()
    if st is not None:
        st.collect(reg)     # goodput/badput + step-component gauges
    hm = get_health_monitor()
    if hm is not None:
        hm.collect(reg)     # ds_fleet_replica_{phi,score,state} gauges
    out = {}
    # per-request async tracks (ISSUE 10) ride the same Chrome-trace
    # document as the host spans — one named tid per request — so
    # `telemetry_report --merge` composes them per rank unchanged
    doc = tracer.chrome_trace()
    pid = doc["traceEvents"][0].get("pid", 0) \
        if doc["traceEvents"] else 0
    if rt is not None:
        doc["traceEvents"].extend(
            rt.chrome_events(pid, tracer.epoch_ns))
    if st is not None:
        # per-step training tracks (ISSUE 20) share the document too,
        # so --merge composes steps + components alongside host spans
        doc["traceEvents"].extend(
            st.chrome_events(pid, tracer.epoch_ns))
    trace_path = os.path.join(out_dir, f"{prefix}.trace.json")
    import json as _json
    with open(trace_path, "w") as f:
        _json.dump(doc, f)
    out["trace"] = trace_path
    out["prometheus"] = reg.dump_prometheus(
        os.path.join(out_dir, f"{prefix}.prom"))
    out["metrics_json"] = reg.dump_json(
        os.path.join(out_dir, f"{prefix}.metrics.json"))
    if rt is not None:
        # structured access log: one JSONL line per completed request
        log_path = rt.write_access_log(
            os.path.join(out_dir, f"{prefix}.access.jsonl"))
        if log_path:
            out["access_log"] = log_path
    if st is not None:
        # step log: one STEP_LOG_KEYS JSONL line per training step;
        # telemetry_report --diff accepts it as a numeric source
        log_path = st.write_step_log(
            os.path.join(out_dir, f"{prefix}.steps.jsonl"))
        if log_path:
            out["step_log"] = log_path
    led = get_ledger()
    if led is not None:
        import json as _json
        path = os.path.join(out_dir, f"{prefix}.ledger.json")
        with open(path, "w") as f:
            _json.dump(led.snapshot(), f, indent=1, default=str)
        out["ledger"] = path
    scope = get_fleet()
    if scope is not None:
        # versioned fleet rollup (ISSUE 17); embeds the health snapshot
        # so telemetry_report --fleet renders from this file alone
        out["fleet"] = scope.write(
            os.path.join(out_dir, f"{prefix}.fleet.json"),
            health=hm.snapshot() if hm is not None else None)
    return out


def dump_flight_record(reason: str,
                       out_dir: Optional[str] = None) -> str:
    """Write a hang-dump artifact NOW (flight-recorder events, open
    spans, ledger, memory, thread stacks) — the entry external
    watchdogs (bench's ``--total-budget-s``) route through. Returns
    the artifact path, or '' when the flight recorder is off."""
    dog = get_watchdog()
    if dog is not None:
        return dog.fire(reason)
    rec = get_flight_recorder()
    if rec is None:
        return ""
    return _flightrec_mod.dump_state(
        reason, out_dir or _ARTIFACT_DIR, recorder=rec,
        tracer=get_tracer(), ledger=get_ledger(),
        registry=get_registry(), reqtrace=get_request_recorder(),
        steptrace=get_step_recorder())
