"""Time-series derivation over the metrics registry (ISSUE 17
tentpole part 1).

The registry is a point-in-time surface: counters only ever say "N
breaches since start", never "how fast are we burning NOW". This
module keeps a bounded in-process ring of periodic registry snapshots
(flattened to ``{series: value}``) and derives the signals the fleet
health plane consumes:

- ``rate(series, window_s)`` — per-second increase of a monotonic
  counter over a lookback window (the Prometheus ``rate()`` analogue,
  computed host-side with no scraper in the loop);
- ``burn_rate(numerator, denominator, window_s)`` — windowed ratio of
  two counter deltas, e.g. SLO breaches per completed request: the
  multi-window fast/slow-burn figure SRE-style alerting keys on
  (a 60 s window catching a cliff, a 3600 s window catching a slow
  leak — see docs/observability.md);
- ``window_percentile(series, window_s, q)`` — sliding-window
  percentile of a gauge's sampled values (queue depth p99 over the
  last minute, free-block p01, ...).

Stem helpers sum label-variants: ``ds_serving_slo_ttft_breaches_total``
may carry labels (one series per label set after flattening), and the
burn computation wants the total.

Sampling is pull-based and rate-limited (``maybe_sample``): the
serving loop calls it on its existing ~4 Hz housekeeping path, and the
ring itself enforces ``interval_s`` so a hot loop cannot oversample.
Host-only, stdlib-only, zero-import when telemetry is disabled (same
contract as reqtrace/flightrec; lint_all's host-only audit covers this
module). A ``clock`` injection point keeps every derivation
fake-clock testable.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

# default multi-window burn lookbacks (seconds): fast burn (a cliff
# shows up within a minute), mid, slow burn (a leak shows up over an
# hour). Mirrored by TelemetryConfig.burn_windows_s.
DEFAULT_BURN_WINDOWS_S = (60.0, 300.0, 3600.0)


def flatten_snapshot(snap: dict) -> dict[str, float]:
    """Registry ``snapshot()`` dict -> flat ``{series: value}``.

    Scalar metrics flatten to ``name[/k=v...]``; histograms contribute
    ``_count``/``_sum``/``_mean`` leaves — the same naming
    ``tools/telemetry_report.parse_metrics_json`` produces, so ring
    samples, fleet rollups and report rows all speak one key space."""
    out: dict[str, float] = {}
    for name, meta in snap.items():
        for entry in meta.get("values", []):
            labels = entry.get("labels") or {}
            suffix = "".join(f"/{k}={v}"
                             for k, v in sorted(labels.items()))
            if meta.get("type") == "histogram":
                out[f"{name}{suffix}_count"] = float(
                    entry.get("count", 0))
                out[f"{name}{suffix}_sum"] = float(entry.get("sum", 0.0))
                out[f"{name}{suffix}_mean"] = float(
                    entry.get("mean", 0.0))
            else:
                out[f"{name}{suffix}"] = float(entry.get("value", 0.0))
    return out


def stem_total(flat: dict[str, float], stem: str) -> float:
    """Sum every series containing ``stem`` (label variants of one
    counter), excluding the non-additive ``_mean`` histogram leaves."""
    return sum(v for k, v in flat.items()
               if stem in k and not k.endswith("_mean"))


class TimeSeriesRing:
    """Bounded ring of ``(t, flat_metrics)`` samples + derivations.

    All readers tolerate an empty/short ring (return ``None``), so the
    health plane degrades to "no signal" instead of raising while the
    first window fills."""

    def __init__(self, capacity: int = 512, interval_s: float = 0.25,
                 clock: Callable[[], float] = time.monotonic):
        self.capacity = max(int(capacity), 8)
        self.interval_s = max(float(interval_s), 0.0)
        self._clock = clock
        self._samples: deque[tuple[float, dict]] = deque(
            maxlen=self.capacity)
        self._lock = threading.Lock()
        self._next_sample = 0.0

    def __len__(self) -> int:
        return len(self._samples)

    # -- writers -------------------------------------------------------
    def record(self, flat: dict[str, float],
               now: Optional[float] = None) -> None:
        """Append one pre-flattened sample (tests, cross-process
        feeds)."""
        t = self._clock() if now is None else float(now)
        with self._lock:
            self._samples.append((t, dict(flat)))

    def sample(self, registry=None, now: Optional[float] = None) -> bool:
        """Snapshot ``registry`` (default: the live one) into the ring.
        Returns False when no registry is available."""
        if registry is None:
            from .registry import get_registry
            registry = get_registry()
        if registry is None:
            return False
        self.record(flatten_snapshot(registry.snapshot()), now=now)
        return True

    def maybe_sample(self, registry=None,
                     now: Optional[float] = None) -> bool:
        """Rate-limited :meth:`sample`: no-op (False) until
        ``interval_s`` has passed since the previous accepted sample.
        The serving loop calls this on its housekeeping path without
        its own cadence bookkeeping."""
        t = self._clock() if now is None else float(now)
        if t < self._next_sample:
            return False
        self._next_sample = t + self.interval_s
        return self.sample(registry, now=t)

    def clear(self) -> None:
        with self._lock:
            self._samples.clear()
        self._next_sample = 0.0

    # -- readers -------------------------------------------------------
    def latest(self) -> Optional[tuple[float, dict]]:
        with self._lock:
            return self._samples[-1] if self._samples else None

    def _window(self, window_s: float,
                now: Optional[float] = None) -> list[tuple[float, dict]]:
        t = self._clock() if now is None else float(now)
        lo = t - float(window_s)
        with self._lock:
            return [(ts, s) for ts, s in self._samples if ts >= lo]

    def _bracket(self, window_s: float, now: Optional[float] = None):
        """(oldest-in-window, newest) sample pair, or None when fewer
        than two samples cover the window."""
        rows = self._window(window_s, now)
        if len(rows) < 2:
            return None
        return rows[0], rows[-1]

    def delta(self, stem: str, window_s: float,
              now: Optional[float] = None) -> Optional[float]:
        """Increase of the stem-summed counter over the window
        (clamped at 0: a registry clear between samples must not read
        as a negative burn)."""
        br = self._bracket(window_s, now)
        if br is None:
            return None
        (_, old), (_, new) = br
        return max(stem_total(new, stem) - stem_total(old, stem), 0.0)

    def rate(self, stem: str, window_s: float,
             now: Optional[float] = None) -> Optional[float]:
        """Per-second increase of the stem-summed counter over the
        window."""
        br = self._bracket(window_s, now)
        if br is None:
            return None
        (t0, old), (t1, new) = br
        dt = t1 - t0
        if dt <= 0:
            return None
        return max(stem_total(new, stem) - stem_total(old, stem),
                   0.0) / dt

    def burn_rate(self, numerator_stem: str, denominator_stem: str,
                  window_s: float,
                  now: Optional[float] = None) -> Optional[float]:
        """Windowed Δnumerator / Δdenominator — e.g. SLO breaches per
        completed request over the window. ``None`` while the window
        lacks two samples, ``0.0`` when the denominator did not move
        (no traffic burns no budget)."""
        br = self._bracket(window_s, now)
        if br is None:
            return None
        (_, old), (_, new) = br
        dn = max(stem_total(new, numerator_stem)
                 - stem_total(old, numerator_stem), 0.0)
        dd = max(stem_total(new, denominator_stem)
                 - stem_total(old, denominator_stem), 0.0)
        if dd <= 0:
            return 0.0
        return dn / dd

    def multi_window_burn(self, numerator_stem: str,
                          denominator_stem: str,
                          windows_s=DEFAULT_BURN_WINDOWS_S,
                          now: Optional[float] = None) -> dict[str, float]:
        """{"60s": burn, "300s": burn, ...} over the configured
        lookbacks — the fast/slow-burn pair (plus any mid windows) an
        alerting rule ANDs together. Windows without data are
        omitted."""
        out = {}
        for w in windows_s:
            b = self.burn_rate(numerator_stem, denominator_stem, w,
                               now=now)
            if b is not None:
                out[f"{int(w)}s"] = b
        return out

    def window_percentile(self, series: str, window_s: float, q: float,
                          now: Optional[float] = None) -> Optional[float]:
        """Percentile ``q`` (0..1) of an EXACT series' sampled values
        over the window (gauges: queue depth, free blocks, phi)."""
        rows = self._window(window_s, now)
        vals = sorted(s[series] for _, s in rows if series in s)
        if not vals:
            return None
        q = min(max(float(q), 0.0), 1.0)
        return vals[min(len(vals) - 1, int(len(vals) * q))]

    def series_names(self) -> list[str]:
        """Union of series keys across the ring (report/debug)."""
        seen: set[str] = set()
        with self._lock:
            for _, s in self._samples:
                seen.update(s)
        return sorted(seen)


# --- module-level current ring (wired by telemetry.configure) ------------

_RING: Optional[TimeSeriesRing] = None


def get_timeseries() -> Optional[TimeSeriesRing]:
    return _RING


def set_timeseries(ring: Optional[TimeSeriesRing]) -> None:
    global _RING
    _RING = ring
