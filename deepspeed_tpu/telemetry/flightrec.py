"""Flight recorder + hang/straggler watchdog (ISSUE 5 tentpole part 3).

BENCH runs have died rc=124 with zero forensics: a wedged XLA compile
or a stuck collective leaves nothing behind but the kill. This module
keeps a lock-free per-rank ring buffer of the last N dispatch /
collective / progress events (``FlightRecorder``) and a watchdog
thread (``HangWatchdog``) that — when the instrumented loops
(``engine.train_batch``, the fused-decode drain) stop reporting
progress past a configurable deadline — dumps everything a post-mortem
needs into an artifact directory: flight-recorder events, the span
tracer's OPEN spans (what the host was inside when it stalled), the
executable ledger, device/host memory, and every thread's Python
stack. Optionally aborts the process afterwards so an external
supervisor restarts it instead of waiting out a harness SIGKILL.

Multiprocess straggler accounting rides the same machinery:
``record_straggler_skew`` host-all-reduces a per-step timestamp and
exposes max-min as ``ds_straggler_skew_seconds``.

Lock-free claim: ``record()`` takes a slot from ``itertools.count``
(atomic under the GIL) and writes one list cell — no lock anywhere on
the hot path, so the recorder can never deadlock-or-slow the loop it
is black-boxing. Host-only API (graftlint GL041): never call from
jit-reachable code.
"""

from __future__ import annotations

import itertools
import json
import math
import os
import threading
import time
from typing import Optional


class FlightRecorder:
    """Bounded ring of recent events plus per-key progress heartbeats.

    An *event* is ``(unix_time, slot, kind, name, meta)``; *progress*
    is a monotonic heartbeat the watchdog compares against its
    deadline (and also lands in the ring, so the dump shows the last
    thing that DID advance)."""

    def __init__(self, capacity: int = 2048):
        self.capacity = max(int(capacity), 8)
        self._buf: list = [None] * self.capacity
        self._slot = itertools.count()
        # key -> monotonic stamp of the key's latest progress report
        self._progress: dict[str, float] = {}

    # -- hot path (lock-free) -----------------------------------------
    def record(self, kind: str, name: str, **meta) -> None:
        slot = next(self._slot)
        self._buf[slot % self.capacity] = (
            time.time(), slot, kind, name, meta or None)

    def progress(self, key: str, **meta) -> None:
        self._progress[key] = time.monotonic()
        self.record("progress", key, **meta)

    # -- readers -------------------------------------------------------
    @property
    def recorded(self) -> int:
        return self._peek_slot()

    def _peek_slot(self) -> int:
        # count() holds the NEXT slot; __reduce__ -> (count, (n,))
        # peeks it without consuming
        return self._slot.__reduce__()[1][0]

    def last_progress(self) -> dict[str, float]:
        return dict(self._progress)

    def stalled_for(self) -> Optional[float]:
        """Seconds since the most recent progress report from ANY key;
        None until something has reported once (never armed before the
        loops start)."""
        if not self._progress:
            return None
        return time.monotonic() - max(self._progress.values())

    def events(self) -> list[dict]:
        rows = [e for e in list(self._buf) if e is not None]
        rows.sort(key=lambda e: e[1])
        return [{"unix_time": t, "slot": s, "kind": k, "name": n,
                 **({"meta": m} if m else {})}
                for t, s, k, n, m in rows]

    def snapshot(self) -> dict:
        now = time.monotonic()
        return {"capacity": self.capacity,
                "recorded": self._peek_slot(),
                "progress_age_s": {k: round(now - v, 4)
                                   for k, v in self._progress.items()},
                "events": self.events()}

    def clear(self) -> None:
        self._buf = [None] * self.capacity
        self._slot = itertools.count()
        self._progress.clear()


# --- straggler skew ------------------------------------------------------

def skew_from_timestamps(timestamps) -> float:
    """Per-step straggler skew: spread (max - min) of the ranks' step
    timestamps. Pure so the multiprocess gauge is unit-testable with
    fake clocks."""
    ts = [float(t) for t in timestamps]
    if len(ts) < 2:
        return 0.0
    return max(ts) - min(ts)


def _sample_skew(reg, step: int, now: Optional[float] = None,
                 reduce_fn=None) -> tuple:
    """One skew sample: two host all-reduces (MIN, MAX) over this
    rank's timestamp. Returns ``(skew, lo)`` — ``lo`` is the
    MIN-reduced timestamp, identical on every rank, which the step
    gate uses to schedule the next sample deterministically."""
    if reduce_fn is None:
        from .. import comm as dist
        reduce_fn = dist.host_all_reduce
    t = time.time() if now is None else now
    from ..comm.comm import ReduceOp
    lo = float(reduce_fn(t, ReduceOp.MIN))
    hi = float(reduce_fn(t, ReduceOp.MAX))
    skew = max(hi - lo, 0.0)
    if reg is not None:
        reg.gauge("ds_straggler_skew_seconds",
                  "cross-rank spread of the latest step timestamp "
                  "(max - min over processes)").set(skew)
        reg.gauge("ds_straggler_last_step",
                  "step the skew gauge was sampled at").set(step)
    return skew, lo


def record_straggler_skew(reg, step: int, now: Optional[float] = None,
                          reduce_fn=None) -> float:
    """Host-all-reduce this rank's step timestamp and expose the
    cross-rank spread as ``ds_straggler_skew_seconds``. Costs two tiny
    host collectives — call at flush boundaries only. Returns the skew
    (0.0 single-process, where no collective runs)."""
    return _sample_skew(reg, step, now=now, reduce_fn=reduce_fn)[0]


class _SkewGate:
    """Deterministic cross-rank gate for the per-step straggler
    cadence. Participation in the two host collectives MUST be decided
    from quantities every rank agrees on — the step counter and the
    MIN-reduced timestamp of the previous sample — never a per-process
    clock, which would let ranks disagree near an interval boundary
    (rank A samples at step N, rank B at step N+1) and desynchronize
    the collective call sequence: mismatched reduces corrupt the skew
    and every later host collective, or hang the job."""

    __slots__ = ("next_step", "prev_step", "prev_lo")

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self.next_step = None     # None -> sample on the first call
        self.prev_step = None
        self.prev_lo = None


_SKEW_GATE = _SkewGate()


def reset_straggler_gate() -> None:
    """Drop the straggler gate's schedule (telemetry ``shutdown()`` /
    ``clear()``) so the cadence starts clean for the next engine or
    test in this process."""
    _SKEW_GATE.reset()


def maybe_record_straggler_skew(reg, step: int,
                                interval_s: float = 1.0,
                                now: Optional[float] = None,
                                reduce_fn=None,
                                gate: Optional[_SkewGate] = None
                                ) -> Optional[float]:
    """Rate-limited :func:`record_straggler_skew` for a per-step call
    cadence (ISSUE 20): the engine ticks this every ``train_batch``
    (same ``process_count > 1`` guard as before) and the two tiny host
    collectives actually run roughly once per ``interval_s``. The gate
    is a step stride derived only from cross-rank-identical inputs
    (the step counter and the MIN-reduced sample timestamps), so every
    rank takes the same sample/skip decision at the same step — see
    :class:`_SkewGate`. Same ``ds_straggler_skew_seconds`` gauge.
    Returns the skew when a sample was taken, None when inside the
    stride."""
    g = _SKEW_GATE if gate is None else gate
    step = int(step)
    if g.next_step is not None and step < g.next_step:
        return None
    skew, lo = _sample_skew(reg, step, now=now, reduce_fn=reduce_fn)
    # convert interval_s into a step stride from the steps/sec between
    # the last two samples; both inputs (step delta, reduced-timestamp
    # delta) are identical on every rank, so next_step is too
    iv = max(float(interval_s), 0.0)
    if (g.prev_lo is not None and lo > g.prev_lo
            and step > g.prev_step):
        rate = (step - g.prev_step) / (lo - g.prev_lo)
        stride = max(int(math.ceil(iv * rate)), 1)
    else:
        stride = 1
    g.prev_step, g.prev_lo = step, lo
    g.next_step = step + stride
    return skew


# --- hang dump -----------------------------------------------------------

def _thread_stacks() -> dict:
    import sys
    import traceback
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        label = f"{names.get(ident, 'unknown')}-{ident}"
        out[label] = traceback.format_stack(frame)
    return out


def dump_state(reason: str, out_dir: str, recorder=None, tracer=None,
               ledger=None, registry=None, reqtrace=None,
               steptrace=None) -> str:
    """Write one self-contained hang-dump JSON artifact and return its
    path. Safe to call from any thread (the watchdog's, bench's
    budget watchdog, a signal handler's deferred path); never raises —
    forensics must not mask the original failure."""
    doc: dict = {"reason": reason, "unix_time": time.time(),
                 "pid": os.getpid()}
    try:
        doc["thread_stacks"] = _thread_stacks()
    except Exception as e:   # noqa: BLE001
        doc["thread_stacks_error"] = repr(e)
    try:
        if recorder is not None:
            doc["flight_recorder"] = recorder.snapshot()
    except Exception as e:   # noqa: BLE001
        doc["flight_recorder_error"] = repr(e)
    try:
        if tracer is not None:
            doc["open_spans"] = tracer.open_spans()
            doc["span_totals"] = {
                name: {"seconds": sec, "count": cnt}
                for name, (sec, cnt) in tracer.totals().items()}
    except Exception as e:   # noqa: BLE001
        doc["open_spans_error"] = repr(e)
    try:
        if ledger is not None:
            doc["ledger"] = ledger.snapshot()
    except Exception as e:   # noqa: BLE001
        doc["ledger_error"] = repr(e)
    try:
        # the stuck REQUESTS, not just the stalled thread (ISSUE 10):
        # uids, trace ids, state and age of everything in flight
        if reqtrace is not None:
            doc["in_flight_requests"] = reqtrace.in_flight()
    except Exception as e:   # noqa: BLE001
        doc["in_flight_requests_error"] = repr(e)
    try:
        # the recent training STEPS (ISSUE 20): last N telescoped step
        # records, the run goodput/badput ledger, and any regression
        # findings — a training hang's dump says what the steps were
        # spending time on right before the stall
        if steptrace is not None:
            doc["steptrace"] = {
                "last_steps": steptrace.last_steps(16),
                "goodput": steptrace.goodput_summary(),
                "regressions": steptrace.regressions()}
    except Exception as e:   # noqa: BLE001
        doc["steptrace_error"] = repr(e)
    try:
        if registry is not None:
            doc["metrics"] = registry.snapshot()
    except Exception as e:   # noqa: BLE001
        doc["metrics_error"] = repr(e)
    try:
        # blocksan journal tail (ISSUE 11): when the KV-accounting
        # sanitizer is active, a wedged serving loop's dump also says
        # what the allocator was DOING — the last accounting ops with
        # call-site provenance, violation log and conservation counters
        from ..analysis.blocksan import get_blocksan
        san = get_blocksan()
        if san is not None:
            doc["blocksan"] = san.snapshot()
    except Exception as e:   # noqa: BLE001
        doc["blocksan_error"] = repr(e)
    try:
        # meshsan contract state + collective stall attribution
        # (ISSUE 15): when the mesh-traffic sanitizer is active, the
        # dump joins the recorder's last dispatch heartbeat against the
        # registered executables' HLO collective content — a wedged
        # multichip run names the collectives (axis, op, bytes) it died
        # inside, not just the host thread stacks
        from ..analysis.meshsan import get_meshsan
        msan = get_meshsan()
        if msan is not None:
            doc["meshsan"] = msan.snapshot()
            if recorder is not None:
                doc["collective_stall"] = msan.stall_attribution(
                    recorder.events())
    except Exception as e:   # noqa: BLE001
        doc["meshsan_error"] = repr(e)
    try:
        # numsan numerics state (ISSUE 18): when the numerics
        # sanitizer is active, the dump carries its counters, recent
        # findings, any deferred (not-yet-drained) quantize-site
        # saturation findings and the last/max saturation per site —
        # a hang that follows an fp16 death spiral or a clipping
        # quantizer is attributable from the artifact alone
        from ..analysis.numsan import get_numsan
        nsan = get_numsan()
        if nsan is not None:
            doc["numsan"] = nsan.snapshot()
    except Exception as e:   # noqa: BLE001
        doc["numsan_error"] = repr(e)
    try:
        # fleet health (ISSUE 17): when the failure detector is
        # active, the dump says what the health plane believed about
        # every replica at the moment of the hang — phi, score, state,
        # heartbeat ages — so "watchdog fired" and "detector saw it"
        # can be correlated from the artifact alone
        from . import health as _health
        hm = _health.get_health_monitor()
        if hm is not None:
            doc["fleet_health"] = hm.snapshot()
    except Exception as e:   # noqa: BLE001
        doc["fleet_health_error"] = repr(e)
    try:
        with open("/proc/self/status") as f:
            doc["host_memory"] = {
                k: v.strip() for k, v in
                (line.split(":", 1) for line in f
                 if line.startswith(("VmRSS", "VmHWM")))}
    except Exception:
        pass
    try:
        # device stats LAST: on a truly wedged runtime the PJRT query
        # itself may block, and everything above is already on disk
        # semantics-wise (the dict is complete before the write below)
        from ..utils.memory import device_memory_stats
        # last-resort device query from the watchdog daemon: ordered
        # LAST precisely because it may block on a wedged runtime, and
        # the dump dict is already complete above
        doc["device_memory"] = device_memory_stats()    # graftlint: disable=GL050
    except Exception as e:   # noqa: BLE001
        doc["device_memory_error"] = repr(e)
    try:
        import jax
        doc["rank"] = jax.process_index()
    except Exception:
        doc["rank"] = 0
    path = os.path.join(
        out_dir, f"hangdump_r{doc['rank']}_{int(doc['unix_time'])}_"
                 f"{os.getpid()}.json")
    try:
        os.makedirs(out_dir, exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f, indent=1, default=str)
    except Exception:   # noqa: BLE001
        return ""
    return path


class HangWatchdog:
    """Daemon thread that dumps forensics when the instrumented loops
    stall. Arms only after the FIRST progress report (so import-time /
    warmup compiles can take as long as they take), fires once per
    stall (re-arms when progress resumes), and optionally SIGABRTs the
    process after the dump so a supervisor restarts instead of an
    external timeout SIGKILLing without artifacts."""

    def __init__(self, recorder: FlightRecorder, deadline_s: float,
                 artifact_dir: str, poll_s: Optional[float] = None,
                 abort: bool = False):
        self.recorder = recorder
        self.deadline_s = float(deadline_s)
        self.artifact_dir = artifact_dir
        self.poll_s = poll_s if poll_s else max(
            min(self.deadline_s / 4.0, 5.0), 0.05)
        self.abort = bool(abort)
        self.dumps: list[str] = []
        self._stop = threading.Event()
        self._fired_at: Optional[float] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="telemetry-hang-watchdog")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    def _run(self) -> None:     # graftsan: domain=daemon
        while not self._stop.wait(self.poll_s):
            stalled = self.recorder.stalled_for()
            if stalled is None or stalled <= self.deadline_s:
                self._fired_at = None
                continue
            last = max(self.recorder.last_progress().values())
            if self._fired_at == last:
                continue       # already dumped THIS stall
            self._fired_at = last
            self.fire(f"no progress for {stalled:.1f}s "
                      f"(deadline {self.deadline_s:.1f}s)")
            if self.abort:
                import signal
                os.kill(os.getpid(), signal.SIGABRT)

    def fire(self, reason: str) -> str:
        """Dump now, regardless of stall state (bench's total-budget
        watchdog routes through here)."""
        from . import (get_ledger, get_registry, get_request_recorder,
                       get_step_recorder, get_tracer)
        path = dump_state(reason, self.artifact_dir,
                          recorder=self.recorder, tracer=get_tracer(),
                          ledger=get_ledger(), registry=get_registry(),
                          reqtrace=get_request_recorder(),
                          steptrace=get_step_recorder())
        if path:
            self.dumps.append(path)
            from ..utils.logging import logger
            logger.error(
                f"telemetry hang watchdog: {reason}; forensics dumped "
                f"to {path}")
        return path


# --- module-level current recorder/watchdog (wired by configure) ---------

_RECORDER: Optional[FlightRecorder] = None
_WATCHDOG: Optional[HangWatchdog] = None


def get_flight_recorder() -> Optional[FlightRecorder]:
    return _RECORDER


def set_flight_recorder(rec: Optional[FlightRecorder]) -> None:
    global _RECORDER
    _RECORDER = rec


def get_watchdog() -> Optional[HangWatchdog]:
    return _WATCHDOG


def set_watchdog(dog: Optional[HangWatchdog]) -> None:
    global _WATCHDOG
    if _WATCHDOG is not None and dog is not _WATCHDOG:
        _WATCHDOG.stop()
    _WATCHDOG = dog
