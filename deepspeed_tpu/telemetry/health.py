"""Replica failure detection + composite health scoring (ISSUE 17
tentpole part 2).

Two signals, one state machine per replica:

- **Liveness** — a phi-accrual-style failure detector (Hayashibara et
  al.; the Akka/Cassandra lineage) over the serving loop's heartbeats.
  Each replica's recent inter-heartbeat intervals form an empirical
  distribution; ``phi`` is the log-scaled suspicion that the CURRENT
  silence is not explained by that distribution (``phi = log10(e) *
  silence / mean_interval`` under the exponential model — monotonic in
  silence, self-calibrating to each replica's own cadence, so a slow
  replica is not a suspect replica). Two robustness guards: the mean
  is floored at ``min_interval_s`` (a burst of fast beats must not
  over-tighten the calibration), and phi reports 0 until the silence
  exceeds the LONGEST interval in the window (a pause the replica
  already survived once is not evidence). Heartbeats are a SEPARATE
  channel
  from the flight recorder's ``progress()``: progress means "work
  advanced" (the hang watchdog's signal, silent while idle by design),
  heartbeats mean "the loop thread is alive" (sent while idle too).

- **Quality** — a composite score in [0, 1] from the signals the
  serving stack already produces: queue saturation, KV free-block
  headroom, windowed SLO burn rate (from
  :mod:`.timeseries`), blocksan/meshsan violation counters, and
  hang-watchdog stall age. The score is the MINIMUM of the available
  sub-scores (weakest link): a replica with one exhausted resource is
  degraded no matter how healthy the rest looks.

States: ``healthy -> degraded -> suspect -> dead``. Liveness owns the
suspect/dead arc (phi thresholds), quality owns degraded. Hysteresis:
leaving ``suspect`` requires phi to fall BELOW
``phi_suspect * recovery_ratio`` (not merely below the trip point), so
jittered heartbeats straddling the threshold cannot flap the state;
``dead`` is terminal under silence — only an explicit recovery
heartbeat (the replica's loop demonstrably running again) re-admits
it, resetting its interval history so stale pre-death cadence does not
poison the revived detector.

The router consumes ``state()`` at placement (suspect/dead excluded,
degraded drains); the hang-watchdog dump embeds ``snapshot()`` as its
``fleet_health`` section; ``collect()`` exports ``ds_fleet_*`` gauges.
Host-only, stdlib-only, zero-import when telemetry is disabled;
``clock`` injection keeps every transition fake-clock testable.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Callable, Optional

HEALTH_STATES = ("healthy", "degraded", "suspect", "dead")
_STATE_RANK = {s: i for i, s in enumerate(HEALTH_STATES)}
_LOG10_E = math.log10(math.e)


class _Replica:
    __slots__ = ("name", "last_beat", "intervals", "state",
                 "transitions", "inputs", "beats", "deaths")

    def __init__(self, name: str, window: int):
        self.name = name
        self.last_beat: Optional[float] = None
        self.intervals: deque[float] = deque(maxlen=window)
        self.state = "healthy"
        self.transitions = 0
        self.inputs: dict = {}
        self.beats = 0
        self.deaths = 0


class HealthMonitor:
    """See module docstring. One instance per process, shared across
    replicas; all methods are host-only and O(window) worst case."""

    def __init__(self, *, phi_suspect: float = 4.0,
                 phi_dead: float = 10.0, heartbeat_window: int = 64,
                 min_heartbeats: int = 3, recovery_ratio: float = 0.5,
                 degraded_score: float = 0.35,
                 free_block_floor: int = 0,
                 stall_deadline_s: float = 5.0,
                 burn_degraded: float = 0.5,
                 min_interval_s: float = 0.05,
                 clock: Callable[[], float] = time.monotonic):
        if not 0.0 < recovery_ratio <= 1.0:
            raise ValueError(
                f"recovery_ratio must be in (0, 1]: {recovery_ratio}")
        if phi_dead < phi_suspect:
            raise ValueError(
                f"phi_dead {phi_dead} < phi_suspect {phi_suspect}")
        self.phi_suspect = float(phi_suspect)
        self.phi_dead = float(phi_dead)
        self.heartbeat_window = max(int(heartbeat_window), 2)
        self.min_heartbeats = max(int(min_heartbeats), 1)
        self.recovery_ratio = float(recovery_ratio)
        self.degraded_score = float(degraded_score)
        self.free_block_floor = int(free_block_floor)
        self.stall_deadline_s = float(stall_deadline_s)
        self.burn_degraded = max(float(burn_degraded), 1e-9)
        # floor on the empirical mean interval: a burst of sub-ms
        # beats from a busy loop must not calibrate the detector so
        # tight that one long engine step reads as infinite silence
        # (Akka's analogous knob is the min std deviation)
        self.min_interval_s = max(float(min_interval_s), 0.0)
        self._clock = clock
        self._replicas: dict[str, _Replica] = {}
        self._lock = threading.Lock()

    def _get(self, name: str) -> _Replica:
        r = self._replicas.get(name)
        if r is None:
            r = self._replicas[name] = _Replica(
                str(name), self.heartbeat_window)
        return r

    # -- liveness ------------------------------------------------------
    def heartbeat(self, name: str, now: Optional[float] = None) -> None:
        """One liveness beat from ``name``'s loop thread. A beat from a
        DEAD replica is the explicit recovery signal: state returns to
        healthy and the interval history resets (post-restart cadence
        starts clean)."""
        t = self._clock() if now is None else float(now)
        with self._lock:
            r = self._get(name)
            r.beats += 1
            if r.state == "dead":
                r.intervals.clear()
                r.last_beat = None
                self._transition(r, "healthy")
            if r.last_beat is not None:
                gap = max(t - r.last_beat, 0.0)
                if self._phi_locked(r, t) >= self.phi_dead:
                    # a gap the detector would have called death is a
                    # REJOIN, not a sample: fold it into the window
                    # and one stale epoch poisons the mean (and the
                    # max-interval guard) for the whole next epoch
                    r.intervals.clear()
                else:
                    r.intervals.append(gap)
            r.last_beat = t

    def _phi_locked(self, r: _Replica, t: float) -> float:
        # caller holds self._lock
        if r.last_beat is None \
                or len(r.intervals) < self.min_heartbeats:
            return 0.0
        silence = max(t - r.last_beat, 0.0)
        # a pause no longer than one the replica already survived is
        # not evidence: without this guard one slow engine step (long
        # tick, GC pause) reads as suspicion whenever the window mean
        # sits well below the window max
        if silence <= max(r.intervals):
            return 0.0
        mean = sum(r.intervals) / len(r.intervals)
        return _LOG10_E * silence / max(mean, self.min_interval_s,
                                        1e-9)

    def phi(self, name: str, now: Optional[float] = None) -> float:
        """Suspicion level for ``name``: 0 while the detector has too
        little history, else log10-scaled and MONOTONIC in silence."""
        t = self._clock() if now is None else float(now)
        with self._lock:
            r = self._replicas.get(name)
            if r is None:
                return 0.0
            return self._phi_locked(r, t)

    # -- quality -------------------------------------------------------
    def observe(self, name: str, *, queue_frac: Optional[float] = None,
                free_blocks: Optional[int] = None,
                slo_burn: Optional[float] = None,
                violations: Optional[int] = None,
                stalled_s: Optional[float] = None) -> None:
        """Composite-score inputs (any subset; absent = no signal).
        ``queue_frac`` is open/capacity in [0, 1]; ``free_blocks``
        scores against ``free_block_floor`` (0 disables); ``slo_burn``
        is a windowed breach fraction (breaches/request) scored
        against ``burn_degraded``; any nonzero sanitizer ``violations``
        zeroes the score (a correctness finding, not a perf number);
        ``stalled_s`` scores against ``stall_deadline_s``."""
        with self._lock:
            r = self._get(name)
            for key, val in (("queue_frac", queue_frac),
                             ("free_blocks", free_blocks),
                             ("slo_burn", slo_burn),
                             ("violations", violations),
                             ("stalled_s", stalled_s)):
                if val is not None:
                    r.inputs[key] = val

    def score(self, name: str) -> float:
        """Composite quality score in [0, 1] (1 = no adverse signal);
        the minimum over the sub-scores of the inputs observed so
        far."""
        with self._lock:
            r = self._replicas.get(name)
            inputs = dict(r.inputs) if r is not None else {}
        subs = [1.0]
        if "queue_frac" in inputs:
            subs.append(1.0 - min(max(float(inputs["queue_frac"]),
                                      0.0), 1.0))
        if "free_blocks" in inputs and self.free_block_floor > 0:
            subs.append(min(max(float(inputs["free_blocks"]), 0.0)
                            / self.free_block_floor, 1.0))
        if "slo_burn" in inputs:
            subs.append(1.0 - min(max(float(inputs["slo_burn"]), 0.0)
                                  / self.burn_degraded, 1.0))
        if "violations" in inputs:
            subs.append(0.0 if inputs["violations"] else 1.0)
        if "stalled_s" in inputs and self.stall_deadline_s > 0:
            subs.append(1.0 - min(max(float(inputs["stalled_s"]), 0.0)
                                  / self.stall_deadline_s, 1.0))
        return min(subs)

    # -- state machine -------------------------------------------------
    def _transition(self, r: _Replica, state: str) -> None:
        if state != r.state:
            if state == "dead":
                r.deaths += 1
            r.state = state
            r.transitions += 1

    def state(self, name: str, now: Optional[float] = None) -> str:
        """Evaluate and return ``name``'s current health state.
        Unknown replicas are healthy (no signal is not a finding)."""
        with self._lock:
            r = self._replicas.get(name)
            if r is None:
                return "healthy"
        p = self.phi(name, now=now)
        with self._lock:
            r = self._get(name)
            if r.state == "dead":
                return "dead"       # only heartbeat() revives
            if p >= self.phi_dead:
                self._transition(r, "dead")
                return "dead"
            if p >= self.phi_suspect:
                self._transition(r, "suspect")
                return "suspect"
            if r.state == "suspect" \
                    and p > self.phi_suspect * self.recovery_ratio:
                # hysteresis: keep suspecting until phi clearly drops
                return "suspect"
        # score() takes the lock itself; compute outside it
        sc = self.score(name)
        with self._lock:
            r = self._get(name)
            if r.state == "dead":
                return "dead"
            self._transition(
                r, "degraded" if sc < self.degraded_score else "healthy")
            return r.state

    def states(self, now: Optional[float] = None) -> dict[str, str]:
        """{replica: state} over every replica seen so far — the
        health snapshot a placement decision records."""
        with self._lock:
            names = list(self._replicas)
        return {n: self.state(n, now=now) for n in names}

    def transitions(self, name: str) -> int:
        with self._lock:
            r = self._replicas.get(name)
            return r.transitions if r is not None else 0

    # -- export --------------------------------------------------------
    def snapshot(self, now: Optional[float] = None) -> dict:
        """Per-replica detector view for the hang dump's
        ``fleet_health`` section and the fleet.json artifact."""
        t = self._clock() if now is None else float(now)
        out = {}
        with self._lock:
            names = list(self._replicas)
        for n in names:
            state = self.state(n, now=t)
            with self._lock:
                r = self._replicas[n]
                row = {"state": state,
                       "phi": round(self._phi_locked(r, t), 4),
                       "score": None,
                       "heartbeats": r.beats,
                       "transitions": r.transitions,
                       "deaths": r.deaths,
                       "last_heartbeat_age_s": (
                           round(t - r.last_beat, 4)
                           if r.last_beat is not None else None),
                       "mean_interval_s": (
                           round(sum(r.intervals) / len(r.intervals), 5)
                           if r.intervals else None),
                       "inputs": dict(r.inputs)}
            row["score"] = round(self.score(n), 4)
            out[n] = row
        return out

    def collect(self, reg) -> None:
        """Export ``ds_fleet_*`` gauges (per-replica phi, score, state
        rank, heartbeat age) — flush-boundary only."""
        if reg is None:
            return
        snap = self.snapshot()
        phi_g = reg.gauge("ds_fleet_replica_phi",
                          "phi-accrual suspicion per replica (log10 "
                          "scale; suspect/dead thresholds in config)")
        score_g = reg.gauge("ds_fleet_replica_score",
                            "composite health score per replica "
                            "(1 = healthy, min over sub-scores)")
        state_g = reg.gauge("ds_fleet_replica_state",
                            "health state rank per replica "
                            "(0 healthy, 1 degraded, 2 suspect, "
                            "3 dead)")
        trans_c = reg.counter("ds_fleet_state_transitions_total",
                              "health state-machine transitions per "
                              "replica")
        for name, row in snap.items():
            phi_g.set(row["phi"], replica=name)
            score_g.set(row["score"], replica=name)
            state_g.set(_STATE_RANK[row["state"]], replica=name)
            trans_c.set_total(row["transitions"], replica=name)

    def clear(self) -> None:
        with self._lock:
            self._replicas.clear()


# --- module-level current monitor (wired by telemetry.configure) ---------

_MONITOR: Optional[HealthMonitor] = None


def get_health_monitor() -> Optional[HealthMonitor]:
    return _MONITOR


def set_health_monitor(mon: Optional[HealthMonitor]) -> None:
    global _MONITOR
    _MONITOR = mon
