"""Executable cost/memory ledger (ISSUE 5 tentpole part 1).

Host-side telemetry (PR 2) can time dispatches but knows nothing about
what a compiled step *costs*: FLOPs, HBM traffic, peak device memory.
XLA does — ``Compiled.cost_analysis()`` / ``memory_analysis()`` carry
the compiler's own accounting of the fused, optimized program. The
ledger keeps one entry per ``(jit name, abstract operand signature)``:
call sites hand it the jitted callable plus the operands of a dispatch
(``observe()``), and on FIRST sight of a signature it compiles the same
AOT path the flops profiler uses (``profiler.lower_compiled`` — cached
by jax per signature, so this costs ONE extra backend compile per new
executable during warmup and a dict lookup afterwards), records the
normalized cost/memory analysis, and — when a mesh is given — walks
the optimized HLO for the collective traffic matrix
(:mod:`.collectives`).

Ledger entry names deliberately match the span names of the same call
sites (``compiled_step``, ``v2/dispatch``, ``v2/fused_dispatch``):
``mfu_by_name()`` joins dispatched FLOPs against the span tracer's
measured seconds to produce live MFU — a lower bound, since the span
window includes host time around the device work.

Everything here is host-only API (graftlint GL041): nothing may be
called from jit-reachable code.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

from . import collectives as _collectives


def _signature(args, kwargs) -> tuple:
    """Abstract (shape, dtype) tuple over the flattened operands —
    the executable-cache key modulo sharding. Works on donated/deleted
    arrays (avals survive donation) and plain numpy/python leaves."""
    import jax
    sig = []
    for leaf in jax.tree_util.tree_leaves((args, kwargs or {})):
        shape = getattr(leaf, "shape", None)
        if shape is None:
            sig.append((type(leaf).__name__,))
        else:
            sig.append((tuple(int(d) for d in shape),
                        str(getattr(leaf, "dtype", "?"))))
    return tuple(sig)


class ExecutableEntry:
    """Ledger row for one compiled executable."""

    __slots__ = ("name", "signature", "flops", "bytes_accessed",
                 "memory", "collectives", "traffic", "calls",
                 "registered_unix", "register_error")

    def __init__(self, name: str, signature: tuple):
        self.name = name
        self.signature = signature
        self.flops = 0.0
        self.bytes_accessed = 0.0
        self.memory: dict = {}
        self.collectives: list[dict] = []
        self.traffic: dict = {}
        self.calls = 0
        self.registered_unix = time.time()
        self.register_error = ""

    @property
    def peak_hbm_bytes(self) -> int:
        return int(self.memory.get("peak", 0))

    def signature_str(self) -> str:
        parts = []
        for leaf in self.signature:
            if len(leaf) == 2:
                shape, dtype = leaf
                parts.append(dtype + "[" + ",".join(map(str, shape))
                             + "]")
            else:
                parts.append(str(leaf[0]))
        return " ".join(parts)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "signature": self.signature_str(),
            "n_operands": len(self.signature),
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "arithmetic_intensity": (
                self.flops / self.bytes_accessed
                if self.bytes_accessed else 0.0),
            "memory": dict(self.memory),
            "peak_hbm_bytes": self.peak_hbm_bytes,
            "calls": self.calls,
            "collectives": list(self.collectives),
            "register_error": self.register_error,
        }


class ExecutableLedger:
    """Process-wide registry of compiled executables' device-truth
    cost. Thread-safe; ``observe()`` is cheap after first registration
    (signature hash + dict lookup) and NEVER raises — a broken cost
    model must not take down the training step it measures."""

    def __init__(self, hlo_collectives: bool = True):
        self.hlo_collectives = bool(hlo_collectives)
        self._lock = threading.Lock()
        self._entries: dict[tuple, ExecutableEntry] = {}
        # compile-path seconds by phase, fed by the jax.monitoring
        # listener in bridges.py (covers EVERY compile in the process,
        # including ones the ledger never sees an observe() for)
        self.compile_seconds: dict[str, float] = {}
        self.compile_events: dict[str, int] = {}

    # -- registration --------------------------------------------------
    def observe(self, name: str, jitted, args: tuple = (),
                kwargs: Optional[dict] = None, mesh=None,
                n_devices: Optional[int] = None) -> \
            Optional[ExecutableEntry]:
        """Count one dispatch of ``jitted`` at these operands,
        registering cost/memory/collective analysis on first sight of
        the (name, signature) pair. Call BEFORE the dispatch when any
        operand is donated. Returns the entry (None only if even the
        signature walk failed)."""
        try:
            key = (name, _signature(args, kwargs))
        except Exception:
            return None
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                entry.calls += 1
                return entry
            entry = self._entries[key] = ExecutableEntry(name, key[1])
            entry.calls = 1
        self._register(entry, jitted, args, kwargs or {}, mesh,
                       n_devices)
        return entry

    def _register(self, entry: ExecutableEntry, jitted, args, kwargs,
                  mesh, n_devices) -> None:
        from ..profiling.flops_profiler.profiler import (
            compiled_cost, compiled_memory, lower_compiled)
        try:
            compiled = lower_compiled(jitted, *args, **kwargs)
        except Exception as e:   # noqa: BLE001 - telemetry never raises
            entry.register_error = f"{type(e).__name__}: {e}"[:200]
            return
        cost = compiled_cost(compiled)
        entry.flops = cost.get("flops", 0.0)
        entry.bytes_accessed = cost.get("bytes accessed", 0.0)
        entry.memory = compiled_memory(compiled)
        if self.hlo_collectives:
            try:
                entry.collectives = _collectives.analyze_hlo(
                    compiled.as_text(), mesh=mesh, n_devices=n_devices)
                entry.traffic = _collectives.traffic_matrix(
                    entry.collectives)
            except Exception as e:   # noqa: BLE001
                entry.register_error = (
                    f"hlo: {type(e).__name__}: {e}"[:200])

    def on_compile_event(self, phase: str, dur_s: float) -> None:
        with self._lock:
            self.compile_seconds[phase] = (
                self.compile_seconds.get(phase, 0.0) + dur_s)
            self.compile_events[phase] = (
                self.compile_events.get(phase, 0) + 1)

    # -- readers -------------------------------------------------------
    def entries(self) -> list[ExecutableEntry]:
        with self._lock:
            return list(self._entries.values())

    def __len__(self) -> int:
        return len(self._entries)

    def dispatched_flops(self) -> dict[str, float]:
        """{name: flops x calls summed over signatures}."""
        out: dict[str, float] = {}
        for e in self.entries():
            out[e.name] = out.get(e.name, 0.0) + e.flops * e.calls
        return out

    def peak_hbm_by_name(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for e in self.entries():
            out[e.name] = max(out.get(e.name, 0), e.peak_hbm_bytes)
        return out

    def calls_by_name(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for e in self.entries():
            out[e.name] = out.get(e.name, 0) + e.calls
        return out

    def traffic(self) -> dict:
        """Dispatch-weighted per-(axis, op) traffic matrix over every
        registered executable: static bytes per execution x calls."""
        return _collectives.merge_traffic(
            *(_collectives.traffic_matrix(e.collectives, e.calls)
              for e in self.entries()))

    def mfu_by_name(self, span_totals: dict, peak_flops: float) -> dict:
        """{name: MFU} joining per-dispatch FLOPs against measured
        span seconds: ``avg_flops_per_call x span_count / span_seconds
        / peak``. ``span_totals`` is ``SpanTracer.totals()`` — or
        ``totals_trimmed()`` for steady-state MFU that excludes the
        warmup span (whose duration includes the XLA compile). Names
        absent from the span totals (or zero-duration) are skipped;
        result values are finite by construction."""
        if peak_flops <= 0:
            return {}
        calls = self.calls_by_name()
        out = {}
        for name, flops in self.dispatched_flops().items():
            tot = span_totals.get(name)
            if not tot or tot[0] <= 0 or flops <= 0:
                continue
            avg = flops / max(calls.get(name, 1), 1)
            out[name] = avg * tot[1] / tot[0] / peak_flops
        return out

    # -- calibration queries (ISSUE 7: consumed by autotuning) ---------
    def step_seconds_by_name(self, span_totals: dict) -> dict:
        """{name: {"seconds_per_call", "calls", "flops_per_call"}}
        joining ledger dispatch counts against measured span seconds
        (pass ``SpanTracer.totals_trimmed()`` so the warmup span's XLA
        compile doesn't pollute the rate). Names with no measured
        window are omitted."""
        calls = self.calls_by_name()
        flops = self.dispatched_flops()
        out: dict = {}
        for name, n in calls.items():
            tot = span_totals.get(name)
            if not tot or tot[0] <= 0 or tot[1] <= 0:
                continue
            seconds, count = float(tot[0]), int(tot[1])
            out[name] = {
                "seconds_per_call": seconds / count,
                "calls": n,
                "flops_per_call": flops.get(name, 0.0) / max(n, 1),
            }
        return out

    def effective_flops_per_s(self, span_totals: dict) -> dict:
        """{name: measured FLOPs/s} — the autotuner's calibration rate:
        per-dispatch executable FLOPs over per-dispatch measured span
        seconds. A lower bound on device throughput (span time includes
        host overhead around the device work)."""
        out: dict = {}
        for name, row in self.step_seconds_by_name(span_totals).items():
            if row["flops_per_call"] > 0 and row["seconds_per_call"] > 0:
                out[name] = row["flops_per_call"] / row["seconds_per_call"]
        return out

    def axis_algbw_bounds(self, window_s: float) -> dict:
        """{axis: {"bytes", "algbw_bytes_per_s"}} lower bounds from the
        dispatch-weighted HLO traffic matrix over a measured window:
        every dispatched byte moved somewhere inside the window, so
        bytes/window is an honest floor on per-axis achieved algorithm
        bandwidth (see :func:`.collectives.bandwidth_bounds`)."""
        return _collectives.axis_bandwidth_bounds(self.traffic(),
                                                  window_s)

    def axis_wire_bytes_per_el(self) -> dict:
        """{axis: observed wire bytes/element} over every registered
        executable's collective traffic — 4.0 on an fp32 wire, ~1.1
        once the ZeRO++ quantized collectives carry int8 payloads +
        fp32 block scales. Recorded into autotuning calibrations
        (``Calibration.axis_wire_bytes_per_el``) so plan artifacts
        show which wire the bandwidth floors were measured at."""
        return _collectives.axis_wire_width(self.traffic())

    def collective_bytes_by_axis(self, name: str) -> dict:
        """{axis: per-DISPATCH collective payload bytes} for one jit
        name, call-weighted across its live signatures — the comm
        baseline a calibration fitted on this executable's measured
        rate already contains (the cost model charges only excess)."""
        totals: dict[str, float] = {}
        calls = 0
        for e in self.entries():
            if e.name != name or e.calls <= 0:
                continue
            calls += e.calls
            for (axis, _op), row in _collectives.traffic_matrix(
                    e.collectives, e.calls).items():
                totals[axis] = totals.get(axis, 0.0) + row["bytes"]
        if calls <= 0:
            return {}
        return {axis: b / calls for axis, b in totals.items()}

    def snapshot(self) -> dict:
        rows = sorted((e.to_dict() for e in self.entries()),
                      key=lambda r: (-r["flops"] * r["calls"],
                                     r["name"]))
        traffic = {f"{axis}/{op}": dict(row) for (axis, op), row
                   in sorted(self.traffic().items())}
        return {"executables": rows,
                "n_executables": len(rows),
                "traffic": traffic,
                "compile_seconds": dict(self.compile_seconds),
                "compile_events": dict(self.compile_events)}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.compile_seconds.clear()
            self.compile_events.clear()


# --- module-level current ledger (wired by telemetry.configure) ---------

_LEDGER: Optional[ExecutableLedger] = None


def get_ledger() -> Optional[ExecutableLedger]:
    return _LEDGER


def set_ledger(ledger: Optional[ExecutableLedger]) -> None:
    global _LEDGER
    _LEDGER = ledger


def device_peak_flops(configured: float = 0.0) -> float:
    """Per-device peak FLOPs for MFU accounting: the configured value
    when nonzero, else the accelerator table (1e12 CPU floor — an
    arbitrary but finite denominator, clearly an estimate on hosts
    with no published peak)."""
    if configured and configured > 0:
        return float(configured)
    try:
        from ..accelerator import get_accelerator
        return float(get_accelerator().peak_flops())
    except Exception:
        return 1e12
