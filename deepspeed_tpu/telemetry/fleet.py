"""Fleet-level metrics aggregation (ISSUE 17 tentpole part 3).

Every telemetry surface so far stops at one process; the elasticity
and SLO-controller work (ROADMAP items 1 and 2) needs fleet rollups
and per-replica views. :class:`FleetScope` merges per-replica registry
snapshots from BOTH membership kinds:

- **in-process replicas** — a live :class:`~.registry.MetricsRegistry`
  (or any zero-arg callable returning a ``snapshot()``-shaped dict:
  a router can register a per-replica metrics closure), snapshotted at
  merge time;
- **cross-process replicas** — ``*.metrics.json`` snapshot files other
  processes exported (``telemetry.export_artifacts``), loaded from
  disk at merge time, so a multi-host serving fleet aggregates through
  a shared artifact directory with no RPC plane.

Merge semantics are exact where exactness is meaningful:

- **counters** sum across replicas per label set — the fleet total of
  a monotonic counter IS the sum of the per-replica totals
  (property-tested in tests/test_fleet.py);
- **histograms** merge bucket-by-bucket (counts, sum, count add;
  mean recomputed), valid because every replica shares the registry's
  bucket layout for a given metric name;
- **gauges** are NOT summed into one number blindly — a point-in-time
  value aggregates as ``{sum, min, max, mean, n}`` so both "total free
  blocks fleet-wide" (sum) and "worst replica" (min) stay readable.

``write()`` emits the versioned ``fleet.json`` artifact
(``schema_version`` + a per-instance monotonic ``version`` bumped on
every write) carrying the fleet rollup, the per-replica flat views,
and the health snapshot — everything ``tools/telemetry_report.py
--fleet`` needs to render per-replica + fleet tables with no other
file. Host-only, stdlib-only, zero-import when telemetry is disabled.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Optional, Union

from .timeseries import flatten_snapshot

FLEET_SCHEMA_VERSION = 1


def merge_snapshots(snaps: dict[str, dict]) -> dict:
    """{replica: registry-snapshot} -> one merged snapshot (same
    shape), with gauges widened to aggregate dicts (see module
    docstring)."""
    merged: dict = {}
    for _replica, snap in sorted(snaps.items()):
        for name, meta in snap.items():
            slot = merged.setdefault(
                name, {"type": meta.get("type", "untyped"),
                       "help": meta.get("help", ""), "values": []})
            for entry in meta.get("values", []):
                _merge_entry(slot, meta.get("type"), entry)
    # finalize gauge aggregates + histogram means
    for meta in merged.values():
        for entry in meta["values"]:
            agg = entry.pop("_agg", None)
            if agg is not None:
                entry["value"] = agg["sum"]
                entry["aggregate"] = {
                    "sum": agg["sum"], "min": agg["min"],
                    "max": agg["max"],
                    "mean": agg["sum"] / max(agg["n"], 1),
                    "n": agg["n"]}
            if "count" in entry:
                entry["mean"] = (entry["sum"] / entry["count"]
                                 if entry.get("count") else 0.0)
    return merged


def _merge_entry(slot: dict, kind: Optional[str], entry: dict) -> None:
    labels = entry.get("labels") or {}
    key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
    row = next((e for e in slot["values"]
                if tuple(sorted((str(k), str(v)) for k, v in
                                (e.get("labels") or {}).items())) == key),
               None)
    if kind == "histogram":
        if row is None:
            row = {"labels": dict(labels), "count": 0, "sum": 0.0,
                   "buckets": {}}
            slot["values"].append(row)
        row["count"] += int(entry.get("count", 0))
        row["sum"] += float(entry.get("sum", 0.0))
        for le, cum in (entry.get("buckets") or {}).items():
            row["buckets"][str(le)] = (row["buckets"].get(str(le), 0)
                                       + int(cum))
        return
    value = float(entry.get("value", 0.0))
    if kind == "gauge":
        if row is None:
            row = {"labels": dict(labels),
                   "_agg": {"sum": 0.0, "min": value, "max": value,
                            "n": 0}}
            slot["values"].append(row)
        agg = row.setdefault("_agg", {"sum": 0.0, "min": value,
                                      "max": value, "n": 0})
        agg["sum"] += value
        agg["min"] = min(agg["min"], value)
        agg["max"] = max(agg["max"], value)
        agg["n"] += 1
        return
    # counters (and untyped scalars): exact sum per label set
    if row is None:
        row = {"labels": dict(labels), "value": 0.0}
        slot["values"].append(row)
    row["value"] += value


class FleetScope:
    """See module docstring. Register members, then ``merge()`` /
    ``write()`` at flush boundaries (never per token)."""

    def __init__(self, fleet_id: str = "fleet0"):
        self.fleet_id = str(fleet_id)
        self._members: dict[str, Union[Callable[[], dict], str]] = {}
        self._version = 0
        self._lock = threading.Lock()

    # -- membership ----------------------------------------------------
    def add_replica(self, name: str, source) -> None:
        """Register an in-process member: a ``MetricsRegistry``, or any
        zero-arg callable returning a snapshot-shaped dict. Re-adding
        a name replaces its source (a restarted replica re-registers)."""
        snap = getattr(source, "snapshot", None)
        fn = snap if callable(snap) else source
        if not callable(fn):
            raise TypeError(
                f"add_replica({name!r}): need a registry or callable, "
                f"got {type(source).__name__}")
        with self._lock:
            self._members[str(name)] = fn

    def add_snapshot_file(self, path: str,
                          name: Optional[str] = None) -> str:
        """Register a cross-process member backed by a
        ``*.metrics.json`` snapshot file (re-read at every merge, so a
        periodically re-exported file tracks the remote process).
        Returns the member name (default: the file stem)."""
        if name is None:
            name = os.path.basename(path)
            for suffix in (".metrics.json", ".json"):
                if name.endswith(suffix):
                    name = name[:-len(suffix)]
                    break
        with self._lock:
            self._members[str(name)] = str(path)
        return str(name)

    def remove_replica(self, name: str) -> None:
        with self._lock:
            self._members.pop(str(name), None)

    def members(self) -> list[str]:
        with self._lock:
            return sorted(self._members)

    # -- aggregation ---------------------------------------------------
    def _collect(self) -> tuple[dict[str, dict], dict[str, str]]:
        """{replica: snapshot} from every member; unreadable members
        land in the errors map instead of failing the merge (one dead
        replica's missing file must not blind the fleet view)."""
        with self._lock:
            members = dict(self._members)
        snaps: dict[str, dict] = {}
        errors: dict[str, str] = {}
        for name, src in members.items():
            try:
                if callable(src):
                    snaps[name] = src()
                else:
                    with open(src) as f:
                        snaps[name] = json.load(f)
            except Exception as e:   # noqa: BLE001 — per-member isolation
                errors[name] = f"{type(e).__name__}: {e}"
        return snaps, errors

    def merge(self) -> dict:
        """Fleet rollup document (not yet written to disk):
        ``{fleet_id, replicas: {name: flat view}, fleet: merged
        snapshot, fleet_flat, errors}``."""
        snaps, errors = self._collect()
        merged = merge_snapshots(snaps)
        return {"fleet_id": self.fleet_id,
                "replicas": {n: flatten_snapshot(s)
                             for n, s in sorted(snaps.items())},
                "fleet": merged,
                "fleet_flat": flatten_snapshot(merged),
                "errors": errors}

    def write(self, path: str, health: Optional[dict] = None) -> str:
        """Write the versioned ``fleet.json`` artifact and return its
        path. ``health`` embeds a
        :meth:`~.health.HealthMonitor.snapshot` so the artifact alone
        renders the fleet view."""
        doc = self.merge()
        with self._lock:
            self._version += 1
            version = self._version
        doc.update({"schema_version": FLEET_SCHEMA_VERSION,
                    "version": version,
                    "generated_unix_s": round(time.time(), 3)})
        if health is not None:
            doc["health"] = health
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True, default=str)
        return path

    def clear(self) -> None:
        with self._lock:
            self._members.clear()


# --- module-level current scope (wired by telemetry.configure) -----------

_FLEET: Optional[FleetScope] = None


def get_fleet() -> Optional[FleetScope]:
    return _FLEET


def set_fleet(scope: Optional[FleetScope]) -> None:
    global _FLEET
    _FLEET = scope
