"""graftlint (ISSUE 3): JAX-aware static analysis + runtime sentinels.

Two halves with opposite costs:

- :mod:`.linter` / :mod:`.rules` — pure-``ast`` static analysis
  (GL001-GL063: host syncs in jit-reachable code, recompile hazards,
  donation gaps, dtype promotion, telemetry-probe enforcement, the
  graftsan thread-domain pass — device calls/blocking off the worker
  thread, cross-domain races, lock-order inversions — and the
  shardlint SPMD pass, ISSUE 15: mesh-axis vocabulary validation,
  rank-divergent collectives, vmap/scan collective hazards,
  sharding-spec hygiene). Imports only the stdlib; run via
  ``python tools/graftlint.py`` (``--select spmd`` for the SPMD group
  alone), ``python tools/lint_all.py`` for the whole static gate, or
  the tier-1 gate in ``tests/test_graftlint.py``. Catalog:
  docs/static-analysis.md.
- :mod:`.sentinels` — runtime enforcement on the hot paths the linter
  cannot see into: a recompile sentinel (piggybacking on the telemetry
  bridges' jax.monitoring compile listener) asserting warmed-up steps
  never retrace, and ``jax.transfer_guard``-based hot-path guards wired
  into ``engine.train_batch`` and the v2 fused-decode dispatch/drain.
  Imports jax — keep it out of linter import paths.
- :mod:`.blocksan` — graftsan runtime sanitizers (ISSUE 11): the KV
  block-accounting journal with conservation-at-quiesce checks + leak
  provenance, and the thread-affinity checker. Stdlib-only like the
  linter; opt-in via ``RaggedInferenceEngineConfig.graftsan`` or env
  ``DS_GRAFTSAN=1``.
- :mod:`.meshsan` — the SPMD rules' runtime half (ISSUE 15): declared
  per-executable traffic contracts cross-checked against the telemetry
  ledger's optimized-HLO collective walk (undeclared-axis traffic,
  GSPMD silent-reshard all-to-alls, wire-dtype downgrades), plus
  per-collective stall attribution in hang-watchdog dumps.
  Stdlib-only; opt-in via the ``meshsan`` config blocks or env
  ``DS_MESHSAN=1``.

Import note: this ``__init__`` stays jax-free so the CLI lints without
paying a jax import; reach sentinels via
``from deepspeed_tpu.analysis import sentinels``.
"""

from .core import Finding  # noqa: F401
from .linter import (apply_baseline, diff_against_baseline,  # noqa: F401
                     format_text, lint_paths, load_baseline,
                     save_baseline, traced_roots)
from .rules import ALL_RULES, RULES_BY_ID  # noqa: F401
