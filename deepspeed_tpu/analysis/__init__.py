"""graftlint (ISSUE 3): JAX-aware static analysis + runtime sentinels.

Two halves with opposite costs:

- :mod:`.linter` / :mod:`.rules` — pure-``ast`` static analysis
  (GL001-GL053: host syncs in jit-reachable code, recompile hazards,
  donation gaps, dtype promotion, telemetry-probe enforcement, and the
  graftsan thread-domain pass — device calls/blocking off the worker
  thread, cross-domain races, lock-order inversions). Imports only the
  stdlib; run via ``python tools/graftlint.py`` or the tier-1 gate in
  ``tests/test_graftlint.py``. Catalog: docs/static-analysis.md.
- :mod:`.sentinels` — runtime enforcement on the hot paths the linter
  cannot see into: a recompile sentinel (piggybacking on the telemetry
  bridges' jax.monitoring compile listener) asserting warmed-up steps
  never retrace, and ``jax.transfer_guard``-based hot-path guards wired
  into ``engine.train_batch`` and the v2 fused-decode dispatch/drain.
  Imports jax — keep it out of linter import paths.
- :mod:`.blocksan` — graftsan runtime sanitizers (ISSUE 11): the KV
  block-accounting journal with conservation-at-quiesce checks + leak
  provenance, and the thread-affinity checker. Stdlib-only like the
  linter; opt-in via ``RaggedInferenceEngineConfig.graftsan`` or env
  ``DS_GRAFTSAN=1``.

Import note: this ``__init__`` stays jax-free so the CLI lints without
paying a jax import; reach sentinels via
``from deepspeed_tpu.analysis import sentinels``.
"""

from .core import Finding  # noqa: F401
from .linter import (apply_baseline, diff_against_baseline,  # noqa: F401
                     format_text, lint_paths, load_baseline,
                     save_baseline, traced_roots)
from .rules import ALL_RULES, RULES_BY_ID  # noqa: F401
