"""graftsan runtime sanitizers (ISSUE 11 tentpole part 2): KV
block-accounting + thread-affinity enforcement for the serving stack.

Two host-only, stdlib-only checkers, opt-in via
``RaggedInferenceEngineConfig.graftsan`` (or env ``DS_GRAFTSAN=1``) the
way the recompile sentinel is:

- :class:`BlockSanitizer` — journals every KV-block accounting
  mutation (``allocate``/``free``/``incref``/``decref``/LRU
  park/evict) with CALL-SITE PROVENANCE, asserts refcounts never go
  negative, blocks are never double-freed or incref'd after free, and
  — at every quiesce point (``DSStateManager.flush``/``park``, i.e.
  after each drain/park-restore roundtrip) — checks **pool
  conservation**: every block is exactly one of *free*, *referenced*
  or *LRU-cached*. A violated invariant names the leaked blocks AND
  the stack that allocated them, so the PR 4 cap-path leak class dies
  with a file:line instead of a slow pool exhaustion. Wired into
  ``BlockedAllocator``/``PrefixCache``/``DSStateManager`` behind
  ``sanitizer is not None`` guards — the disabled path is one attribute
  load per accounting call.

- :class:`ThreadAffinityChecker` — the runtime half of the GL050
  thread-domain contract: the engine stamps the thread that owns JAX
  dispatch (the async server re-stamps its worker thread at loop
  start; closed-loop drivers auto-stamp on first dispatch) and every
  subsequent dispatch from ANY other thread raises
  :class:`AffinityError` naming both threads.

Violations also bump ``ds_blocksan_violations_total`` /
``ds_affinity_violations_total`` in the telemetry registry (guarded
through the zero-import probe) so ``tools/telemetry_report.py``
surfaces them, and the active sanitizer's journal tail rides every
hang-watchdog dump (telemetry/flightrec.py).

This module must stay importable without jax (the linter half of
``analysis/`` never pays a jax import; neither does this).
"""

from __future__ import annotations

import itertools
import os
import sys
import threading
from collections import deque
from typing import Optional


class BlockSanError(RuntimeError):
    """A KV block-accounting invariant was violated."""


class AffinityError(RuntimeError):
    """JAX dispatch attempted from a thread that does not own the
    engine."""


def _count_violation(metric: str, kind: str) -> None:
    """Bump the sanitizer-violation counter in the telemetry registry
    when telemetry is active; free (one sys.modules probe) otherwise."""
    try:
        from ..utils.telemetry_probe import active_telemetry
        tel = active_telemetry()
        reg = tel.get_registry() if tel is not None else None
        if reg is not None:
            reg.counter(metric,
                        "graftsan runtime-sanitizer violations "
                        "(ISSUE 11; see docs/static-analysis.md)"
                        ).inc(kind=kind)
    except Exception:   # noqa: BLE001 — telemetry must never mask the finding
        pass


def _call_site(depth: int = 3) -> str:
    """``file:line (func)`` chain of the nearest ``depth`` frames
    outside this module — the provenance attached to every journal
    entry and allocation."""
    frames = []
    f = sys._getframe(1)
    here = __file__
    while f is not None and len(frames) < depth:
        fn = f.f_code.co_filename
        if fn != here:
            frames.append(f"{os.path.basename(fn)}:{f.f_lineno} "
                          f"({f.f_code.co_name})")
        f = f.f_back
    return " <- ".join(frames) if frames else "<unknown>"


class BlockSanitizer:
    """See module docstring. One instance audits one
    :class:`~..inference.v2.ragged.DSStateManager`'s pool; attach via
    ``DSStateManager.attach_sanitizer``."""

    def __init__(self, num_blocks: int, mode: str = "raise",
                 journal_size: int = 512):
        if mode not in ("raise", "warn"):
            raise ValueError(
                f"blocksan mode must be raise|warn, got {mode!r}")
        self.n = int(num_blocks)
        self.mode = mode
        self.journal: deque = deque(maxlen=max(int(journal_size), 16))
        # mirrors, updated by the hooks: catching a missed transition
        # (mirror drift vs the allocator's own structures) is itself a
        # conservation failure — it means a free-routing path bypassed
        # the audited choke point
        self.ref = [0] * self.n
        self.freed: set[int] = set(range(self.n))
        self.alloc_site: dict[int, str] = {}
        self.counters = {"ops": 0, "violations": 0, "quiesce_checks": 0}
        self.violation_log: list[str] = []
        # quantized-KV scale-pool mirror (ISSUE 12): when attached, a
        # block's scale slot goes live on allocate and dies on free —
        # conservation asserts the scale partition tracks the KV
        # partition slot-for-slot (a scale slot outliving its freed
        # block, or missing from a live one, is a finding)
        self.scale_slots: Optional[set[int]] = None

    def attach_scale_pool(self) -> None:
        """Audit the quantized pool's scale slabs alongside the KV
        payload (ISSUE 12 satellite): the scale pool shares the KV
        pool's block indices, so its live slots must partition
        IDENTICALLY to the non-free blocks at every quiesce point."""
        self.scale_slots = set()

    # -- plumbing ------------------------------------------------------
    def _journal(self, op: str, blocks, site: str) -> None:
        self.counters["ops"] += 1
        self.journal.append((op, tuple(int(b) for b in blocks), site))

    def _fail(self, msg: str, kind: str) -> None:
        self.counters["violations"] += 1
        self.violation_log.append(msg)
        _count_violation("ds_blocksan_violations_total", kind)
        if self.mode == "raise":
            raise BlockSanError(f"blocksan: {msg}")
        from ..utils.logging import logger
        logger.warning(f"blocksan: {msg}")

    def _provenance(self, block: int) -> str:
        return self.alloc_site.get(block, "<pre-sanitizer allocation>")

    # -- hooks (called by BlockedAllocator / PrefixCache) --------------
    def on_allocate(self, blocks) -> None:
        site = _call_site()
        self._journal("allocate", blocks, site)
        for b in blocks:
            if b not in self.freed:
                self._fail(f"allocate: block {b} handed out while not "
                           f"on the free list (previous owner: "
                           f"{self._provenance(b)}; at {site})",
                           "bad-allocate")
            self.freed.discard(b)
            self.ref[b] = 1
            self.alloc_site[b] = site
            if self.scale_slots is not None:
                self.scale_slots.add(b)

    def on_free(self, blocks) -> None:
        site = _call_site()
        self._journal("free", blocks, site)
        for b in blocks:
            if b in self.freed:
                self._fail(f"double-free: block {b} freed at {site} "
                           f"but already on the free list (allocated "
                           f"at {self._provenance(b)})", "double-free")
                continue
            self.freed.add(b)
            self.ref[b] = 0
            if self.scale_slots is not None:
                self.scale_slots.discard(b)

    def on_incref(self, blocks) -> None:
        site = _call_site()
        self._journal("incref", blocks, site)
        for b in blocks:
            if b in self.freed:
                self._fail(f"use-after-free: incref of freed block {b} "
                           f"at {site} (allocated at "
                           f"{self._provenance(b)})", "use-after-free")
            self.ref[b] += 1

    def on_decref(self, blocks) -> None:
        site = _call_site()
        self._journal("decref", blocks, site)
        for b in blocks:
            if self.ref[b] <= 0:
                self._fail(f"negative refcount: decref of block {b} at "
                           f"refcount {self.ref[b]} ({site}; allocated "
                           f"at {self._provenance(b)})",
                           "negative-refcount")
            self.ref[b] = max(0, self.ref[b] - 1)

    def on_cache_park(self, block: int) -> None:
        site = _call_site()
        self._journal("lru_park", (block,), site)
        if block in self.freed:
            self._fail(f"LRU park of freed block {block} at {site}",
                       "lru-park")
        elif self.ref[block] != 0:
            self._fail(f"LRU park of block {block} with refcount "
                       f"{self.ref[block]} at {site} — only "
                       "unreferenced blocks may park", "lru-park")

    def on_cache_evict(self, block: int) -> None:
        self._journal("lru_evict", (block,), _call_site())

    # -- cross-engine hand-off accounting (ISSUE 13) -------------------
    def on_export(self, uid: int, blocks, tokens: int) -> int:
        """A sequence's KV block set left this pool for another engine
        (``InferenceEngineV2.export_request``). The blocks themselves
        are released through the normal flush choke right after — this
        hook records the hand-off in the PROCESS-WIDE transit ledger
        with the export call site, so a serialized block set that never
        reaches an ``import_request`` is a named finding
        (:func:`check_transit`), not a silent drop. Returns the
        hand-off id that rides the :class:`KVExportState`."""
        site = _call_site()
        self._journal("export", blocks, site)
        hid = next(_HANDOFF_IDS)
        with _TRANSIT_LOCK:
            _TRANSIT[hid] = {"uid": int(uid),
                             "blocks": len(tuple(blocks)),
                             "tokens": int(tokens), "site": site,
                             "mode": self.mode}
        return hid

    def on_import(self, uid: int, blocks,
                  handoff_id: Optional[int]) -> None:
        """A migrated block set landed in this pool
        (``import_request``): journal the arrival and mark the
        exporter's transit entry delivered. The blocks were allocated
        through the audited ``allocate`` hook just before, so
        conservation on THIS pool covers them from here on."""
        self._journal("import", blocks, _call_site())
        if handoff_id is not None:
            record_import(handoff_id)

    # -- quiesce-point conservation ------------------------------------
    def check_conservation(self, allocator, cache, label: str) -> None:
        """Pool conservation at a quiesce point: free + referenced +
        LRU-cached must partition the pool exactly. Derived from the
        LIVE allocator/cache structures (the mirrors only supply
        provenance), so a leak is caught even if a hook was bypassed."""
        self.counters["quiesce_checks"] += 1
        free = set(allocator._free)
        referenced = {b for b in range(self.n) if allocator._ref[b] > 0}
        lru = set(cache.lru) if cache is not None else set()
        problems = []
        for name_a, set_a, name_b, set_b in (
                ("free", free, "referenced", referenced),
                ("free", free, "LRU-cached", lru),
                ("referenced", referenced, "LRU-cached", lru)):
            both = set_a & set_b
            if both:
                problems.append(
                    f"blocks {sorted(both)} are {name_a} AND {name_b}")
        leaked = set(range(self.n)) - free - referenced - lru
        if leaked:
            sites = "; ".join(
                f"block {b} allocated at {self._provenance(b)}"
                for b in sorted(leaked))
            problems.append(
                f"{len(leaked)} block(s) leaked — on no list and "
                f"referenced by nothing: {sites}")
        if self.freed != free:
            drift = self.freed.symmetric_difference(free)
            problems.append(
                f"journal missed a free-list transition on blocks "
                f"{sorted(drift)} (a free-routing path bypassed the "
                "audited choke point)")
        if self.scale_slots is not None:
            # quantized KV (ISSUE 12): scale slots must partition the
            # pool exactly as the payload blocks do — a live block
            # without its scale slot reads garbage scales; a scale slot
            # on a freed block is a leaked slot the next occupant will
            # inherit
            expect = set(range(self.n)) - free
            leaked_s = self.scale_slots - expect
            missing_s = expect - self.scale_slots
            if leaked_s:
                sites = "; ".join(
                    f"block {b} allocated at {self._provenance(b)}"
                    for b in sorted(leaked_s))
                problems.append(
                    f"scale slots {sorted(leaked_s)} leaked — live "
                    f"scale entries on freed blocks ({sites})")
            if missing_s:
                problems.append(
                    f"blocks {sorted(missing_s)} are live without a "
                    "scale slot — their quantized payload would "
                    "dequantize through stale scales")
        if problems:
            self._fail(f"conservation at quiesce point '{label}': "
                       + " | ".join(problems), "conservation")

    # -- reporting -----------------------------------------------------
    def journal_tail(self, n: int = 64) -> list[dict]:
        return [{"op": op, "blocks": list(blocks), "site": site}
                for op, blocks, site in list(self.journal)[-n:]]

    def snapshot(self) -> dict:
        """Hang-dump / forensics view (telemetry/flightrec.py embeds
        this in every watchdog dump while a sanitizer is active)."""
        return {"pool_size": self.n,
                "mode": self.mode,
                "scale_slots": (len(self.scale_slots)
                                if self.scale_slots is not None else None),
                "counters": dict(self.counters),
                "violations": list(self.violation_log[-16:]),
                "pending_handoffs": pending_handoffs(),
                "journal_tail": self.journal_tail()}


class ThreadAffinityChecker:
    """See module docstring. ``bind()`` stamps the calling thread as
    the engine owner (``force=True`` re-stamps — the async server does
    this at worker start, since engine warmup may have auto-bound the
    constructing thread); ``check()`` auto-binds on first dispatch and
    raises :class:`AffinityError` from any other thread afterwards."""

    def __init__(self, mode: str = "raise"):
        if mode not in ("raise", "warn"):
            raise ValueError(
                f"affinity mode must be raise|warn, got {mode!r}")
        self.mode = mode
        self.violations = 0
        self._tid: Optional[int] = None
        self._tname = ""

    def bind(self, force: bool = False) -> None:
        if self._tid is None or force:
            t = threading.current_thread()
            self._tid, self._tname = t.ident, t.name

    def unbind(self) -> None:
        """Release ownership (server shutdown) so a later closed-loop
        driver on another thread can re-stamp instead of raising."""
        self._tid = None
        self._tname = ""

    def check(self, label: str) -> None:
        if self._tid is None:
            self.bind()
            return
        t = threading.current_thread()
        if t.ident == self._tid:
            return
        self.violations += 1
        _count_violation("ds_affinity_violations_total", label)
        msg = (f"graftsan thread-affinity: {label} dispatched from "
               f"thread '{t.name}' ({t.ident}) but the engine is owned "
               f"by '{self._tname}' ({self._tid}) — every JAX call "
               "must run on the worker thread (marshal through the "
               "serving mailbox, or bind(force=True) on a deliberate "
               "ownership transfer)")
        if self.mode == "raise":
            raise AffinityError(msg)
        from ..utils.logging import logger
        logger.warning(msg)


# --- cross-engine hand-off transit ledger (ISSUE 13) ----------------------
# Exports and imports happen on DIFFERENT pools (often different
# sanitizers), so in-transit accounting is process-wide: on_export
# records here, import_request clears — even when the importing pool
# runs unsanitized (the engine clears by handoff_id directly).

_HANDOFF_IDS = itertools.count(1)
_TRANSIT: dict[int, dict] = {}
_TRANSIT_LOCK = threading.Lock()


def record_import(handoff_id: int) -> None:
    """Mark one hand-off delivered (idempotent; unknown ids — e.g. a
    cross-process import — are a no-op)."""
    with _TRANSIT_LOCK:
        _TRANSIT.pop(int(handoff_id), None)


def pending_handoffs() -> list[dict]:
    """Exports not yet imported (legitimately non-empty mid-flight)."""
    with _TRANSIT_LOCK:
        return [dict(v, handoff_id=k) for k, v in _TRANSIT.items()]


def check_transit(mode: str = "raise") -> list[str]:
    """Assert no hand-off was dropped in transit: every export must
    have reached an import by the time a caller (tests, a router
    drain, a shutdown path) declares the system quiescent. Each
    finding names the EXPORT call site — the provenance that turns a
    slow pool-capacity mystery into a file:line. Reported entries are
    consumed (report-once)."""
    with _TRANSIT_LOCK:
        pend = dict(_TRANSIT)
        _TRANSIT.clear()
    msgs = []
    for hid, info in sorted(pend.items()):
        msg = (f"hand-off {hid}: {info['blocks']} block(s) / "
               f"{info['tokens']} tokens for uid {info['uid']} "
               f"exported at {info['site']} were never imported "
               "(dropped in transit)")
        msgs.append(msg)
        _count_violation("ds_blocksan_violations_total",
                         "dropped-handoff")
        if mode == "raise":
            raise BlockSanError(f"blocksan: {msg}")
        from ..utils.logging import logger
        logger.warning(f"blocksan: {msg}")
    return msgs


# --- process-wide handle for forensics (hang dumps) -----------------------
# Engines register their sanitizer here so the hang watchdog can embed
# the journal tail without holding an engine reference; last-enabled
# wins, which is exact for the one-engine serving processes this is for.

_SAN: Optional[BlockSanitizer] = None


def get_blocksan() -> Optional[BlockSanitizer]:
    return _SAN


def set_blocksan(san: Optional[BlockSanitizer]) -> None:
    global _SAN
    _SAN = san


def env_enabled() -> bool:
    """The ``DS_GRAFTSAN=1`` env knob (conftest/CI opt-in): truthy
    values enable the runtime sanitizers even when the config block
    leaves them off."""
    return os.environ.get("DS_GRAFTSAN", "") not in ("", "0")
