"""graftlint core (ISSUE 3 tentpole): AST plumbing shared by every rule.

The linter is pure ``ast`` — it never imports jax or the modules it
checks, so it runs in milliseconds over the whole package and can lint
broken/in-progress code. Precision comes from two analyses:

- **jit-reachability** (:class:`ModuleIndex`): which functions' bodies
  execute under a JAX trace. Roots are functions passed to
  ``jax.jit`` / ``grad`` / ``vmap`` / ``shard_map`` / ``lax`` control
  flow (directly, decorated, or through ``functools.partial``), plus —
  because this framework jits across module boundaries
  (``engine_v2.py`` jits ``paged.fused_decode_loop``) — any def whose
  name the driver saw traced *anywhere* in the lint run
  (``traced_names``). Functions lexically nested in, or called by name
  from, reachable code are reachable.

- **traced-value inference** (:meth:`ModuleIndex.traced_locals`): which
  local names inside a reachable function hold device values. Seeded
  from calls into ``jnp.*`` / ``jax.*`` (minus a host-metadata
  allowlist: ``finfo``, ``eval_shape``, ``tree.map`` …) and propagated
  through assignments. Deliberately does NOT treat bare parameters as
  traced — partial-bound configs (``model``, ``use_kernel``) are
  indistinguishable from arrays by name, and a linter that cries wolf
  gets disabled. The cost is missing ``float(param)`` on a genuine
  array param; the trace would raise loudly there anyway.

Suppression syntax (same line or the line directly above)::

    x = float(loss)   # graftlint: disable=GL001
    # graftlint: disable=GL001,GL004 <optional justification>
    # graftlint: disable            <all rules, use sparingly>

File-level, in the first ten lines::

    # graftlint: disable-file=GL020

**Concurrency domains (ISSUE 11, graftsan)**: every function may carry a
set of *thread domains* — which kind of thread its body runs on —
consumed by the GL050-GL053 rules in :mod:`.rules.concurrency`:

- ``worker``: the engine-owning thread (the only one allowed to touch
  JAX; the async server's ``_work`` loop, or the main thread in
  closed-loop drivers);
- ``asyncio``: the event loop — must never device-call or block;
- ``daemon``: background watchers (watchdog, flight-recorder pollers) —
  may sleep, must not own device work;
- ``any``: author-audited as safe from every thread; exempt from the
  domain rules (use sparingly, it is a declaration, not an inference).

Domains are seeded from declarative annotations on the ``def`` line (or
the line directly above)::

    def _work(self):   # graftsan: domain=worker

``async def`` functions are seeded ``asyncio`` automatically. Seeds
propagate along the same call-graph machinery jit-reachability uses:
lexically nested functions inherit (unless annotated, or handed to a
domain-transfer call — ``loop.call_soon_threadsafe(cb)`` pins ``cb`` to
``asyncio`` regardless of where it is defined), and ``f()`` /
``self.m()`` calls push the caller's domains onto the callee. Across
modules, pass 1 of the lint run exports the names each annotated/async
function calls but does not define (one propagation hop — the same
name-based scheme ``traced_names`` uses).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Iterable, Optional

# --------------------------------------------------------------------
# findings & suppressions
# --------------------------------------------------------------------


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    text: str = ""          # stripped source line (baseline matching key)

    @property
    def key(self) -> tuple:
        """Line-number-free identity used by the baseline: a finding
        only counts as NEW if its (rule, path, source text) triple is
        not already in the baseline — pure line drift never trips the
        gate."""
        return (self.rule, self.path, self.text)

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message, "text": self.text}

    @classmethod
    def from_dict(cls, d: dict) -> "Finding":
        return cls(rule=d["rule"], path=d["path"], line=int(d.get("line", 0)),
                   col=int(d.get("col", 0)), message=d.get("message", ""),
                   text=d.get("text", ""))


_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable(?!-file)(?:=([A-Z0-9, ]+))?")
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*graftlint:\s*disable-file=([A-Z0-9, ]+)")

# thread-domain annotation (ISSUE 11): see module docstring
_DOMAIN_RE = re.compile(r"#\s*graftsan:\s*domain=([a-z_]+)")

# mesh-axis vocabulary annotation (ISSUE 15, shardlint): declares extra
# valid axis names for the SPMD rules (GL060/GL063) — the escape hatch
# for axes built dynamically (f-strings, config values) that the static
# declaration scan below cannot see. Anywhere in the file; additive.
# Syntax (the <...> placeholders keep THIS comment out of the vocab):
#
#     # shardlint: axes=<name>,<name>
_AXES_ANNOT_RE = re.compile(r"#\s*shardlint:\s*axes=([A-Za-z0-9_, ]+)")

# the domain vocabulary; unknown names in an annotation are ignored so
# a typo degrades to "no domain" (no false findings) instead of crashing
DOMAINS = frozenset({"worker", "asyncio", "daemon", "any"})

# callables that move a function REFERENCE onto a known domain: the
# async server hands worker-side closures to the event loop this way
DOMAIN_TRANSFER = {
    "call_soon_threadsafe": "asyncio",
    "call_soon": "asyncio",
    "run_coroutine_threadsafe": "asyncio",
}


def _comment_lines(source: str):
    """(lineno, comment text) for every real COMMENT token — a
    'graftlint: disable' inside a string/docstring must not suppress
    anything. Falls back to a line scan on tokenize failure (the caller
    already ast-parsed the source, so that's near-unreachable)."""
    import io
    import tokenize
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        for i, line in enumerate(source.splitlines(), start=1):
            if "#" in line:
                yield i, line[line.index("#"):]


class Suppressions:
    """Per-file suppression table parsed from comments."""

    def __init__(self, source: str):
        self.by_line: dict[int, Optional[set[str]]] = {}  # None = all rules
        self.file_rules: set[str] = set()
        for i, comment in _comment_lines(source):
            if "graftlint" not in comment:
                continue
            mf = _SUPPRESS_FILE_RE.search(comment)
            if mf:
                # file-level form is only honored near the top; further
                # down it is ignored outright (NOT downgraded to a line
                # suppression — `disable(?!-file)` above cannot match it)
                if i <= 10:
                    self.file_rules |= {r.strip()
                                        for r in mf.group(1).split(",")
                                        if r.strip()}
                continue
            m = _SUPPRESS_RE.search(comment)
            if m:
                rules = (None if m.group(1) is None else
                         {r.strip() for r in m.group(1).split(",")
                          if r.strip()})
                self.by_line[i] = rules

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_rules:
            return True
        for ln in (line, line - 1):
            if ln in self.by_line:
                rules = self.by_line[ln]
                if rules is None or rule in rules:
                    return True
        return False


def _domain_annotations(source: str) -> dict[int, str]:
    """lineno -> domain for every ``# graftsan: domain=<d>`` COMMENT
    (string/docstring occurrences don't count, same as suppressions).
    Unknown domain names are ignored — a typo degrades to "no domain"
    rather than crashing the lint run."""
    out: dict[int, str] = {}
    for i, comment in _comment_lines(source):
        if "graftsan" not in comment:
            continue
        m = _DOMAIN_RE.search(comment)
        if m and m.group(1) in DOMAINS:
            out[i] = m.group(1)
    return out


def _def_sig_lines(node: ast.AST) -> range:
    """Line span of a def's signature: the ``def`` line through the
    line before the first body statement — a multi-line signature puts
    the annotation comment wherever it fits, commonly the
    closing-paren line."""
    lineno = getattr(node, "lineno", 0)
    body = getattr(node, "body", None)
    end = body[0].lineno - 1 if isinstance(body, list) and body else lineno
    return range(lineno, max(lineno, end) + 1)


def _domain_for_def(ann: dict[int, str], sig_lines: set,
                    node: ast.AST) -> Optional[str]:
    """Annotation applying to a def: any line of its signature span
    (see :func:`_def_sig_lines`), or the line directly above the
    ``def`` — UNLESS that line belongs to some def's signature (an
    annotation on ``def _work(): # graftsan: domain=worker``, incl. a
    multi-line signature's closing line, must not leak onto a nested
    def starting on the very next line)."""
    for ln in _def_sig_lines(node):
        d = ann.get(ln)
        if d is not None:
            return d
    prev = getattr(node, "lineno", 0) - 1
    if prev in sig_lines:
        return None
    return ann.get(prev)


# --------------------------------------------------------------------
# AST helpers
# --------------------------------------------------------------------

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

# jnp/jax attribute tails that return host metadata, not device values
HOST_META_ATTRS = {
    "finfo", "iinfo", "dtype", "shape", "ndim", "size", "result_type",
    "promote_types", "issubdtype", "can_cast", "eval_shape",
    "ShapeDtypeStruct", "default_backend", "devices", "device_count",
    "local_device_count", "process_index", "process_count",
    "make_jaxpr", "typeof", "named_scope", "debug",
}

# attribute accesses on a value that yield static (host) information
STATIC_VALUE_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize",
                      "sharding", "aval", "weak_type"}

# callables that introduce a traced context for a function argument
TRACE_WRAPPERS = {
    "jit", "grad", "value_and_grad", "vmap", "pmap", "checkpoint",
    "remat", "shard_map", "scan", "while_loop", "cond", "fori_loop",
    "switch", "custom_vjp", "custom_jvp",
    "associative_scan", "named_call", "linearize", "vjp",
    "jvp", "make_jaxpr",
}
# names too generic to match bare: builtin map(f, xs) / jax.tree.map
# must not mark f as traced — require the lax prefix
_PREFIX_REQUIRED = {"map": ("lax",)}

# host-introspection builtins: a Name inside these is a type/shape
# probe, not a device-value use
HOST_INTROSPECTION = {"isinstance", "hasattr", "getattr", "len", "type",
                      "id", "repr", "callable"}


def attr_chain(node: ast.AST) -> list[str]:
    """``jax.lax.scan`` Attribute/Name chain -> ["jax", "lax", "scan"];
    empty when the chain bottoms out in a call/subscript."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []


def is_device_call(node: ast.AST) -> bool:
    """A Call that produces a device value: rooted at jnp/jax (or
    jax.numpy/lax/nn/random/scipy...), excluding host-metadata tails."""
    if not isinstance(node, ast.Call):
        return False
    chain = attr_chain(node.func)
    if not chain or chain[0] not in ("jnp", "jax", "lax"):
        return False
    if chain[-1] in HOST_META_ATTRS:
        return False
    # jax.tree.map / jax.tree_util.* operate on host containers
    if len(chain) >= 2 and chain[1] in ("tree", "tree_util", "monitoring",
                                        "profiler", "errors", "config",
                                        "sharding", "debug"):
        return False
    if chain[-1] in ("jit", "vmap", "pmap", "grad", "value_and_grad",
                     "checkpoint", "remat", "partial", "device_put"):
        # transform constructors / explicit transfers are not *hidden*
        # device computations at this site
        return False
    if chain[-1] in ("psum", "pmax", "pmin", "pmean") and node.args \
            and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, (int, float)) \
            and not isinstance(node.args[0].value, bool):
        # axis-size probe: a collective over a LITERAL operand
        # constant-folds at trace time (``world = lax.psum(1, axis)``
        # is THE idiom for a static axis size inside shard_map/pmap) —
        # host metadata, not a device value, so int()/arithmetic on it
        # is sync-free (surfaced by the ZeRO++ hierarchical gather,
        # where GL001 false-fired on exactly this probe)
        return False
    return True


def contains_device_call(node: ast.AST) -> bool:
    return any(is_device_call(n) for n in ast.walk(node))


def _func_name_args(call: ast.Call) -> list[str]:
    """Names of functions handed to a trace wrapper call: bare names,
    ``functools.partial(f, ...)`` targets, and the terminal attribute of
    method references (``self.module.loss`` -> ``loss``)."""
    out: list[str] = []

    def visit(arg: ast.AST) -> None:
        if isinstance(arg, ast.Name):
            out.append(arg.id)
        elif isinstance(arg, ast.Attribute):
            out.append(arg.attr)
        elif isinstance(arg, ast.Call):
            chain = attr_chain(arg.func)
            if chain and chain[-1] == "partial" and arg.args:
                visit(arg.args[0])
    for a in call.args:
        visit(a)
    return out


def iter_trace_wrapper_calls(tree: ast.AST) -> Iterable[ast.Call]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if not chain or chain[-1] not in TRACE_WRAPPERS:
                continue
            need = _PREFIX_REQUIRED.get(chain[-1])
            if need and (len(chain) < 2 or chain[-2] not in need):
                continue
            # jax.tree.map / tree_util.* never trace their argument
            if len(chain) >= 2 and chain[-2] in ("tree", "tree_util"):
                continue
            yield node


def collect_traced_names(tree: ast.AST) -> set[str]:
    """Pass-1 API for the driver: function names this module hands to a
    trace wrapper that it does NOT define itself (imported functions,
    method references). Locally-defined jitted names are resolved by the
    module's own ModuleIndex — exporting them would mark unrelated
    same-named defs across the package (engine.py's local ``put``
    closure must not make engine_v2's ``put`` method jit-reachable)."""
    local_defs = {getattr(n, "name", None) for n in ast.walk(tree)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    names: set[str] = set()
    for call in iter_trace_wrapper_calls(tree):
        names.update(_func_name_args(call))
    return names - local_defs


_BUILTIN_NAMES = frozenset(dir(__import__("builtins")))


# --------------------------------------------------------------------
# mesh-axis vocabulary (ISSUE 15, shardlint pass 1)
# --------------------------------------------------------------------

# an assignment target / parameter whose name mentions axis/axes is an
# axis DECLARATION site (AXIS_ORDER, BATCH_AXES, INNER_AXIS, sp_axis=...)
_AXISY_NAME_RE = re.compile(r"ax[ie]s", re.IGNORECASE)


def _string_literals(node: ast.AST) -> set[str]:
    """String constants in ``node``: a bare literal, or the string
    elements of a (possibly nested) tuple/list/set literal. Dynamic
    elements contribute nothing."""
    out: set[str] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for e in node.elts:
            out |= _string_literals(e)
    return out


def collect_axis_declarations(tree: ast.AST, source: str) -> set[str]:
    """Pass-1 API for the driver (ISSUE 15): the mesh-axis names this
    module DECLARES — the vocabulary GL060/GL063 check axis uses
    against. Declaration sites, never use sites (a typo'd ``lax.psum``
    axis must not make itself valid):

    - ``Mesh(devices, axis_names)`` literal names (``shard_map``'s
      ``axis_names`` is deliberately NOT a source — it is a USE site
      over axes some mesh declares, and a source role would let a
      typo'd shard_map legalize itself);
    - assignments and parameter defaults whose NAME mentions axis/axes
      (``AXIS_ORDER = ("pp", "dp", ...)``, ``INNER_AXIS = "zps"``,
      ``sp_axis: str = "sp"``) with literal string / tuple-of-string
      values;
    - ``# shardlint: axes=...`` annotations (the dynamic-axis escape
      hatch).

    Over-inclusion only weakens the check (an extra vocabulary entry
    can never cause a false finding), so the name heuristic leans
    permissive."""
    axes: set[str] = set()
    for _i, comment in _comment_lines(source):
        if "shardlint" not in comment:
            continue
        m = _AXES_ANNOT_RE.search(comment)
        if m:
            axes |= {a.strip() for a in m.group(1).split(",")
                     if a.strip()}
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain and chain[-1] in ("Mesh", "AbstractMesh",
                                       "make_mesh"):
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        axes |= _string_literals(kw.value)
                if len(node.args) >= 2:
                    axes |= _string_literals(node.args[1])
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) \
                        and _AXISY_NAME_RE.search(t.id):
                    axes |= _string_literals(node.value)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if isinstance(node.target, ast.Name) \
                    and _AXISY_NAME_RE.search(node.target.id):
                axes |= _string_literals(node.value)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
            a = node.args
            pos = a.posonlyargs + a.args
            for param, default in zip(pos[len(pos) - len(a.defaults):],
                                      a.defaults):
                if _AXISY_NAME_RE.search(param.arg):
                    axes |= _string_literals(default)
            for param, default in zip(a.kwonlyargs, a.kw_defaults):
                if default is not None \
                        and _AXISY_NAME_RE.search(param.arg):
                    axes |= _string_literals(default)
    return axes


def collect_domain_exports(tree: ast.AST, source: str) -> dict[str, set]:
    """Pass-1 API for the driver (ISSUE 11): ONE cross-module
    propagation hop for thread domains. For every function this module
    seeds a domain on (explicit ``# graftsan: domain=`` annotation, or
    ``async def``), export the names its body CALLS that the module does
    not define itself, tagged with the caller's domain — the same
    local-defs-subtracted scheme :func:`collect_traced_names` uses, so a
    common local helper name cannot poison same-named defs across the
    package. ``any`` seeds export nothing (it is an exemption, not a
    constraint)."""
    ann = _domain_annotations(source)
    defs = [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    local_defs = {n.name for n in defs}
    sig_lines = {ln for n in defs for ln in _def_sig_lines(n)}
    out: dict[str, set] = {}
    for node in defs:
        dom = _domain_for_def(ann, sig_lines, node)
        if dom is None and isinstance(node, ast.AsyncFunctionDef):
            dom = "asyncio"
        if dom is None or dom == "any":
            continue
        for call in ast.walk(node):
            if isinstance(call, ast.Call) \
                    and isinstance(call.func, ast.Name) \
                    and call.func.id not in local_defs \
                    and call.func.id not in _BUILTIN_NAMES:
                out.setdefault(call.func.id, set()).add(dom)
    return out


# --------------------------------------------------------------------
# per-module analysis
# --------------------------------------------------------------------


@dataclass
class FuncInfo:
    node: ast.AST                       # FunctionDef/AsyncFunctionDef/Lambda
    name: str
    parent: Optional["FuncInfo"]
    is_root: bool = False               # directly handed to a trace wrapper
    reachable: bool = False             # body may run under trace
    traced: set[str] = field(default_factory=set)   # device-valued locals
    # thread domains (ISSUE 11): which kind of thread may run this body.
    # Empty = unknown (no seed reaches it) — the concurrency rules stay
    # quiet there. ``domain_pinned`` marks an explicit annotation or a
    # domain-transfer site: the author's declaration wins, propagation
    # must not accumulate onto it.
    domains: set[str] = field(default_factory=set)
    domain_pinned: bool = False


class ModuleIndex:
    """One file's parsed AST plus jit-reachability + traced-local facts.

    ``external_traced_names``: function names known (from the whole lint
    run's pass 1) to be traced somewhere — how cross-module jit sites
    (engine_v2 jitting paged.fused_decode_loop) mark defs here.

    ``external_domains``: ``{function name: {domains}}`` from pass 1's
    :func:`collect_domain_exports` over the whole run — how a domain
    annotated in one module reaches the functions it calls in another.

    ``axis_vocab``: the mesh-axis vocabulary from pass 1's
    :func:`collect_axis_declarations` over the whole run (ISSUE 15) —
    how ``parallel/mesh.py``'s ``AXIS_ORDER`` validates a literal axis
    string used in another module. ``None``/empty means "no vocabulary
    declared anywhere": the axis-validity rules stay quiet (a
    vocabulary must exist to be violated), so linting a lone file with
    no declarations never false-fires.
    """

    def __init__(self, path: str, source: str,
                 external_traced_names: Optional[set[str]] = None,
                 external_domains: Optional[dict] = None,
                 axis_vocab: Optional[set[str]] = None):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # standalone construction (no driver pass 1): the module's own
        # declarations still count, so a single-file index is usable
        self.axis_vocab: set[str] = (
            set(axis_vocab) if axis_vocab is not None
            else collect_axis_declarations(self.tree, source))
        self.suppressions = Suppressions(source)
        self._external = external_traced_names or set()
        self._external_domains = external_domains or {}
        self._domain_by_line = _domain_annotations(source)
        self._parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node
        self.functions: dict[ast.AST, FuncInfo] = {}
        self._by_name: dict[str, list[FuncInfo]] = {}
        self._build_functions()
        self._mark_roots()
        self._propagate_reachability()
        for info in self.functions.values():
            if info.reachable:
                info.traced = self._infer_traced_locals(info)
        self._assign_domains()

    # -- structure -------------------------------------------------
    def _build_functions(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, _FUNC_NODES):
                name = getattr(node, "name", "<lambda>")
                parent = self.enclosing_function(node)
                info = FuncInfo(node=node, name=name, parent=None)
                self.functions[node] = info
                self._by_name.setdefault(name, []).append(info)
        for node, info in self.functions.items():
            enc = self.enclosing_function(node)
            info.parent = self.functions.get(enc) if enc is not None else None

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            if isinstance(cur, _FUNC_NODES):
                return cur
            cur = self._parents.get(cur)
        return None

    def enclosing_info(self, node: ast.AST) -> Optional[FuncInfo]:
        enc = self.enclosing_function(node)
        return self.functions.get(enc) if enc is not None else None

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        """Nearest enclosing ClassDef (crossing intermediate function
        scopes: a closure nested in a method still belongs to the class
        whose ``self`` it closes over)."""
        cur = self._parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = self._parents.get(cur)
        return None

    def in_loop(self, node: ast.AST) -> bool:
        """Node sits inside a for/while loop or comprehension within its
        own function (loops outside the enclosing def don't count)."""
        cur = self._parents.get(node)
        while cur is not None and not isinstance(cur, _FUNC_NODES):
            if isinstance(cur, (ast.For, ast.While, ast.AsyncFor,
                                ast.comprehension, ast.ListComp,
                                ast.SetComp, ast.DictComp, ast.GeneratorExp)):
                return True
            cur = self._parents.get(cur)
        return False

    # -- jit-reachability ------------------------------------------
    def _mark_roots(self) -> None:
        for call in iter_trace_wrapper_calls(self.tree):
            for name in _func_name_args(call):
                for info in self._resolve_name_at(call, name):
                    info.is_root = True
            # inline lambda argument: jax.jit(lambda t: t, ...)
            for a in call.args:
                if isinstance(a, ast.Lambda) and a in self.functions:
                    self.functions[a].is_root = True
        for info in self.functions.values():
            if info.name in self._external:
                info.is_root = True
            # decorator form: @jax.jit / @functools.partial(jax.jit, ...)
            for dec in getattr(info.node, "decorator_list", []):
                chain = attr_chain(dec if not isinstance(dec, ast.Call)
                                   else dec.func)
                if chain and chain[-1] in TRACE_WRAPPERS:
                    info.is_root = True
                if isinstance(dec, ast.Call) and chain \
                        and chain[-1] == "partial":
                    inner = attr_chain(dec.args[0]) if dec.args else []
                    if inner and inner[-1] in TRACE_WRAPPERS:
                        info.is_root = True

    def _resolve_name_at(self, call: ast.AST, name: str) -> list[FuncInfo]:
        """Defs `name` could refer to at this call site, innermost scope
        first: a jit of a nested closure must not mark a same-named
        method elsewhere in the module (hybrid_engine jits a local
        ``generate``; the engine's ``generate`` METHOD is host code).
        Falls back to every same-named def when no scope matches."""
        candidates = self._by_name.get(name, [])
        if len(candidates) <= 1:
            return candidates
        scope = self.enclosing_function(call)
        while scope is not None:
            scope_info = self.functions.get(scope)
            local = [c for c in candidates if c.parent is scope_info]
            if local:
                return local
            scope = self.enclosing_function(scope)
        top = [c for c in candidates if c.parent is None]
        return top or candidates

    def _propagate_reachability(self) -> None:
        for info in self.functions.values():
            info.reachable = info.is_root
        changed = True
        while changed:
            changed = False
            for info in self.functions.values():
                if info.reachable:
                    continue
                # lexically nested in a reachable function
                if info.parent is not None and info.parent.reachable:
                    info.reachable = True
                    changed = True
                    continue
            # call edges: f() by name inside a reachable body
            for info in list(self.functions.values()):
                if not info.reachable:
                    continue
                for node in ast.walk(info.node):
                    if isinstance(node, ast.Call) \
                            and isinstance(node.func, ast.Name):
                        for callee in self._by_name.get(node.func.id, []):
                            if not callee.reachable:
                                callee.reachable = True
                                changed = True

    # -- traced locals ---------------------------------------------
    def _infer_traced_locals(self, info: FuncInfo) -> set[str]:
        """Names assigned (directly or transitively) from jnp/jax device
        calls, in statement order, one forward pass per fixpoint round."""
        traced: set[str] = set()

        def expr_traced(expr: ast.AST) -> bool:
            return self.mentions_device_value(expr, traced)

        def name_targets(t: ast.AST) -> list[str]:
            # only plain-Name (and tuple/list-of-Name) targets become
            # traced: `x[i] = v` / `x.a = v` / `self.x = v` say nothing
            # about the base name holding a device value
            if isinstance(t, ast.Name):
                return [t.id]
            if isinstance(t, (ast.Tuple, ast.List)):
                out: list[str] = []
                for e in t.elts:
                    out.extend(name_targets(e))
                return out
            if isinstance(t, ast.Starred):
                return name_targets(t.value)
            return []

        body = getattr(info.node, "body", None)
        if body is None or isinstance(body, ast.AST):   # lambda
            return traced
        changed = True
        while changed:
            changed = False
            for node in ast.walk(info.node):
                targets: list[ast.AST] = []
                value = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AugAssign):
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.AnnAssign) and node.value:
                    targets, value = [node.target], node.value
                if value is None or not expr_traced(value):
                    continue
                for t in targets:
                    for name in name_targets(t):
                        if name not in traced:
                            traced.add(name)
                            changed = True
        return traced

    def mentions_device_value(self, expr: ast.AST, traced: set[str]) -> bool:
        """Expression touches a device value: a jnp/jax device call, or
        a traced local used as a value (not via .shape/.dtype/... and
        not inside isinstance/hasattr/len/... host introspection)."""
        intro_spans: list[tuple[int, int, int, int]] = []
        for n in ast.walk(expr):
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                    and n.func.id in HOST_INTROSPECTION:
                if n.end_lineno is not None:
                    intro_spans.append((n.lineno, n.col_offset,
                                        n.end_lineno, n.end_col_offset))

        def in_intro(n: ast.AST) -> bool:
            for (l0, c0, l1, c1) in intro_spans:
                if (l0, c0) <= (n.lineno, n.col_offset) \
                        and (n.end_lineno, n.end_col_offset) <= (l1, c1):
                    return True
            return False

        for n in ast.walk(expr):
            if is_device_call(n) and not in_intro(n):
                return True
            if isinstance(n, ast.Name) and n.id in traced \
                    and n.id not in ("self", "cls"):
                p = self._parents.get(n)
                if isinstance(p, ast.Attribute) \
                        and p.attr in STATIC_VALUE_ATTRS:
                    continue
                if in_intro(n):
                    continue
                return True
        return False

    # -- thread domains (ISSUE 11) ---------------------------------
    def _assign_domains(self) -> None:
        """Seed + propagate thread domains (see module docstring):
        explicit annotations pin; ``async def`` seeds ``asyncio``;
        pass-1 cross-module exports seed by name; references handed to
        a domain-transfer call (``call_soon_threadsafe``) pin to the
        transfer's domain; then a fixpoint pushes domains to lexically
        nested defs and to callees resolved by bare name or
        ``self.m()``/``cls.m()`` within the same class."""
        sig_lines = {ln for i in self.functions.values()
                     for ln in _def_sig_lines(i.node)}
        for info in self.functions.values():
            node = info.node
            dom = _domain_for_def(self._domain_by_line, sig_lines, node)
            if dom is not None:
                info.domains = {dom}
                info.domain_pinned = True
            elif isinstance(node, ast.AsyncFunctionDef):
                info.domains = {"asyncio"}
            elif info.name in self._external_domains:
                info.domains = set(self._external_domains[info.name])
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            dom = DOMAIN_TRANSFER.get(chain[-1]) if chain else None
            if dom is None:
                continue
            targets: list[FuncInfo] = []
            for a in node.args:
                if isinstance(a, ast.Name):
                    targets.extend(self._resolve_name_at(node, a.id))
                elif isinstance(a, ast.Lambda) and a in self.functions:
                    targets.append(self.functions[a])
            for t in targets:
                if not t.domain_pinned:
                    t.domains = {dom}
                    t.domain_pinned = True

        def absorb(dst: FuncInfo, doms: set[str]) -> bool:
            if dst.domain_pinned:
                return False
            new = doms - dst.domains
            if new:
                dst.domains |= new
                return True
            return False

        changed = True
        while changed:
            changed = False
            for info in self.functions.values():
                doms = info.domains - {"any"}
                if not doms:
                    continue
                for child in self.functions.values():
                    if child.parent is info:
                        changed |= absorb(child, doms)
                for node in ast.walk(info.node):
                    if not isinstance(node, ast.Call) \
                            or self.enclosing_function(node) \
                            is not info.node:
                        continue
                    callees: list[FuncInfo] = []
                    if isinstance(node.func, ast.Name):
                        callees = self._resolve_name_at(node,
                                                        node.func.id)
                    elif isinstance(node.func, ast.Attribute) \
                            and isinstance(node.func.value, ast.Name) \
                            and node.func.value.id in ("self", "cls"):
                        cls = self.enclosing_class(info.node)
                        if cls is not None:
                            callees = [
                                c for c in self._by_name.get(
                                    node.func.attr, [])
                                if self.enclosing_class(c.node) is cls]
                    for c in callees:
                        changed |= absorb(c, doms)

    def domain_functions(self, *domains: str) -> list[FuncInfo]:
        """Functions whose domain set intersects ``domains`` and that
        are not declared ``any`` (author-audited exemption)."""
        want = set(domains)
        return [i for i in self.functions.values()
                if i.domains & want and "any" not in i.domains]

    def traced_union(self, info: "FuncInfo") -> set[str]:
        """Traced locals visible in ``info``: its own plus every
        enclosing function's (closure reads)."""
        out: set[str] = set()
        cur: Optional[FuncInfo] = info
        while cur is not None:
            out |= cur.traced
            cur = cur.parent
        return out

    # -- convenience -----------------------------------------------
    def reachable_functions(self) -> list[FuncInfo]:
        return [i for i in self.functions.values() if i.reachable]

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


# --------------------------------------------------------------------
# rule protocol
# --------------------------------------------------------------------


class Context:
    """What one rule sees for one file."""

    def __init__(self, index: ModuleIndex, relpath: str):
        self.index = index
        self.relpath = relpath
        self.findings: list[Finding] = []

    def report(self, rule_id: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 0)
        if self.index.suppressions.suppressed(rule_id, line):
            return
        self.findings.append(Finding(
            rule=rule_id, path=self.relpath, line=line,
            col=getattr(node, "col_offset", 0), message=message,
            text=self.index.line_text(line)))


class Rule:
    """Base class; subclasses set id/name/summary and implement check."""

    id: str = "GL000"
    name: str = "base"
    summary: str = ""

    def check(self, ctx: Context) -> None:     # pragma: no cover
        raise NotImplementedError
