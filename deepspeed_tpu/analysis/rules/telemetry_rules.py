"""Telemetry-boundary rule (GL040, satellite of ISSUE 3).

The ISSUE 2 overhead contract: a telemetry-disabled run must never
import ``deepspeed_tpu.telemetry`` — instrumented call sites go through
``utils/telemetry_probe.py`` (a ``sys.modules`` probe) so the disabled
path allocates nothing. A direct import anywhere else silently breaks
the contract for the whole process; this rule makes the probe the
single enforced gateway.
"""

from __future__ import annotations

import ast

from ..core import Context, Rule

# modules allowed to name the package: the probe itself (its activate()
# helper is THE sanctioned import point) and the package's own files
def _allowed(relpath: str) -> bool:
    p = relpath.replace("\\", "/")
    return (p.endswith("utils/telemetry_probe.py")
            or "telemetry" in p.split("/")[:-1])


class DirectTelemetryImport(Rule):
    id = "GL040"
    name = "direct-telemetry-import"
    summary = ("deepspeed_tpu.telemetry imported outside "
               "utils/telemetry_probe.py — breaks the zero-import "
               "disabled-mode contract; go through the probe "
               "(active_telemetry()/tel_span()/activate())")

    def check(self, ctx: Context) -> None:
        if _allowed(ctx.relpath):
            return
        for node in ast.walk(ctx.index.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if "telemetry" in alias.name.split("."):
                        self._flag(ctx, node)
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod.split(".")[-1] == "telemetry" \
                        or ".telemetry." in f".{mod}.":
                    self._flag(ctx, node)
                elif any(a.name == "telemetry" for a in node.names):
                    self._flag(ctx, node)

    def _flag(self, ctx: Context, node: ast.AST) -> None:
        ctx.report(
            self.id, node,
            "import of deepspeed_tpu.telemetry outside the probe; use "
            "utils.telemetry_probe (active_telemetry/tel_span, or "
            "activate() to turn telemetry on)")


RULES = [DirectTelemetryImport()]
