"""Telemetry-boundary rule (GL040, satellite of ISSUE 3).

The ISSUE 2 overhead contract: a telemetry-disabled run must never
import ``deepspeed_tpu.telemetry`` — instrumented call sites go through
``utils/telemetry_probe.py`` (a ``sys.modules`` probe) so the disabled
path allocates nothing. A direct import anywhere else silently breaks
the contract for the whole process; this rule makes the probe the
single enforced gateway.
"""

from __future__ import annotations

import ast

from ..core import Context, Rule, attr_chain

# modules allowed to name the package: the probe itself (its activate()
# helper is THE sanctioned import point) and the package's own files
def _allowed(relpath: str) -> bool:
    p = relpath.replace("\\", "/")
    return (p.endswith("utils/telemetry_probe.py")
            or "telemetry" in p.split("/")[:-1])


class DirectTelemetryImport(Rule):
    id = "GL040"
    name = "direct-telemetry-import"
    summary = ("deepspeed_tpu.telemetry imported outside "
               "utils/telemetry_probe.py — breaks the zero-import "
               "disabled-mode contract; go through the probe "
               "(active_telemetry()/tel_span()/activate())")

    def check(self, ctx: Context) -> None:
        if _allowed(ctx.relpath):
            return
        for node in ast.walk(ctx.index.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if "telemetry" in alias.name.split("."):
                        self._flag(ctx, node)
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod.split(".")[-1] == "telemetry" \
                        or ".telemetry." in f".{mod}.":
                    self._flag(ctx, node)
                elif any(a.name == "telemetry" for a in node.names):
                    self._flag(ctx, node)

    def _flag(self, ctx: Context, node: ast.AST) -> None:
        ctx.report(
            self.id, node,
            "import of deepspeed_tpu.telemetry outside the probe; use "
            "utils.telemetry_probe (active_telemetry/tel_span, or "
            "activate() to turn telemetry on)")


_HOST_ONLY_GETTERS = {"get_flight_recorder", "get_ledger",
                      "get_watchdog", "dump_flight_record"}
_RECORD_METHODS = {"record", "progress", "observe", "fire"}
# receiver-name stems identifying a flight-recorder/ledger handle
_RECEIVER_STEMS = ("ledger", "flight", "recorder", "flightrec")
_RECEIVER_EXACT = {"fr", "led"}


def _device_truth_receiver(chain: list[str]) -> bool:
    for part in chain[:-1]:
        low = part.lower()
        if low in _RECEIVER_EXACT or any(s in low
                                         for s in _RECEIVER_STEMS):
            return True
    return False


class DeviceTruthRecordInJit(Rule):
    id = "GL041"
    name = "flightrec-in-jit"
    summary = ("flight-recorder/executable-ledger API "
               "(record/progress/observe, or the get_* handles) called "
               "inside jit-reachable code — host-only telemetry must "
               "never ride a traced program (it would bake host state "
               "mutation into the executable, or silently freeze at "
               "trace-time values)")

    def check(self, ctx: Context) -> None:
        if _allowed(ctx.relpath):
            return
        for info in ctx.index.reachable_functions():
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Call) \
                        or ctx.index.enclosing_function(node) \
                        is not info.node:
                    continue
                if not isinstance(node.func, ast.Attribute):
                    continue
                chain = attr_chain(node.func)
                attr = node.func.attr
                if attr in _HOST_ONLY_GETTERS:
                    ctx.report(
                        self.id, node,
                        f"{attr}() inside jit-reachable code; the "
                        "flight-recorder/ledger handles are host-only "
                        "— hoist to the dispatch call site")
                elif attr in _RECORD_METHODS and chain \
                        and _device_truth_receiver(chain):
                    ctx.report(
                        self.id, node,
                        f".{attr}() on a flight-recorder/ledger "
                        "handle inside jit-reachable code; record at "
                        "the host dispatch boundary instead (the "
                        "traced body runs at trace time, not per "
                        "step)")


RULES = [DirectTelemetryImport(), DeviceTruthRecordInJit()]
