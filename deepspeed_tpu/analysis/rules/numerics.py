"""Mixed-precision / numerics rules (GL070-GL073) — ISSUE 18.

The codebase runs saturated with reduced precision (bf16 training,
fp16 dynamic loss scaling, int8/fp8 KV cache, stochastic-rounded
quantized wire, int8 MoE dispatch): exactly the regimes where ZeRO++
and EQuARX show quantized paths live or die on accumulation-dtype and
clipping discipline. These rules are the static half of that
discipline; the runtime half is ``analysis/numsan.py``.

- **GL070** low-precision accumulation: a reduce/contraction
  (``sum``/``mean``/``einsum``/``dot``/``matmul``/softmax/norm) over a
  value the module committed to bf16/fp16, with no fp32 accumulator
  route (``preferred_element_type=``, ``precision=``, an accumulator
  ``dtype=``, or widening ``.astype`` before the reduce).
- **GL071** unguarded ``exp``/``log``/``sqrt``/``rsqrt``/division on
  traced values with no clamp/eps/max guard in the expression.
- **GL072** precision-losing cast to an 8-bit dtype with no rounding
  route (``stochastic_round``/``round``/``clip`` before the cast) —
  a plain ``.astype(int8)`` on a grad/wire value silently truncates.
- **GL073** PRNG key reuse: the same key reaching two sampling /
  rounding call sites with no ``split``/reassignment between them
  (the determinism contract every parity test rests on).
"""

from __future__ import annotations

import ast
from typing import Optional

from ..core import Context, Rule, attr_chain

# dtypes that commit a value to reduced precision
_LOW_PREC = {"bfloat16", "float16"}
# reduce/contraction heads (tail of the attr chain, jnp/jax rooted)
_REDUCE_TAILS = {"sum", "mean", "einsum", "dot", "matmul", "var", "std",
                 "softmax", "log_softmax", "logsumexp", "norm", "tensordot"}
# kwargs that route accumulation through a wider dtype
_ACC_KWARGS = {"preferred_element_type", "precision", "dtype", "acc_dtype"}
# call tails that widen / re-commit the dtype of their operand
_WIDEN_TAILS = {"float32", "float64", "promote_types"}
# guard call tails: clamp / eps / max-subtract / provably-safe shapes
_GUARD_TAILS = {"clip", "clamp", "minimum", "maximum", "max", "min",
                "where", "abs", "square", "softmax", "log_softmax",
                "logsumexp", "sigmoid", "tanh", "log1p", "expm1",
                "nan_to_num", "relu", "norm", "isfinite", "floor", "ceil"}
# jax.random samplers that CONSUME a key (fold_in derives, PRNGKey
# mints — neither consumes)
_KEY_CONSUMERS = {"split", "normal", "uniform", "bernoulli", "categorical",
                  "gumbel", "randint", "truncated_normal", "permutation",
                  "choice", "exponential", "laplace", "bits", "gamma",
                  "beta", "poisson", "dirichlet"}


def _is_eps_name(node: ast.AST) -> bool:
    """A Name/Attribute whose identifier looks like an epsilon."""
    tail = None
    if isinstance(node, ast.Name):
        tail = node.id
    elif isinstance(node, ast.Attribute):
        tail = node.attr
    return tail is not None and "eps" in tail.lower()


def _is_small_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.UAdd):
        return _is_small_literal(node.operand)
    return isinstance(node, ast.Constant) \
        and isinstance(node.value, (int, float))


def _low_prec_cast(node: ast.AST) -> bool:
    """Expression commits its result to bf16/fp16: ``.astype(jnp.
    bfloat16)`` / ``.astype("float16")`` / ``dtype=jnp.bfloat16``."""
    if not isinstance(node, ast.Call):
        return False
    def dt_low(arg: ast.AST) -> bool:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value in _LOW_PREC
        chain = attr_chain(arg)
        return bool(chain) and chain[-1] in _LOW_PREC
    if isinstance(node.func, ast.Attribute) and node.func.attr == "astype" \
            and node.args and dt_low(node.args[0]):
        return True
    return any(k.arg == "dtype" and dt_low(k.value) for k in node.keywords)


def _has_widening(expr: ast.AST) -> bool:
    """Expression routes through fp32+ somewhere (``.astype(jnp.
    float32)``, ``jnp.float32(...)``, fp32 ``dtype=``)."""
    for n in ast.walk(expr):
        if not isinstance(n, ast.Call):
            continue
        if isinstance(n.func, ast.Attribute) and n.func.attr == "astype" \
                and n.args:
            chain = attr_chain(n.args[0])
            if (chain and chain[-1] in _WIDEN_TAILS) or (
                    isinstance(n.args[0], ast.Constant)
                    and n.args[0].value in ("float32", "float64")):
                return True
        chain = attr_chain(n.func)
        if chain and chain[-1] in _WIDEN_TAILS:
            return True
        for k in n.keywords:
            if k.arg in _ACC_KWARGS:
                kchain = attr_chain(k.value)
                if not kchain or kchain[-1] not in _LOW_PREC:
                    return True
    return False


def _low_prec_names(info) -> set[str]:
    """Names this function commits to bf16/fp16: assigned from a
    low-precision cast, or propagated through arithmetic on such a name
    with no widening route (weak-typed Python scalars don't widen)."""
    low: set[str] = set()

    def expr_low(expr: ast.AST) -> bool:
        if _has_widening(expr):
            return False
        for n in ast.walk(expr):
            if _low_prec_cast(n):
                return True
            if isinstance(n, ast.Name) and n.id in low:
                return True
        return False

    changed = True
    while changed:
        changed = False
        for node in ast.walk(info.node):
            targets = []
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AugAssign):
                targets, value = [node.target], node.value
            elif isinstance(node, ast.AnnAssign) and node.value:
                targets, value = [node.target], node.value
            if value is None or not expr_low(value):
                continue
            for t in targets:
                if isinstance(t, ast.Name) and t.id not in low:
                    low.add(t.id)
                    changed = True
    return low


def _guarded_names(info) -> set[str]:
    """Names assigned from expressions that carry a guard (clip /
    maximum / + eps ...): dividing by such a name is safe."""
    guarded: set[str] = set()
    for node in ast.walk(info.node):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)) \
                and node.value is not None:
            targets = [node.target]
        else:
            continue
        if _expr_guarded(node.value, guarded):
            for t in targets:
                if isinstance(t, ast.Name):
                    guarded.add(t.id)
    return guarded


def _expr_guarded(expr: ast.AST, guarded: set[str] = frozenset()) -> bool:
    """Expression carries a clamp/eps/max guard somewhere inside."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            chain = attr_chain(n.func)
            if chain and chain[-1] in _GUARD_TAILS:
                return True
        if isinstance(n, ast.BinOp) and isinstance(n.op, (ast.Add, ast.Sub)):
            for side in (n.left, n.right):
                if _is_small_literal(side) or _is_eps_name(side):
                    return True
        if isinstance(n, ast.Name) and n.id in guarded:
            return True
        if _is_eps_name(n):
            return True
    return False


class LowPrecisionAccumulation(Rule):
    id = "GL070"
    name = "low-precision-accumulation"
    summary = ("reduce/contraction (sum/mean/einsum/dot/softmax/norm) "
               "over a bf16/fp16-committed value with no fp32 "
               "accumulator route (preferred_element_type=, "
               "precision=, dtype=, or a widening .astype)")

    def check(self, ctx: Context) -> None:
        for info in ctx.index.reachable_functions():
            low = _low_prec_names(info)
            if not low:
                continue
            for node in ast.walk(info.node):
                if ctx.index.enclosing_function(node) is not info.node:
                    continue
                if not isinstance(node, ast.Call):
                    continue
                chain = attr_chain(node.func)
                tail = None
                if chain and chain[0] in ("jnp", "jax", "lax"):
                    tail = chain[-1]
                elif isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id in low:
                    tail = node.func.attr       # x.sum() / x.mean()
                if tail not in _REDUCE_TAILS:
                    continue
                if any(k.arg in _ACC_KWARGS for k in node.keywords):
                    continue
                args = node.args
                if chain and tail == "einsum" and len(args) > 1:
                    args = args[1:]             # skip the equation
                hit = None
                for a in args:
                    if _has_widening(a):
                        continue
                    for n in ast.walk(a):
                        if isinstance(n, ast.Name) and n.id in low:
                            hit = n.id
                            break
                    if hit:
                        break
                if isinstance(node.func, ast.Attribute) and not chain \
                        and isinstance(node.func.value, ast.Name):
                    hit = hit or node.func.value.id
                if hit is None:
                    continue
                ctx.report(
                    self.id, node,
                    f"'{hit}' is committed to bf16/fp16 but this "
                    f"'{tail}' has no fp32 accumulator: route through "
                    "preferred_element_type=jnp.float32, precision=, "
                    "an accumulator dtype=, or .astype(jnp.float32) "
                    "before the reduce")


class UnguardedTranscendental(Rule):
    id = "GL071"
    name = "unguarded-transcendental"
    summary = ("exp/log/sqrt/rsqrt/division on a traced value with no "
               "clamp/eps/max guard in the expression — NaN/Inf "
               "factory in reduced precision")

    _FNS = {"exp", "log", "sqrt", "rsqrt", "log2", "log10", "exp2"}

    def check(self, ctx: Context) -> None:
        for info in ctx.index.reachable_functions():
            traced = ctx.index.traced_union(info)
            guarded = _guarded_names(info)
            for node in ast.walk(info.node):
                if ctx.index.enclosing_function(node) is not info.node:
                    continue
                if isinstance(node, ast.Call):
                    self._check_call(ctx, node, traced, guarded)
                elif isinstance(node, ast.BinOp) \
                        and isinstance(node.op, ast.Div):
                    self._check_div(ctx, node, traced, guarded)

    def _check_call(self, ctx, node, traced, guarded) -> None:
        chain = attr_chain(node.func)
        if not chain or chain[0] not in ("jnp", "jax", "lax"):
            return
        fn = chain[-1]
        if fn not in self._FNS or not node.args:
            return
        arg = node.args[0]
        if not ctx.index.mentions_device_value(arg, traced):
            return
        if _expr_guarded(arg, guarded):
            return
        if fn in ("exp", "exp2"):
            # exp(x - m) / exp(-d) are the guarded idioms: any
            # subtraction or negation bounds the argument above
            for n in ast.walk(arg):
                if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Sub):
                    return
                if isinstance(n, ast.UnaryOp) and isinstance(n.op, ast.USub):
                    return
        if fn in ("sqrt", "rsqrt"):
            # sum of squares / x**2 is non-negative by construction
            for n in ast.walk(arg):
                if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Pow):
                    return
        ctx.report(
            self.id, node,
            f"unguarded '{fn}' on a traced value: clamp the argument "
            "(jnp.clip / jnp.maximum), add an eps, or subtract the max "
            "before exponentiating")

    def _check_div(self, ctx, node, traced, guarded) -> None:
        den = node.right
        # only flag denominators we can positively identify as traced
        # and unguarded: a bare traced Name, or a jnp reduce call
        if isinstance(den, ast.Name):
            if den.id not in traced or den.id in guarded \
                    or _is_eps_name(den):
                return
        elif isinstance(den, ast.Call):
            chain = attr_chain(den.func)
            if not chain or chain[0] not in ("jnp", "jax", "lax"):
                return
            if chain[-1] in _GUARD_TAILS or chain[-1] not in (
                    "sum", "mean", "prod", "dot"):
                return
        else:
            return
        if not ctx.index.mentions_device_value(den, traced):
            return
        ctx.report(
            self.id, node,
            "division by an unguarded traced value: bound the "
            "denominator away from zero (jnp.maximum(d, eps) / + eps)")


class UnroundedNarrowingCast(Rule):
    id = "GL072"
    name = "unrounded-narrowing-cast"
    summary = ("plain .astype to an 8-bit dtype on a traced value with "
               "no rounding/clipping route — grad/wire values must go "
               "through round+clip or stochastic_round before the cast")

    _NARROW = {"int8", "uint8", "float8_e4m3fn", "float8_e5m2",
               "float8_e4m3", "float8_e5m2fnuz", "float8_e4m3fnuz"}
    _ROUND_TAILS = {"round", "rint", "clip", "floor", "ceil", "trunc",
                    "stochastic_round", "quantize_int8", "quantize_fp8",
                    "kv_quantize", "sign", "where"}

    def check(self, ctx: Context) -> None:
        for info in ctx.index.reachable_functions():
            traced = ctx.index.traced_union(info)
            for node in ast.walk(info.node):
                if ctx.index.enclosing_function(node) is not info.node:
                    continue
                if not isinstance(node, ast.Call) \
                        or not isinstance(node.func, ast.Attribute) \
                        or node.func.attr != "astype" or not node.args:
                    continue
                target = node.args[0]
                narrow = False
                if isinstance(target, ast.Constant) \
                        and target.value in self._NARROW:
                    narrow = True
                else:
                    chain = attr_chain(target)
                    narrow = bool(chain) and chain[-1] in self._NARROW
                if not narrow:
                    continue
                obj = node.func.value
                if not ctx.index.mentions_device_value(obj, traced):
                    continue
                if self._rounded(obj, info, ctx):
                    continue
                ctx.report(
                    self.id, node,
                    "8-bit cast with no rounding route: .astype(int8/"
                    "fp8) truncates toward zero — round+clip first "
                    "(quantize_int8 / stochastic_round, cf. "
                    "zero_quantized_rounding)")

    def _rounded(self, obj: ast.AST, info, ctx) -> bool:
        for n in ast.walk(obj):
            if isinstance(n, ast.Call):
                chain = attr_chain(n.func)
                if chain and chain[-1] in self._ROUND_TAILS:
                    return True
        # a bare name: accept when IT was assigned through a rounding
        # route anywhere in the function (codes out of a quantizer)
        if isinstance(obj, ast.Name):
            for node in ast.walk(info.node):
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == obj.id
                        for t in node.targets):
                    for n in ast.walk(node.value):
                        if isinstance(n, ast.Call):
                            chain = attr_chain(n.func)
                            if chain and chain[-1] in self._ROUND_TAILS:
                                return True
        return False


class PRNGKeyReuse(Rule):
    id = "GL073"
    name = "prng-key-reuse"
    summary = ("the same PRNG key reaches two sampling/rounding call "
               "sites with no split/reassignment between them — "
               "correlated noise breaks the determinism contract")

    def check(self, ctx: Context) -> None:
        for info in ctx.index.reachable_functions():
            self._check_function(ctx, info)

    # -- key identification ----------------------------------------
    @staticmethod
    def _key_id(node: ast.AST) -> Optional[str]:
        """Stable identifier for a key operand: a bare Name or a
        Name[int-literal] subscript; None for anything else."""
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Subscript) \
                and isinstance(node.value, ast.Name) \
                and isinstance(node.slice, ast.Constant) \
                and isinstance(node.slice.value, int):
            return f"{node.value.id}[{node.slice.value}]"
        return None

    @classmethod
    def _consumed_key(cls, call: ast.Call) -> Optional[str]:
        chain = attr_chain(call.func)
        if not chain:
            return None
        if len(chain) >= 2 and chain[-2] == "random" \
                and chain[-1] in _KEY_CONSUMERS and call.args:
            return cls._key_id(call.args[0])
        if chain[-1] == "stochastic_round":
            for k in call.keywords:
                if k.arg == "key":
                    return cls._key_id(k.value)
            if len(call.args) >= 2:
                return cls._key_id(call.args[1])
        return None

    # -- branch-awareness ------------------------------------------
    def _branch_path(self, ctx, node: ast.AST):
        """(id(If), arm) ancestry so two uses in MUTUALLY EXCLUSIVE
        arms of one If never conflict."""
        path = []
        cur = ctx.index.parent(node)
        child = node
        while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if isinstance(cur, ast.If):
                arm = "body" if self._in_list(cur.body, child) else "orelse"
                path.append((id(cur), arm))
            child = cur
            cur = ctx.index.parent(cur)
        return path

    @staticmethod
    def _in_list(stmts, node) -> bool:
        for s in stmts:
            if s is node or any(n is node for n in ast.walk(s)):
                return True
        return False

    @staticmethod
    def _exclusive(p1, p2) -> bool:
        d1, d2 = dict(p1), dict(p2)
        return any(d1.get(k) != arm for k, arm in d2.items() if k in d1)

    def _check_function(self, ctx, info) -> None:
        events = []      # (lineno, kind, key_id, node)
        for node in ast.walk(info.node):
            if ctx.index.enclosing_function(node) is not info.node:
                continue
            if isinstance(node, ast.Call):
                kid = self._consumed_key(node)
                if kid is not None:
                    events.append((node.lineno, "use", kid, node))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    for name in self._names_in_target(t):
                        events.append((node.lineno, "redef", name, node))
            elif isinstance(node, (ast.For, ast.comprehension)):
                lineno = getattr(node, "lineno",
                                 getattr(node.target, "lineno", 0))
                for name in self._names_in_target(node.target):
                    events.append((lineno, "redef", name, node))
        events.sort(key=lambda e: e[0])
        last_use: dict = {}
        for lineno, kind, kid, node in events:
            if kind == "redef":
                last_use.pop(kid, None)
                # redefining `ks` invalidates every tracked ks[i]
                for k in [k for k in last_use if k.startswith(f"{kid}[")]:
                    last_use.pop(k, None)
                continue
            prev = last_use.get(kid)
            if prev is not None and not self._exclusive(
                    self._branch_path(ctx, prev), self._branch_path(ctx, node)):
                ctx.report(
                    self.id, node,
                    f"PRNG key '{kid}' already consumed at line "
                    f"{prev.lineno} with no split/reassignment since: "
                    "derive fresh keys (jax.random.split / fold_in) "
                    "per call site")
            else:
                if prev is None and ctx.index.in_loop(node) \
                        and not self._redef_in_loop(ctx, node, kid):
                    ctx.report(
                        self.id, node,
                        f"PRNG key '{kid}' consumed inside a loop "
                        "without a per-iteration split/fold_in: every "
                        "iteration samples identical noise")
            last_use[kid] = node

    @staticmethod
    def _names_in_target(t: ast.AST) -> list[str]:
        out = []
        if isinstance(t, ast.Name):
            out.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                out.extend(PRNGKeyReuse._names_in_target(e))
        elif isinstance(t, ast.Starred):
            out.extend(PRNGKeyReuse._names_in_target(t.value))
        return out

    def _redef_in_loop(self, ctx, node, kid) -> bool:
        base = kid.split("[")[0]
        cur = ctx.index.parent(node)
        while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if isinstance(cur, (ast.For, ast.While)):
                for n in ast.walk(cur):
                    if isinstance(n, (ast.Assign, ast.AugAssign)):
                        targets = (n.targets if isinstance(n, ast.Assign)
                                   else [n.target])
                        for t in targets:
                            if base in self._names_in_target(t):
                                return True
                    if isinstance(n, ast.For) and base in \
                            self._names_in_target(n.target):
                        return True
                return False
            cur = ctx.index.parent(cur)
        return False


RULES = [LowPrecisionAccumulation(), UnguardedTranscendental(),
         UnroundedNarrowingCast(), PRNGKeyReuse()]
