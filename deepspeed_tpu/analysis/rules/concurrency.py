"""Concurrency-domain rules (GL050-GL053, ISSUE 11 tentpole part 1).

The serving stack runs three kinds of threads with sharply different
contracts: the WORKER thread owns every JAX call (serving/server.py's
``_work`` loop, or the main thread in closed-loop drivers), the ASYNCIO
event loop must never block or device-call (one stray ``Event.wait``
stalls every stream), and DAEMON watchers (hang watchdog, pollers) may
sleep but must not own device work. ``core.py`` assigns each function a
set of thread domains from ``# graftsan: domain=...`` annotations and
``async def`` seeds, propagated along the call graph (see the core
module docstring for the syntax and propagation rules); these rules
turn a domain-contract violation into a lint failure instead of a
production hang:

- GL050: JAX/device calls reachable from a non-worker domain;
- GL051: blocking primitives reachable from the asyncio domain;
- GL052: shared instance state mutated from >= 2 domains without a
  common lock;
- GL053: lock acquisition under a held lock in inconsistent order
  (the classic AB/BA deadlock shape).

Functions with no domain stay exempt (unknown != violation), as do
functions declared ``domain=any`` (an author-audited exemption).
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from ..core import Context, FuncInfo, Rule, attr_chain, is_device_call

# --------------------------------------------------------------------
# shared predicates
# --------------------------------------------------------------------

# jnp/jax tails that are runtime/transfer calls rather than traced math:
# is_device_call deliberately excludes them (they are not *hidden*
# device work at a jit site), but from an asyncio/daemon thread ANY
# runtime interaction is a domain violation
_RUNTIME_TAILS = {"device_put", "device_get", "block_until_ready"}

# repo-local helpers that query the device runtime (the watchdog's
# last-resort memory probe lives behind one of these)
_DEVICE_HELPER_NAMES = {"device_memory_stats", "live_arrays",
                        "live_buffers"}


def _is_device_touch(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    if is_device_call(node):
        return True
    chain = attr_chain(node.func)
    if not chain:
        return False
    if chain[0] in ("jnp", "jax", "lax") and chain[-1] in _RUNTIME_TAILS:
        return True
    return chain[-1] in _DEVICE_HELPER_NAMES


# receiver-name stems identifying a lock-ish object in a with-item or
# .acquire() call
_LOCK_STEMS = ("lock", "mutex", "mtx", "semaphore", "sem_", "cond")


def _lockish_name(expr: ast.AST) -> Optional[str]:
    """Dotted name of a lock-like context expr (``self._mail_lock`` ->
    ``self._mail_lock``); None when the expr is not name-shaped or does
    not look like a lock."""
    chain = attr_chain(expr)
    if not chain:
        return None
    low = chain[-1].lower()
    if any(s in low for s in _LOCK_STEMS):
        return ".".join(chain)
    return None


def _held_locks(index, node: ast.AST) -> frozenset:
    """Lock names held at ``node``: lock-ish with-items of every
    enclosing ``with`` within the same function."""
    held: set[str] = set()
    cur = index.parent(node)
    while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            for item in cur.items:
                name = _lockish_name(item.context_expr)
                if name:
                    held.add(name)
        cur = index.parent(cur)
    return frozenset(held)


def _owned_nodes(ctx: Context, info: FuncInfo) -> Iterable[ast.AST]:
    for node in ast.walk(info.node):
        if node is info.node:
            continue
        if ctx.index.enclosing_function(node) is info.node:
            yield node


# --------------------------------------------------------------------
# GL050
# --------------------------------------------------------------------


class DeviceCallOffWorker(Rule):
    id = "GL050"
    name = "device-call-off-worker"
    summary = ("JAX/device call reachable from a non-worker thread "
               "domain (asyncio event loop or a daemon watcher) — only "
               "the worker thread owns the engine; a device call from "
               "the event loop blocks every stream, and one from a "
               "daemon races the worker's dispatch state")

    def check(self, ctx: Context) -> None:
        for info in ctx.index.domain_functions("asyncio", "daemon"):
            bad = sorted(info.domains & {"asyncio", "daemon"})
            for node in _owned_nodes(ctx, info):
                if _is_device_touch(node):
                    ctx.report(
                        self.id, node,
                        f"device/runtime call in the {'/'.join(bad)} "
                        f"domain (function '{info.name}'); move it to "
                        "the worker thread (marshal through the "
                        "mailbox) or annotate a justified exception")


# --------------------------------------------------------------------
# GL051
# --------------------------------------------------------------------

# blocking attr tails that are unambiguous on any receiver
_BLOCK_ANY_RECV = {"wait", "acquire"}
# blocking attr tails that need a receiver-name hint (``.get()`` /
# ``.join()`` are too common on dicts/strings to flag bare)
_BLOCK_BY_RECV = {
    "get": ("queue", "mailbox", "mbox", "jobs", "_q", "q"),
    "join": ("thread", "worker", "proc", "process"),
    "result": ("future", "fut", "promise"),
}
_SLEEP_CHAINS = {("time", "sleep")}


def _stem_match(part: str, stem: str) -> bool:
    """Multi-char stems match by containment ("queue" in "work_queue");
    the 1-2 char q stems must match the whole part or a ``_``-suffix —
    containment would false-fire on any name merely containing the
    letter ("q" in "requests" is a dict lookup, not a Queue)."""
    if len(stem) > 2:
        return stem in part
    base = stem.lstrip("_")
    return part in (stem, base) or part.endswith("_" + base)


def _blocking_reason(node: ast.Call) -> Optional[str]:
    chain = attr_chain(node.func)
    if not chain:
        return None
    if tuple(chain) in _SLEEP_CHAINS:
        return "time.sleep()"
    tail = chain[-1]
    recv = [p.lower() for p in chain[:-1]]
    if len(chain) >= 2 and tail in _BLOCK_ANY_RECV:
        return f".{tail}()"
    stems = _BLOCK_BY_RECV.get(tail)
    if stems and any(_stem_match(part, stem)
                     for part in recv for stem in stems):
        return f"{chain[-2]}.{tail}()"
    return None


class BlockingCallInAsyncio(Rule):
    id = "GL051"
    name = "blocking-call-in-asyncio"
    summary = ("blocking primitive (Event.wait / Lock.acquire / "
               "Queue.get / thread join / time.sleep / `with lock:`) "
               "reachable from the asyncio domain — it stalls the whole "
               "event loop, freezing every request stream at once")

    def check(self, ctx: Context) -> None:
        for info in ctx.index.domain_functions("asyncio"):
            for node in _owned_nodes(ctx, info):
                if isinstance(node, ast.Call):
                    # awaited calls are the asyncio-native non-blocking
                    # forms (await q.get(), await lock.acquire())
                    if isinstance(ctx.index.parent(node), ast.Await):
                        continue
                    reason = _blocking_reason(node)
                    if reason:
                        ctx.report(
                            self.id, node,
                            f"{reason} in the asyncio domain blocks "
                            "the event loop; use the asyncio "
                            "equivalent (await) or marshal to the "
                            "worker thread")
                elif isinstance(node, ast.With):
                    for item in node.items:
                        name = _lockish_name(item.context_expr)
                        if name:
                            ctx.report(
                                self.id, node,
                                f"`with {name}:` in the asyncio domain "
                                "acquires a thread lock on the event "
                                "loop; keep critical sections off the "
                                "loop (or justify: O(1) body, never "
                                "held around device work)")


# --------------------------------------------------------------------
# GL052
# --------------------------------------------------------------------

# method names that mutate their receiver in place
_MUTATORS = {"append", "extend", "add", "update", "pop", "popitem",
             "popleft", "appendleft", "clear", "remove", "discard",
             "insert", "setdefault", "sort"}


def _self_attr(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Attribute) \
            and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self":
        return expr.attr
    return None


def _mutated_attr(node: ast.AST) -> Optional[str]:
    """Name of the ``self.<attr>`` an AST node mutates, if any."""
    targets: list[ast.AST] = []
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    elif isinstance(node, ast.Delete):
        targets = node.targets
    elif isinstance(node, ast.Call) \
            and isinstance(node.func, ast.Attribute) \
            and node.func.attr in _MUTATORS:
        return _self_attr(node.func.value)
    for t in targets:
        stack = [t]
        while stack:
            cur = stack.pop()
            if isinstance(cur, (ast.Tuple, ast.List)):
                stack.extend(cur.elts)
                continue
            if isinstance(cur, ast.Starred):
                stack.append(cur.value)
                continue
            if isinstance(cur, ast.Subscript):
                cur = cur.value
            attr = _self_attr(cur)
            if attr:
                return attr
    return None


class CrossDomainMutationWithoutLock(Rule):
    id = "GL052"
    name = "cross-domain-mutation-without-lock"
    summary = ("instance attribute mutated from >= 2 thread domains "
               "with no common lock across the sites — a data race on "
               "shared engine/scheduler state (or a GIL-atomicity "
               "assumption that deserves an explicit justification)")

    def check(self, ctx: Context) -> None:
        index = ctx.index
        by_class: dict = {}
        for info in index.functions.values():
            if "any" in info.domains:
                continue        # author-audited exemption
            doms = info.domains - {"any"}
            if not doms:
                continue
            cls = index.enclosing_class(info.node)
            if cls is None:
                continue
            for node in _owned_nodes(ctx, info):
                attr = _mutated_attr(node)
                if attr is None:
                    continue
                by_class.setdefault(cls, {}).setdefault(attr, []).append(
                    (doms, _held_locks(index, node), node, info))
        for cls, attrs in by_class.items():
            for attr, sites in attrs.items():
                domains = set().union(*(d for d, _, _, _ in sites))
                if len(domains) < 2:
                    continue
                common = frozenset.intersection(
                    *(locks for _, locks, _, _ in sites))
                if common:
                    continue
                sites.sort(key=lambda s: s[2].lineno)
                _, _, first, _ = sites[0]
                where = ", ".join(
                    f"{i.name}:{n.lineno} [{'/'.join(sorted(d))}]"
                    for d, _, n, i in sites)
                ctx.report(
                    self.id, first,
                    f"self.{attr} is mutated from domains "
                    f"{sorted(domains)} with no common lock "
                    f"(sites: {where}); lock it, confine it to one "
                    "domain, or justify the benign race inline")


# --------------------------------------------------------------------
# GL053
# --------------------------------------------------------------------


class InconsistentLockOrder(Rule):
    id = "GL053"
    name = "inconsistent-lock-order"
    summary = ("lock acquired while holding another lock, with the "
               "opposite order taken elsewhere in the module — two "
               "threads running the two paths deadlock (AB/BA)")

    def check(self, ctx: Context) -> None:
        index = ctx.index
        edges: dict[tuple, list[ast.AST]] = {}
        for node in ast.walk(index.tree):
            inner: Optional[str] = None
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    inner = _lockish_name(item.context_expr)
                    if inner:
                        break
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "acquire":
                inner = _lockish_name(node.func.value)
            if not inner:
                continue
            for outer in _held_locks(index, node):
                if outer != inner:
                    edges.setdefault((outer, inner), []).append(node)
        reported: set[frozenset] = set()
        for (a, b), nodes in edges.items():
            if (b, a) not in edges or frozenset((a, b)) in reported:
                continue
            reported.add(frozenset((a, b)))
            other = edges[(b, a)][0]
            ctx.report(
                self.id, nodes[0],
                f"lock order {a} -> {b} here, but {b} -> {a} at line "
                f"{other.lineno}: two threads taking the two paths "
                "deadlock; pick one global order (or collapse to one "
                "lock)")


RULES = [DeviceCallOffWorker(), BlockingCallInAsyncio(),
         CrossDomainMutationWithoutLock(), InconsistentLockOrder()]
