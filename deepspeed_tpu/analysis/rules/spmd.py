"""SPMD sharding/collective correctness rules (GL060-GL063, ISSUE 15
tentpole part 1 — shardlint).

The roadmap's multi-mesh tentpoles (MoE over ``ep``, elastic reshard
restore, the fsdp×zps hierarchical wire) all die silently on SPMD
mistakes a type checker cannot see: a typo'd axis string raises only at
trace time (or worse, traces fine under ``shard_map``'s dynamic axis
env and deadlocks a pod), a collective guarded by a rank-dependent
branch wedges every other participant forever, and a sharding-spec typo
makes GSPMD silently replicate (or reshard) a tensor that was supposed
to stay put. These rules check the *source* against a package-wide
**mesh-axis vocabulary** collected in the linter's pass 1
(:func:`..core.collect_axis_declarations`: ``Mesh``/``shard_map``
``axis_names``, axis-named assignments/defaults like
``AXIS_ORDER = ("pp", "dp", ...)``, and ``# shardlint: axes=...``
annotations). Only LITERAL axis strings are checked — a variable axis
is invisible to the AST and stays exempt; declare its values with the
annotation when you want coverage. An empty vocabulary disables
GL060/GL063 entirely (nothing declared -> nothing to violate), so
single-file lints of undeclared code never false-fire.

- GL060: axis string passed to a ``lax`` collective /
  ``axis_index`` / ``shard_map(axis_names=...)`` not in the vocabulary
  (``"fdsp"`` dies at lint time, with a did-you-mean);
- GL061: collective reachable under a conditional whose predicate
  derives from ``axis_index``/``process_index``/per-rank state — the
  classic SPMD deadlock (rank 0 enters the all-reduce, everyone else
  waits forever);
- GL062: collective hazards under ``vmap``/``scan`` bodies, and paired
  quantize/collective calls (the qgZ codes+scales two-hop shape) whose
  payload and scales travel different routes;
- GL063: sharding-spec hygiene — ``PartitionSpec`` axis names checked
  against the same vocabulary, and multi-operand identity-reshard jits
  without donation (generalizing GL021's single-operand form).

Runtime counterpart: :mod:`..meshsan` checks each compiled
executable's ACTUAL collective traffic (from the telemetry ledger's
optimized-HLO walk) against a declared per-executable contract.
"""

from __future__ import annotations

import ast
import difflib
from typing import Iterable, Optional

from ..core import (Context, Rule, attr_chain, iter_trace_wrapper_calls,
                    _func_name_args)

# ``lax`` collectives / axis queries and the positional slot their axis
# argument rides in (keyword form: ``axis_name`` / ``axis_names``)
_AXIS_ARG_POS = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1,
    "all_gather": 1, "psum_scatter": 1, "all_to_all": 1,
    "ppermute": 1, "pshuffle": 1, "pbroadcast": 1,
    "axis_index": 0, "axis_size": 0,
}

# the subset that actually moves bytes (GL061/GL062 scope; axis_index
# and friends are queries, not synchronization points)
_COLLECTIVE_TAILS = frozenset({
    "psum", "pmean", "pmax", "pmin", "all_gather", "psum_scatter",
    "all_to_all", "ppermute", "pshuffle", "pbroadcast",
})

# calls whose result is a per-rank value: the seeds of GL061's
# rank-derived-name inference (process_count is deliberately absent —
# it is uniform across ranks and branching on it is fine)
_RANK_SOURCE_TAILS = frozenset({"axis_index", "process_index",
                                "get_rank"})


def _is_lax_rooted(chain: list[str]) -> bool:
    """``lax.psum`` / ``jax.lax.psum`` — the repo's comm facade wraps
    these, so the facade's own internals are checked here and its
    callers (which pass dynamic group names) are not; ``self.psum`` /
    ``dist.all_gather`` never match."""
    return "lax" in chain[:-1]


def _collective_calls(tree: ast.AST) -> Iterable[tuple[ast.Call, str]]:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = attr_chain(node.func)
        if not chain or chain[-1] not in _COLLECTIVE_TAILS:
            continue
        if not _is_lax_rooted(chain):
            continue
        yield node, chain[-1]


def _axis_expr(call: ast.Call, tail: str) -> Optional[ast.AST]:
    """The axis argument of a collective/axis-query call, positional or
    keyword; None when absent."""
    for kw in call.keywords:
        if kw.arg in ("axis_name", "axis_names"):
            return kw.value
    pos = _AXIS_ARG_POS.get(tail)
    if pos is not None and len(call.args) > pos:
        return call.args[pos]
    return None


def _literal_axis_strings(node: ast.AST) -> list[tuple[str, ast.AST]]:
    """(axis string, node) for every string literal inside an axis
    expression — a bare literal or the literal elements of a
    tuple/list/set (mixed literal/dynamic checks the literal part)."""
    out: list[tuple[str, ast.AST]] = []
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.append((node.value, node))
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for e in node.elts:
            out.extend(_literal_axis_strings(e))
    return out


def _suggest(axis: str, vocab: set[str]) -> str:
    close = difflib.get_close_matches(axis, sorted(vocab), n=1)
    return f" (did you mean '{close[0]}'?)" if close else ""


# --------------------------------------------------------------------
# GL060
# --------------------------------------------------------------------


class UnknownMeshAxis(Rule):
    id = "GL060"
    name = "unknown-mesh-axis"
    summary = ("literal axis string passed to a lax collective / "
               "axis_index / shard_map(axis_names=...) that no mesh "
               "declaration or `# shardlint: axes=` annotation defines "
               "— a typo'd axis raises at trace time at best, "
               "deadlocks a pod at worst")

    def check(self, ctx: Context) -> None:
        vocab = ctx.index.axis_vocab
        if not vocab:
            return
        seen: set[int] = set()
        for call, tail in _collective_calls(ctx.index.tree):
            expr = _axis_expr(call, tail)
            if expr is None:
                continue
            for axis, node in _literal_axis_strings(expr):
                if axis not in vocab and id(node) not in seen:
                    seen.add(id(node))
                    ctx.report(
                        self.id, call,
                        f"lax.{tail} over unknown mesh axis "
                        f"'{axis}'{_suggest(axis, vocab)}; declared "
                        f"axes: {sorted(vocab)} — fix the name or "
                        "declare it with `# shardlint: axes=...`")
        # axis QUERIES (axis_index/axis_size) and shard_map axis_names
        # are not in the byte-moving tail set; same literal-axis check
        for node in ast.walk(ctx.index.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if not chain:
                continue
            tail = chain[-1]
            if tail in ("axis_index", "axis_size") \
                    and _is_lax_rooted(chain):
                expr = _axis_expr(node, tail)
                if expr is None:
                    continue
                for axis, lit in _literal_axis_strings(expr):
                    if axis not in vocab and id(lit) not in seen:
                        seen.add(id(lit))
                        ctx.report(
                            self.id, node,
                            f"lax.{tail} over unknown mesh axis "
                            f"'{axis}'{_suggest(axis, vocab)}; "
                            f"declared axes: {sorted(vocab)}")
            elif tail == "shard_map":
                for kw in node.keywords:
                    if kw.arg != "axis_names":
                        continue
                    for axis, lit in _literal_axis_strings(kw.value):
                        if axis not in vocab and id(lit) not in seen:
                            seen.add(id(lit))
                            ctx.report(
                                self.id, node,
                                f"shard_map over unknown mesh axis "
                                f"'{axis}'{_suggest(axis, vocab)}; "
                                f"declared axes: {sorted(vocab)}")


# --------------------------------------------------------------------
# GL061
# --------------------------------------------------------------------


def _rank_derived_locals(index, info) -> set[str]:
    """Names in ``info`` assigned (directly or transitively) from a
    rank source — the same forward-fixpoint scheme traced-locals
    inference uses, seeded from axis_index/process_index/get_rank."""
    derived: set[str] = set()

    def expr_derived(expr: ast.AST) -> bool:
        for n in ast.walk(expr):
            if isinstance(n, ast.Call):
                chain = attr_chain(n.func)
                if chain and chain[-1] in _RANK_SOURCE_TAILS:
                    return True
            if isinstance(n, ast.Name) and n.id in derived:
                return True
        return False

    def name_targets(t: ast.AST) -> list[str]:
        if isinstance(t, ast.Name):
            return [t.id]
        if isinstance(t, (ast.Tuple, ast.List)):
            out: list[str] = []
            for e in t.elts:
                out.extend(name_targets(e))
            return out
        return []

    changed = True
    while changed:
        changed = False
        for node in ast.walk(info.node):
            targets, value = [], None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AugAssign):
                targets, value = [node.target], node.value
            elif isinstance(node, ast.AnnAssign) and node.value:
                targets, value = [node.target], node.value
            if value is None or not expr_derived(value):
                continue
            for t in targets:
                for name in name_targets(t):
                    if name not in derived:
                        derived.add(name)
                        changed = True
    return derived


class RankDivergentCollective(Rule):
    id = "GL061"
    name = "rank-divergent-collective"
    summary = ("collective under a conditional whose predicate derives "
               "from axis_index/process_index/per-rank state — ranks "
               "that skip the branch never enter the collective, so "
               "the ranks that did wait forever (the classic SPMD "
               "multi-host deadlock)")

    def check(self, ctx: Context) -> None:
        index = ctx.index
        for info in index.functions.values():
            derived = None     # computed lazily, once per function
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Call) \
                        or index.enclosing_function(node) is not info.node:
                    continue
                chain = attr_chain(node.func)
                if not chain or chain[-1] not in _COLLECTIVE_TAILS \
                        or not _is_lax_rooted(chain):
                    continue
                # walk up to every enclosing if/while/ternary WITHIN
                # this function and test the predicate for rank taint
                cur = index.parent(node)
                guard = None
                while cur is not None and cur is not info.node:
                    test = None
                    if isinstance(cur, (ast.If, ast.While, ast.IfExp)):
                        test = cur.test
                    if test is not None:
                        if derived is None:
                            derived = _rank_derived_locals(index, info)
                        if self._rank_tainted(test, derived):
                            guard = cur
                            break
                    cur = index.parent(cur)
                if guard is not None:
                    ctx.report(
                        self.id, node,
                        f"lax.{chain[-1]} reachable only under a "
                        "rank-dependent predicate (line "
                        f"{guard.lineno}): ranks that skip the branch "
                        "never join the collective and the rest "
                        "deadlock; make the collective unconditional "
                        "(mask the OPERAND with jnp.where instead) or "
                        "suppress with the uniformity argument")

    @staticmethod
    def _rank_tainted(test: ast.AST, derived: set[str]) -> bool:
        for n in ast.walk(test):
            if isinstance(n, ast.Call):
                chain = attr_chain(n.func)
                if chain and chain[-1] in _RANK_SOURCE_TAILS:
                    return True
            if isinstance(n, ast.Name) and n.id in derived:
                return True
        return False


# --------------------------------------------------------------------
# GL062
# --------------------------------------------------------------------

# loop/batching wrappers whose body re-issues its collectives every
# iteration / batch element
_LOOP_WRAPPER_TAILS = {"scan", "fori_loop", "while_loop",
                       "associative_scan"}
_VMAP_TAILS = {"vmap"}

# ppermute under scan is THE ring-attention / pipeline-schedule idiom
# (one neighbor hop per step is the algorithm, and its payload is the
# O(S/P) block being rotated) — exempt under loops, still flagged
# under vmap
_LOOP_EXEMPT_TAILS = {"ppermute", "pshuffle"}


class CollectiveUnderLoopOrVmap(Rule):
    id = "GL062"
    name = "collective-under-vmap-or-scan"
    summary = ("reduction/gather collective inside a scan/while/vmap "
               "body — it re-runs every iteration (a latency-bound "
               "collective per loop step is a silent perf cliff), and "
               "under vmap without spmd_axis_name it is a trace error "
               "waiting for a batched input; also flags paired "
               "quantize/collective calls (qgZ codes+scales) whose "
               "payload and scales take different routes")

    def check(self, ctx: Context) -> None:
        self._check_loop_bodies(ctx)
        self._check_quant_pairs(ctx)

    # -- (a) collectives in loop/vmap bodies -----------------------
    def _check_loop_bodies(self, ctx: Context) -> None:
        index = ctx.index
        # id(FuncInfo) -> (info, wrapper kind); FuncInfo is an unhashable
        # dataclass
        body_kind: dict[int, tuple] = {}
        for call in iter_trace_wrapper_calls(index.tree):
            chain = attr_chain(call.func)
            tail = chain[-1]
            if tail in _VMAP_TAILS:
                # vmap with an explicit axis name is the author saying
                # "I know this batches a collective"
                if any(k.arg in ("axis_name", "spmd_axis_name")
                       for k in call.keywords):
                    continue
                kind = "vmap"
            elif tail in _LOOP_WRAPPER_TAILS:
                kind = tail
            else:
                continue
            for name in _func_name_args(call):
                for info in index._resolve_name_at(call, name):
                    body_kind.setdefault(id(info), (info, kind))
            for a in call.args:
                if isinstance(a, ast.Lambda) and a in index.functions:
                    info = index.functions[a]
                    body_kind.setdefault(id(info), (info, kind))
        if not body_kind:
            return
        for info, kind in body_kind.values():
            # the body function and everything lexically inside it
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Call):
                    continue
                chain = attr_chain(node.func)
                if not chain or chain[-1] not in _COLLECTIVE_TAILS \
                        or not _is_lax_rooted(chain):
                    continue
                if kind != "vmap" and chain[-1] in _LOOP_EXEMPT_TAILS:
                    continue
                ctx.report(
                    self.id, node,
                    f"lax.{chain[-1]} inside a lax.{kind} body "
                    f"('{info.name}'): it re-issues every "
                    + ("batch element and needs spmd_axis_name to "
                       "even trace" if kind == "vmap" else
                       "iteration — hoist it out of the loop, or "
                       "suppress with the reason the per-step "
                       "exchange IS the algorithm"))

    # -- (b) paired quantize/collective route mismatch -------------
    def _check_quant_pairs(self, ctx: Context) -> None:
        index = ctx.index
        for info in index.functions.values():
            # tuple-unpack assignments: q, s(, ...) = f(...)
            groups: list[set[str]] = []
            for node in ast.walk(info.node):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Tuple) \
                        and isinstance(node.value, ast.Call):
                    names = {e.id for e in node.targets[0].elts
                             if isinstance(e, ast.Name)}
                    if len(names) >= 2:
                        groups.append(names)
            if not groups:
                continue
            # collective calls whose FIRST operand is one of the pair,
            # ACCUMULATED per name in source order: the two-hop qgZ
            # shape exchanges each of q/s twice, and keying on the name
            # alone would let a later matching hop overwrite (and mask)
            # a divergent first hop
            routes: dict[int, dict[str, list[tuple]]] = {}
            calls: dict[int, dict[str, list[ast.Call]]] = {}
            ordered = sorted(
                (n for n in ast.walk(info.node)
                 if isinstance(n, ast.Call) and n.args),
                key=lambda n: (n.lineno, n.col_offset))
            for node in ordered:
                chain = attr_chain(node.func)
                if not chain or chain[-1] not in (
                        "all_to_all", "all_gather", "psum_scatter"):
                    continue
                if not _is_lax_rooted(chain):
                    continue
                op0 = node.args[0]
                if not isinstance(op0, ast.Name):
                    continue
                for gi, names in enumerate(groups):
                    if op0.id not in names:
                        continue
                    route = self._route(node, chain[-1])
                    routes.setdefault(gi, {}).setdefault(
                        op0.id, []).append(route)
                    calls.setdefault(gi, {}).setdefault(
                        op0.id, []).append(node)
            for gi, by_name in routes.items():
                if len(by_name) < 2:
                    continue
                distinct = {tuple(seq) for seq in by_name.values()}
                if len(distinct) > 1:
                    names = sorted(by_name)
                    last = calls[gi][names[-1]][-1]
                    ctx.report(
                        self.id, last,
                        f"paired collectives over {names} (unpacked "
                        "from one call — the quantized codes+scales "
                        "shape) take DIFFERENT routes (axis/split/"
                        "concat args or hop sequences differ): scales "
                        "that travel a different path than their "
                        "payload dequantize the wrong blocks")

    @staticmethod
    def _route(call: ast.Call, tail: str) -> tuple:
        parts = [tail]
        expr = _axis_expr(call, tail)
        parts.append(ast.dump(expr) if expr is not None else "?")
        for kw in sorted((k for k in call.keywords if k.arg),
                         key=lambda k: k.arg):
            if kw.arg in ("split_axis", "concat_axis",
                          "scatter_dimension", "axis", "tiled"):
                parts.append(f"{kw.arg}={ast.dump(kw.value)}")
        for i, a in enumerate(call.args[2:], start=2):
            parts.append(f"pos{i}={ast.dump(a)}")
        return tuple(parts)


# --------------------------------------------------------------------
# GL063
# --------------------------------------------------------------------


class ShardingSpecHygiene(Rule):
    id = "GL063"
    name = "sharding-spec-hygiene"
    summary = ("PartitionSpec axis name outside the declared mesh-axis "
               "vocabulary (GSPMD treats an unknown axis as a silent "
               "full replication — the tensor you sharded isn't), or a "
               "multi-operand identity-reshard jit without donation "
               "(generalizing GL021: source and destination layouts "
               "both stay live)")

    def check(self, ctx: Context) -> None:
        self._check_spec_axes(ctx)
        self._check_reshards(ctx)

    def _check_spec_axes(self, ctx: Context) -> None:
        vocab = ctx.index.axis_vocab
        if not vocab:
            return
        for node in ast.walk(ctx.index.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if not chain or chain[-1] not in ("PartitionSpec", "P"):
                continue
            for a in node.args:
                for axis, _lit in _literal_axis_strings(a):
                    if axis not in vocab:
                        ctx.report(
                            self.id, node,
                            f"PartitionSpec axis '{axis}' is not a "
                            f"declared mesh axis{_suggest(axis, vocab)}"
                            f"; declared: {sorted(vocab)} — GSPMD "
                            "will silently replicate this dim")

    def _check_reshards(self, ctx: Context) -> None:
        for node in ast.walk(ctx.index.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if not chain or chain[-1] != "jit" or not node.args:
                continue
            if any(k.arg in ("donate_argnums", "donate_argnames")
                   for k in node.keywords):
                continue
            if not any(k.arg == "out_shardings" for k in node.keywords):
                continue
            target = node.args[0]
            if not isinstance(target, ast.Lambda):
                continue
            args = target.args
            pos = args.posonlyargs + args.args
            if len(pos) < 2 or args.kwonlyargs:
                continue        # single-operand form is GL021's
            if self._is_identity_body(target.body,
                                      [p.arg for p in pos]):
                ctx.report(
                    self.id, node,
                    "multi-operand identity-reshard jit without "
                    "donate_argnums: every input's source layout "
                    "stays live alongside its resharded copy — "
                    "donate the inputs")

    @staticmethod
    def _is_identity_body(body: ast.AST, params: list[str]) -> bool:
        """Body is a pure rearrangement of the parameter names
        (tuple/list of Names drawn from params, each at most once)."""
        if isinstance(body, ast.Name):
            return body.id in params
        if isinstance(body, (ast.Tuple, ast.List)):
            seen: list[str] = []
            for e in body.elts:
                if not isinstance(e, ast.Name) or e.id not in params \
                        or e.id in seen:
                    return False
                seen.append(e.id)
            return bool(seen)
        return False


RULES = [UnknownMeshAxis(), RankDivergentCollective(),
         CollectiveUnderLoopOrVmap(), ShardingSpecHygiene()]
