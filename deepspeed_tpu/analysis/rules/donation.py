"""Buffer-donation rules (GL020-GL021).

An un-donated state buffer doubles the step's live memory (old + new
state coexist across the dispatch) and forces XLA to emit copies where
an in-place update was legal. The training engine's state-carrying jits
donate; these rules keep it that way as the jit population grows.
"""

from __future__ import annotations

import ast

from ..core import Context, Rule, attr_chain

# parameter names that mark a function as state-carrying: the value is
# threaded call-to-call and the previous buffer dies with the dispatch
STATE_PARAM_NAMES = {"state", "pools", "opt_state", "carry", "acc",
                     "accum", "buffers"}


def _jit_calls(ctx: Context):
    for node in ast.walk(ctx.index.tree):
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain and chain[-1] == "jit" and node.args:
                yield node


def _has_donation(call: ast.Call) -> bool:
    return any(k.arg in ("donate_argnums", "donate_argnames")
               for k in call.keywords)


def _resolve_target(ctx: Context, target: ast.AST):
    """jit's first argument -> (function node, display name) when the
    function is defined in this module; (None, None) for attributes and
    imported callables (cross-module resolution isn't worth the false
    positives)."""
    if isinstance(target, ast.Lambda):
        return target, "<lambda>"
    if isinstance(target, ast.Name):
        for info in ctx.index.functions.values():
            if info.name == target.id and not isinstance(info.node,
                                                         ast.Lambda):
                return info.node, target.id
        return None, None
    if isinstance(target, ast.Call):
        chain = attr_chain(target.func)
        if chain and chain[-1] == "partial" and target.args:
            return _resolve_target(ctx, target.args[0])
    return None, None


class StateJitWithoutDonation(Rule):
    id = "GL020"
    name = "state-jit-without-donation"
    summary = ("jax.jit of a state-carrying step function (a parameter "
               "named state/pools/opt_state/carry/acc/...) without "
               "donate_argnums — the old state buffer stays live across "
               "the dispatch, doubling step memory")

    def check(self, ctx: Context) -> None:
        for call in _jit_calls(ctx):
            if _has_donation(call):
                continue
            fn, name = _resolve_target(ctx, call.args[0])
            if fn is None:
                continue
            args = getattr(fn, "args", None)
            if args is None:
                continue
            pos = [a.arg for a in args.posonlyargs + args.args]
            stateful = [p for p in pos if p in STATE_PARAM_NAMES]
            if stateful:
                ctx.report(
                    self.id, call,
                    f"jax.jit({name}) carries state parameter(s) "
                    f"{stateful} but donates nothing; add donate_argnums "
                    "(or suppress with a comment explaining why the "
                    "input must outlive the call)")


class ReshardWithoutDonation(Rule):
    id = "GL021"
    name = "reshard-without-donation"
    summary = ("jax.jit(lambda t: t, out_shardings=...) without donation "
               "— an identity reshard that keeps source AND destination "
               "buffers live; donating the input halves its memory "
               "high-water")

    def check(self, ctx: Context) -> None:
        for call in _jit_calls(ctx):
            if _has_donation(call):
                continue
            if not any(k.arg == "out_shardings" for k in call.keywords):
                continue
            target = call.args[0]
            if not isinstance(target, ast.Lambda):
                continue
            args = target.args
            pos = args.posonlyargs + args.args
            if len(pos) == 1 and not args.kwonlyargs \
                    and isinstance(target.body, ast.Name) \
                    and target.body.id == pos[0].arg:
                ctx.report(
                    self.id, call,
                    "identity-reshard jit without donate_argnums: the "
                    "input layout is dead after the copy — donate it")


RULES = [StateJitWithoutDonation(), ReshardWithoutDonation()]
