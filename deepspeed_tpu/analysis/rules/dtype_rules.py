"""Dtype-promotion rules (GL030).

JAX's weak-type rules keep bare Python floats from widening bf16
arithmetic — but a constant wrapped in ``np.float32(...)`` /
``jnp.array(0.5)`` is a committed 32-bit array, and one of them in a
bf16 compute path silently promotes every downstream op to f32 (2x HBM
traffic on the promoted tensors; the MXU path may change too).
"""

from __future__ import annotations

import ast

from ..core import Context, Rule, attr_chain

_WIDENING_CASTS = {("np", "float32"), ("np", "float64"),
                   ("numpy", "float32"), ("numpy", "float64"),
                   ("jnp", "float32"), ("jnp", "float64")}
_ARRAY_CTORS = {("np", "array"), ("np", "asarray"),
                ("jnp", "array"), ("jnp", "asarray")}


def _is_float_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and isinstance(node.value, float):
        return True
    if isinstance(node, ast.UnaryOp) and isinstance(node.op,
                                                    (ast.USub, ast.UAdd)):
        return _is_float_literal(node.operand)
    return False


class NonWeakFloatConstant(Rule):
    id = "GL030"
    name = "non-weak-float-constant"
    summary = ("committed 32/64-bit float constant (np.float32(c), "
               "jnp.array(c)) used in arithmetic inside jit-reachable "
               "code — upcasts bf16 operands where a weak Python float "
               "would not")

    def check(self, ctx: Context) -> None:
        for info in ctx.index.reachable_functions():
            for node in ast.walk(info.node):
                if ctx.index.enclosing_function(node) is not info.node:
                    continue
                if not isinstance(node, ast.BinOp):
                    continue
                for side in (node.left, node.right):
                    if self._widening_const(side):
                        ctx.report(
                            self.id, side,
                            "committed float constant in arithmetic "
                            "under jit: use a bare Python float (weak "
                            "type follows the array operand) or cast "
                            "with .astype(x.dtype)")
                        break

    @staticmethod
    def _widening_const(node: ast.AST) -> bool:
        if not isinstance(node, ast.Call) or not node.args:
            return False
        chain = tuple(attr_chain(node.func))
        if chain in _WIDENING_CASTS:
            return _is_float_literal(node.args[0])
        if chain in _ARRAY_CTORS and not any(
                k.arg == "dtype" for k in node.keywords):
            return _is_float_literal(node.args[0])
        return False


RULES = [NonWeakFloatConstant()]
