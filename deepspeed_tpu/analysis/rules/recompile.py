"""Recompile-hazard rules (GL010-GL012).

T3's (arXiv:2401.16677) observation for collectives holds for the whole
dispatch path: throughput dies on trace/compile gaps, not kernels. These
rules flag patterns that bake call-varying host values into the traced
program — every distinct value is a silent recompile.
"""

from __future__ import annotations

import ast

from ..core import Context, Rule, attr_chain

# parameter names that are near-certainly arrays at a jit boundary
ARRAYISH_PARAM_NAMES = {
    "params", "state", "batch", "tokens", "grads", "grad", "pools",
    "x", "xs", "arr", "tree", "leaf", "logits", "kv", "cache", "master",
    "opt_state", "acc", "carry", "inputs", "labels",
}


def _bare_param_names(node: ast.AST) -> set[str]:
    """Positional, default-less parameter names of a function def —
    the ones bound per call at a jit boundary (params with literal
    defaults are config-like and usually partial-bound static)."""
    args = getattr(node, "args", None)
    if args is None:
        return set()
    pos = args.posonlyargs + args.args
    n_default = len(args.defaults)
    no_default = pos[:len(pos) - n_default] if n_default else pos
    return {a.arg for a in no_default}


class ControlFlowOnCallVaryingValue(Rule):
    id = "GL010"
    name = "trace-varying-control-flow"
    summary = ("Python if/while/for over a bare per-call parameter of a "
               "jit-root function — the branch is resolved at trace time, "
               "so every distinct value compiles a new executable")

    def check(self, ctx: Context) -> None:
        for info in ctx.index.reachable_functions():
            if not info.is_root:
                continue
            params = _bare_param_names(info.node)
            if not params:
                continue
            for node in ast.walk(info.node):
                if ctx.index.enclosing_function(node) is not info.node:
                    continue        # nested defs have their own params
                if isinstance(node, (ast.If, ast.While)):
                    expr = node.test
                elif isinstance(node, ast.For):
                    expr = node.iter
                else:
                    continue
                hit = self._bare_param_ref(expr, params)
                if hit:
                    ctx.report(
                        self.id, node,
                        f"control flow over per-call parameter "
                        f"'{hit}' inside a jit root: each distinct value "
                        "traces a new program — make it static "
                        "(closure/partial) or move the branch in-graph "
                        "(lax.cond / jnp.where)")

    @classmethod
    def _bare_param_ref(cls, expr: ast.AST, params: set[str]):
        """A param used as a bare VALUE operand of the test itself.
        Descends only through boolean/arithmetic/comparison structure:
        a param inside a call (``len(x)``, ``is_quantized(x)``), behind
        an attribute (``cfg.flag``, ``x.shape``) or subscript is
        trace-time host plumbing, and identity/membership tests
        (``x is None``, ``name in cache``) are the static-idiom escape
        hatches — none of those are per-value retrace hazards we can
        call with confidence."""
        if isinstance(expr, ast.Name):
            return expr.id if expr.id in params else None
        if isinstance(expr, ast.BoolOp):
            for v in expr.values:
                hit = cls._bare_param_ref(v, params)
                if hit:
                    return hit
            return None
        if isinstance(expr, ast.UnaryOp):
            return cls._bare_param_ref(expr.operand, params)
        if isinstance(expr, ast.BinOp):
            return (cls._bare_param_ref(expr.left, params)
                    or cls._bare_param_ref(expr.right, params))
        if isinstance(expr, ast.Compare):
            if any(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                   for op in expr.ops):
                return None
            for v in (expr.left, *expr.comparators):
                hit = cls._bare_param_ref(v, params)
                if hit:
                    return hit
            return None
        if isinstance(expr, ast.Call):
            # only range(param) — the canonical trace-varying loop bound
            if isinstance(expr.func, ast.Name) and expr.func.id == "range":
                for a in expr.args:
                    hit = cls._bare_param_ref(a, params)
                    if hit:
                        return hit
            return None
        return None


class StaticArgnumsOnArray(Rule):
    id = "GL011"
    name = "static-argnums-on-array"
    summary = ("static_argnums/static_argnames covering a likely-array "
               "parameter — arrays hashed as static recompile per value "
               "(or fail to hash at all)")

    def check(self, ctx: Context) -> None:
        for node in ast.walk(ctx.index.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if not chain or chain[-1] != "jit":
                continue
            static_kw = {k.arg: k.value for k in node.keywords
                         if k.arg in ("static_argnums", "static_argnames")}
            if not static_kw or not node.args:
                continue
            target = node.args[0]
            fn = self._resolve(ctx, target)
            if fn is None:
                continue
            args = getattr(fn, "args", None)
            if args is None:
                continue
            pos = [a.arg for a in args.posonlyargs + args.args]
            bad: list[str] = []
            nums = static_kw.get("static_argnums")
            if nums is not None:
                for idx in self._int_elts(nums):
                    if 0 <= idx < len(pos) \
                            and pos[idx] in ARRAYISH_PARAM_NAMES:
                        bad.append(pos[idx])
            names = static_kw.get("static_argnames")
            if names is not None:
                for s in self._str_elts(names):
                    if s in ARRAYISH_PARAM_NAMES:
                        bad.append(s)
            if bad:
                ctx.report(
                    self.id, node,
                    f"static_argnums/argnames covers parameter(s) "
                    f"{bad} that look like arrays; arrays must be "
                    "traced operands, not static hash keys")

    @staticmethod
    def _resolve(ctx: Context, target: ast.AST):
        if isinstance(target, ast.Lambda):
            return target
        if isinstance(target, ast.Name):
            for info in ctx.index.functions.values():
                if info.name == target.id:
                    return info.node
        return None

    @staticmethod
    def _int_elts(node: ast.AST) -> list[int]:
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            return [node.value]
        if isinstance(node, (ast.Tuple, ast.List)):
            return [e.value for e in node.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, int)]
        return []

    @staticmethod
    def _str_elts(node: ast.AST) -> list[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return [node.value]
        if isinstance(node, (ast.Tuple, ast.List)):
            return [e.value for e in node.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
        return []


_CLOCK_CHAINS = {("time", "time"), ("time", "perf_counter"),
                 ("time", "monotonic"), ("time", "process_time")}


class HostEffectUnderJit(Rule):
    id = "GL012"
    name = "host-effect-under-jit"
    summary = ("print()/time.time()/f-string-on-traced-value inside "
               "jit-reachable code — runs once at trace time, then never "
               "again (stale logs, zero timings), or forces a retrace")

    def check(self, ctx: Context) -> None:
        for info in ctx.index.reachable_functions():
            traced = ctx.index.traced_union(info)
            for node in ast.walk(info.node):
                if ctx.index.enclosing_function(node) is not info.node:
                    continue
                if isinstance(node, ast.Call):
                    chain = tuple(attr_chain(node.func))
                    if chain == ("print",):
                        ctx.report(
                            self.id, node,
                            "print() under jit executes at trace time "
                            "only; use jax.debug.print for runtime "
                            "values")
                    elif chain in _CLOCK_CHAINS:
                        ctx.report(
                            self.id, node,
                            f"{'.'.join(chain)}() under jit is evaluated "
                            "once at trace time — it cannot measure the "
                            "compiled program; time at the dispatch "
                            "boundary instead")
                elif isinstance(node, ast.JoinedStr):
                    for v in node.values:
                        if isinstance(v, ast.FormattedValue) and any(
                                isinstance(n, ast.Name)
                                and n.id in traced
                                and n.id not in ("self", "cls")
                                for n in ast.walk(v.value)):
                            ctx.report(
                                self.id, node,
                                "f-string formatting a traced value "
                                "under jit embeds the tracer repr at "
                                "trace time (or retraces); format at "
                                "the host boundary")
                            break


RULES = [ControlFlowOnCallVaryingValue(), StaticArgnumsOnArray(),
         HostEffectUnderJit()]
