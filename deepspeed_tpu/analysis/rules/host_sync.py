"""Host-synchronization rules (GL001-GL005).

The class of bug that cost PR 1 a 125 ms host-dispatch RTT against
17 ms of TPU work: device values pulled to the host (or host round
trips hidden in traced code) on paths that should stay async.
"""

from __future__ import annotations

import ast

from ..core import Context, Rule, attr_chain, contains_device_call

_SCALAR_CASTS = {"float", "int", "bool", "complex"}


def _reachable_nodes(ctx: Context):
    """(info, traced-union, node) triples, each node yielded exactly
    once — owned by its innermost enclosing function."""
    for info in ctx.index.reachable_functions():
        traced = ctx.index.traced_union(info)
        for node in ast.walk(info.node):
            if node is info.node:
                continue
            enc = ctx.index.enclosing_function(node)
            if enc is not info.node:
                continue
            yield info, traced, node


def _mentions_traced(index, expr: ast.AST, traced: set[str]) -> bool:
    return index.mentions_device_value(expr, traced)


class HostSyncInJit(Rule):
    id = "GL001"
    name = "host-sync-in-jit"
    summary = (".item()/float()/int()/bool() on a device value inside "
               "jit-reachable code — a host sync baked into the traced "
               "program (raises at trace time or, worse, silently "
               "retraces per call)")

    def check(self, ctx: Context) -> None:
        for info, traced, node in _reachable_nodes(ctx):
            if not isinstance(node, ast.Call):
                continue
            # x.item()
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args:
                ctx.report(self.id, node,
                           ".item() inside jit-reachable code is a "
                           "device->host sync; keep the value on "
                           "device or move the read to a flush "
                           "boundary")
                continue
            # float(x)/int(x)/bool(x) on a traced value
            if isinstance(node.func, ast.Name) \
                    and node.func.id in _SCALAR_CASTS \
                    and len(node.args) == 1 \
                    and _mentions_traced(ctx.index, node.args[0], traced):
                ctx.report(
                    self.id, node,
                    f"{node.func.id}() of a traced value inside "
                    "jit-reachable code forces a host sync; use jnp "
                    "ops (astype/where) to stay on device")


class TracedTruthiness(Rule):
    id = "GL002"
    name = "traced-truthiness"
    summary = ("Python if/while/assert on a device value inside "
               "jit-reachable code — implicit bool() is a host sync (and "
               "a per-value retrace when it survives tracing)")

    def check(self, ctx: Context) -> None:
        for info, traced, node in _reachable_nodes(ctx):
            if isinstance(node, (ast.If, ast.While, ast.Assert, ast.IfExp)):
                test = node.test
            else:
                continue
            # `x is None` / `x is not None` arg-presence checks are
            # host-static by construction
            if isinstance(test, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot))
                    for op in test.ops):
                continue
            if _mentions_traced(ctx.index, test, traced):
                ctx.report(
                    self.id, node,
                    "branching on a device value inside jit-reachable "
                    "code; use jnp.where / lax.cond to keep control "
                    "flow in-graph")


class BlockUntilReadyInLoop(Rule):
    id = "GL003"
    name = "sync-in-loop"
    summary = ("block_until_ready inside a Python loop — serializes "
               "dispatch against device completion every iteration, "
               "killing dispatch-ahead")

    def check(self, ctx: Context) -> None:
        for node in ast.walk(ctx.index.tree):
            if not isinstance(node, ast.Call):
                continue
            is_method = (isinstance(node.func, ast.Attribute)
                         and node.func.attr == "block_until_ready")
            chain = attr_chain(node.func)
            is_fn = bool(chain) and chain[-1] == "block_until_ready"
            if (is_method or is_fn) and ctx.index.in_loop(node):
                ctx.report(
                    self.id, node,
                    "block_until_ready in a loop syncs every iteration; "
                    "hoist the sync past the loop (or batch the work "
                    "into one dispatch)")


class ScalarPullInHostLoop(Rule):
    id = "GL004"
    name = "scalar-pull-in-host-loop"
    summary = ("float()/int()/bool() wrapped around a jnp/jax computation "
               "inside a host loop — one blocking device round trip per "
               "iteration (the per-leaf sync pattern); fuse the reduction "
               "into one jit and pull one scalar")

    def check(self, ctx: Context) -> None:
        for node in ast.walk(ctx.index.tree):
            if not isinstance(node, ast.Call):
                continue
            if not (isinstance(node.func, ast.Name)
                    and node.func.id in _SCALAR_CASTS
                    and len(node.args) == 1):
                continue
            info = ctx.index.enclosing_info(node)
            if info is not None and info.reachable:
                continue       # GL001's territory
            if not contains_device_call(node.args[0]):
                continue
            if ctx.index.in_loop(node):
                ctx.report(
                    self.id, node,
                    f"{node.func.id}(<device computation>) inside a host "
                    "loop blocks once per iteration; compute the "
                    "reduction for all items in one jitted call and "
                    "transfer a single scalar")


class AsarrayOfTraced(Rule):
    id = "GL005"
    name = "asarray-of-traced"
    summary = ("np.asarray/np.array of a traced value inside "
               "jit-reachable code — materializes the array on host "
               "mid-trace (ConcretizationError at best)")

    def check(self, ctx: Context) -> None:
        for info, traced, node in _reachable_nodes(ctx):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if len(chain) != 2 or chain[0] not in ("np", "numpy") \
                    or chain[1] not in ("asarray", "array"):
                continue
            if node.args and _mentions_traced(
                    ctx.index, node.args[0], traced):
                ctx.report(
                    self.id, node,
                    f"np.{chain[1]}() of a traced value inside "
                    "jit-reachable code; use jnp.asarray (stays on "
                    "device) or move the conversion outside the "
                    "traced function")


RULES = [HostSyncInJit(), TracedTruthiness(), BlockUntilReadyInLoop(),
         ScalarPullInHostLoop(), AsarrayOfTraced()]
