"""graftlint rule registry: one module per rule family, each exporting
``RULES``; the catalog below is the linter's (and the docs') single
source of truth. IDs are stable — retired rules are never reused."""

from __future__ import annotations

from . import (concurrency, donation, dtype_rules, host_sync, recompile,
               telemetry_rules)

ALL_RULES = (host_sync.RULES + recompile.RULES + donation.RULES
             + dtype_rules.RULES + telemetry_rules.RULES
             + concurrency.RULES)

RULES_BY_ID = {r.id: r for r in ALL_RULES}

assert len(RULES_BY_ID) == len(ALL_RULES), "duplicate rule id"
