"""graftlint rule registry: one module per rule family, each exporting
``RULES``; the catalog below is the linter's (and the docs') single
source of truth. IDs are stable — retired rules are never reused.

``RULE_GROUPS`` names each family for the CLI's ``--select`` (e.g.
``--select spmd`` runs only the GL060-family SPMD pass in CI)."""

from __future__ import annotations

from . import (concurrency, donation, dtype_rules, host_sync, numerics,
               recompile, spmd, telemetry_rules)

ALL_RULES = (host_sync.RULES + recompile.RULES + donation.RULES
             + dtype_rules.RULES + telemetry_rules.RULES
             + concurrency.RULES + spmd.RULES + numerics.RULES)

RULES_BY_ID = {r.id: r for r in ALL_RULES}

RULE_GROUPS = {
    "host-sync": tuple(r.id for r in host_sync.RULES),
    "recompile": tuple(r.id for r in recompile.RULES),
    "donation": tuple(r.id for r in donation.RULES),
    "dtype": tuple(r.id for r in dtype_rules.RULES),
    "telemetry": tuple(r.id for r in telemetry_rules.RULES),
    "concurrency": tuple(r.id for r in concurrency.RULES),
    "spmd": tuple(r.id for r in spmd.RULES),
    "numerics": tuple(r.id for r in numerics.RULES),
}

# CLI spellings: ``graftlint --select NUM`` == ``--select numerics``
RULE_GROUP_ALIASES = {
    "num": "numerics",
}

assert len(RULES_BY_ID) == len(ALL_RULES), "duplicate rule id"
