"""meshsan — runtime mesh-traffic sanitizer (ISSUE 15 tentpole part 2).

The static SPMD rules (:mod:`.rules.spmd`) check what the *source*
says; this module checks what the *compiler actually emitted*. The
telemetry executable ledger (PR 5) already walks every registered
executable's optimized HLO and decodes each collective's payload bytes,
wire width and mesh axis from its ``replica_groups``
(:mod:`..telemetry.collectives`). :class:`MeshSanitizer` cross-checks
those records against a per-executable **declared traffic contract** —
which axes this jit is allowed to move bytes on, which axes may carry
all-to-all / collective-permute traffic, and what wire width an axis is
configured for — and turns three silent SPMD failure classes into
named findings carrying the executable name, axis, op and bytes:

- **undeclared-axis**: the executable moves bytes on a mesh axis its
  contract never mentions — a sharding-rule regression or an
  unintended GSPMD reshard routed traffic somewhere new;
- **unexpected-op**: ``all-to-all`` / ``collective-permute`` on an
  axis not declared to carry them — the "GSPMD silently resharded"
  signature (a spec mismatch between producer and consumer makes the
  partitioner insert a reshard exchange where none was designed);
- **wire-downgrade**: payload wider than the axis's configured wire
  (fp32 bytes on an axis the ZeRO++ config says runs int8) — the
  quantized wire silently failed to engage and every step pays 4x the
  bandwidth.

Contracts are seeded from the engine/serve-loop call sites (training:
mesh axes >1 plus the ZeRO++ wire flags; inference v2: the tp axis)
and annotatable via the ``meshsan`` config block. Checking happens once
per NEW executable at ledger-registration time (signature-deduped), so
the steady-state dispatch path pays one set lookup.

A per-collective **stall attributor** rides the same records: when the
hang watchdog fires, :meth:`MeshSanitizer.stall_attribution` joins the
flight recorder's last progress event against the registered
executables' collective content, so a wedged multichip run's dump
names the collectives (axis, op, bytes) the stalled dispatch was built
from — not just the host thread stacks
(see :func:`..telemetry.flightrec.dump_state`).

Like blocksan, this module is host-only and stdlib-only (the records
it checks are plain dicts), violations bump
``ds_meshsan_violations_total{kind}`` through the zero-import
telemetry probe, and nothing is imported when the config block is off.
"""

from __future__ import annotations

import os
import threading
from typing import Iterable, Optional

from .blocksan import _count_violation


class MeshSanError(RuntimeError):
    """A declared mesh-traffic contract was violated."""


# collectives.analyze_hlo attributes ops it cannot map to an axis
# combination as "n<group_size>"; those carry no axis NAME to check
def _unattributed(axis: str) -> bool:
    return len(axis) > 1 and axis[0] == "n" and axis[1:].isdigit()


class TrafficContract:
    """What one executable is allowed to put on the wire.

    ``axes``: mesh axes the executable may move bytes on (a combined
    label like ``"fsdp+zps"`` is allowed iff every component is).
    ``all_to_all_axes`` / ``permute_axes``: the subsets that may carry
    all-to-all / collective-permute traffic (a SUBSTANTIAL one showing
    up elsewhere is the GSPMD reshard signature).
    ``wire_bytes_per_el``: ``{axis: {op: max bytes/element}}`` for
    axes with a configured quantized wire (int8 payload + fp32 block
    scales lands ~1.03-1.5 B/el; 2.0 is a safe ceiling). Limits are
    PER OP CLASS because each ZeRO++ flag quantizes one traffic
    direction only: qgZ covers the gradient exchange (all_to_all, and
    the reduce_scatter/all_reduce shapes a disengaged qgZ degrades
    into) while the weight all_gather legitimately stays fp32 unless
    qwZ is also on — an axis-wide ceiling would fail correct
    single-flag configs on their full-precision direction.
    ``min_bytes`` gates the op-class and wire checks: GSPMD routinely
    inserts KILOBYTE-scale reshard shuffles (observed: a 3 KiB
    all-to-all in a plain ZeRO-2 step from a partitioner
    rematerialization) and tiny fp32 control reductions (loss means,
    found-inf flags) are not wire traffic — the findings meshsan hunts
    are the megabyte ones that eat a step's bandwidth. Undeclared-AXIS
    traffic is never size-gated: any byte on an axis the contract
    doesn't mention means the topology assumption itself broke.
    ``allow_world``: whether a full-mesh collective (axis label
    ``"world"``) is expected (training loss reductions are; a serving
    dispatch's usually is not — but mesh-unaware walks also label
    unattributed full-extent groups "world", so default True).
    """

    def __init__(self, axes: Iterable[str] = (),
                 all_to_all_axes: Iterable[str] = (),
                 permute_axes: Iterable[str] = (),
                 wire_bytes_per_el: Optional[dict] = None,
                 min_bytes: int = 65536,
                 allow_world: bool = True):
        self.axes = frozenset(axes)
        self.all_to_all_axes = frozenset(all_to_all_axes)
        self.permute_axes = frozenset(permute_axes)
        # {axis: {op: limit}}; a bare float value means "every op"
        self.wire_bytes_per_el = {
            axis: (dict(v) if isinstance(v, dict) else {"*": float(v)})
            for axis, v in (wire_bytes_per_el or {}).items()}
        self.min_bytes = int(min_bytes)
        self.allow_world = bool(allow_world)

    def _components(self, axis: str) -> list[str]:
        return axis.split("+")

    def axis_declared(self, axis: str) -> bool:
        if axis == "world":
            return self.allow_world
        return all(c in self.axes for c in self._components(axis))

    def op_declared(self, axis: str, op: str) -> bool:
        if op == "all_to_all":
            allowed = self.all_to_all_axes
        elif op == "ppermute":
            allowed = self.permute_axes
        else:
            return True
        return all(c in allowed for c in self._components(axis))

    def wire_limit(self, axis: str, op: str) -> Optional[float]:
        limits = []
        for c in self._components(axis):
            by_op = self.wire_bytes_per_el.get(c)
            if not by_op:
                continue
            lim = by_op.get(op, by_op.get("*"))
            if lim is not None:
                limits.append(float(lim))
        return max(limits) if limits else None

    def to_dict(self) -> dict:
        return {"axes": sorted(self.axes),
                "all_to_all_axes": sorted(self.all_to_all_axes),
                "permute_axes": sorted(self.permute_axes),
                "wire_bytes_per_el": dict(self.wire_bytes_per_el),
                "min_bytes": self.min_bytes,
                "allow_world": self.allow_world}


class MeshSanitizer:
    """See module docstring. One instance audits one engine's
    executables; register per-name contracts with :meth:`declare`, feed
    ledger entries through :meth:`observe_entry` (the engine choke
    points do), or hand synthetic record lists to
    :meth:`check_records` directly (tests, offline HLO audits)."""

    def __init__(self, mode: str = "raise"):
        if mode not in ("raise", "warn"):
            raise ValueError(
                f"meshsan mode must be raise|warn, got {mode!r}")
        self.mode = mode
        self._lock = threading.Lock()
        self.contracts: dict[str, TrafficContract] = {}
        # executables checked already: (name, signature) of each ledger
        # entry — observe_entry is called once per DISPATCH but checks
        # once per executable
        self._seen: set = set()
        # name -> merged per-instruction records, kept for hang-dump
        # stall attribution
        self.records_by_name: dict[str, list[dict]] = {}
        self.counters = {"checked_executables": 0, "violations": 0}
        self.violation_log: list[str] = []

    # -- contracts -----------------------------------------------------
    def declare(self, name: str, contract: TrafficContract) -> None:
        """Register the traffic contract for executables named
        ``name`` (the ledger/span name: ``compiled_step``,
        ``v2/dispatch``, ``v2/fused_dispatch``)."""
        with self._lock:
            self.contracts[name] = contract

    # -- checking ------------------------------------------------------
    def observe_entry(self, entry) -> list[str]:
        """Check one executable-ledger entry (``ExecutableEntry``:
        ``.name``, ``.signature``, ``.collectives``) against its
        contract. Deduped per (name, signature); executables with no
        declared contract are recorded for stall attribution but not
        checked."""
        if entry is None:
            return []
        key = (entry.name, getattr(entry, "signature", None))
        with self._lock:
            if key in self._seen:
                return []
            self._seen.add(key)
        return self.check_records(entry.name,
                                  list(getattr(entry, "collectives", [])))

    def check_records(self, name: str, records: list[dict]) -> list[str]:
        """Core check, synthetic-record friendly: each record is the
        :func:`..telemetry.collectives.analyze_hlo` dict shape
        (``op``, ``axis``, ``bytes``, optional ``wire_bytes_per_el``).
        Returns the finding messages (raised/warned per ``mode``)."""
        with self._lock:
            self.records_by_name.setdefault(name, []).extend(records)
            contract = self.contracts.get(name)
            if contract is not None:
                self.counters["checked_executables"] += 1
        if contract is None:
            return []
        msgs: list[str] = []
        for r in records:
            axis = str(r.get("axis", ""))
            op = str(r.get("op", "?"))
            nbytes = int(r.get("bytes", 0))
            if not axis or _unattributed(axis):
                continue        # no axis name to hold a contract against
            if not contract.axis_declared(axis):
                msgs.append(self._fail(
                    f"executable '{name}': {nbytes} B {op} on "
                    f"UNDECLARED axis '{axis}' (declared: "
                    f"{sorted(contract.axes)}) — a sharding change or "
                    "GSPMD reshard moved traffic onto an axis this "
                    "executable never declared", "undeclared-axis"))
                continue
            if nbytes >= contract.min_bytes \
                    and not contract.op_declared(axis, op):
                msgs.append(self._fail(
                    f"executable '{name}': unexpected {op} on axis "
                    f"'{axis}' ({nbytes} B) — the GSPMD "
                    "silent-reshard signature (a producer/consumer "
                    "spec mismatch makes the partitioner insert an "
                    "exchange no call site asked for)",
                    "unexpected-op"))
                continue
            limit = contract.wire_limit(axis, op)
            wpe = float(r.get("wire_bytes_per_el", 0.0) or 0.0)
            if limit is not None and nbytes >= contract.min_bytes \
                    and wpe > limit:
                msgs.append(self._fail(
                    f"executable '{name}': wire downgrade on axis "
                    f"'{axis}' — {nbytes} B {op} at "
                    f"{wpe:.2f} B/element exceeds the configured "
                    f"{limit:.2f} B/element (quantized wire did not "
                    "engage; every step pays the full-precision "
                    "bandwidth)", "wire-downgrade"))
        return msgs

    def _fail(self, msg: str, kind: str) -> str:
        with self._lock:
            self.counters["violations"] += 1
            self.violation_log.append(msg)
        _count_violation("ds_meshsan_violations_total", kind)
        if self.mode == "raise":
            raise MeshSanError(f"meshsan: {msg}")
        from ..utils.logging import logger
        logger.warning(f"meshsan: {msg}")
        return msg

    # -- stall attribution ---------------------------------------------
    # flight-recorder progress keys -> the executable whose dispatch
    # they heartbeat (v2_dispatch carries the span name in its meta)
    _PROGRESS_TO_EXEC = {"train_batch": "compiled_step"}

    def stall_attribution(self, events: list[dict],
                          top: int = 6) -> Optional[dict]:
        """Join the flight recorder's most recent dispatch heartbeat
        against the registered executables' collective content: the
        hang dump names the collectives (axis, op, bytes) the stalled
        dispatch was built from, which on a wedged multichip run is the
        set the program died inside. ``events`` is
        ``FlightRecorder.events()`` (slot-ordered); returns None when
        nothing attributable was recorded."""
        for ev in reversed(events or []):
            name = str(ev.get("name", ""))
            meta = ev.get("meta") or {}
            exec_name = (meta.get("span")
                         or self._PROGRESS_TO_EXEC.get(name)
                         or (name if name in self.records_by_name
                             else None))
            if exec_name is None or exec_name not in self.records_by_name:
                continue
            recs = self.records_by_name[exec_name]
            ranked = sorted(recs, key=lambda r: -int(r.get("bytes", 0)))
            return {
                "last_progress": name,
                "executable": exec_name,
                "n_collectives": len(recs),
                "collectives": [
                    {"axis": r.get("axis"), "op": r.get("op"),
                     "bytes": int(r.get("bytes", 0)),
                     "group_size": r.get("group_size")}
                    for r in ranked[:top]],
                "hint": ("the stalled dispatch contains these "
                         "collectives; on a multi-host hang, one of "
                         "them is the rendezvous some rank never "
                         "reached"),
            }
        return None

    # -- reporting -----------------------------------------------------
    def snapshot(self) -> dict:
        """Hang-dump / forensics view (telemetry/flightrec.py embeds
        this in every watchdog dump while meshsan is active)."""
        with self._lock:
            return {
                "mode": self.mode,
                "counters": dict(self.counters),
                "violations": list(self.violation_log[-16:]),
                "contracts": {n: c.to_dict()
                              for n, c in self.contracts.items()},
                "executables": {
                    n: len(recs)
                    for n, recs in self.records_by_name.items()},
            }


# --- contract seeding (engine / serve-loop call sites) --------------------


# the HLO op classes each ZeRO++ wire flag quantizes: qgZ's gradient
# exchange is an all-to-all (and a DISENGAGED qgZ degrades into the
# plain reduce_scatter/all_reduce it replaced — exactly the fp32 shape
# the ceiling must catch); qwZ covers the weight all-gather
_QGZ_WIRE_OPS = ("all_to_all", "reduce_scatter", "all_reduce")
_QWZ_WIRE_OPS = ("all_gather",)


def seed_training_contract(axis_sizes: dict,
                           quantized_gradients: bool = False,
                           quantized_weights: bool = False,
                           min_bytes: int = 65536,
                           moe_dispatch: bool = False,
                           moe_quantized_dispatch: bool = False
                           ) -> TrafficContract:
    """The compiled train step's contract, derived from the mesh
    topology and the ZeRO++ wire flags exactly as the engine configures
    them: bytes may move on every mesh axis with extent > 1; all-to-all
    is expected on ``sp`` (Ulysses) / ``ep`` (MoE dispatch) and — when
    qgZ is on — on the sharded-DP axes the quantized gradient exchange
    runs over (the hierarchical two-hop variant exchanges over fsdp and
    zps individually, both already in the set); collective-permute on
    ``pp`` (pipeline) and ``sp`` (ring attention). Sharded-DP axes
    carry a <= 2.0 B/element wire ceiling PER QUANTIZED DIRECTION
    (int8 payload + fp32 block scales is ~1.03-1.5): qgZ limits the
    gradient-exchange op class, qwZ the weight all-gather — the other
    direction legitimately stays fp32 when its flag is off.

    ``moe_dispatch`` (ISSUE 16): the engine's ep-sharded MoE dispatcher
    routes the token shuffle through an explicit capacity
    reduce-scatter/all-gather over the TOKEN axes (dp/fsdp/zps), which
    XLA is free to lower as all-to-all + local reduce — those axes join
    the expected-a2a set whenever the dispatcher is engaged, so a
    dispatch landing on any OTHER axis (a mis-sharded table) is still a
    named finding. No wire ceiling rides the MoE a2a op class even for
    an int8/fp8 wire (``moe_quantized_dispatch``): the combine leg and
    the dispatch transpose legitimately stay full-precision and lower
    to all-to-alls on the SAME (axis, op) buckets, so an aggregate
    ceiling there would flag correct programs — the int8 dispatch-byte
    claim is audited by the bench's per-op HLO accounting instead
    (bench.py moe_train, `--gate moe`)."""
    live = {a for a, n in (axis_sizes or {}).items() if int(n) > 1}
    a2a = {"sp", "ep"} & live
    if quantized_gradients:
        a2a |= {"fsdp", "zps"} & live
    if moe_dispatch or moe_quantized_dispatch:
        a2a |= {"dp", "fsdp", "zps"} & live
    wire_ops: dict[str, float] = {}
    if quantized_gradients:
        wire_ops.update({op: 2.0 for op in _QGZ_WIRE_OPS})
    if quantized_weights:
        wire_ops.update({op: 2.0 for op in _QWZ_WIRE_OPS})
    wire = ({a: dict(wire_ops) for a in ("fsdp", "zps") if a in live}
            if wire_ops else {})
    if (moe_dispatch or moe_quantized_dispatch) and wire:
        # qgZ's a2a ceiling cannot coexist with an engaged MoE
        # dispatcher: the full-precision combine/transpose legs of the
        # token shuffle share those (axis, op) buckets (see above)
        for by_op in wire.values():
            by_op.pop("all_to_all", None)
    return TrafficContract(
        axes=live,
        all_to_all_axes=a2a,
        permute_axes={"pp", "sp"} & live,
        wire_bytes_per_el=wire,
        min_bytes=min_bytes,
        allow_world=True)


def seed_serving_contract(tp: int = 1,
                          min_bytes: int = 65536) -> TrafficContract:
    """The inference v2 dispatch families' contract: a tp-sharded
    forward moves bytes on ``tp`` only (the output-projection
    all-reduce and kv-head gathers); an all-to-all or permute anywhere
    is the reshard signature, and any OTHER axis carrying traffic means
    the serving params/pools picked up a training-style sharding."""
    return TrafficContract(
        axes={"tp"} if int(tp) > 1 else set(),
        all_to_all_axes=(),
        permute_axes=(),
        min_bytes=min_bytes,
        allow_world=True)


# --- process-wide handle for forensics (hang dumps) -----------------------
# Engines register their sanitizer here so the hang watchdog can embed
# contract state + stall attribution without holding an engine
# reference; last-enabled wins (exact for one-engine processes).

_SAN: Optional[MeshSanitizer] = None


def get_meshsan() -> Optional[MeshSanitizer]:
    return _SAN


def set_meshsan(san: Optional[MeshSanitizer]) -> None:
    global _SAN
    _SAN = san


def env_enabled() -> bool:
    """The ``DS_MESHSAN=1`` env knob (conftest/CI opt-in), mirroring
    ``DS_GRAFTSAN``."""
    return os.environ.get("DS_MESHSAN", "") not in ("", "0")
