"""numsan — runtime numerics sanitizer (ISSUE 18 tentpole part 2).

The static rules (:mod:`.rules.numerics`, GL070-GL073) check what the
*source* says about accumulation/guard/rounding discipline; this module
checks what the *numbers actually did*. Until now the only runtime
numerics signal was one anonymous overflow bit
(``runtime/loss_scaler.py``): a blown-up step told you nothing about
which executable produced it, which PyTree leaf went non-finite, or
whether a quantized path was silently clipping long before the
overflow. :class:`NumericsSanitizer` promotes those forensics to named
findings:

- **nonfinite-grads**: the engine's train step folds per-leaf
  non-finite counts + max|g| into the same fused reduction that already
  computes the overflow bit (``_grad_stats``); a bad step raises/warns
  with the executable's ledger name (``compiled_step``) and the worst
  leaf's PyTree path — "which executable, which leaf, what kind of
  blow-up" instead of one bit.
- **nonfinite-logits / logits-range**: opt-in inference v2 dispatch
  probe — non-finite logits, or |logits| beyond a configured limit
  (the pre-NaN saturation signature of a mis-scaled KV cache).
- **nonfinite-kv-scale**: opt-in probe over the quantized KV pools'
  scale slabs.
- **saturation**: every quantize site (KV write, qgZ wire, MoE
  dispatch) reports its saturating-code fraction through
  :func:`report_saturation` (a trace-time-armed ``jax.debug.callback``
  at the site — see ``ops/pallas/quantization.saturation_probe``);
  the fraction lands on the ``ds_numsan_saturation_ratio{site}``
  gauge and a fraction above the configured ceiling is a finding —
  silent clipping becomes a named, site-labelled signal.

Findings raise (:class:`NumSanError`) or warn per ``mode`` and bump
``ds_numsan_violations_total{kind}`` through the zero-import telemetry
probe. Findings born inside ``jax.debug.callback`` (the saturation
probes) cannot raise usefully from the runtime's callback thread, so
they are DEFERRED: the callback records them and the next host
choke-point calls :meth:`drain` (engine ``train_batch``, the v2
dispatch path, the seeded-fault tests) which raises the first pending
finding in raise mode.

Like blocksan/meshsan this module is host-only and stdlib-only — the
probes that ride executables live at the call sites (engine,
``ops/pallas/quantization.py``), keyed off :func:`get_numsan` through
a ``sys.modules`` lookup so nothing here is imported while the config
block and ``DS_NUMSAN`` are off; the disabled path stays
byte-identical.
"""

from __future__ import annotations

import os
import threading
from typing import Iterable, Optional

from .blocksan import _count_violation

_LOG_CAP = 64


class NumSanError(RuntimeError):
    """A numerics contract was violated (non-finite values or
    saturation beyond the configured ceiling)."""


def _set_gauge(metric: str, help_: str, value: float, **labels) -> None:
    """Best-effort gauge through the zero-import telemetry probe."""
    try:
        from ..utils.telemetry_probe import active_telemetry
        tel = active_telemetry()
        reg = tel.get_registry() if tel is not None else None
        if reg is not None:
            reg.gauge(metric, help_).set(value, **labels)
    except Exception:
        pass


class NumericsSanitizer:
    """Named numerics findings with per-executable / per-leaf / per-site
    attribution. ``mode`` is raise|warn, mirroring the other
    sanitizers."""

    def __init__(self, mode: str = "raise",
                 saturation_ceiling: float = 0.05,
                 logits_limit: float = 1e4,
                 probe_interval: int = 16,
                 saturation_probe: bool = True):
        if mode not in ("raise", "warn"):
            raise ValueError(
                f"numsan mode must be raise|warn, got {mode!r}")
        self.mode = mode
        self.saturation_ceiling = float(saturation_ceiling)
        self.logits_limit = float(logits_limit)
        self.probe_interval = max(1, int(probe_interval))
        # armed at trace time by ops/pallas/quantization.saturation_probe
        self.saturation_probe = bool(saturation_probe)
        self._lock = threading.Lock()
        self.counters = {"checked_steps": 0, "saturation_reports": 0,
                         "violations": 0}
        self.violation_log: list[str] = []
        self.last_saturation: dict[str, float] = {}
        self.max_saturation: dict[str, float] = {}
        self._pending: list[str] = []

    # -- gradient attribution (engine train step) ----------------------
    def check_grad_stats(self, executable: str,
                         leaf_stats: Iterable[tuple],
                         loss_scale: Optional[float] = None) -> list[str]:
        """Check one step's per-leaf gradient stats. ``leaf_stats`` is
        an iterable of ``(path, nonfinite_count, max_abs)`` host
        numbers in PyTree-leaf order (the engine pairs the fused
        reduction's vectors with ``tree_leaves_with_path``). Returns
        finding messages; raises in raise mode."""
        with self._lock:
            self.counters["checked_steps"] += 1
        stats = [(str(p), int(n), float(m)) for p, n, m in leaf_stats]
        bad = [s for s in stats if s[1] > 0]
        if not bad:
            return []
        total = sum(s[1] for s in bad)
        worst = max(bad, key=lambda s: (s[1], s[2]))
        scale = (f", loss_scale={loss_scale:g}"
                 if loss_scale is not None else "")
        return [self._fail(
            f"executable '{executable}': {total} non-finite gradient "
            f"element(s) across {len(bad)}/{len(stats)} leaves — worst "
            f"leaf '{worst[0]}' ({worst[1]} non-finite, "
            f"max|g|={worst[2]:.3e}{scale}); the overflow bit now has "
            "a name: chase this leaf's producer, not the loss scaler",
            "nonfinite-grads")]

    def check_grad_vectors(self, executable: str, paths: list,
                           nonfinite: list, maxabs: list,
                           loss_scale: Optional[float] = None
                           ) -> list[str]:
        """Vector form of :meth:`check_grad_stats` — the engine hands
        the fused reduction's per-leaf count/max vectors straight
        through; the common all-finite step pays one sum, no zip."""
        if sum(int(n) for n in nonfinite) == 0:
            with self._lock:
                self.counters["checked_steps"] += 1
            return []
        return self.check_grad_stats(
            executable, zip(paths, nonfinite, maxabs),
            loss_scale=loss_scale)

    # -- inference probes ----------------------------------------------
    def check_logits(self, executable: str, nonfinite: int,
                     max_abs: float) -> list[str]:
        """Opt-in v2 dispatch logits-range probe."""
        with self._lock:
            self.counters["checked_steps"] += 1
        if int(nonfinite) > 0:
            return [self._fail(
                f"executable '{executable}': {int(nonfinite)} "
                "non-finite logit(s) in the dispatched batch",
                "nonfinite-logits")]
        if float(max_abs) > self.logits_limit:
            return [self._fail(
                f"executable '{executable}': max|logit|="
                f"{float(max_abs):.3e} exceeds the configured "
                f"limit {self.logits_limit:g} — the pre-NaN "
                "saturation signature (mis-scaled KV cache or "
                "unbounded residual growth)", "logits-range")]
        return []

    def check_kv_scales(self, executable: str, nonfinite: int,
                        max_scale: float) -> list[str]:
        """Opt-in probe over the quantized KV pools' scale slabs."""
        with self._lock:
            self.counters["checked_steps"] += 1
        if int(nonfinite) > 0:
            return [self._fail(
                f"executable '{executable}': {int(nonfinite)} "
                "non-finite KV quantization scale(s) in the pools — "
                "a non-finite activation was quantized into the cache "
                f"(max finite scale {float(max_scale):.3e})",
                "nonfinite-kv-scale")]
        return []

    # -- quantize-site saturation --------------------------------------
    def report_saturation(self, site: str, ratio: float) -> None:
        """Record one quantize site's saturating-code fraction (called
        from ``jax.debug.callback`` on the runtime's callback thread —
        findings are deferred to :meth:`drain`)."""
        ratio = float(ratio)
        with self._lock:
            self.counters["saturation_reports"] += 1
            self.last_saturation[site] = ratio
            if ratio > self.max_saturation.get(site, 0.0):
                self.max_saturation[site] = ratio
        _set_gauge("ds_numsan_saturation_ratio",
                   "fraction of quantized codes at the clip boundary, "
                   "per quantize site", ratio, site=site)
        if ratio > self.saturation_ceiling:
            self._fail(
                f"quantize site '{site}': saturating-code fraction "
                f"{ratio:.4f} exceeds the configured ceiling "
                f"{self.saturation_ceiling:g} — values are being "
                "silently clipped at the quantization boundary "
                "(shrink the block/vector scale granularity, widen "
                "the wire dtype, or clip upstream deliberately)",
                "saturation", defer=True)

    # -- finding plumbing ----------------------------------------------
    def _fail(self, msg: str, kind: str, defer: bool = False) -> str:
        with self._lock:
            self.counters["violations"] += 1
            self.violation_log.append(msg)
            del self.violation_log[:-_LOG_CAP]
        _count_violation("ds_numsan_violations_total", kind)
        if self.mode == "raise":
            if defer:
                with self._lock:
                    self._pending.append(msg)
                return msg
            raise NumSanError(f"numsan: {msg}")
        from ..utils.logging import logger
        logger.warning(f"numsan: {msg}")
        return msg

    def drain(self) -> None:
        """Raise the first deferred (in-graph callback) finding, if
        any. Host choke points call this once per dispatch; warn mode
        never defers, so this is a no-op there."""
        with self._lock:
            pending, self._pending = list(self._pending), []
        if pending and self.mode == "raise":
            raise NumSanError(f"numsan: {pending[0]}")

    # -- reporting -----------------------------------------------------
    def snapshot(self) -> dict:
        """Hang-dump / forensics view (telemetry/flightrec.py embeds
        this next to blocksan's and meshsan's sections)."""
        with self._lock:
            return {
                "mode": self.mode,
                "saturation_ceiling": self.saturation_ceiling,
                "counters": dict(self.counters),
                "violations": list(self.violation_log[-16:]),
                "pending": len(self._pending),
                "saturation": {s: round(r, 6)
                               for s, r in self.last_saturation.items()},
                "saturation_max": {
                    s: round(r, 6)
                    for s, r in self.max_saturation.items()},
            }


# --- process-wide handle (probes + hang dumps) ----------------------------
# Engines register their sanitizer here; the quantize-site probes and
# the hang watchdog read it back without holding an engine reference
# (last-enabled wins — exact for one-engine processes).

_SAN: Optional[NumericsSanitizer] = None


def get_numsan() -> Optional[NumericsSanitizer]:
    return _SAN


def set_numsan(san: Optional[NumericsSanitizer]) -> None:
    global _SAN
    _SAN = san


def env_enabled() -> bool:
    """The ``DS_NUMSAN=1`` env knob (conftest/CI opt-in), mirroring
    ``DS_GRAFTSAN``/``DS_MESHSAN``."""
    return os.environ.get("DS_NUMSAN", "") not in ("", "0")
