"""Runtime sentinels (ISSUE 3 tentpole part 2): enforcement the linter
cannot do statically, on the two hot paths that matter.

- :class:`RecompileSentinel` — asserts a warmed-up step never retraces.
  Piggybacks on the telemetry bridges' ``jax.monitoring`` compile
  listener (ISSUE 2): ``backend_compile`` fires once per executable
  built and never on an executable-cache hit, so "zero events inside
  the watch window" == "no recompile". The caller declares *expected*
  compiles (warmup, a new bucket shape, a curriculum seqlen change)
  via :meth:`expect`; an unexpected one raises :class:`RecompileError`
  (or warns, per ``mode``) naming the label — catching shape/dtype
  drift that would otherwise silently recompile every step.

- :func:`hot_path_guard` — ``jax.transfer_guard("disallow")`` scoped to
  a dispatch/drain region: implicit host<->device transfers (a Python
  scalar riding into an op, a hidden __array__ pull) raise immediately,
  while explicit ones (``jax.device_put``, the fused-decode token drain
  via ``np.asarray``/``jax.device_get``) stay legal. This is precisely
  the contract of the fused decode loop: K ticks per dispatch with the
  token ring buffer as the only host read.

Wired into ``engine.train_batch`` (the compiled-step dispatch) and the
v2 fused-decode dispatch/drain behind opt-in config
(``sentinels.enabled`` / ``RaggedInferenceEngineConfig.sentinels``) —
zero overhead when off. This module imports jax; the linter half of the
analysis package deliberately does not.
"""

from __future__ import annotations

import contextlib
from typing import Optional

from ..utils.logging import logger


class RecompileError(RuntimeError):
    """A warmed-up hot path compiled a new executable."""


def _compile_count() -> int:
    # the bridges listener keeps plain process-wide tallies even while
    # the telemetry registry is inactive — install once, read forever
    from ..telemetry import bridges  # graftlint: disable=GL040
    # (sentinels are opt-in runtime enforcement: enabling them is an
    # explicit request for the listener, unlike passive hot-path
    # instrumentation which must stay zero-import)
    bridges.install_jax_compile_listener()
    return bridges.compile_event_count("backend_compile")


def install() -> None:
    """Install the shared compile listener now (idempotent). Calling it
    before warmup keeps the first watch window honest."""
    _compile_count()


class RecompileSentinel:
    """Watches a labelled hot path for unexpected executable builds.

    Usage::

        s = RecompileSentinel("train_batch", mode="raise", warmup_calls=1)
        with s.watch():            # call 1: warmup, compiles allowed
            step(state, batch)
        with s.watch():            # steady state: a compile here raises
            step(state, batch)
        s.expect("curriculum seqlen changed")
        with s.watch():            # declared: allowed once
            step(state, batch)
    """

    def __init__(self, label: str, mode: str = "raise",
                 warmup_calls: int = 1):
        if mode not in ("raise", "warn"):
            raise ValueError(f"sentinel mode must be raise|warn, got {mode!r}")
        self.label = label
        self.mode = mode
        self.warmup_calls = int(warmup_calls)
        self.calls = 0
        self.violations = 0
        self.compiles_seen = 0
        self._expected: Optional[str] = None
        install()

    def expect(self, reason: str = "expected") -> None:
        """Declare that the next watched window may compile (new bucket
        shape, rebuilt jit, fallback path). Consumed by one window."""
        self._expected = reason

    @contextlib.contextmanager
    def watch(self):
        before = _compile_count()
        try:
            yield
        finally:
            delta = _compile_count() - before
            self.calls += 1
            self.compiles_seen += delta
            expected, self._expected = self._expected, None
            if delta and expected is None \
                    and self.calls > self.warmup_calls:
                self.violations += 1
                msg = (f"recompile sentinel [{self.label}]: "
                       f"{delta} executable build(s) on call "
                       f"{self.calls} after warmup "
                       f"({self.warmup_calls}) — shape/dtype drift is "
                       "recompiling a warmed-up hot path")
                if self.mode == "raise":
                    raise RecompileError(msg)
                logger.warning(msg)


def hot_path_guard(enabled: bool = True):
    """``jax.transfer_guard("disallow")`` as a reusable scope: implicit
    transfers raise, explicit ones pass. No-op when ``enabled`` is
    false so call sites don't branch."""
    if not enabled:
        return contextlib.nullcontext()
    import jax
    return jax.transfer_guard("disallow")
