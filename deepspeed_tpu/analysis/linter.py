"""graftlint driver: walk files, run rules, diff against a baseline.

Two passes over the file set:

1. collect every function name handed to a trace wrapper anywhere
   (``jax.jit``/``grad``/``lax.scan``/... — including through
   ``functools.partial`` and method references), because this codebase
   jits across module boundaries (engine_v2 jits paged.fused_decode_loop;
   the engines jit model loss methods);
2. lint each file with that global traced-name set seeding its
   jit-reachability analysis.

The gate is "no NEW violations": findings are matched against the
baseline by (rule, path, source-line text) — not line numbers — so
unrelated edits never trip it, while a pre-existing violation that gets
*duplicated* does (counts are compared per key).

This module imports only the stdlib — no jax — so the CLI and the
tier-1 gate run in milliseconds.
"""

from __future__ import annotations

import json
import os
from collections import Counter
from dataclasses import dataclass, field
from typing import Iterable, Optional, Sequence

from .core import (Context, Finding, ModuleIndex,
                   collect_axis_declarations, collect_domain_exports,
                   collect_traced_names)
from .rules import ALL_RULES, RULES_BY_ID

BASELINE_DEFAULT = ".graftlint-baseline.json"
BASELINE_VERSION = 1

# directories never linted when walking a package tree
_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "build", "dist"}


@dataclass
class LintResult:
    findings: list[Finding] = field(default_factory=list)
    new: list[Finding] = field(default_factory=list)
    errors: list[Finding] = field(default_factory=list)   # parse failures
    files: int = 0

    @property
    def ok(self) -> bool:
        return not self.new and not self.errors

    def to_dict(self) -> dict:
        return {
            "version": BASELINE_VERSION,
            "files": self.files,
            "findings": [f.to_dict() for f in self.findings],
            "new": [f.to_dict() for f in self.new],
            "errors": [f.to_dict() for f in self.errors],
        }


def iter_python_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def _relpath(path: str, root: Optional[str]) -> str:
    if root:
        try:
            return os.path.relpath(path, root).replace(os.sep, "/")
        except ValueError:
            pass
    return path.replace(os.sep, "/")


def lint_paths(paths: Sequence[str], *,
               rules: Optional[Sequence[str]] = None,
               disable: Sequence[str] = (),
               root: Optional[str] = None) -> LintResult:
    """Lint files/trees. ``rules`` restricts to those ids; ``disable``
    removes ids; ``root`` makes finding paths relative (baselines should
    be repo-root-relative so they survive checkouts)."""
    active = list(ALL_RULES)
    if rules is not None:
        unknown = [r for r in rules if r not in RULES_BY_ID]
        if unknown:
            raise ValueError(f"unknown rule id(s): {unknown}")
        active = [RULES_BY_ID[r] for r in rules]
    active = [r for r in active if r.id not in set(disable)]

    files = list(iter_python_files(paths))
    result = LintResult(files=len(files))

    # pass 1: global traced-name registry + cross-module thread-domain
    # exports (ISSUE 11: one propagation hop — names called from
    # annotated/async functions carry the caller's domain package-wide)
    # + the mesh-axis vocabulary (ISSUE 15: axis names declared
    # ANYWHERE in the run — parallel/mesh.py's AXIS_ORDER validates a
    # literal axis string used in any other module)
    sources: dict[str, str] = {}
    traced_names: set[str] = set()
    domain_exports: dict[str, set] = {}
    axis_vocab: set[str] = set()
    for path in files:
        try:
            with open(path, encoding="utf-8") as f:
                sources[path] = f.read()
        except OSError as e:
            result.errors.append(Finding(
                rule="GL000", path=_relpath(path, root), line=0, col=0,
                message=f"unreadable: {e}"))
            continue
        try:
            import ast
            tree = ast.parse(sources[path])
            traced_names |= collect_traced_names(tree)
            for name, doms in collect_domain_exports(
                    tree, sources[path]).items():
                domain_exports.setdefault(name, set()).update(doms)
            axis_vocab |= collect_axis_declarations(tree, sources[path])
        except SyntaxError:
            pass    # reported in pass 2

    # pass 2: per-file rules
    for path in files:
        if path not in sources:
            continue
        rel = _relpath(path, root)
        try:
            index = ModuleIndex(rel, sources[path],
                                external_traced_names=traced_names,
                                external_domains=domain_exports,
                                axis_vocab=axis_vocab)
        except SyntaxError as e:
            result.errors.append(Finding(
                rule="GL000", path=rel, line=e.lineno or 0, col=0,
                message=f"syntax error: {e.msg}"))
            continue
        ctx = Context(index, rel)
        for rule in active:
            rule.check(ctx)
        result.findings.extend(ctx.findings)

    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return result


def traced_roots(paths: Sequence[str], *,
                 root: Optional[str] = None) -> list[dict]:
    """Host-only-package audit (ISSUE 7 satellite): every function in
    the given files/trees that is jit-REACHABLE from tracing inside
    those same files — ``[{path, name, line}]``, empty when the code is
    pure host. Planner/cost-model packages (``autotuning/``) must stay
    empty: a planner that traces its own scoring code would bake
    wall-clock-dependent host state into an executable and break the
    deterministic-ranking contract (see docs/static-analysis.md,
    GL041 catalog notes). The traced-name registry is built over the
    AUDITED file set only (a jit in module A of functions defined in
    sibling module B counts) — unlike :func:`lint_paths`'s repo-wide
    pass, names jitted *elsewhere* in the repo are not violations of
    this package's contract, only tracing the package does itself."""
    import ast
    sources: dict[str, str] = {}
    traced_names: set[str] = set()
    for path in iter_python_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                sources[path] = f.read()
        except OSError:
            continue
        try:
            traced_names |= collect_traced_names(
                ast.parse(sources[path]))
        except SyntaxError:
            continue
    out: list[dict] = []
    for path, source in sources.items():
        try:
            index = ModuleIndex(_relpath(path, root), source,
                                external_traced_names=traced_names)
        except SyntaxError:
            continue
        for info in index.reachable_functions():
            out.append({"path": index.path, "name": info.name,
                        "line": getattr(info.node, "lineno", 0)})
    out.sort(key=lambda r: (r["path"], r["line"]))
    return out


# --------------------------------------------------------------------
# baseline
# --------------------------------------------------------------------


def load_baseline(path: str) -> list[Finding]:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: unsupported version {data.get('version')!r}")
    return [Finding.from_dict(d) for d in data.get("findings", [])]


def save_baseline(path: str, findings: Sequence[Finding]) -> None:
    payload = {
        "version": BASELINE_VERSION,
        "comment": ("graftlint accepted-violations baseline; regenerate "
                    "with `python tools/graftlint.py <paths> "
                    "--write-baseline` (see docs/static-analysis.md)"),
        "findings": [f.to_dict() for f in findings],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")


def diff_against_baseline(findings: Sequence[Finding],
                          baseline: Sequence[Finding]) -> list[Finding]:
    """Findings not covered by the baseline. Matched on
    (rule, path, line text) with multiplicity: two identical violations
    against a baseline holding one leaves one NEW."""
    budget = Counter(f.key for f in baseline)
    new: list[Finding] = []
    for f in findings:
        if budget[f.key] > 0:
            budget[f.key] -= 1
        else:
            new.append(f)
    return new


def apply_baseline(result: LintResult, baseline_path: Optional[str]) -> None:
    """Populate ``result.new`` (all findings are new when no baseline)."""
    if baseline_path and os.path.exists(baseline_path):
        base = load_baseline(baseline_path)
        result.new = diff_against_baseline(result.findings, base)
    else:
        result.new = list(result.findings)


# --------------------------------------------------------------------
# formatting
# --------------------------------------------------------------------


def format_text(result: LintResult, *, baseline_used: bool) -> str:
    out: list[str] = []
    for f in result.errors:
        out.append(f"{f.path}:{f.line}: {f.rule} {f.message}")
    marked = {id(f) for f in result.new}
    for f in result.findings:
        tag = "" if id(f) in marked else " [baseline]"
        out.append(f"{f.path}:{f.line}:{f.col}: {f.rule}{tag} {f.message}")
        if f.text:
            out.append(f"    {f.text}")
    n_base = len(result.findings) - len(result.new)
    summary = (f"graftlint: {result.files} files, "
               f"{len(result.findings)} finding(s)")
    if baseline_used:
        summary += f" ({n_base} baselined, {len(result.new)} new)"
    if result.errors:
        summary += f", {len(result.errors)} file error(s)"
    out.append(summary)
    return "\n".join(out)
