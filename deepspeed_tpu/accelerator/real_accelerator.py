"""Accelerator auto-detection (reference: accelerator/real_accelerator.py:51).

Resolution order mirrors the reference: explicit ``set_accelerator()`` >
``DS_ACCELERATOR`` env var (:59) > probe for an attached TPU > CPU.
"""

from __future__ import annotations

import os
from typing import Optional

from .abstract_accelerator import DeepSpeedAccelerator

SUPPORTED_ACCELERATOR_LIST = ("tpu", "cpu")

_accelerator: Optional[DeepSpeedAccelerator] = None


def _make(name: str) -> DeepSpeedAccelerator:
    if name == "tpu":
        from .tpu_accelerator import TPU_Accelerator
        return TPU_Accelerator()
    if name == "cpu":
        from .cpu_accelerator import CPU_Accelerator
        return CPU_Accelerator()
    raise ValueError(
        f"DS_ACCELERATOR={name!r} not in {SUPPORTED_ACCELERATOR_LIST}")


def get_accelerator() -> DeepSpeedAccelerator:
    global _accelerator
    if _accelerator is not None:
        return _accelerator

    name = os.environ.get("DS_ACCELERATOR")
    if name is not None:
        _accelerator = _make(name)
        return _accelerator

    from .tpu_accelerator import TPU_Accelerator
    tpu = TPU_Accelerator()
    if tpu.is_available():
        _accelerator = tpu
    else:
        from .cpu_accelerator import CPU_Accelerator
        _accelerator = CPU_Accelerator()
    return _accelerator


def set_accelerator(accel: DeepSpeedAccelerator) -> None:
    global _accelerator
    _accelerator = accel


def is_current_accelerator_supported() -> bool:
    return get_accelerator()._name in SUPPORTED_ACCELERATOR_LIST
