"""CPU accelerator implementation (reference parallel:
accelerator/cpu_accelerator.py). Used by the test suite's virtual 8-device
mesh and as the fallback when no TPU is attached."""

from __future__ import annotations

from typing import Any, Optional

import jax

from .abstract_accelerator import DeepSpeedAccelerator


class CPU_Accelerator(DeepSpeedAccelerator):

    def __init__(self):
        super().__init__()
        self._name = "cpu"
        self.communication_backend = "xla"

    def _devices(self):
        return [d for d in jax.local_devices() if d.platform == "cpu"]

    def is_available(self) -> bool:
        return True

    def device_name(self, device_index: Optional[int] = None) -> str:
        if device_index is None:
            return "cpu"
        return f"cpu:{device_index}"

    def device(self, device_index: Optional[int] = None) -> Any:
        return self._devices()[device_index or 0]

    def device_count(self) -> int:
        return len(self._devices())

    def global_device_count(self) -> int:
        return len([d for d in jax.devices() if d.platform == "cpu"])

    def synchronize(self, device_index: Optional[int] = None) -> None:
        pass

    def memory_stats(self, device_index: Optional[int] = None) -> dict:
        try:
            import resource
            peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        except Exception:
            peak = 0
        try:
            import psutil
            vm = psutil.virtual_memory()
            return {"bytes_in_use": vm.used, "peak_bytes_in_use": peak,
                    "bytes_limit": vm.total}
        except Exception:
            return {"bytes_in_use": 0, "peak_bytes_in_use": peak,
                    "bytes_limit": 0}

    def peak_flops(self, dtype: Any = None, device_index: Optional[int] = None) -> float:
        return 1e12  # arbitrary floor, matches bench.py's CPU smoke value

    def pin_memory(self, array, align_bytes: int = 1):
        return array  # host memory is host memory
