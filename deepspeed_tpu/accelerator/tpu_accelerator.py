"""TPU accelerator implementation (reference parallel:
accelerator/cuda_accelerator.py — the "real device" backend)."""

from __future__ import annotations

from typing import Any, Optional

import jax

from .abstract_accelerator import DeepSpeedAccelerator

# Peak dense bf16 FLOPS per chip by device-kind prefix. Sources: public TPU
# spec sheets (same numbers bench.py uses for MFU accounting).
_PEAK_FLOPS_BF16 = (
    ("TPU v6 lite", 918e12),   # Trillium
    ("TPU v5 lite", 197e12),   # v5e
    ("TPU v5", 459e12),        # v5p
    ("TPU v4 lite", 138e12),
    ("TPU v4", 275e12),
    ("TPU v3", 123e12),
    ("TPU v2", 45e12),
)


class TPU_Accelerator(DeepSpeedAccelerator):

    def __init__(self):
        super().__init__()
        self._name = "tpu"
        # Collectives are XLA-emitted over ICI/DCN; there is no NCCL-style
        # user-visible backend. The name is informational (comm facade).
        self.communication_backend = "xla"

    def _devices(self):
        return [d for d in jax.local_devices() if d.platform == "tpu"]

    def is_available(self) -> bool:
        try:
            return len(self._devices()) > 0
        except RuntimeError:
            return False

    def device_name(self, device_index: Optional[int] = None) -> str:
        if device_index is None:
            return "tpu"
        return f"tpu:{device_index}"

    def device(self, device_index: Optional[int] = None) -> Any:
        return self._devices()[device_index or 0]

    def device_count(self) -> int:
        return len(self._devices())

    def global_device_count(self) -> int:
        return len([d for d in jax.devices() if d.platform == "tpu"])

    def synchronize(self, device_index: Optional[int] = None) -> None:
        # Drain the async dispatch queue on every local device. This IS
        # the synchronization primitive: the per-device sync is its
        # contract, not an accident.
        for d in self._devices():
            try:
                jax.block_until_ready(   # graftlint: disable=GL003
                    jax.device_put(0, d))
            except Exception:
                pass

    def memory_stats(self, device_index: Optional[int] = None) -> dict:
        try:
            return dict(self.device(device_index).memory_stats() or {})
        except Exception:
            return {}

    def peak_flops(self, dtype: Any = None, device_index: Optional[int] = None) -> float:
        kind = getattr(self.device(device_index), "device_kind", "")
        for prefix, flops in _PEAK_FLOPS_BF16:
            if kind.startswith(prefix):
                import jax.numpy as jnp
                if dtype == jnp.float32:
                    return flops / 2  # MXU fp32 runs at half bf16 rate
                return flops
        return 1e12
