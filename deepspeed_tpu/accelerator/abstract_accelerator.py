"""Accelerator abstraction (reference: accelerator/abstract_accelerator.py:10).

The reference defines a ~75-method ABC because eager torch needs explicit
streams, events, allocator stats, and per-vendor op builders. Under JAX the
runtime already virtualises devices, and XLA owns scheduling — so the TPU
ABC keeps the *queryable* surface (device identity/count, memory stats,
RNG, dtype support, op-builder dispatch, synchronization) and drops the
stream/event machinery that has no XLA analogue (graph execution replaces
hand-scheduled streams; see SURVEY §7 "XLA semantics").

Every subsystem that needs a device fact goes through ``get_accelerator()``
just like the reference, which is what makes the test suite run unmodified
on the CPU backend (reference parallel: tests are accelerator-portable by
construction, SURVEY §4).
"""

from __future__ import annotations

import abc
from typing import Any, Optional


class DeepSpeedAccelerator(abc.ABC):
    """Queryable device facts + op dispatch for one platform."""

    def __init__(self):
        self._name: str = ""
        self.communication_backend: str = ""

    # --- device identity --------------------------------------------------
    @abc.abstractmethod
    def device_name(self, device_index: Optional[int] = None) -> str:
        ...

    @abc.abstractmethod
    def device(self, device_index: Optional[int] = None) -> Any:
        """The jax.Device for local index ``device_index`` (default 0)."""
        ...

    @abc.abstractmethod
    def device_count(self) -> int:
        """Local (this-process) device count."""
        ...

    @abc.abstractmethod
    def global_device_count(self) -> int:
        ...

    def current_device(self) -> int:
        return 0

    def current_device_name(self) -> str:
        return self.device_name(0)

    @abc.abstractmethod
    def is_available(self) -> bool:
        ...

    def communication_backend_name(self) -> str:
        return self.communication_backend

    # --- execution --------------------------------------------------------
    @abc.abstractmethod
    def synchronize(self, device_index: Optional[int] = None) -> None:
        """Block until all queued work on the device is complete (the
        reference's stream synchronize; here: drain the XLA async queue)."""
        ...

    # --- RNG (reference: ABC RNG APIs; JAX RNG is explicit keys) ----------
    def manual_seed(self, seed: int):
        import jax
        return jax.random.PRNGKey(seed)

    def initial_seed(self) -> int:
        return 0

    # --- memory -----------------------------------------------------------
    @abc.abstractmethod
    def memory_stats(self, device_index: Optional[int] = None) -> dict:
        ...

    def memory_allocated(self, device_index: Optional[int] = None) -> int:
        return int(self.memory_stats(device_index).get("bytes_in_use", 0))

    def max_memory_allocated(self, device_index: Optional[int] = None) -> int:
        return int(self.memory_stats(device_index).get("peak_bytes_in_use", 0))

    def total_memory(self, device_index: Optional[int] = None) -> int:
        return int(self.memory_stats(device_index).get("bytes_limit", 0))

    def available_memory(self, device_index: Optional[int] = None) -> int:
        stats = self.memory_stats(device_index)
        return int(stats.get("bytes_limit", 0)) - int(stats.get("bytes_in_use", 0))

    def empty_cache(self) -> None:
        pass

    def reset_peak_memory_stats(self, device_index: Optional[int] = None) -> None:
        pass

    # --- dtype support (reference: is_bf16_supported etc.) ----------------
    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        return True

    def supported_dtypes(self) -> list:
        import jax.numpy as jnp
        out = [jnp.float32]
        if self.is_fp16_supported():
            out.append(jnp.float16)
        if self.is_bf16_supported():
            out.append(jnp.bfloat16)
        return out

    def preferred_dtype(self):
        """bf16 on TPU (MXU-native), fp32 fallback — the analogue of the
        reference test helper preferred_dtype() (tests/unit/common.py:503)."""
        import jax.numpy as jnp
        return jnp.bfloat16 if self.is_bf16_supported() else jnp.float32

    # --- peak FLOPS (TPU addition: MFU accounting needs it) ---------------
    @abc.abstractmethod
    def peak_flops(self, dtype: Any = None, device_index: Optional[int] = None) -> float:
        ...

    # --- profiler ranges (reference: range_push/pop → nvtx) ---------------
    def range_push(self, msg: str):
        import jax
        tc = jax.profiler.TraceAnnotation(msg)
        tc.__enter__()
        self._ranges = getattr(self, "_ranges", [])
        self._ranges.append(tc)

    def range_pop(self):
        ranges = getattr(self, "_ranges", [])
        if ranges:
            ranges.pop().__exit__(None, None, None)

    # --- op builder dispatch (reference: op_builder_dir selection) --------
    def create_op_builder(self, class_name: str):
        builder = self.get_op_builder(class_name)
        return builder() if builder is not None else None

    def get_op_builder(self, class_name: str):
        from ..ops import op_builder
        return getattr(op_builder, class_name, None)

    # --- host pinned memory ------------------------------------------------
    def pin_memory(self, array, align_bytes: int = 1):
        """Place a host array into the pinned_host memory space so device
        DMA doesn't bounce through pageable memory (reference: torch
        .pin_memory(); here: jax memory_kind transfer)."""
        import jax
        try:
            sharding = jax.sharding.SingleDeviceSharding(
                self.device(), memory_kind="pinned_host")
            return jax.device_put(array, sharding)
        except Exception:
            return array

    def is_pinned(self, array) -> bool:
        try:
            return array.sharding.memory_kind == "pinned_host"
        except AttributeError:
            return False
