from .real_accelerator import get_accelerator, set_accelerator, is_current_accelerator_supported  # noqa: F401
from .abstract_accelerator import DeepSpeedAccelerator  # noqa: F401
from .tpu_accelerator import TPU_Accelerator  # noqa: F401
from .cpu_accelerator import CPU_Accelerator  # noqa: F401
