// Host-side vectorized optimizers for offloaded optimizer states
// (reference: csrc/adam/cpu_adam_impl.cpp, csrc/adagrad/cpu_adagrad.cpp,
// csrc/lion/cpu_lion_impl.cpp, csrc/lamb/ — AVX-vectorized, OMP-parallel
// steps over host-resident master params/moments; the compute engine of
// ZeRO-Offload's CPU optimizer path).
//
// TPU build: plain C ABI over contiguous float buffers (loaded via ctypes,
// no pybind11). SIMD comes from `#pragma omp simd` + -O3 -march=native,
// parallelism from OMP — same performance recipe as the reference without
// hand-written intrinsics (the compiler emits AVX2/AVX512 on x86 hosts).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <algorithm>

#if defined(_OPENMP)
#include <omp.h>
#endif

extern "C" {

// Adam / AdamW (reference: cpu_adam_impl.cpp Adam_Optimizer::Step).
// adamw_mode=1 decouples weight decay (AdamW); bias correction always on.
void ds_cpu_adam_step(float* params,
                      const float* grads,
                      float* exp_avg,
                      float* exp_avg_sq,
                      int64_t n,
                      float lr,
                      float beta1,
                      float beta2,
                      float eps,
                      float weight_decay,
                      int step,
                      int adamw_mode) {
    const float bc1 = 1.0f - std::pow(beta1, (float)step);
    const float bc2 = 1.0f - std::pow(beta2, (float)step);
    const float step_size = lr / bc1;
    const float sqrt_bc2 = std::sqrt(bc2);
    const float decay = (adamw_mode && weight_decay > 0.0f)
                            ? (1.0f - lr * weight_decay)
                            : 1.0f;
    const float l2 = (!adamw_mode && weight_decay > 0.0f) ? weight_decay : 0.0f;

#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i] + l2 * params[i];
        float m = beta1 * exp_avg[i] + (1.0f - beta1) * g;
        float v = beta2 * exp_avg_sq[i] + (1.0f - beta2) * g * g;
        exp_avg[i] = m;
        exp_avg_sq[i] = v;
        float denom = std::sqrt(v) / sqrt_bc2 + eps;
        params[i] = params[i] * decay - step_size * (m / denom);
    }
}

// Adagrad (reference: csrc/adagrad/cpu_adagrad.cpp).
void ds_cpu_adagrad_step(float* params,
                         const float* grads,
                         float* accum,
                         int64_t n,
                         float lr,
                         float eps,
                         float weight_decay) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i] + weight_decay * params[i];
        float a = accum[i] + g * g;
        accum[i] = a;
        params[i] -= lr * g / (std::sqrt(a) + eps);
    }
}

// Lion (reference: csrc/lion/cpu_lion_impl.cpp).
void ds_cpu_lion_step(float* params,
                      const float* grads,
                      float* exp_avg,
                      int64_t n,
                      float lr,
                      float beta1,
                      float beta2,
                      float weight_decay) {
    const float decay = 1.0f - lr * weight_decay;
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i];
        float m = exp_avg[i];
        float c = beta1 * m + (1.0f - beta1) * g;
        float upd = (c > 0.0f) ? 1.0f : ((c < 0.0f) ? -1.0f : 0.0f);
        params[i] = params[i] * decay - lr * upd;
        exp_avg[i] = beta2 * m + (1.0f - beta2) * g;
    }
}

// LAMB phase 1: Adam-style update direction + squared norms
// (reference: csrc/lamb/fused_lamb_cuda_kernel.cu two-phase reduction).
// Writes the raw update into `update_out`; returns norms via out params.
void ds_cpu_lamb_phase1(const float* params,
                        const float* grads,
                        float* exp_avg,
                        float* exp_avg_sq,
                        float* update_out,
                        int64_t n,
                        float beta1,
                        float beta2,
                        float eps,
                        float weight_decay,
                        int step,
                        float* param_norm_sq,
                        float* update_norm_sq) {
    const float bc1 = 1.0f - std::pow(beta1, (float)step);
    const float bc2 = 1.0f - std::pow(beta2, (float)step);
    const float sqrt_bc2 = std::sqrt(bc2);
    double pn = 0.0, un = 0.0;
#pragma omp parallel for schedule(static) reduction(+ : pn, un)
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i];
        float m = beta1 * exp_avg[i] + (1.0f - beta1) * g;
        float v = beta2 * exp_avg_sq[i] + (1.0f - beta2) * g * g;
        exp_avg[i] = m;
        exp_avg_sq[i] = v;
        float u = (m / bc1) / (std::sqrt(v) / sqrt_bc2 + eps)
                  + weight_decay * params[i];
        update_out[i] = u;
        pn += (double)params[i] * params[i];
        un += (double)u * u;
    }
    *param_norm_sq = (float)pn;
    *update_norm_sq = (float)un;
}

// LAMB phase 2: apply trust-ratio-scaled update.
void ds_cpu_lamb_phase2(float* params,
                        const float* update,
                        int64_t n,
                        float lr,
                        float trust_ratio) {
    const float s = lr * trust_ratio;
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        params[i] -= s * update[i];
    }
}

// Momentum SGD on host (completes the offload-optimizer family).
void ds_cpu_sgd_step(float* params,
                     const float* grads,
                     float* momentum_buf,
                     int64_t n,
                     float lr,
                     float momentum,
                     float weight_decay) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i] + weight_decay * params[i];
        float m = momentum * momentum_buf[i] + g;
        momentum_buf[i] = m;
        params[i] -= lr * m;
    }
}

int ds_cpu_optimizer_num_threads() {
#if defined(_OPENMP)
    return omp_get_max_threads();
#else
    return 1;
#endif
}

}  // extern "C"
