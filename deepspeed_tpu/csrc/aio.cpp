// Async tensor I/O for NVMe offload (reference: csrc/aio/ — DeepNVMe.
// deepspeed_py_aio_handle.cpp exposes an `aio_handle` with async
// pread/pwrite of pinned buffers against NVMe files, backed by a thread
// pool + libaio io_submit; used by runtime/swap_tensor/*).
//
// TPU build: C ABI handle (ctypes-loaded) with the same operation set —
// async pread/pwrite, blocked into `block_size` chunks spread over
// `num_threads` workers, plus a synchronous path. Uses plain
// pread/pwrite syscalls (portable; O_DIRECT is an open flag away and the
// thread pool already gives queue-depth parallelism an io_uring backend
// would).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <unistd.h>

namespace {

struct Task {
    std::function<void()> fn;
};

class AioHandle {
   public:
    AioHandle(int64_t block_size, int num_threads)
        : block_size_(block_size > 0 ? block_size : (1 << 20)),
          stop_(false),
          pending_(0),
          errors_(0) {
        int n = num_threads > 0 ? num_threads : 1;
        for (int i = 0; i < n; ++i) {
            workers_.emplace_back([this] { this->worker(); });
        }
    }

    ~AioHandle() {
        {
            std::unique_lock<std::mutex> lk(mu_);
            stop_ = true;
        }
        cv_.notify_all();
        for (auto& t : workers_) t.join();
    }

    // Split [buf, buf+n) into block-sized chunks; each chunk is one task.
    void submit_io(const std::string& path, char* buf, int64_t n,
                   int64_t file_offset, bool is_read, bool create) {
        int flags = is_read ? O_RDONLY : (O_WRONLY | (create ? O_CREAT : 0));
        for (int64_t off = 0; off < n; off += block_size_) {
            int64_t len = std::min(block_size_, n - off);
            char* p = buf + off;
            int64_t foff = file_offset + off;
            enqueue([this, path, p, len, foff, flags, is_read] {
                int fd = ::open(path.c_str(), flags, 0644);
                if (fd < 0) {
                    errors_.fetch_add(1);
                    return;
                }
                int64_t done = 0;
                while (done < len) {
                    ssize_t r = is_read
                                    ? ::pread(fd, p + done, len - done,
                                              foff + done)
                                    : ::pwrite(fd, p + done, len - done,
                                               foff + done);
                    if (r <= 0) {
                        errors_.fetch_add(1);
                        break;
                    }
                    done += r;
                }
                ::close(fd);
            });
        }
    }

    // Block until every queued op completes; returns -errors.
    int synchronize() {
        std::unique_lock<std::mutex> lk(mu_);
        done_cv_.wait(lk, [this] { return pending_ == 0; });
        return -(int)errors_.exchange(0);
    }

    int64_t block_size() const { return block_size_; }
    int num_threads() const { return (int)workers_.size(); }

   private:
    void enqueue(std::function<void()> fn) {
        {
            std::unique_lock<std::mutex> lk(mu_);
            tasks_.push_back({std::move(fn)});
            ++pending_;
        }
        cv_.notify_one();
    }

    void worker() {
        for (;;) {
            Task t;
            {
                std::unique_lock<std::mutex> lk(mu_);
                cv_.wait(lk, [this] { return stop_ || !tasks_.empty(); });
                if (stop_ && tasks_.empty()) return;
                t = std::move(tasks_.front());
                tasks_.pop_front();
            }
            t.fn();
            {
                std::unique_lock<std::mutex> lk(mu_);
                if (--pending_ == 0) done_cv_.notify_all();
            }
        }
    }

    int64_t block_size_;
    bool stop_;
    int64_t pending_;
    std::atomic<int64_t> errors_;
    std::deque<Task> tasks_;
    std::vector<std::thread> workers_;
    std::mutex mu_;
    std::condition_variable cv_, done_cv_;
};

}  // namespace

extern "C" {

void* ds_aio_handle_new(int64_t block_size, int num_threads) {
    return new AioHandle(block_size, num_threads);
}

void ds_aio_handle_free(void* h) { delete static_cast<AioHandle*>(h); }

// Async: returns immediately; pair with ds_aio_synchronize.
void ds_aio_pread(void* h, const char* path, void* buf, int64_t n,
                  int64_t file_offset) {
    static_cast<AioHandle*>(h)->submit_io(path, static_cast<char*>(buf), n,
                                          file_offset, /*is_read=*/true,
                                          /*create=*/false);
}

void ds_aio_pwrite(void* h, const char* path, const void* buf, int64_t n,
                   int64_t file_offset) {
    static_cast<AioHandle*>(h)->submit_io(
        path, const_cast<char*>(static_cast<const char*>(buf)), n,
        file_offset, /*is_read=*/false, /*create=*/true);
}

// Blocking variants (reference: aio_handle.sync_pread/sync_pwrite).
int ds_aio_sync_pread(void* h, const char* path, void* buf, int64_t n,
                      int64_t file_offset) {
    auto* handle = static_cast<AioHandle*>(h);
    handle->submit_io(path, static_cast<char*>(buf), n, file_offset, true,
                      false);
    return handle->synchronize();
}

int ds_aio_sync_pwrite(void* h, const char* path, const void* buf, int64_t n,
                       int64_t file_offset) {
    auto* handle = static_cast<AioHandle*>(h);
    handle->submit_io(path,
                      const_cast<char*>(static_cast<const char*>(buf)), n,
                      file_offset, false, true);
    return handle->synchronize();
}

int ds_aio_synchronize(void* h) {
    return static_cast<AioHandle*>(h)->synchronize();
}

int64_t ds_aio_block_size(void* h) {
    return static_cast<AioHandle*>(h)->block_size();
}

int ds_aio_num_threads(void* h) {
    return static_cast<AioHandle*>(h)->num_threads();
}

}  // extern "C"
