// Async tensor I/O for NVMe offload (reference: csrc/aio/ — DeepNVMe.
// deepspeed_py_aio_handle.cpp exposes an `aio_handle` with async
// pread/pwrite of pinned buffers against NVMe files, backed by a thread
// pool + libaio io_submit; used by runtime/swap_tensor/*).
//
// TPU build: C ABI handle (ctypes-loaded) with the same operation set —
// async pread/pwrite, blocked into `block_size` chunks spread over
// `num_threads` workers, plus a synchronous path. `use_direct` opens
// files with O_DIRECT so sweeps measure the DEVICE, not the page cache
// (reference: deepspeed_py_aio_handle.cpp runs libaio on O_DIRECT fds):
// each worker keeps a reusable 4 KiB-aligned bounce buffer (the caller's
// numpy memory has arbitrary alignment) — full aligned chunks go through
// the direct fd, the unaligned tail through a buffered fd. The thread
// pool gives the queue-depth parallelism io_submit's ring would, and the
// per-worker bounce buffers double-buffer transfers against compute.

#ifndef _GNU_SOURCE
#define _GNU_SOURCE   // O_DIRECT
#endif

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <unistd.h>

namespace {

constexpr int64_t kDirectAlign = 4096;

struct Task {
    std::function<void()> fn;
};

// per-worker aligned bounce buffer, sized on first use and freed at
// thread exit (a raw thread_local pointer would leak per destroyed
// handle's worker threads)
struct Bounce {
    char* p = nullptr;
    int64_t len = 0;
    ~Bounce() { std::free(p); }
};
thread_local Bounce tls_bounce;

char* bounce_buffer(int64_t len) {
    if (tls_bounce.len < len) {
        std::free(tls_bounce.p);
        if (posix_memalign(reinterpret_cast<void**>(&tls_bounce.p),
                           kDirectAlign, (size_t)len) != 0) {
            tls_bounce.p = nullptr;
            tls_bounce.len = 0;
            return nullptr;
        }
        tls_bounce.len = len;
    }
    return tls_bounce.p;
}

class AioHandle {
   public:
    AioHandle(int64_t block_size, int num_threads, bool use_direct)
        : block_size_(block_size > 0 ? block_size : (1 << 20)),
          use_direct_(use_direct),
          stop_(false),
          pending_(0),
          errors_(0) {
        if (use_direct_ && (block_size_ % kDirectAlign) != 0) {
            block_size_ = ((block_size_ + kDirectAlign - 1) / kDirectAlign)
                          * kDirectAlign;
        }
        int n = num_threads > 0 ? num_threads : 1;
        for (int i = 0; i < n; ++i) {
            workers_.emplace_back([this] { this->worker(); });
        }
    }

    ~AioHandle() {
        {
            std::unique_lock<std::mutex> lk(mu_);
            stop_ = true;
        }
        cv_.notify_all();
        for (auto& t : workers_) t.join();
    }

    // Split [buf, buf+n) into block-sized chunks; each chunk is one task.
    void submit_io(const std::string& path, char* buf, int64_t n,
                   int64_t file_offset, bool is_read, bool create) {
        int flags = is_read ? O_RDONLY : (O_WRONLY | (create ? O_CREAT : 0));
        for (int64_t off = 0; off < n; off += block_size_) {
            int64_t len = std::min(block_size_, n - off);
            char* p = buf + off;
            int64_t foff = file_offset + off;
            // O_DIRECT needs file-offset and length alignment; the
            // bounce buffer supplies the memory alignment. The tail (or
            // an unaligned file offset) takes the buffered path.
            bool direct = use_direct_ && (foff % kDirectAlign) == 0 &&
                          (len % kDirectAlign) == 0;
            enqueue([this, path, p, len, foff, flags, is_read, direct] {
                int fd = ::open(path.c_str(),
                                direct ? (flags | O_DIRECT) : flags, 0644);
                if (fd < 0 && direct) {
                    // filesystem without O_DIRECT (tmpfs): buffered —
                    // COUNTED so callers can tell a sweep row measured
                    // the page cache after all
                    direct_fallbacks_.fetch_add(1);
                    fd = ::open(path.c_str(), flags, 0644);
                }
                if (fd < 0) {
                    errors_.fetch_add(1);
                    return;
                }
                char* io_buf = p;
                if (direct) {
                    io_buf = bounce_buffer(len);
                    if (io_buf == nullptr) {
                        errors_.fetch_add(1);
                        ::close(fd);
                        return;
                    }
                    if (!is_read) std::memcpy(io_buf, p, (size_t)len);
                }
                int64_t done = 0;
                bool cur_direct = direct;
                while (done < len) {
                    ssize_t r = is_read
                                    ? ::pread(fd, io_buf + done, len - done,
                                              foff + done)
                                    : ::pwrite(fd, io_buf + done,
                                               len - done, foff + done);
                    if (r <= 0) {
                        errors_.fetch_add(1);
                        break;
                    }
                    done += r;
                    // a short direct transfer can leave a remainder that
                    // violates O_DIRECT's offset/length alignment (EOF,
                    // some filesystems); finish via a buffered fd instead
                    // of failing the misaligned direct retry with EINVAL
                    if (cur_direct && done < len &&
                        (done % kDirectAlign) != 0) {
                        direct_fallbacks_.fetch_add(1);
                        ::close(fd);
                        fd = ::open(path.c_str(), flags, 0644);
                        if (fd < 0) {
                            errors_.fetch_add(1);
                            return;
                        }
                        cur_direct = false;
                    }
                }
                if (direct && is_read && done == len) {
                    std::memcpy(p, io_buf, (size_t)len);
                }
                ::close(fd);
            });
        }
    }

    // Block until every queued op completes; returns -errors.
    int synchronize() {
        std::unique_lock<std::mutex> lk(mu_);
        done_cv_.wait(lk, [this] { return pending_ == 0; });
        return -(int)errors_.exchange(0);
    }

    int64_t block_size() const { return block_size_; }
    int num_threads() const { return (int)workers_.size(); }
    int64_t direct_fallbacks() const { return direct_fallbacks_.load(); }

   private:
    void enqueue(std::function<void()> fn) {
        {
            std::unique_lock<std::mutex> lk(mu_);
            tasks_.push_back({std::move(fn)});
            ++pending_;
        }
        cv_.notify_one();
    }

    void worker() {
        for (;;) {
            Task t;
            {
                std::unique_lock<std::mutex> lk(mu_);
                cv_.wait(lk, [this] { return stop_ || !tasks_.empty(); });
                if (stop_ && tasks_.empty()) return;
                t = std::move(tasks_.front());
                tasks_.pop_front();
            }
            t.fn();
            {
                std::unique_lock<std::mutex> lk(mu_);
                if (--pending_ == 0) done_cv_.notify_all();
            }
        }
    }

    int64_t block_size_;
    bool use_direct_;
    bool stop_;
    int64_t pending_;
    std::atomic<int64_t> errors_;
    std::atomic<int64_t> direct_fallbacks_{0};
    std::deque<Task> tasks_;
    std::vector<std::thread> workers_;
    std::mutex mu_;
    std::condition_variable cv_, done_cv_;
};

}  // namespace

extern "C" {

void* ds_aio_handle_new(int64_t block_size, int num_threads) {
    return new AioHandle(block_size, num_threads, /*use_direct=*/false);
}

// O_DIRECT-capable constructor (reference: aio config block's
// use_direct / the sweep's page-cache-off mode).
void* ds_aio_handle_new_direct(int64_t block_size, int num_threads,
                               int use_direct) {
    return new AioHandle(block_size, num_threads, use_direct != 0);
}

void ds_aio_handle_free(void* h) { delete static_cast<AioHandle*>(h); }

// Async: returns immediately; pair with ds_aio_synchronize.
void ds_aio_pread(void* h, const char* path, void* buf, int64_t n,
                  int64_t file_offset) {
    static_cast<AioHandle*>(h)->submit_io(path, static_cast<char*>(buf), n,
                                          file_offset, /*is_read=*/true,
                                          /*create=*/false);
}

void ds_aio_pwrite(void* h, const char* path, const void* buf, int64_t n,
                   int64_t file_offset) {
    static_cast<AioHandle*>(h)->submit_io(
        path, const_cast<char*>(static_cast<const char*>(buf)), n,
        file_offset, /*is_read=*/false, /*create=*/true);
}

// Blocking variants (reference: aio_handle.sync_pread/sync_pwrite).
int ds_aio_sync_pread(void* h, const char* path, void* buf, int64_t n,
                      int64_t file_offset) {
    auto* handle = static_cast<AioHandle*>(h);
    handle->submit_io(path, static_cast<char*>(buf), n, file_offset, true,
                      false);
    return handle->synchronize();
}

int ds_aio_sync_pwrite(void* h, const char* path, const void* buf, int64_t n,
                       int64_t file_offset) {
    auto* handle = static_cast<AioHandle*>(h);
    handle->submit_io(path,
                      const_cast<char*>(static_cast<const char*>(buf)), n,
                      file_offset, false, true);
    return handle->synchronize();
}

int ds_aio_synchronize(void* h) {
    return static_cast<AioHandle*>(h)->synchronize();
}

int64_t ds_aio_block_size(void* h) {
    return static_cast<AioHandle*>(h)->block_size();
}

int ds_aio_num_threads(void* h) {
    return static_cast<AioHandle*>(h)->num_threads();
}

// chunks that requested O_DIRECT but fell back to buffered I/O
int64_t ds_aio_direct_fallbacks(void* h) {
    return static_cast<AioHandle*>(h)->direct_fallbacks();
}

}  // extern "C"
