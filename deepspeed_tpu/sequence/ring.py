"""Ring attention: context parallelism for long sequences.

Not present in the reference snapshot (SURVEY §2.3: "CP/ring attention not
present — long-context is Ulysses + sparse attention"); this is a
capability the TPU build adds. Blockwise causal attention with online
softmax: k/v blocks rotate around the ``sp`` ring via ``ppermute`` while
each device keeps its query block — comm volume O(S/P) per step over ICI,
memory O(S/P * S/P) per block instead of O(S^2).

Math follows the blockwise-parallel-attention recipe (flash-attention
style log-sum-exp accumulation in fp32).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def _block_attn_update(q, k, v, m, l, acc, *, scale, mask):
    """One online-softmax update. q:[B,Sq,H,D] k/v:[B,Sk,H,D]
    m,l:[B,H,Sq] acc:[B,Sq,H,D]; mask broadcastable to [B,H,Sq,Sk]."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    s = jnp.where(mask, s, -jnp.inf)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # rows with nothing to attend to yet keep m=-inf; guard the exp
    safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - safe_m[..., None])
    p = jnp.where(mask, p, 0.0)
    corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
    l_new = l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    acc_new = acc * corr.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, acc_new


def ring_attention(mesh: Mesh, sp_axis: str = "sp",
                   batch_axes=("dp", "fsdp"), tp_axis: str = "tp") -> Callable:
    """Returns an attn_fn(q, k, v, causal=True) running causal ring
    attention over the sp mesh axis. Sequence blocks are laid out
    contiguously in rank order (block r holds tokens [r*S/P, (r+1)*S/P))."""

    def attn(q, k, v, *, causal: bool = True, **_kw):
        sp = mesh.shape.get(sp_axis, 1)
        if sp <= 1:
            from ..ops.layers import dot_product_attention
            return dot_product_attention(q, k, v, causal=causal)
        if not causal:
            raise NotImplementedError("ring attention is causal-only")
        nq, nkv = q.shape[2], k.shape[2]
        if nq != nkv:  # GQA: replicate kv to q heads for the ring pass
            rep = nq // nkv
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        scale = 1.0 / np.sqrt(q.shape[-1])
        perm = [(i, (i + 1) % sp) for i in range(sp)]

        def body(q, k, v):
            b, s_loc, h, d = q.shape
            my = lax.axis_index(sp_axis)
            dtype_in = q.dtype
            qf = q.astype(jnp.float32)
            m0 = jnp.full((b, h, s_loc), -jnp.inf, jnp.float32)
            l0 = jnp.zeros((b, h, s_loc), jnp.float32)
            a0 = jnp.zeros((b, s_loc, h, d), jnp.float32)
            qi = jnp.arange(s_loc)[:, None]
            ki = jnp.arange(s_loc)[None, :]

            def step(i, carry):
                kb, vb, m, l, acc = carry
                src = (my - i) % sp  # which seq block kb currently holds
                # block-level causal structure
                diag = qi >= ki                       # same block
                full = jnp.ones((s_loc, s_loc), bool)  # earlier block
                none = jnp.zeros((s_loc, s_loc), bool)  # later block
                mask = jnp.where(src == my, diag,
                                 jnp.where(src < my, full, none))
                mask = mask[None, None]
                m, l, acc = _block_attn_update(
                    qf, kb.astype(jnp.float32), vb.astype(jnp.float32),
                    m, l, acc, scale=scale, mask=mask)
                kb = lax.ppermute(kb, sp_axis, perm)
                vb = lax.ppermute(vb, sp_axis, perm)
                return kb, vb, m, l, acc

            _, _, m, l, acc = lax.fori_loop(0, sp, step, (k, v, m0, l0, a0))
            l = jnp.maximum(l, 1e-20)
            out = acc / l.transpose(0, 2, 1)[..., None]
            return out.astype(dtype_in)

        from .layer import _shard_map_sp
        return _shard_map_sp(body, mesh, sp_axis, 3)(q, k, v)

    return attn
