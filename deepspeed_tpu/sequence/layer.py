"""Ulysses-style sequence parallelism (reference: deepspeed/sequence/layer.py).

``DistributedAttention`` wraps any local attention: the sequence-sharded
q/k/v ``[B, S/P, H, D]`` are all-to-all'd into head-sharded, full-sequence
``[B, S, H/P, D]`` (reference ``_SeqAllToAll``/``single_all_to_all``,
layer.py:153,216), local attention runs, and the output is all-to-all'd
back. On TPU the all-to-all is a single XLA collective along the ``sp``
mesh axis inside ``shard_map`` — comm volume O(S/P) per device, riding ICI.

Composes with tensor parallelism: heads may additionally be sharded over
``tp`` (in/out specs carry both axes); the all-to-all only trades the sp
axis. Uneven head counts (reference layer.py:43): GQA kv-heads that don't
divide sp are replicated up front, and q-head counts not divisible by sp
are zero-padded to the next sp multiple and sliced back after the reverse
all-to-all.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.layers import dot_product_attention
from ..utils.jax_compat import (fallback_replicated_axes,
                                get_abstract_mesh, shard_map)


def _seq_all_to_all(x, axis_name: str, *, scatter_idx: int, gather_idx: int):
    """single_all_to_all equivalent: scatter `scatter_idx` dim, gather
    `gather_idx` dim along the sp axis (reference layer.py:153)."""
    return lax.all_to_all(x, axis_name, split_axis=scatter_idx,
                          concat_axis=gather_idx, tiled=True)


def _shard_map_sp(body, mesh, sp_axis, n_args):
    """Partial-manual shard_map over just the sp axis. Batch/tp/fsdp
    sharding stays under GSPMD, which also makes the wrapper nestable
    inside other manual regions (e.g. the compiled pipeline): when an
    abstract mesh is already active (inside jit), it is used instead of the
    concrete one so nested shard_maps agree."""
    active = get_abstract_mesh()
    use = active if (active is not None and active.shape) else mesh
    spec = P(*([None] * 1), sp_axis)  # [B, S(sp), H, D]: dim1 manual
    specs = tuple([spec] * n_args)
    return shard_map(body, mesh=use, axis_names={sp_axis},
                     in_specs=specs, out_specs=spec, check_vma=False)


class DistributedAttention:
    """reference: sequence/layer.py:271 DistributedAttention.

    Args mirror the reference: a local attention callable, the sequence
    "process group" (mesh + sp axis name), and the scatter/gather dims
    (default: scatter heads=2, gather seq=1 on [B, S, H, D]).
    """

    def __init__(self, local_attention: Callable | None = None,
                 mesh: Mesh | None = None, sp_axis: str = "sp",
                 scatter_idx: int = 2, gather_idx: int = 1,
                 batch_axes=("dp", "fsdp"), tp_axis: str = "tp"):
        self.local_attn = local_attention or dot_product_attention
        self.mesh = mesh
        self.sp_axis = sp_axis
        self.scatter_idx = scatter_idx
        self.gather_idx = gather_idx
        self.batch_axes = batch_axes
        self.tp_axis = tp_axis

    def __call__(self, q, k, v, *, causal: bool = True, **kw):
        mesh = self.mesh
        sp = mesh.shape.get(self.sp_axis, 1)
        if sp <= 1:
            return self.local_attn(q, k, v, causal=causal, **kw)
        if self.sp_axis in fallback_replicated_axes():
            # 0.4.x full-manual fallback, nested inside another such
            # region (e.g. the compiled 1F1B pipeline's shard_map): the
            # outer map already made EVERY mesh axis manual — including
            # sp — so a nested shard_map over sp cannot lower ("Axis
            # ... is also found in manual_axes"; this crashed dryrun B
            # through PR 8). The guard holds ONLY when every enclosing
            # fallback frame left sp unmentioned in its specs, i.e. the
            # inputs here are genuinely replicated along sp — then the
            # Ulysses all-to-all round trip is the identity up to
            # layout, and local attention on the full arrays is
            # bit-identical (redundant compute along sp, the documented
            # cost of this fallback; see utils/jax_compat.shard_map).
            # An outer region that actually SHARDS the sequence along
            # sp keeps the old loud lowering error instead of silently
            # computing block-diagonal attention. On jax >= 0.5
            # partial-manual nesting works and this never triggers.
            return self.local_attn(q, k, v, causal=causal, **kw)

        nq, nkv = q.shape[2], k.shape[2]
        tp = mesh.shape.get(self.tp_axis, 1)
        if nq % tp != 0:
            raise ValueError(
                f"DistributedAttention: q heads ({nq}) must be divisible "
                f"by the tensor-parallel degree ({tp}); the uneven-head "
                f"padding only supports head counts uneven in sp")
        local_q = nq // tp
        if nkv != nq:
            if nq % nkv != 0:
                raise ValueError(
                    f"DistributedAttention: GQA needs q heads ({nq}) to "
                    f"be a multiple of kv heads ({nkv})")
            if nkv % tp != 0 or (nkv // tp) % sp != 0:
                # kv heads don't shard evenly over tp*sp: replicate kv
                # up to the q head count (reference supports uneven head
                # counts; full replication is the TPU-simple equivalent
                # for GQA, and nq is already tp-divisible)
                k = jnp.repeat(k, nq // nkv, axis=2)
                v = jnp.repeat(v, nq // nkv, axis=2)
        pad = 0
        if local_q % sp != 0:
            # uneven q heads (reference layer.py:43 supports head counts
            # not divisible by the SP degree): pad zero heads up to the
            # next sp multiple per tp shard; the all-to-alls stay even
            # and the pad heads are sliced off after the reverse
            # all-to-all (head order is preserved across the round trip,
            # so the pad stays at the tail). Overhead = pad/H compute.
            if k.shape[2] != nq:
                k = jnp.repeat(k, nq // k.shape[2], axis=2)
                v = jnp.repeat(v, nq // v.shape[2], axis=2)
            target = -(-local_q // sp) * sp * tp
            pad = target - nq
            widths = [(0, 0), (0, 0), (0, pad), (0, 0)]
            q = jnp.pad(q, widths)
            k = jnp.pad(k, widths)
            v = jnp.pad(v, widths)

        def body(q, k, v):
            # local in: [B, S/P, H_local, D]; scatter heads, gather seq
            q = _seq_all_to_all(q, self.sp_axis,
                                scatter_idx=self.scatter_idx,
                                gather_idx=self.gather_idx)
            k = _seq_all_to_all(k, self.sp_axis,
                                scatter_idx=self.scatter_idx,
                                gather_idx=self.gather_idx)
            v = _seq_all_to_all(v, self.sp_axis,
                                scatter_idx=self.scatter_idx,
                                gather_idx=self.gather_idx)
            o = self.local_attn(q, k, v, causal=causal, **kw)
            # back: scatter seq, gather heads
            return _seq_all_to_all(o, self.sp_axis,
                                   scatter_idx=self.gather_idx,
                                   gather_idx=self.scatter_idx)

        out = _shard_map_sp(body, mesh, self.sp_axis, 3)(q, k, v)
        return out[:, :, :nq] if pad else out


def ulysses_attention(mesh: Mesh, local_attention: Callable | None = None,
                      **kw) -> Callable:
    """Convenience: an attn_fn for DecoderLM.apply(..., attn_fn=...)."""
    da = DistributedAttention(local_attention, mesh, **kw)
    return lambda q, k, v, causal=True: da(q, k, v, causal=causal)
