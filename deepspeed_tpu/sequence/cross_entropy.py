"""Vocab-parallel cross entropy (reference: deepspeed/sequence/cross_entropy.py).

When the lm head is tensor-parallel (logits sharded over the vocab dim),
computing the loss must not all-gather the full [B, S, V] logits. This
shard_map implementation exchanges only per-token scalars (max, sum, true
logit) over the tp axis — the explicit form of what the reference's
vocab_parallel_cross_entropy autograd Function does with two all-reduces.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def vocab_parallel_cross_entropy(logits, targets, mesh: Mesh,
                                 tp_axis: str = "tp",
                                 batch_axes=("dp", "fsdp"),
                                 sp_axis: str = "sp",
                                 ignore_index: int = -100):
    """Mean cross entropy over tokens; logits [B, S, V] sharded over
    tp on the vocab dim, targets [B, S] global ids."""
    tp = mesh.shape.get(tp_axis, 1)
    if tp <= 1:
        from ..ops.layers import cross_entropy_loss
        return cross_entropy_loss(logits, targets, ignore_index=ignore_index)

    bat = tuple(a for a in batch_axes if mesh.shape.get(a, 1) > 1)
    sp = sp_axis if mesh.shape.get(sp_axis, 1) > 1 else None
    logit_spec = P(bat or None, sp, tp_axis)
    tgt_spec = P(bat or None, sp)

    def body(lg, tg):
        # lg: [b, s, V/tp] fp32; tg: [b, s]
        lg = lg.astype(jnp.float32)
        vshard = lg.shape[-1]
        rank = lax.axis_index(tp_axis)
        offset = rank * vshard
        local_max = jnp.max(lg, axis=-1)
        gmax = lax.pmax(local_max, tp_axis)
        sumexp = jnp.sum(jnp.exp(lg - gmax[..., None]), axis=-1)
        gsum = lax.psum(sumexp, tp_axis)
        lse = gmax + jnp.log(gsum)
        # true logit: only the owning shard contributes
        local_idx = jnp.clip(tg - offset, 0, vshard - 1)
        owned = (tg >= offset) & (tg < offset + vshard)
        tl = jnp.take_along_axis(lg, local_idx[..., None], axis=-1)[..., 0]
        true_logit = lax.psum(jnp.where(owned, tl, 0.0), tp_axis)
        nll = lse - true_logit
        valid = tg != ignore_index
        nll = jnp.where(valid, nll, 0.0)
        # partial sums; mean finalized outside (sp/batch dims still sharded)
        return nll, valid.astype(jnp.float32)

    nll, valid = shard_map(
        body, mesh=mesh, in_specs=(logit_spec, tgt_spec),
        out_specs=(tgt_spec, tgt_spec), check_vma=False)(logits, targets)
    return jnp.sum(nll) / jnp.maximum(jnp.sum(valid), 1.0)
