from .cross_entropy import vocab_parallel_cross_entropy  # noqa: F401
from .layer import DistributedAttention, ulysses_attention  # noqa: F401
from .ring import ring_attention  # noqa: F401
