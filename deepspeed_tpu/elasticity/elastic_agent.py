"""Elastic restart agent (reference: deepspeed/elasticity/elastic_agent.py:32).

The reference extends torch-elastic's LocalElasticAgent: on worker-group
membership change it restarts workers with a new WORLD_SIZE. The TPU
equivalent is slice-granular: when hosts join or leave, the job restarts
with a re-shaped ``jax.sharding.Mesh`` and resumes from a universal
checkpoint (which re-shards to any DP/TP/PP degree — SURVEY §5
checkpoint/resume). This agent packages that loop:

  agent = ElasticTrainingAgent(ds_config, ckpt_dir, build_fn)
  agent.run()   # build_fn(n_devices, micro_batch, gas) -> train loop

``build_fn`` is invoked once per membership epoch; if it raises
``WorldSizeChanged`` (or the device count observably changes between
epochs) the agent recomputes the elastic batch plan and re-invokes.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Callable, Optional

_RESTART_COUNT_ENV = "DS_TPU_ELASTIC_RESTARTS"

import jax

from ..utils.logging import logger
from .elasticity import (compute_elastic_config,
                         ElasticityIncompatibleWorldSize)


class WorldSizeChanged(Exception):
    """Raised by training code when it detects a membership change
    (the analogue of torch-elastic's worker-failure signal)."""


class ElasticTrainingAgent:

    def __init__(self, ds_config: dict,
                 checkpoint_dir: Optional[str] = None,
                 max_restarts: int = 100,
                 restart_backoff_s: float = 5.0):
        self.ds_config = ds_config
        self.checkpoint_dir = checkpoint_dir
        self.max_restarts = max_restarts
        self.restart_backoff_s = restart_backoff_s
        self.restart_count = 0

    def current_world_size(self) -> int:
        return jax.device_count()

    def plan_for(self, world_size: int):
        """(final_batch, micro_batch, gas) for this membership epoch."""
        final_batch, _, micro, gas = compute_elastic_config(
            self.ds_config, world_size=world_size, return_microbatch=True)
        return final_batch, micro, gas

    def run(self, build_fn: Callable[[int, int, int], None]) -> None:
        """Run ``build_fn(world_size, micro_batch, gas)`` once for this
        process's membership epoch (reference: elastic_agent.py:127
        _invoke_run). On ``WorldSizeChanged`` the process RE-EXECS itself:
        jax's device topology is fixed once the backend initializes, so a
        new membership epoch requires a fresh process — the same model as
        torch-elastic restarting its worker group. Restart count rides an
        env var across the exec. Training state must come back via
        checkpoint (universal checkpoints reshard to the new world)."""
        self.restart_count = int(os.environ.get(_RESTART_COUNT_ENV, "0"))
        world = self.current_world_size()
        try:
            batch, micro, gas = self.plan_for(world)
        except ElasticityIncompatibleWorldSize:
            raise RuntimeError(
                f"device count {world} is outside the elastic "
                "schedule; restart the job on a valid slice shape")
        logger.info(
            f"elastic epoch: world={world} batch={batch} "
            f"micro={micro} gas={gas} (restart {self.restart_count})")
        try:
            build_fn(world, micro, gas)
        except WorldSizeChanged:
            if self.restart_count + 1 > self.max_restarts:
                raise RuntimeError(
                    f"exceeded max_restarts={self.max_restarts}")
            logger.warning(
                "membership change: re-exec for a fresh device topology")
            time.sleep(self.restart_backoff_s)
            os.environ[_RESTART_COUNT_ENV] = str(self.restart_count + 1)
            os.execv(sys.executable, [sys.executable] + sys.argv)
