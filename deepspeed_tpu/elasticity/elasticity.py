"""Elastic batch-size math (reference: deepspeed/elasticity/elasticity.py).

Given a set of candidate micro-batch sizes and an acceptable total-batch
ceiling, enumerate the (total_batch, micro_batch, GAS) combinations that
stay valid across a whole range of chip counts — so training can restart
on a different slice shape without changing the effective batch size.

v0.1 (reference :83): chip counts compatible with one chosen batch size.
v0.2 (reference :126): adds model-parallel size and chips-per-node
divisibility constraints (a TPU pod slice analogue: world size must be a
multiple of chips-per-host when hosts come and go whole).
"""

from __future__ import annotations

from typing import Iterable, List, Tuple

from .config import ElasticityConfig, LATEST_ELASTICITY_VERSION


class ElasticityError(Exception):
    """Base error for elasticity (reference: constants + exceptions)."""


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


def _lcm(a: int, b: int) -> int:
    from math import gcd
    return a * b // gcd(a, b)


def get_candidate_batch_sizes(base_list: Iterable[int],
                              max_acceptable_batch_size: int) -> List[int]:
    """All LCM-combinations of the micro-batch candidates, capped at the
    ceiling (reference: elasticity.py:40 get_candidate_batch_sizes)."""
    base_list = sorted(set(base_list))
    # Closure of the list under LCM, capped at the ceiling. Equivalent to
    # enumerating all subset-LCMs but O(n * distinct_lcms) instead of 2^n.
    lcms: set[int] = set()
    for b in base_list:
        if b > max_acceptable_batch_size:
            continue
        new = {b}
        for x in lcms:
            v = _lcm(x, b)
            if v <= max_acceptable_batch_size:
                new.add(v)
        lcms |= new
    return sorted(lcms)


def get_compatible_gpus_v01(micro_batches: Iterable[int],
                            max_acceptable_batch_size: int,
                            min_gpus: int = 1,
                            max_gpus: int = 10000,
                            prefer_larger: bool = True
                            ) -> Tuple[int, List[int], dict]:
    """reference: elasticity.py:83 — pick final_batch_size and the chip
    counts it can run on. Returns (final_batch, valid_gpus,
    {gpus: (micro_batch, gas)})."""
    micro_batches = list(micro_batches)
    candidates = get_candidate_batch_sizes(micro_batches,
                                           max_acceptable_batch_size)
    if not candidates:
        raise ElasticityConfigError(
            f"No valid batch size <= {max_acceptable_batch_size} from "
            f"micro batches {list(micro_batches)}")

    best = None  # (num_valid, batch, valid_gpus, plan)
    for batch in candidates:
        valid_gpus = []
        plan = {}
        for n in range(min_gpus, min(max_gpus, batch) + 1):
            if batch % n != 0:
                continue
            per_gpu = batch // n
            # pick the largest micro batch that divides the per-chip share
            mbs = [m for m in micro_batches if per_gpu % m == 0]
            if not mbs:
                continue
            micro = max(mbs)
            valid_gpus.append(n)
            plan[n] = (micro, per_gpu // micro)
        if not valid_gpus:
            continue
        key = (len(valid_gpus), batch if prefer_larger else -batch)
        if best is None or key > best[0]:
            best = (key, batch, valid_gpus, plan)
    if best is None:
        raise ElasticityConfigError(
            "No batch size is runnable on any chip count in "
            f"[{min_gpus}, {max_gpus}]")
    _, batch, valid_gpus, plan = best
    return batch, valid_gpus, plan


def get_compatible_gpus_v02(micro_batches: Iterable[int],
                            max_acceptable_batch_size: int,
                            min_gpus: int = 1,
                            max_gpus: int = 10000,
                            prefer_larger: bool = True,
                            num_gpus_per_node: int = 1,
                            model_parallel_size: int = 1
                            ) -> Tuple[int, List[int], dict]:
    """reference: elasticity.py:126 — v0.2 adds model-parallelism and
    whole-node granularity: the DP degree is world/(mp), and world must be
    a multiple of chips-per-node (hosts join/leave whole)."""
    if model_parallel_size > 1:
        if num_gpus_per_node % model_parallel_size != 0 and \
                model_parallel_size % num_gpus_per_node != 0:
            raise ElasticityConfigError(
                f"model_parallel_size {model_parallel_size} incompatible "
                f"with num_gpus_per_node {num_gpus_per_node}")
    dp_min = max(1, min_gpus // model_parallel_size)
    dp_max = max(1, max_gpus // model_parallel_size)
    batch, valid_dp, plan = get_compatible_gpus_v01(
        micro_batches, max_acceptable_batch_size,
        min_gpus=dp_min, max_gpus=dp_max, prefer_larger=prefer_larger)

    valid_gpus, out_plan = [], {}
    for dp in valid_dp:
        world = dp * model_parallel_size
        if world % num_gpus_per_node != 0:
            continue
        valid_gpus.append(world)
        out_plan[world] = plan[dp]
    if not valid_gpus:
        raise ElasticityConfigError(
            "No world size satisfies whole-node + model-parallel "
            "divisibility")
    return batch, valid_gpus, out_plan


def elasticity_enabled(ds_config: dict) -> bool:
    return bool(ds_config.get("elasticity", {}).get("enabled", False))


def ensure_immutable_elastic_config(runtime_elastic_config_dict: dict,
                                    stored_elastic_config_dict: dict) -> None:
    """reference: elasticity.py:196 — a resumed job must not silently
    change the elastic schedule (that would break batch-size continuity)."""
    for key in ("max_train_batch_size", "micro_batch_sizes", "version"):
        a = runtime_elastic_config_dict.get(key)
        b = stored_elastic_config_dict.get(key)
        if a != b:
            raise ElasticityConfigError(
                f"Elastic config field {key!r} changed across restart: "
                f"{b!r} -> {a!r}. Elastic schedules are immutable.")


def compute_elastic_config(ds_config: dict,
                           target_deepspeed_version: str = "",
                           world_size: int = 0,
                           return_microbatch: bool = False):
    """reference: elasticity.py:233. Returns (final_batch_size,
    valid_gpus[, micro_batch, gas when world_size>0])."""
    cfg = ElasticityConfig(**ds_config.get("elasticity", {}))
    if not cfg.enabled:
        raise ElasticityConfigError("elasticity is not enabled in config")
    if cfg.version > LATEST_ELASTICITY_VERSION:
        raise ElasticityConfigError(
            f"Unsupported elasticity version {cfg.version}")

    if cfg.version >= 0.2:
        final_batch, valid_gpus, plan = get_compatible_gpus_v02(
            cfg.micro_batch_sizes, cfg.max_train_batch_size,
            min_gpus=cfg.min_gpus, max_gpus=cfg.max_gpus,
            prefer_larger=cfg.prefer_larger_batch,
            num_gpus_per_node=cfg.num_gpus_per_node,
            model_parallel_size=cfg.model_parallel_size)
    else:
        final_batch, valid_gpus, plan = get_compatible_gpus_v01(
            cfg.micro_batch_sizes, cfg.max_train_batch_size,
            min_gpus=cfg.min_gpus, max_gpus=cfg.max_gpus,
            prefer_larger=cfg.prefer_larger_batch)

    if world_size > 0:
        if world_size not in plan:
            raise ElasticityIncompatibleWorldSize(
                f"world size {world_size} not in valid set {valid_gpus} "
                f"for elastic batch {final_batch}")
        micro, gas = plan[world_size]
        if return_microbatch:
            return final_batch, valid_gpus, micro, gas
        return final_batch, valid_gpus, micro
    return final_batch, valid_gpus
