from .elasticity import (  # noqa: F401
    compute_elastic_config,
    elasticity_enabled,
    get_compatible_gpus_v01,
    get_compatible_gpus_v02,
    ensure_immutable_elastic_config,
    ElasticityError,
    ElasticityConfigError,
    ElasticityIncompatibleWorldSize,
)
from .config import ElasticityConfig  # noqa: F401
from .elastic_agent import ElasticTrainingAgent  # noqa: F401
