"""Elasticity config (reference: deepspeed/elasticity/config.py).

JSON shape follows the reference's ``elasticity`` block:

  "elasticity": {
    "enabled": true,
    "max_train_batch_size": 2000,
    "micro_batch_sizes": [2, 4, 6],
    "min_gpus": 1, "max_gpus": 10000,
    "min_time": 20,
    "prefer_larger_batch": true,
    "ignore_non_elastic_batch_info": false,
    "version": 0.2,
    "model_parallel_size": 1,
    "num_gpus_per_node": 4
  }

On TPU "gpus" reads as "chips"; the field names are kept verbatim so
reference configs parse unchanged.
"""

from __future__ import annotations

from typing import List

from pydantic import Field

from ..runtime.config_utils import DeepSpeedConfigModel

LATEST_ELASTICITY_VERSION = 0.2
MINIMUM_DEEPSPEED_VERSION = "0.1.0"


class ElasticityConfig(DeepSpeedConfigModel):
    enabled: bool = False
    max_train_batch_size: int = Field(2000, alias="max_acceptable_batch_size")
    micro_batch_sizes: List[int] = [2, 4, 6]
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    version: float = LATEST_ELASTICITY_VERSION
    prefer_larger_batch: bool = Field(True, alias="prefer_larger_batch_size")
    ignore_non_elastic_batch_info: bool = False
    model_parallel_size: int = 1
    num_gpus_per_node: int = 1
