"""FastGen-equivalent inference (reference: deepspeed/inference/v2/)."""

from .engine_v2 import (InferenceEngineV2, KVCacheConfig,  # noqa: F401
                        PrefixCacheConfig, RaggedInferenceEngineConfig)
from .engine_factory import SUPPORTED_MODEL_TYPES, build_engine  # noqa: F401
from .ragged import (BlockedAllocator, DSStateManager,  # noqa: F401
                     PrefixCache)
