"""Open-loop continuous-batching driver over the fused decode loop
(ISSUE 6 tentpole).

``FusedServeLoop`` is the admission/enqueue/drain scheduler that used to
live inside ``InferenceEngineV2.generate_fused`` (``_drive_fused``),
factored out and generalized so ONE driver serves both callers:

- **closed-loop** (``generate_fused``): a fixed prompt list is submitted
  up front and ``step()`` is called until ``has_work()`` is False —
  token-for-token the behavior of the PR 1 driver (the parity tests in
  tests/test_inference_v2.py run through this path);
- **open-loop** (``deepspeed_tpu.serving.AsyncInferenceServer``):
  requests arrive over time with priority tiers, stream their tokens
  through :class:`TokenEvent`, can be cancelled mid-flight, and may be
  PREEMPTED — a low-priority sequence's KV blocks are swapped out
  (parked; with the prefix cache enabled its full blocks stay warm in
  the LRU) to admit a higher-priority prompt, and restored later from
  its host-retained token history.

Two dispatch disciplines, selected by ``RaggedInferenceEngineConfig``:

- **chain mode** (default): up to ``max_inflight_dispatches`` fused
  dispatches in flight (PR 1 hard-coded 2); the host drains the oldest
  dispatch's ring buffer while newer ones run. Byte-identical to the
  PR 1 driver at the default depth.
- **ring mode** (``fused_admission=True``): dispatches chain through
  :func:`~.paged.fused_serve_loop` — waiting prompts are PRE-STAGED
  (prefilled, blocks reserved, one stage per row) and swapped into a
  finished row's slot INSIDE the compiled loop, and sampled tokens
  accumulate in a device-side ring the host reads ONCE per chain
  instead of once per dispatch. Host-blocking syncs per token drop by
  the chain depth on top of the 1/K the fused loop already bought.

The loop is single-threaded by design: callers marshal ``submit``/
``cancel`` onto the thread that runs ``step()`` (the async server does
this with a mailbox; see serving/server.py).
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...utils.telemetry_probe import (NULL_CM as _NULLCM,
                                      active_telemetry as _telemetry)

# scheduler-level counters surfaced through serving_metrics() /
# AsyncInferenceServer.metrics() — one schema for both consumers.
# "imports" counts migrated sequences admitted through the external-
# prefill path (ISSUE 13 disaggregation).
LOOP_COUNTER_KEYS = ("preemptions", "restores", "cancellations",
                     "admitted", "chain_drains", "imports")


@dataclass
class ServeRequest:
    """One in-flight generation request. ``generated`` accumulates
    across preemptions: on restore the full ``prompt + generated``
    history is re-admitted (prefix-cache warm where published), so the
    continuation is position-exact — greedy and position-keyed
    stochastic decode both resume bit-identically."""
    uid: int
    prompt: list[int]
    max_new_tokens: int
    priority: int = 1
    order: int = 0
    generated: list[int] = field(default_factory=list)
    preemptions: int = 0
    # cross-mesh migration (ISSUE 13): a KVExportState awaiting
    # admission — consumed (import_request) the first time the request
    # is admitted; a later preemption/restore re-prefills from the
    # host-side history like any parked request
    kv_import: Optional[object] = None
    # re-emit the already-generated suffix at admission (closed-loop
    # callers; the router streams it itself before the hand-off)
    emit_carried: bool = False
    # admitted via import this round: the prefill pass must skip it
    # (its single pending token is the next fused-dispatch input)
    was_imported: bool = False

    @property
    def budget(self) -> int:
        return self.max_new_tokens - len(self.generated)

    @property
    def admission_tokens(self) -> list[int]:
        return self.prompt + self.generated


@dataclass
class TokenEvent:
    """One emission from :meth:`FusedServeLoop.step`: ``tokens`` newly
    decoded for ``uid`` (may be empty on a pure state change), with
    ``finished`` set on the request's last event. ``error`` carries the
    failure reason for requests that can never run (e.g. a prompt that
    cannot fit the KV pool) in non-strict mode."""
    uid: int
    tokens: list[int]
    finished: bool = False
    error: Optional[str] = None


class FusedServeLoop:
    """See module docstring. Construct against a live
    :class:`~.engine_v2.InferenceEngineV2`; sampling parameters default
    to the engine config and are fixed for the loop's lifetime. The
    serving controller (ISSUE 19) may adjust chain depth and toggle
    the draft length between chains via :meth:`set_chain_depth` /
    :meth:`set_draft_len` — at most two compiled executable families
    per loop, both pinned by the recompile sentinel."""

    def __init__(self, engine, *, k_steps: Optional[int] = None,
                 temperature: Optional[float] = None,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 eos_id: Optional[int] = None, seed: int = 0,
                 strict: bool = False, preemption: bool = True,
                 replica: str = ""):
        cfg = engine._config
        self.e = engine
        # replica label (ISSUE 13): stamped on every request trace this
        # loop admits, so the access log names the serving replica
        self.replica = str(replica)
        self.k = max(1, int(k_steps if k_steps is not None
                            else (cfg.fused_decode_steps or 8)))
        (self.temperature, self.top_k, self.top_p,
         self.eos) = engine._sampling_args(temperature, top_k, top_p,
                                           eos_id)
        self.seed = int(seed)
        self.strict = bool(strict)
        self.preemption = bool(preemption)
        self.depth = max(1, int(cfg.max_inflight_dispatches))
        # the configured depth is the runtime CEILING: set_chain_depth
        # (ISSUE 19 controller knob) may step below it but never above,
        # so the ring capacity sized from it is always sufficient
        self.max_depth = self.depth
        self.ring_mode = bool(cfg.fused_admission)
        # speculative decoding (ISSUE 9): swap in the spec executables;
        # every scheduling decision below sizes advances by
        # k * (1 + draft_len) instead of k. The configured draft length
        # is the only nonzero runtime value — the spec executables bake
        # it at trace time, so set_draft_len toggles between exactly
        # two compiled families: {0, _draft_cfg}.
        self._draft_cfg = (int(cfg.speculative.draft_len)
                           if cfg.speculative.enabled else 0)
        self._pending_draft: Optional[int] = None
        if self.ring_mode:
            # fixed at the MAXIMUM family's advance so runtime depth /
            # draft changes never change operand shapes (the recompile
            # sentinel pins each family to one warmup)
            self.ring_cap = (self.k * self.max_depth
                             * (1 + self._draft_cfg))
        self._bind_fn(self._draft_cfg)

        self.waiting: list[ServeRequest] = []
        self.live: dict[int, ServeRequest] = {}
        self.staged: dict[int, ServeRequest] = {}   # ring mode only
        self.infl: deque = deque()
        self.to_flush: list[int] = []
        self.counters = dict.fromkeys(LOOP_COUNTER_KEYS, 0)
        # (seconds since previous drain, decode steps drained) — the
        # bench's tick-percentile source (wall per decode step with the
        # chain's host syncs amortized in)
        self.drain_stats: list[tuple[float, int]] = []
        self._cancelled: set[int] = set()
        self._order = itertools.count()
        self._uid = itertools.count()
        self._last_drain_t = time.perf_counter()
        # chain-mode rebuild state (mirrors the PR 1 closure variables)
        self._carry = None
        self._rowset: list[int] = []
        self._budgets: dict[int, int] = {}
        self._tables = self._row_keys = None
        self._n_enq = 0
        # telemetry (resolved once; every probe is per-admission /
        # per-dispatch / per-drain — never per token)
        self._tel = _telemetry()
        reg = (self._tel.get_registry() if self._tel is not None
               else None)
        from .engine_v2 import _LatencyProbe
        self._lat = _LatencyProbe(reg) if reg is not None else None
        # per-request lifecycle recorder (ISSUE 10): every call below
        # is guarded, so the telemetry-disabled loop is untouched
        self._rt = (self._tel.get_request_recorder()
                    if self._tel is not None else None)
        # fleet health monitor (ISSUE 17): closed-loop drivers run this
        # loop without an AsyncInferenceServer around it, so the loop
        # itself beats the failure detector under its replica label
        # (one dict write per step; None when the fleet plane is off)
        self._hm = (self._tel.get_health_monitor()
                    if self._tel is not None else None)
        self._beat_next = 0.0   # beat rate limit (see step())

    def _bind_fn(self, draft_len: int) -> None:
        """Bind ``self.fn``/``self._fn_key`` to the executable family
        for ``draft_len`` (0 = plain decode). Called once at
        construction and again by the boundary-applied
        :meth:`set_draft_len` toggle; each (key, operand-shape) pair
        still warms up exactly once under the recompile sentinel."""
        e, cfg = self.e, self.e._config
        self.draft_len = int(draft_len)
        self.spec = self.draft_len > 0
        sp_key = (self.draft_len, cfg.speculative.min_ngram)
        if self.ring_mode:
            if self.spec:
                self.fn = e._spec_serve_fn(
                    self.k, self.temperature, self.top_k, self.top_p,
                    self.eos)
                self._fn_key = ("spec_serve", self.k, *sp_key,
                                self.temperature, self.top_k,
                                self.top_p, self.eos)
            else:
                self.fn = e._serve_fn(self.k, self.temperature,
                                      self.top_k, self.top_p, self.eos)
                self._fn_key = ("serve", self.k, self.temperature,
                                self.top_k, self.top_p, self.eos)
        elif self.spec:
            self.fn = e._spec_fn(self.k, self.temperature,
                                 self.top_k, self.top_p, self.eos)
            self._fn_key = ("spec", self.k, *sp_key, self.temperature,
                            self.top_k, self.top_p, self.eos)
        else:
            self.fn = e._fused_fn(self.k, self.temperature,
                                  self.top_k, self.top_p, self.eos)
            self._fn_key = (self.k, self.temperature, self.top_k,
                            self.top_p, self.eos)

    # ------------------------------------------------------------------
    # runtime control knobs (ISSUE 19): the serving controller adjusts
    # these between chains — both are recompile-free by construction
    def set_chain_depth(self, depth: int) -> int:
        """Set the live chain depth, clamped to [1, configured
        ``max_inflight_dispatches``]. Effective immediately — depth only
        bounds the host-side enqueue loops, never an operand shape
        (``ring_cap`` stays sized for the configured maximum)."""
        self.depth = max(1, min(int(depth), self.max_depth))
        return self.depth

    def set_draft_len(self, draft_len: int) -> int:
        """Request a speculative draft-length toggle: 0 disables
        drafting, any nonzero value means the CONFIGURED draft length
        (the spec executables bake it at trace time, so those are the
        only two compiled families). Applied at the next chain
        boundary — mid-chain device state (in-flight dispatches, carry
        operands) belongs to the current family. Returns the value that
        will be in effect after it applies."""
        want = self._draft_cfg if int(draft_len) > 0 else 0
        self._pending_draft = None if want == self.draft_len else want
        return want

    def _apply_pending_draft(self) -> None:
        """Boundary application of :meth:`set_draft_len`: with nothing
        in flight every device commit has landed, so dropping the carry
        and rebuilding host operands under the other family replays
        nothing (the same rebuild a membership change forces)."""
        if self._pending_draft is None or self.infl:
            return
        self._carry = None
        self._bind_fn(self._pending_draft)
        self._pending_draft = None

    # ------------------------------------------------------------------
    # request intake (single-threaded with step(); see module docstring)
    def submit(self, prompt, max_new_tokens: int = 32, *,
               priority: int = 1,
               uid: Optional[int] = None) -> int:   # graftsan: domain=worker
        """Queue one prompt; returns its uid. Lower ``priority`` values
        run first; ties admit in submission order."""
        toks = [int(t) for t in prompt]
        if not toks:
            raise ValueError("submit() needs at least one prompt token")
        if uid is None:
            uid = next(self._uid)
        self.waiting.append(ServeRequest(
            uid=int(uid), prompt=toks,
            max_new_tokens=max(1, int(max_new_tokens)),
            priority=int(priority), order=next(self._order)))
        if self._rt is not None:
            # idempotent: the async server already recorded the true
            # submit time (mailbox latency counts as queue wait)
            self._rt.enqueue(int(uid), priority=int(priority),
                             prompt_tokens=len(toks),
                             max_new_tokens=max(1, int(max_new_tokens)))
        return int(uid)

    def submit_imported(self, state, max_new_tokens: int = 32, *,
                        priority: int = 1, uid: Optional[int] = None,
                        emit_carried: bool = False) -> int:   # graftsan: domain=worker
        """Queue a MIGRATED sequence (a
        :class:`~.ragged.KVExportState` from another engine's
        ``export_request``) — the external-prefill admission path
        (ISSUE 13). The KV payload is imported at ADMISSION time, not
        here, so a queued hand-off holds no pool blocks while it
        waits; once admitted it joins the fused loop position-exactly
        with no prefill pass (greedy continuation is bit-identical to
        a co-located run). ``emit_carried`` re-emits the
        already-generated suffix as the request's first TokenEvent
        (closed-loop callers; the router streams those tokens itself
        during the hand-off). ``max_new_tokens`` is the request's
        TOTAL generation budget, carried tokens included."""
        toks = [int(t) for t in state.tokens]
        n_gen = int(state.n_generated)
        prompt = toks[:len(toks) - n_gen]
        generated = toks[len(toks) - n_gen:]
        if not prompt:
            raise ValueError(
                "submit_imported() needs at least one prompt token")
        max_new = max(1, int(max_new_tokens))
        if max_new <= n_gen:
            raise ValueError(
                f"imported request already generated {n_gen} of "
                f"{max_new} tokens — nothing left to decode (finish "
                "such requests without a hand-off)")
        if uid is None:
            uid = next(self._uid)
        req = ServeRequest(uid=int(uid), prompt=prompt,
                           max_new_tokens=max_new,
                           priority=int(priority),
                           order=next(self._order),
                           generated=generated, kv_import=state,
                           emit_carried=bool(emit_carried))
        self.waiting.append(req)
        if self._rt is not None:
            self._rt.enqueue(int(uid), priority=int(priority),
                             prompt_tokens=len(prompt),
                             max_new_tokens=max_new)
        return int(uid)

    def cancel(self, uid: int) -> None:
        """Drop a request mid-stream; its KV blocks are released at the
        next dispatch boundary (the leak-regression contract)."""
        self._cancelled.add(int(uid))

    def has_work(self) -> bool:
        return bool(self.waiting or self.live or self.staged or self.infl
                    or self.to_flush or self._cancelled)

    # ------------------------------------------------------------------
    def step(self) -> list[TokenEvent]:     # graftsan: domain=worker
        """One scheduler iteration: boundary housekeeping (flush /
        cancel / preempt / admit / prefill), then enqueue up to the
        configured chain depth and drain. Returns the tokens decoded
        this iteration; an empty list means the loop is idle (or
        waiting on admission headroom)."""
        ev: list[TokenEvent] = []
        if self._hm is not None:
            # rate-limited: a fast tick loop must not calibrate the
            # detector tighter than its min interval (sub-ms beats
            # would flush the real cadence out of the bounded window)
            now = time.monotonic()
            if now >= self._beat_next:
                self._beat_next = now + max(
                    self._hm.min_interval_s, 1e-3)
                self._hm.heartbeat(self.replica or "replica0")
        if not self.has_work():
            self._apply_pending_draft()
            return ev
        self._apply_pending_draft()
        try:
            if self.ring_mode:
                self._step_ring(ev)
            else:
                self._step_chain(ev)
        except BaseException:
            self._emergency_flush()
            raise
        return ev

    def close(self) -> None:    # graftsan: domain=worker
        """Release every request's KV state (server shutdown)."""
        self._emergency_flush()
        if self._rt is not None:
            for r in self.waiting:
                self._rt.finished(r.uid, "aborted")
        self.waiting.clear()
        self._cancelled.clear()

    def _emergency_flush(self) -> None:
        """Block-leak guard (PR 4): drain what's in flight (commits are
        lost, but the device must stop referencing the tables before
        the blocks recycle), then release every scheduled-but-unfinished
        sequence's KV blocks."""
        try:
            jax.block_until_ready([f[1] for f in self.infl])
        except Exception:   # noqa: BLE001 — best-effort drain
            pass
        self.infl.clear()
        self._carry = None
        for u in (set(self.live) | set(self.staged) | set(self.to_flush)):
            self.e.flush(u)
        if self._rt is not None:
            # to_flush uids already recorded their outcome (finished()
            # is a no-op on unknown uids); live/staged die aborted
            for u in (set(self.live) | set(self.staged)):
                self._rt.finished(u, "aborted")
        self.live.clear()
        self.staged.clear()
        self.to_flush.clear()

    # ------------------------------------------------------------------
    # boundary housekeeping: runs only with nothing in flight
    def _boundary(self, ev: list[TokenEvent]) -> list[int]:
        assert not self.infl
        for u in self.to_flush:
            self.e.flush(u)
        self.to_flush.clear()
        self._apply_cancels(ev)
        ids = self._admit(ev)
        if ids:
            self._carry = None
            self._prefill(ids, ev)
        return ids

    def _apply_cancels(self, ev: list[TokenEvent]) -> None:
        if not self._cancelled:
            return
        for uid in sorted(self._cancelled):
            req = self.live.pop(uid, None) or self.staged.pop(uid, None)
            if req is None:
                before = len(self.waiting)
                self.waiting = [r for r in self.waiting if r.uid != uid]
                if len(self.waiting) == before:
                    continue        # unknown/already-finished uid
            else:
                self.e.flush(uid)
                self._carry = None  # membership changed mid-rowset
            self.counters["cancellations"] += 1
            if self._rt is not None:
                self._rt.finished(uid, "cancelled")
            ev.append(TokenEvent(uid, [], finished=True,
                                 error="cancelled"))
        self._cancelled.clear()

    def _finish(self, uid: int, ev: list[TokenEvent],
                staged: bool = False) -> None:
        (self.staged if staged else self.live).pop(uid, None)
        self.to_flush.append(uid)
        if self._lat is not None:
            self._lat.finished(uid)
        cancelled = uid in self._cancelled
        if self._rt is not None:
            self._rt.finished(uid, "cancelled" if cancelled
                              else "completed")
        if cancelled:
            self._cancelled.discard(uid)
            self.counters["cancellations"] += 1
            ev.append(TokenEvent(uid, [], finished=True,
                                 error="cancelled"))
        else:
            ev.append(TokenEvent(uid, [], finished=True))

    # ------------------------------------------------------------------
    # admission (+ preemption) — port of the PR 1 generate_fused admit():
    # the FULL worst-case block budget (history + remaining new tokens)
    # is allocated up front, because fused dispatches write KV in-graph
    # through tables fixed at build time.
    def _admit(self, ev: list[TokenEvent]) -> list[int]:
        e, mgr = self.e, self.e.state_manager
        bs = mgr.block_size
        max_live = e._config.max_ragged_sequence_count
        self.waiting.sort(key=lambda r: (r.priority, r.order))
        batch: list[ServeRequest] = []
        free = mgr.available_blocks
        while self.waiting:
            # ring mode additionally admits PRE-STAGED requests beyond
            # max_live — at most one per decode row; they join the
            # batch (prefilled + blocks reserved) and are swapped into
            # a finished row's slot in-graph. Recomputed every
            # iteration: preemption frees rows mid-pass.
            stage_from = max_live - len(self.live)
            n_to_live = min(len(batch), stage_from)
            n_to_stage = len(batch) - n_to_live
            n_rows_after = len(self.live) + n_to_live
            req = self.waiting[0]
            if n_to_live >= stage_from and not (
                    self.ring_mode
                    and len(self.staged) + n_to_stage < n_rows_after):
                # decode ROWS, not blocks, are the binding constraint:
                # a high-priority arrival may still park a lower-
                # priority occupant to free its row
                if self._try_preempt(req, 0, ev, free_rows=True):
                    free = mgr.available_blocks - sum(
                        self._admission_cost(
                            mgr, r, -(-(len(r.admission_tokens)
                                        + r.budget) // bs))
                        for r in batch)
                    continue
                break
            toks = req.admission_tokens
            need = -(-(len(toks) + req.budget) // bs)
            if need > mgr.max_blocks_per_seq or \
                    need > mgr.allocator.num_blocks:
                msg = (f"prompt {req.uid}: {len(toks)} tokens + "
                       f"{req.budget} new can never fit the KV pool "
                       f"(needs {need} blocks)")
                if self.strict:
                    raise ValueError(msg)
                self.waiting.pop(0)
                if self._rt is not None:
                    self._rt.finished(req.uid, "failed", error=msg)
                ev.append(TokenEvent(req.uid, [], finished=True,
                                     error=msg))
                continue
            cost = self._admission_cost(mgr, req, need)
            if cost > free:
                if self._try_preempt(req, cost - free, ev):
                    free = mgr.available_blocks - sum(
                        self._admission_cost(
                            mgr, r, -(-(len(r.admission_tokens)
                                        + r.budget) // bs))
                        for r in batch)
                    continue        # re-check the same request
                break
            self.waiting.pop(0)
            free -= cost
            batch.append(req)
        if self._lat is not None:
            self._lat.admitted([r.uid for r in batch],
                               waiting=len(self.waiting))
        if not batch:
            return []
        fresh = [r for r in batch if r.kv_import is None]
        if fresh:
            e.schedule([r.uid for r in fresh],
                       [r.admission_tokens for r in fresh])
        # the whole batch joins the tracked sets BEFORE importing /
        # reserving: a failure mid-batch must leave every scheduled
        # uid visible to the block-leak guard
        for i, r in enumerate(batch):
            if self.ring_mode and i >= stage_from:
                self.staged[r.uid] = r
            else:
                self.live[r.uid] = r
        qd = len(self.waiting)
        for r in [r for r in batch if r.kv_import is not None]:
            # external-prefill admission (ISSUE 13): the migrated KV
            # payload lands NOW — position-exact, no prefill pass
            state, r.kv_import = r.kv_import, None
            if self._rt is not None:
                self._rt.admitted(r.uid, queue_depth=qd,
                                  replica=self.replica)
            try:
                e.import_request(r.uid, state)
            except (RuntimeError, ValueError) as err:
                # defensive: a layout mismatch must fail the request,
                # not wedge the loop (headroom races cannot happen —
                # the loop is single-threaded and cost was checked)
                self.live.pop(r.uid, None)
                self.staged.pop(r.uid, None)
                batch.remove(r)
                if self._rt is not None:
                    self._rt.finished(r.uid, "failed", error=str(err))
                ev.append(TokenEvent(r.uid, [], finished=True,
                                     error=str(err)))
                continue
            r.was_imported = True
            self.counters["imports"] += 1
            if self._rt is not None:
                self._rt.migrated(r.uid, replica=self.replica,
                                  nbytes=state.payload_bytes,
                                  blocks=state.payload_blocks,
                                  source=state.source)
            if r.emit_carried and r.generated:
                ev.append(TokenEvent(r.uid, list(r.generated)))
                if self._lat is not None:
                    self._lat.tokens(r.uid, len(r.generated),
                                     first=True)
                if self._rt is not None:
                    self._rt.tokens_landed(r.uid, len(r.generated))
        if not batch:
            return []
        for r in batch:
            mgr.reserve(r.uid, r.budget)
        self.counters["admitted"] += len(batch)
        self.counters["restores"] += sum(1 for r in batch
                                         if r.preemptions > 0
                                         and r.generated)
        if self._rt is not None:
            qd = len(self.waiting)
            for r in fresh:
                seen = mgr.seqs[r.uid].seen
                self._rt.admitted(
                    r.uid, queue_depth=qd, cached_tokens=seen,
                    cached_blocks=seen // bs,
                    restore=r.preemptions > 0 and bool(r.generated),
                    replica=self.replica)
        return [r.uid for r in batch]

    def _admission_cost(self, mgr, req: ServeRequest,
                        need: int) -> int:
        """Blocks one admission consumes from the available headroom:
        a migrated request (ISSUE 13) allocates its FULL history fresh
        at import — no prefix-cache credit — while everything else
        gets the cache-credited cost."""
        if req.kv_import is not None:
            return need
        return mgr.admission_cost(req.admission_tokens, need)

    def _try_preempt(self, req: ServeRequest, short_blocks: int,
                     ev: list[TokenEvent],
                     free_rows: bool = False) -> bool:
        """Park strictly-lower-priority requests (KV swap-out: blocks
        released — prefix-cached full blocks stay parked in the LRU for
        a warm restore — token history retained host-side) until
        ``req`` fits. ``free_rows`` parks ONE victim to free a decode
        row when rows, not blocks, are the binding constraint. Only
        called at a dispatch boundary, so no victim is referenced by an
        in-flight dispatch."""
        if not self.preemption or (short_blocks <= 0 and not free_rows):
            return False
        victims = sorted(
            (r for r in (*self.staged.values(), *self.live.values())
             if r.priority > req.priority),
            key=lambda r: (-r.priority, -r.order))
        if not victims:
            return False
        parked = False
        mgr = self.e.state_manager
        for v in victims:
            freed_before = mgr.available_blocks
            # KV swap-out: blocks dec-ref'd (published full blocks park
            # in the prefix-cache LRU for a warm restore); the token
            # history lives on in v.prompt/v.generated
            mgr.park(v.uid)
            self.staged.pop(v.uid, None)
            self.live.pop(v.uid, None)
            v.preemptions += 1
            # a once-imported victim restores through the normal
            # re-prefill path (its KV left the pool with the park)
            v.was_imported = False
            self.waiting.append(v)
            self.counters["preemptions"] += 1
            if self._lat is not None:
                self._lat.finished(v.uid)
            if self._rt is not None:
                self._rt.parked(v.uid)
            self._carry = None
            parked = True
            short_blocks -= mgr.available_blocks - freed_before
            if free_rows or short_blocks <= 0:
                break
        if parked:
            # keep the pass priority-ordered: a parked victim must
            # outrank lower-priority waiters for the blocks it just
            # freed (its original `order` keeps FIFO resume within its
            # tier), or the next head would steal them and the victim
            # would preempt it right back — churn
            self.waiting.sort(key=lambda r: (r.priority, r.order))
        return parked

    # ------------------------------------------------------------------
    def _prefill(self, uids_new: list[int], ev: list[TokenEvent]) -> None:
        """Chunked prefill of newly admitted prompts, then the first
        generated token — sampled with the same op and position keying
        as the in-graph loop, so it belongs to the same stochastic
        stream (port of the PR 1 closure)."""
        e, mgr, tel = self.e, self.e.state_manager, self._tel
        # migrated admissions (ISSUE 13) arrive ALREADY at the
        # dispatch-boundary state (one pending token, first token(s)
        # generated on the exporting side) — running them through the
        # prefill pass would consume their pending dispatch input
        filling = []
        for u in uids_new:
            req = self.live.get(u) or self.staged.get(u)
            if req is not None and not req.was_imported:
                filling.append(u)
        firsts: dict[int, jnp.ndarray] = {}
        with (tel.span("v2/prefill", rows=len(filling))
              if tel is not None else _NULLCM):
            while filling:
                run = [u for u in filling if mgr.seqs[u].pending]
                logits = e._run(run)
                for i, u in enumerate(run):
                    if not mgr.seqs[u].pending:
                        firsts[u] = logits[i]
                        filling.remove(u)
        if not firsts:
            return
        uids_f = list(firsts)
        if self._rt is not None:
            # prefill compute done; first-token sampling/stream-out
            # lands in the first_drain component
            self._rt.prefill_done(uids_f)
        toks = e.sample_first_tokens(firsts, self.temperature,
                                     self.top_k, self.top_p, self.seed)
        for u, tok in ((u, toks[u]) for u in uids_f):
            req = self.live.get(u) or self.staged.get(u)
            req.generated.append(tok)
            e.serving_stats["decoded_tokens"] += 1
            ev.append(TokenEvent(u, [tok]))
            if self._lat is not None:
                self._lat.tokens(u, 1, first=len(req.generated) == 1)
            if self._rt is not None:
                self._rt.tokens_landed(u, 1)
            if req.budget <= 0 or (self.eos is not None
                                   and tok == self.eos):
                self._finish(u, ev, staged=u in self.staged)
            else:
                # the first token becomes the pending input of the
                # first fused dispatch (blocks preallocated)
                mgr.extend(u, [tok])

    # ------------------------------------------------------------------
    # chain mode: the PR 1 _drive_fused loop with a configurable depth
    def _step_chain(self, ev: list[TokenEvent]) -> None:
        e, mgr = self.e, self.e.state_manager
        stats = e.serving_stats
        tel = self._tel
        if not self.live and not self.infl:
            self._carry = None
            ids = self._boundary(ev)
            if (not self.live and self.waiting and not self.staged
                    and not ids):
                self._handle_stuck(ev)
            return

        while self.live and len(self.infl) < self.depth:
            if self._carry is None and self.infl:
                # rebuild needs the in-flight dispatch's commits first —
                # rebuilding from stale host state would replay its
                # decode steps
                break
            if self._carry is None:
                self._rowset = sorted(self.live)
                self._budgets = {u: self.live[u].budget
                                 for u in self._rowset}
                if self.spec:
                    (tok_a, pos_a, self._tables, act_a, rem_a,
                     self._row_keys, hist_a) = e._spec_operands(
                         self._rowset, self.k, self._budgets, self.seed)
                else:
                    (tok_a, pos_a, self._tables, act_a, rem_a,
                     self._row_keys) = e._fused_operands(
                         self._rowset, self.k, self._budgets, self.seed)
                    hist_a = None
                self._n_enq = 0
            elif self.spec:
                tok_a, pos_a, act_a, rem_a, hist_a = self._carry
            else:
                tok_a, pos_a, act_a, rem_a = self._carry
            # the first dispatch after a rebuild always goes; a chained
            # one only when no admission is waiting and some row's
            # budget can outlast the chain (a spec dispatch can advance
            # up to k*(1+draft_len) tokens per row)
            adv = self.k * (1 + self.draft_len)
            if self._n_enq > 0 and (self.waiting
                                    or max(self._budgets.values())
                                    <= adv * self._n_enq):
                break
            ops = (tok_a, pos_a, self._tables, act_a, rem_a,
                   self._row_keys)
            if self.spec:
                ops = ops + (hist_a,)
            if tel is not None:
                e._device_truth_observe(tel, "v2/fused_dispatch",
                                        self.fn, ops)
            with (tel.span("v2/fused_enqueue",
                           dispatch_id=stats["fused_dispatches"] + 1,
                           rows=len(self._rowset), k=self.k)
                  if tel is not None else _NULLCM):
                with e._fused_dispatch_scope(
                        self._fn_key, ops,
                        variant="carry" if self._n_enq > 0 else "host"):
                    if self.spec:
                        (out, optr, steps, t2, p2, a2, r2, h2, sstat,
                         e.pools) = self.fn(e.params, e.pools, *ops)
                        self._carry = (t2, p2, a2, r2, h2)
                    else:
                        out, steps, t2, p2, a2, r2, e.pools = self.fn(
                            e.params, e.pools, *ops)
                        optr = sstat = None
                        self._carry = (t2, p2, a2, r2)
            self._n_enq += 1
            if not self.infl:
                # chain start: clock drain intervals from here, so the
                # first sample measures the chain, not the admission/
                # prefill (or open-loop idle) time that preceded it
                self._last_drain_t = time.perf_counter()
            self.infl.append((list(self._rowset), out, optr, steps,
                              sstat))
            stats["host_dispatches"] += 1
            stats["fused_dispatches"] += 1
            if self._rt is not None:
                self._rt.dispatched(self._rowset,
                                    stats["fused_dispatches"], k=self.k)

        if not self.infl:       # chain declined to enqueue: rebuild
            self._carry = None
            return
        # drain the OLDEST dispatch's ring buffer (device may still be
        # running a newer chained one — that's the overlap)
        rows, out, optr, steps, sstat = self.infl.popleft()
        t_drain = time.perf_counter() if tel is not None else 0.0
        with (tel.span("v2/fused_drain", rows=len(rows))
              if tel is not None else _NULLCM):
            # the ONE sanctioned host read of the decode loop; under
            # the sentinel it runs inside transfer_guard("disallow")
            with (e._hot_guard() if e._hot_guard is not None
                  else _NULLCM):
                toks = np.asarray(out)
                n_exec = int(steps)
                ptrs = np.asarray(optr) if optr is not None else None
                if sstat is not None:
                    e._absorb_spec_stats(np.asarray(sstat))
        stats["fused_steps"] += n_exec
        stats["fused_slots"] += n_exec * len(rows)
        now = time.perf_counter()
        win_start = self._last_drain_t     # dispatch-window open (ISSUE 10)
        self.drain_stats.append((now - self._last_drain_t, n_exec))
        self._last_drain_t = now
        self.counters["chain_drains"] += 1
        membership_changed = False
        for i, u in enumerate(rows):
            req = self.live.get(u)
            if req is None:       # finished in an earlier dispatch
                continue
            row = [int(t) for t in
                   (toks[i, :ptrs[i]] if ptrs is not None else toks[i])
                   if t >= 0]
            if not row:
                continue
            mgr.commit_device_tokens(u, row)
            req.generated.extend(row)
            stats["decoded_tokens"] += len(row)
            stats["fused_slot_tokens"] += len(row)
            if ptrs is None:
                # one token per live slot; the spec path's live-slot
                # count arrived in the absorbed device stats
                stats["fused_live_slots"] += len(row)
            if self._lat is not None:
                self._lat.tokens(u, len(row))
            if self._rt is not None:
                self._rt.tokens_landed(u, len(row),
                                       window_start=win_start,
                                       steps=n_exec, row=i)
            if u not in self._cancelled:
                ev.append(TokenEvent(u, row))
            if (req.budget <= 0
                    or (self.eos is not None and row[-1] == self.eos)
                    or u in self._cancelled):
                self._finish(u, ev)
                membership_changed = True
        if tel is not None:
            e._record_dispatch_telemetry(tel, time.perf_counter()
                                         - t_drain)
        if membership_changed or self.waiting:
            # a finished row's slot should go to a waiting prompt;
            # rebuild operands once the in-flight chain drains
            self._carry = None
        if not self.infl:
            # nothing in flight references the old tables/blocks: safe
            # to recycle KV blocks and admit
            self._boundary(ev)

    def _handle_stuck(self, ev: list[TokenEvent]) -> None:
        """Nothing live/in-flight and the head request did not admit."""
        if self.strict:
            raise RuntimeError(
                "continuous-batching deadlock: pending prompts but "
                "nothing admissible")
        mgr = self.e.state_manager
        if not mgr.seqs and self.waiting:
            # the engine is empty and the head request STILL does not
            # fit: it never will — fail it instead of spinning
            req = self.waiting.pop(0)
            msg = (f"request {req.uid} cannot fit the KV pool even "
                   "with the engine idle")
            if self._rt is not None:
                self._rt.finished(req.uid, "failed", error=msg)
            ev.append(TokenEvent(req.uid, [], finished=True, error=msg))

    # ------------------------------------------------------------------
    # ring mode: in-graph admission + one host read per chain
    def _step_ring(self, ev: list[TokenEvent]) -> None:
        e, mgr = self.e, self.e.state_manager
        stats = e.serving_stats
        tel = self._tel
        ids = self._boundary(ev)
        if not self.live and self.staged:
            # every decode row finished while stage slots survived
            # (e.g. the whole live set hit EOS in one chain): promote
            # the staged requests — they are prefilled and reserved,
            # i.e. valid decode rows
            for uid in sorted(self.staged):
                self.live[uid] = self.staged.pop(uid)
        if not self.live:
            if self.waiting and not ids:
                self._handle_stuck(ev)
            return
        rowset = sorted(self.live)
        budgets = {u: self.live[u].budget for u in rowset}
        # one stage per row, bound to the rows most likely to free
        # first (smallest remaining budget)
        stage_map: dict[int, int] = {}
        if self.staged:
            by_budget = sorted(range(len(rowset)),
                               key=lambda i: budgets[rowset[i]])
            for i, su in zip(by_budget, sorted(self.staged)):
                stage_map[i] = su
        ops = self._serve_operands(rowset, budgets, stage_map)
        (tok_a, pos_a, tables, act_a, rem_a, row_keys, epoch,
         s_tok, s_pos, s_rem, s_keys, s_tab, s_valid,
         ring, ring_ep, ring_ptr) = ops[:16]
        # chain length from the max remaining budget (staged occupant
        # included). With eos_id set, rows may terminate early and the
        # tail dispatches of a chain become device no-ops (the
        # while_loop exits at step 0) — the launches still count in
        # host_dispatches, the honest price of speculative chaining;
        # EOS-heavy traffic should run a smaller chain depth. Checking
        # liveness before each launch would cost the per-dispatch host
        # sync this path exists to remove.
        eff = max(budgets[rowset[i]]
                  + (self.staged[stage_map[i]].budget
                     if i in stage_map else 0)
                  for i in range(len(rowset)))
        adv = self.k * (1 + self.draft_len)
        chain_len = max(1, min(self.depth, -(-eff // adv)))
        if self.waiting:
            # un-staged prompts are waiting for a host-side admission:
            # keep the chain short so they are not starved
            chain_len = 1
        # chain start: clock the drain interval from the first enqueue
        # (admission/prefill/idle time must not pollute tick stats)
        self._last_drain_t = time.perf_counter()
        if self.spec:
            hist_a, s_hist, sstat = ops[16:]
            carry = (tok_a, pos_a, tables, act_a, rem_a, row_keys,
                     hist_a, epoch, s_valid, ring, ring_ep, ring_ptr,
                     sstat)
            step_handles = []
            for j in range(chain_len):
                (tok_a, pos_a, tables, act_a, rem_a, row_keys, hist_a,
                 epoch, s_valid, ring, ring_ep, ring_ptr,
                 sstat) = carry
                dis_ops = (tok_a, pos_a, tables, act_a, rem_a,
                           row_keys, hist_a, epoch, s_tok, s_pos,
                           s_rem, s_keys, s_tab, s_hist, s_valid,
                           ring, ring_ep, ring_ptr, sstat)
                (ring, ring_ep, ring_ptr, steps, t2, p2, a2, r2, k2,
                 tb2, h2, ep2, sv2, sstat,
                 e.pools) = self._enqueue_chained(j, dis_ops, rowset,
                                                  tel)
                carry = (t2, p2, tb2, a2, r2, k2, h2, ep2, sv2, ring,
                         ring_ep, ring_ptr, sstat)
                step_handles.append(steps)
            self._drain_ring(ev, rowset, stage_map, ring, ring_ep,
                             ring_ptr, carry[7], step_handles, sstat)
            return
        carry = (tok_a, pos_a, tables, act_a, rem_a, row_keys, epoch,
                 s_valid)
        for j in range(chain_len):
            (tok_a, pos_a, tables, act_a, rem_a, row_keys, epoch,
             s_valid) = carry
            dis_ops = (tok_a, pos_a, tables, act_a, rem_a, row_keys,
                       epoch, s_tok, s_pos, s_rem, s_keys, s_tab,
                       s_valid, ring, ring_ep, ring_ptr)
            (ring, ring_ep, ring_ptr, t2, p2, a2, r2, k2, tb2, ep2,
             sv2, e.pools) = self._enqueue_chained(j, dis_ops, rowset,
                                                   tel)
            carry = (t2, p2, tb2, a2, r2, k2, ep2, sv2)
        self._drain_ring(ev, rowset, stage_map, ring, ring_ep, ring_ptr,
                         carry[6])

    def _enqueue_chained(self, j: int, dis_ops: tuple, rowset, tel):
        """One chained ring-mode enqueue, shared by the spec and
        non-spec loops so the per-dispatch discipline cannot drift:
        device-truth observation BEFORE the call (pools are donated),
        the enqueue span, the recompile-sentinel scope (``host``
        operands on the chain's first link, device ``carry``
        afterwards), and the dispatch counters. Returns ``self.fn``'s
        raw result tuple — arity differs between the executables, so
        unpacking stays at the call site."""
        e = self.e
        stats = e.serving_stats
        if tel is not None:
            e._device_truth_observe(tel, "v2/fused_dispatch", self.fn,
                                    dis_ops)
        with (tel.span("v2/fused_enqueue",
                       dispatch_id=stats["fused_dispatches"] + 1,
                       rows=len(rowset), k=self.k)
              if tel is not None else _NULLCM):
            with e._fused_dispatch_scope(
                    self._fn_key, dis_ops,
                    variant="carry" if j > 0 else "host"):
                res = self.fn(e.params, e.pools, *dis_ops)
        stats["host_dispatches"] += 1
        stats["fused_dispatches"] += 1
        if self._rt is not None:
            self._rt.dispatched(rowset, stats["fused_dispatches"],
                                k=self.k)
        return res

    def _drain_ring(self, ev, rowset, stage_map, ring, ring_ep,
                    ring_ptr, epoch_final, step_handles=None,
                    sstat=None) -> None:
        """ONE host read for the whole chain: ring tokens + epochs +
        final per-row epoch, attributed to each row's occupant
        timeline (epoch 0 = the row's original uid, epoch 1 = its
        staged request, swapped in in-graph). In spec mode
        ``ring_ptr`` is per-row [B] (variable advance), the executed
        tick counts arrive via ``step_handles`` and the chain's device
        spec counters via ``sstat``."""
        e, mgr, tel = self.e, self.e.state_manager, self._tel
        stats = e.serving_stats
        spec = step_handles is not None
        t_drain = time.perf_counter() if tel is not None else 0.0
        with (tel.span("v2/fused_drain", rows=len(rowset))
              if tel is not None else _NULLCM):
            with (e._hot_guard() if e._hot_guard is not None
                  else _NULLCM):
                # ONE blocking pull for the whole chain (four separate
                # np.asarray calls would pay the host<->device RTT
                # once each — exactly the cost this path removes)
                if spec:
                    toks, eps, ptrs, ep_fin, n_steps, st_arr = \
                        jax.device_get((ring, ring_ep, ring_ptr,
                                        epoch_final, step_handles,
                                        sstat))
                    e._absorb_spec_stats(st_arr)
                    n_exec = int(sum(int(s) for s in n_steps))
                else:
                    toks, eps, n_cols, ep_fin = jax.device_get(
                        (ring, ring_ep, ring_ptr, epoch_final))
                    n_cols = n_exec = int(n_cols)
        stats["fused_steps"] += n_exec
        stats["fused_slots"] += n_exec * len(rowset)
        now = time.perf_counter()
        win_start = self._last_drain_t     # chain-window open (ISSUE 10)
        self.drain_stats.append((now - self._last_drain_t, n_exec))
        self._last_drain_t = now
        self.counters["chain_drains"] += 1
        for i, u0 in enumerate(rowset):
            owners = [u0] + ([stage_map[i]] if i in stage_map else [])
            cols = int(ptrs[i]) if spec else n_cols
            for e_idx, uid in enumerate(owners):
                seg = [int(t) for t, ep in zip(toks[i, :cols],
                                               eps[i, :cols])
                       if ep == e_idx and t >= 0]
                staged = e_idx > 0
                req = (self.staged if staged else self.live).get(uid)
                if req is None or not seg:
                    continue
                mgr.commit_device_tokens(uid, seg)
                req.generated.extend(seg)
                stats["decoded_tokens"] += len(seg)
                stats["fused_slot_tokens"] += len(seg)
                if not spec:
                    # one token per live slot (spec live-slot counts
                    # came from the chain's device stats)
                    stats["fused_live_slots"] += len(seg)
                if self._lat is not None:
                    self._lat.tokens(uid, len(seg))
                if self._rt is not None:
                    self._rt.tokens_landed(uid, len(seg),
                                           window_start=win_start,
                                           steps=n_exec, row=i,
                                           epoch=e_idx)
                if uid not in self._cancelled:
                    ev.append(TokenEvent(uid, seg))
                if staged and int(ep_fin[i]) >= 1:
                    # the stage was consumed in-graph: the request now
                    # owns the row
                    self.live[uid] = self.staged.pop(uid)
                if (req.budget <= 0
                        or (self.eos is not None and seg[-1] == self.eos)
                        or uid in self._cancelled):
                    self._finish(uid, ev)
        if tel is not None:
            e._record_dispatch_telemetry(tel,
                                         time.perf_counter() - t_drain)

    def _serve_operands(self, rowset: list[int],
                        budgets: dict[int, int],
                        stage_map: dict[int, int]):
        """Host-side build of a ring-mode chain's operands: the PR 1
        fused operands (via the engine's own ``_fused_operands`` —
        pending==1 checks, reserve, bucketing, sentinel-padded key rows
        all shared) plus per-row staged token/position/budget/key/table
        operands and the zeroed output ring. Block tables are widened
        to ONE joint power-of-two width covering live AND staged rows
        (a staged table truncated below its own block count would
        silently clamp in-graph KV writes)."""
        from .engine_v2 import _bucket
        e, mgr, k = self.e, self.e.state_manager, self.k
        if self.spec:
            (tok_a, pos_a, tables, act_a, rem_a, row_keys,
             hist_a) = e._spec_operands(rowset, k, budgets, self.seed)
        else:
            (tok_a, pos_a, tables, act_a, rem_a,
             row_keys) = e._fused_operands(rowset, k, budgets, self.seed)
        seqs = [mgr.seqs[u] for u in rowset]
        bb = int(tok_a.shape[0])
        epoch = np.zeros((bb,), np.int32)
        s_tok = np.zeros((bb,), np.int32)
        s_pos = np.zeros((bb,), np.int32)
        s_rem = np.zeros((bb,), np.int32)
        s_valid = np.zeros((bb,), bool)
        max_blocks = max(len(s.blocks) for s in seqs)
        stage_tables: dict[int, np.ndarray] = {}
        for i, su in stage_map.items():
            sq = mgr.seqs[su]
            if sq.pending != 1:
                raise RuntimeError(
                    f"fused serve: staged sequence {su} must have "
                    f"exactly one pending token, got {sq.pending}")
            s_tok[i] = sq.tokens[-1]
            s_pos[i] = sq.seen
            s_rem[i] = self.staged[su].budget
            s_valid[i] = s_rem[i] > 0
            stage_tables[i] = mgr.block_table(sq)
            max_blocks = max(max_blocks, len(sq.blocks))
        kb = min(_bucket(max(max_blocks, 1)), mgr.max_blocks_per_seq)
        if kb > tables.shape[1]:
            # a staged sequence holds more blocks than the live rows:
            # re-stack at the joint width (narrower would clamp its
            # in-graph writes onto the wrong block)
            t_np = np.stack([mgr.block_table(s) for s in seqs]
                            + [mgr.block_table(seqs[0])]
                            * (bb - len(seqs)))
            tables = jnp.asarray(t_np[:, :kb])
        else:
            kb = tables.shape[1]
        fallback = np.full((mgr.max_blocks_per_seq,),
                           mgr.allocator.num_blocks, np.int32)
        s_tab = np.stack([stage_tables.get(i, fallback)
                          for i in range(bb)])[:, :kb]
        base = e._base_key(self.seed)
        s_ids = jnp.asarray(np.asarray(
            [stage_map.get(i, (1 << 30) + bb + i) for i in range(bb)],
            np.uint32))
        s_keys = jax.vmap(lambda u: jax.random.fold_in(base, u))(s_ids)
        ring = np.full((bb, self.ring_cap), -1, np.int32)
        base = (tok_a, pos_a, tables, act_a, rem_a, row_keys,
                jnp.asarray(epoch), jnp.asarray(s_tok),
                jnp.asarray(s_pos), jnp.asarray(s_rem), s_keys,
                jnp.asarray(s_tab), jnp.asarray(s_valid),
                jnp.asarray(ring), jnp.asarray(ring))
        if not self.spec:
            return base + (jnp.asarray(0, jnp.int32),)
        # spec extras: per-row ring pointers (variable advance), each
        # staged request's own drafter history, and the chain's device
        # spec counters (proposed/accepted/hit) zeroed at chain start
        hw = int(e._config.speculative.history_window)
        s_hist = np.full((bb, hw), -1, np.int32)
        for i, su in stage_map.items():
            s_hist[i] = mgr.history_tail(su, hw)
        return base + (jnp.asarray(np.zeros((bb,), np.int32)),
                       hist_a, jnp.asarray(s_hist),
                       jnp.asarray(np.zeros((4,), np.int32)))
