"""Blocked (paged) KV cache + ragged batch bookkeeping (reference:
inference/v2/ragged/ — DSStateManager (ragged_manager.py:19) owns a pool
of fixed-size KV blocks and per-sequence page tables; RaggedBatchWrapper
(ragged_wrapper.py:31) packs every scheduled sequence's tokens into one
flat batch; the blocked allocator gates admission (engine_v2.py
query/can_schedule:158/:184)).

TPU translation: the pool is one device array per k/v with layout
``[L, num_blocks, block_size, H_kv, D]``; page tables and sequence
descriptors are host-side numpy (they change every step — keeping them off
the compiled path avoids recompiles); attention reads the pool through the
page table (paged.py). Shapes entering XLA are bucketed, not ragged.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np


@dataclass
class SequenceDescriptor:
    """reference: ragged/sequence_descriptor.py"""
    uid: int
    tokens: list[int]                    # full token history (prompt+gen)
    seen: int = 0                        # tokens already in the KV cache
    blocks: list[int] = field(default_factory=list)
    done: bool = False

    @property
    def pending(self) -> int:
        return len(self.tokens) - self.seen


class BlockedAllocator:
    """Fixed-pool block allocator (reference:
    ragged/blocked_allocator.py — free-list over num_blocks)."""

    def __init__(self, num_blocks: int):
        self.num_blocks = num_blocks
        self._free = list(range(num_blocks - 1, -1, -1))

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def allocate(self, n: int) -> list[int]:
        if n > len(self._free):
            raise RuntimeError(
                f"KV pool exhausted: want {n} blocks, {len(self._free)} free")
        return [self._free.pop() for _ in range(n)]

    def free(self, blocks: list[int]) -> None:
        self._free.extend(blocks)


class DSStateManager:
    """Sequence tracking + block accounting (reference:
    ragged/ragged_manager.py:19)."""

    def __init__(self, block_size: int, num_blocks: int,
                 max_blocks_per_seq: int):
        self.block_size = block_size
        self.allocator = BlockedAllocator(num_blocks)
        self.max_blocks_per_seq = max_blocks_per_seq
        self.seqs: dict[int, SequenceDescriptor] = {}

    def get_or_create(self, uid: int) -> SequenceDescriptor:
        if uid not in self.seqs:
            self.seqs[uid] = SequenceDescriptor(uid=uid, tokens=[])
        return self.seqs[uid]

    def blocks_needed(self, seq: SequenceDescriptor, new_tokens: int) -> int:
        total = len(seq.tokens) + new_tokens
        need = -(-total // self.block_size)  # ceil
        return max(0, need - len(seq.blocks))

    def can_schedule(self, uid: int, new_tokens: int) -> bool:
        """reference: engine_v2.can_schedule:184"""
        seq = self.seqs.get(uid) or SequenceDescriptor(uid=uid, tokens=[])
        need = self.blocks_needed(seq, new_tokens)
        total_blocks = len(seq.blocks) + need
        return (need <= self.allocator.free_blocks
                and total_blocks <= self.max_blocks_per_seq)

    def extend(self, uid: int, tokens: list[int]) -> SequenceDescriptor:
        """Append tokens to a sequence, allocating blocks to cover them."""
        seq = self.get_or_create(uid)
        need = self.blocks_needed(seq, len(tokens))
        if len(seq.blocks) + need > self.max_blocks_per_seq:
            raise RuntimeError(
                f"sequence {uid} exceeds max length "
                f"({self.max_blocks_per_seq * self.block_size} tokens)")
        seq.blocks.extend(self.allocator.allocate(need))
        seq.tokens.extend(int(t) for t in tokens)
        return seq

    def reserve(self, uid: int, future_tokens: int) -> int:
        """Preallocate blocks so the sequence can grow by
        ``future_tokens`` WITHOUT further allocation. Required before a
        fused decode dispatch: its in-graph KV writes advance through
        the block table with no host in the loop, so every position the
        device may write must already map to a real block. Idempotent —
        only the missing delta is allocated. Returns the number of
        blocks newly allocated."""
        seq = self.seqs[uid]
        need = self.blocks_needed(seq, future_tokens)
        if need == 0:
            return 0
        if len(seq.blocks) + need > self.max_blocks_per_seq:
            raise RuntimeError(
                f"sequence {uid}: reserving {future_tokens} future tokens "
                f"exceeds the max length "
                f"({self.max_blocks_per_seq * self.block_size} tokens)")
        seq.blocks.extend(self.allocator.allocate(need))
        return need

    def commit_device_tokens(self, uid: int, tokens: list[int]) -> None:
        """Append tokens a fused dispatch generated ON DEVICE. Their KV
        entries (all but the last token's) were already written in-graph,
        so ``seen`` advances with the history: afterwards exactly the
        last generated token is pending — it is the next dispatch's
        input. Blocks must have been preallocated via :meth:`reserve`
        (the device wrote through them)."""
        if not tokens:
            return
        seq = self.seqs[uid]
        if seq.pending != 1:
            raise RuntimeError(
                f"sequence {uid}: commit_device_tokens expects exactly "
                f"one pending token (the dispatch input), got "
                f"{seq.pending}")
        total = len(seq.tokens) + len(tokens)
        if -(-total // self.block_size) > len(seq.blocks):
            raise RuntimeError(
                f"sequence {uid}: device wrote past its reserved blocks "
                f"({total} tokens, {len(seq.blocks)} blocks) — reserve() "
                "was not called before the fused dispatch")
        seq.tokens.extend(int(t) for t in tokens)
        seq.seen += len(tokens)

    def flush(self, uid: int) -> None:
        """Release a finished sequence (reference: engine_v2.flush:242)."""
        seq = self.seqs.pop(uid, None)
        if seq is not None:
            self.allocator.free(seq.blocks)

    def block_table(self, seq: SequenceDescriptor) -> np.ndarray:
        """Padded [max_blocks_per_seq] table; unused entries point past the
        pool (scatter mode='drop' discards writes through them)."""
        t = np.full((self.max_blocks_per_seq,),
                    self.allocator.num_blocks, np.int32)
        t[:len(seq.blocks)] = seq.blocks
        return t
