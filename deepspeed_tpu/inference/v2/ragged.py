"""Blocked (paged) KV cache + ragged batch bookkeeping (reference:
inference/v2/ragged/ — DSStateManager (ragged_manager.py:19) owns a pool
of fixed-size KV blocks and per-sequence page tables; RaggedBatchWrapper
(ragged_wrapper.py:31) packs every scheduled sequence's tokens into one
flat batch; the blocked allocator gates admission (engine_v2.py
query/can_schedule:158/:184)).

TPU translation: the pool is one device array per k/v with layout
``[L, num_blocks, block_size, H_kv, D]``; page tables and sequence
descriptors are host-side numpy (they change every step — keeping them off
the compiled path avoids recompiles); attention reads the pool through the
page table (paged.py). Shapes entering XLA are bucketed, not ragged.

Automatic prefix caching (ISSUE 4): the allocator is REF-COUNTED and a
hash-chained :class:`PrefixCache` indexes every *full* block by
``(parent_chain_hash, block_tokens)``. A new sequence whose leading
tokens match a cached chain shares those blocks (refcount bump) and
skips their prefill entirely; ``flush()`` dec-refs, parking cached
blocks whose refcount hits zero in an LRU pool that is evicted only
when an allocation would otherwise fail. Tail/partial blocks are always
privately allocated — decode only ever writes positions >= ``seen``,
which by construction live in a sequence's own private blocks, so
sharing needs no copy-on-write. Everything here is host-side
python/numpy; block *sharing* is free at the kernel level because paged
attention already reads KV strictly through per-sequence block tables.
"""

from __future__ import annotations

import json
import struct
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

# cumulative prefix-cache counters, exposed 1:1 through
# InferenceEngineV2.serving_metrics() and telemetry.bridges — one
# schema, three consumers (engine reset, bridges, bench), no drift.
PREFIX_STAT_KEYS = ("prefix_hits", "prefix_misses", "prefix_evictions",
                    "prefill_tokens_saved")

# chain seed for the root of every block-hash chain (arbitrary odd
# constant; only equality matters)
_CHAIN_ROOT = 0x9E3779B97F4A7C15


def kv_block_bytes(block_size: int, num_kv_heads: int, head_dim: int,
                   payload_itemsize: float,
                   scale_heads: int = 0) -> int:
    """HBM bytes ONE block costs per layer, k+v pools together:
    payload plus (for quantized pools) the f32 per-vector scale slab
    riding the same block index. The allocator deals in blocks; this
    is the block -> bytes conversion every sizing/telemetry consumer
    shares (engine pool build, ``ds_kv_pool_bytes``, the bench
    ``kvquant`` stage)."""
    payload = block_size * num_kv_heads * head_dim * payload_itemsize
    scales = block_size * scale_heads * 4
    return int(2 * (payload + scales))


def quantized_block_budget(num_blocks: int, full_block_bytes: int,
                           quant_block_bytes: int) -> int:
    """Blocks the QUANTIZED pool may hold inside the HBM budget of
    ``num_blocks`` full-precision blocks (ISSUE 12: the allocator is
    sized in quantized bytes, so the same budget yields 2-4x more
    resident blocks — never fewer than configured)."""
    return max(int(num_blocks),
               int(num_blocks) * int(full_block_bytes)
               // max(int(quant_block_bytes), 1))


# cross-mesh KV migration wire format (ISSUE 13). Version bumps on any
# layout change — import refuses a mismatched version outright.
MIGRATION_WIRE_VERSION = 1


def _resolve_dtype(name: str) -> np.dtype:
    """numpy dtype from its name, falling back to the ml_dtypes
    extension types (float8_e4m3fn etc.) jax registers — the pool
    payload of a quantized engine travels in exactly its storage
    dtype, never dequantized."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


@dataclass
class KVExportState:
    """One sequence's serialized KV block set — the unit of cross-mesh
    migration (ISSUE 13): ``DSStateManager.park()`` generalized so the
    KV BYTES travel with the token history instead of being recomputed.

    ``payload`` holds the sequence's full-and-tail blocks gathered from
    the exporting engine's pools, one array per pool slab keyed exactly
    like ``InferenceEngineV2.pools`` (``k``/``v`` payload, plus
    ``ks``/``vs`` scale slabs on quantized engines) with the pool's
    block axis narrowed to this sequence's blocks: quantized codes and
    their write-once scales travel AS-IS — no dequantize leg, so the
    wire cost is ``kv_bytes_per_token`` of the storage format, the
    whole point of migrating after PR 12. Import is position-exact:
    the sequence resumes on the importing engine with identical
    ``tokens``/``seen``/pool bytes, so greedy continuation is
    bit-identical to never having moved.

    ``n_generated`` splits ``tokens`` into prompt and
    already-generated suffix (the pending token last); the importing
    scheduler seeds its request bookkeeping from it."""
    tokens: list[int]
    n_generated: int
    seen: int
    block_size: int
    kv_dtype: str
    payload: dict[str, np.ndarray]
    handoff_id: Optional[int] = None      # blocksan transit tag
    source: str = ""                      # exporting engine/replica

    @property
    def prompt_tokens(self) -> list[int]:
        return self.tokens[:len(self.tokens) - self.n_generated]

    @property
    def generated_tokens(self) -> list[int]:
        return self.tokens[len(self.tokens) - self.n_generated:]

    @property
    def payload_blocks(self) -> int:
        """Blocks of KV payload travelling (the pending token's block
        tail is re-reserved on import, not shipped empty)."""
        return int(next(iter(self.payload.values())).shape[1]) \
            if self.payload else 0

    @property
    def payload_bytes(self) -> int:
        """Wire bytes of the KV payload (scale slabs included) — the
        figure the migration-cost assertion compares against
        ``kv_bytes_per_token``."""
        return int(sum(a.nbytes for a in self.payload.values()))

    def bytes_per_token(self) -> float:
        """Payload bytes per migrated KV token (block granularity —
        the tail block ships whole, like it is stored)."""
        toks = self.payload_blocks * self.block_size
        return self.payload_bytes / max(toks, 1)

    # -- wire format ---------------------------------------------------
    def to_bytes(self) -> bytes:
        """One self-describing buffer: little-endian u32 header length,
        JSON header (token history, layout, per-array name/shape/dtype
        manifest), then the raw array bytes in manifest order. Arrays
        round-trip bit-exactly in their storage dtype."""
        arrays = [(k, self.payload[k]) for k in sorted(self.payload)]
        header = json.dumps({
            "version": MIGRATION_WIRE_VERSION,
            "tokens": [int(t) for t in self.tokens],
            "n_generated": int(self.n_generated),
            "seen": int(self.seen),
            "block_size": int(self.block_size),
            "kv_dtype": self.kv_dtype,
            "source": self.source,
            "handoff_id": self.handoff_id,
            "arrays": [{"name": k, "shape": list(a.shape),
                        "dtype": a.dtype.name} for k, a in arrays],
        }).encode()
        parts = [struct.pack("<I", len(header)), header]
        parts += [np.ascontiguousarray(a).tobytes() for _, a in arrays]
        return b"".join(parts)

    @classmethod
    def from_bytes(cls, buf: bytes) -> "KVExportState":
        (hlen,) = struct.unpack_from("<I", buf, 0)
        head = json.loads(buf[4:4 + hlen].decode())
        if head["version"] != MIGRATION_WIRE_VERSION:
            raise ValueError(
                f"KV migration wire version {head['version']} != "
                f"{MIGRATION_WIRE_VERSION} — refusing a cross-version "
                "import")
        off = 4 + hlen
        payload = {}
        for spec in head["arrays"]:
            dt = _resolve_dtype(spec["dtype"])
            n = int(np.prod(spec["shape"])) * dt.itemsize
            payload[spec["name"]] = np.frombuffer(
                buf[off:off + n], dtype=dt).reshape(spec["shape"])
            off += n
        return cls(tokens=head["tokens"],
                   n_generated=head["n_generated"], seen=head["seen"],
                   block_size=head["block_size"],
                   kv_dtype=head["kv_dtype"], payload=payload,
                   handoff_id=head.get("handoff_id"),
                   source=head.get("source", ""))


@dataclass
class SequenceDescriptor:
    """reference: ragged/sequence_descriptor.py"""
    uid: int
    tokens: list[int]                    # full token history (prompt+gen)
    seen: int = 0                        # tokens already in the KV cache
    blocks: list[int] = field(default_factory=list)
    done: bool = False
    # prefix-cache chain state: hash of the chain after `published` full
    # blocks (blocks matched at admission arrive already published)
    cached_key: int = _CHAIN_ROOT
    published: int = 0

    @property
    def pending(self) -> int:
        return len(self.tokens) - self.seen


class BlockedAllocator:
    """Fixed-pool REF-COUNTED block allocator (reference:
    ragged/blocked_allocator.py — free-list over num_blocks, grown here
    with per-block refcounts so prefix-cached blocks can be shared
    across sequences). ``evict_source`` (set by :class:`DSStateManager`
    when prefix caching is on) is asked to surrender one cached-but-
    unreferenced block at a time when the free list runs short — cached
    blocks are evicted only when an allocation would otherwise fail."""

    def __init__(self, num_blocks: int):
        self.num_blocks = num_blocks
        self._free = list(range(num_blocks - 1, -1, -1))
        self._ref = [0] * num_blocks
        self.evict_source = None        # () -> Optional[int]
        # opt-in block-accounting sanitizer (ISSUE 11,
        # analysis/blocksan.py): every hook below is behind an
        # attribute-load guard, so the disabled path is untouched
        self.sanitizer = None

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def refcount(self, block: int) -> int:
        return self._ref[block]

    def allocate(self, n: int) -> list[int]:
        while n > len(self._free) and self.evict_source is not None:
            b = self.evict_source()
            if b is None:
                break
            # route the evicted block through free() — the ONE way
            # blocks return to the free list, so the sanitizer sees
            # every transition (no raw _free.append path exists)
            self.free([b])
        if n > len(self._free):
            raise RuntimeError(
                f"KV pool exhausted: want {n} blocks, {len(self._free)} free")
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        if self.sanitizer is not None:
            self.sanitizer.on_allocate(out)
        return out

    def incref(self, blocks) -> None:
        if self.sanitizer is not None:
            self.sanitizer.on_incref(blocks)
        for b in blocks:
            self._ref[b] += 1

    def decref(self, blocks) -> list[int]:
        """Drop one reference per block; returns the blocks that reached
        refcount zero (NOT freed — the caller routes them to the free
        list or the prefix cache's LRU pool)."""
        if self.sanitizer is not None:
            self.sanitizer.on_decref(blocks)
        zeros = []
        for b in blocks:
            self._ref[b] -= 1
            if self._ref[b] <= 0:
                self._ref[b] = 0
                zeros.append(b)
        return zeros

    def free(self, blocks: list[int]) -> None:
        """Raw return to the free list (refcounts cleared)."""
        if self.sanitizer is not None:
            self.sanitizer.on_free(blocks)
        for b in blocks:
            self._ref[b] = 0
        self._free.extend(blocks)


class PrefixCache:
    """Hash-chained index of FULL KV blocks for automatic prefix reuse
    (the vLLM/FastGen automatic-prefix-caching scheme, host-side only).

    Every full block is keyed by ``(parent_hash, tuple(block_tokens))``
    where ``parent_hash`` summarizes the whole ancestor chain
    (``hash`` of the parent's key) — two prefixes that share a block's
    tokens but differ anywhere earlier in the chain get distinct keys,
    and the dict compares the current block's tokens by equality, so a
    match is collision-safe up to a hash collision over the *full*
    parent chain. Blocks with refcount zero stay indexed and parked in
    an LRU; they count as allocatable headroom and are evicted
    oldest-first only when an allocation needs them (or when
    ``max_cached_blocks`` caps the index)."""

    def __init__(self, block_size: int, min_match_blocks: int = 1,
                 max_cached_blocks: int = 0):
        self.block_size = block_size
        self.min_match_blocks = max(1, int(min_match_blocks))
        self.max_cached_blocks = int(max_cached_blocks)   # 0 = pool-bounded
        self.index: dict[tuple, int] = {}     # (parent, tokens) -> block
        self.block_key: dict[int, tuple] = {}
        self.lru: "OrderedDict[int, None]" = OrderedDict()  # ref==0 blocks
        self.stats = dict.fromkeys(PREFIX_STAT_KEYS, 0)
        # where cap-evicted blocks go (set by DSStateManager to the
        # allocator's free list) — an evicted block is on neither the
        # free list nor the index, so dropping it would leak it
        self.free_sink = None               # (block: int) -> None
        # opt-in block-accounting sanitizer (ISSUE 11); see allocator
        self.sanitizer = None

    @property
    def cached_blocks(self) -> int:
        return len(self.index)

    @property
    def evictable_blocks(self) -> int:
        return len(self.lru)

    def reset_stats(self) -> None:
        for k in self.stats:
            self.stats[k] = 0

    def match(self, tokens: list[int], limit_blocks: int) -> list[tuple]:
        """Longest cached chain over the first ``limit_blocks`` full
        blocks of ``tokens``; returns ``[(key, block), ...]`` (empty
        when shorter than ``min_match_blocks``). Pure query — no
        refcount or stats mutation."""
        bs = self.block_size
        parent = _CHAIN_ROOT
        out: list[tuple] = []
        for i in range(limit_blocks):
            key = (parent, tuple(tokens[i * bs:(i + 1) * bs]))
            blk = self.index.get(key)
            if blk is None:
                break
            out.append((key, blk))
            parent = hash(key)
        if len(out) < self.min_match_blocks:
            return []
        return out

    def publish(self, parent: int, block_tokens: tuple,
                block: int) -> int:
        """Index one freshly-computed full block under its chain key;
        returns the child chain hash. First publisher wins (a concurrent
        duplicate keeps its block private); at ``max_cached_blocks`` an
        unreferenced LRU block is evicted to make room and returned to
        the allocator via ``free_sink``, and if nothing is evictable the
        publication is skipped (the chain hash still advances, so later
        blocks stay publishable)."""
        key = (parent, block_tokens)
        if key not in self.index:
            if (self.max_cached_blocks > 0
                    and len(self.index) >= self.max_cached_blocks):
                evicted = self.evict_one()
                if evicted is None:
                    return hash(key)
                # the evicted block is refcount-0 and was parked OFF the
                # allocator's free list — hand it back or it leaks
                if self.free_sink is not None:
                    self.free_sink(evicted)
            self.index[key] = block
            self.block_key[block] = key
        return hash(key)

    def release(self, block: int) -> bool:
        """A block's refcount hit zero: park it (most-recently-used) if
        it is indexed; returns False when the block is uncached and the
        caller should return it to the free list."""
        if block not in self.block_key:
            return False
        if self.sanitizer is not None:
            self.sanitizer.on_cache_park(block)
        self.lru[block] = None
        self.lru.move_to_end(block)
        return True

    def evict_one(self) -> Optional[int]:
        """Drop the least-recently-used unreferenced cached block from
        the index; returns its id (now plain free) or None."""
        if not self.lru:
            return None
        block, _ = self.lru.popitem(last=False)
        del self.index[self.block_key.pop(block)]
        self.stats["prefix_evictions"] += 1
        if self.sanitizer is not None:
            self.sanitizer.on_cache_evict(block)
        return block


class DSStateManager:
    """Sequence tracking + block accounting (reference:
    ragged/ragged_manager.py:19)."""

    def __init__(self, block_size: int, num_blocks: int,
                 max_blocks_per_seq: int,
                 prefix_cache: Optional[PrefixCache] = None):
        self.block_size = block_size
        self.allocator = BlockedAllocator(num_blocks)
        self.max_blocks_per_seq = max_blocks_per_seq
        self.seqs: dict[int, SequenceDescriptor] = {}
        self.cache = prefix_cache
        self.sanitizer = None           # ISSUE 11; attach_sanitizer
        if prefix_cache is not None:
            self.allocator.evict_source = prefix_cache.evict_one
            prefix_cache.free_sink = self._free_sink

    def _free_sink(self, block: int) -> None:
        """Cap-path eviction outlet (PrefixCache.publish): routes the
        evicted refcount-zero block through ``allocator.free`` — the
        sanitizer-audited choke every freed block passes — so the PR 4
        cap-path leak class is structurally impossible (there is no
        second way out of the index)."""
        self.allocator.free([block])

    def attach_sanitizer(self, san) -> None:
        """Wire the opt-in KV block-accounting sanitizer (ISSUE 11,
        analysis/blocksan.py) into every accounting mutation point:
        the allocator's allocate/free/incref/decref, the prefix
        cache's LRU park/evict, and this manager's quiesce points
        (flush/park conservation checks)."""
        self.sanitizer = san
        self.allocator.sanitizer = san
        if self.cache is not None:
            self.cache.sanitizer = san

    def _quiesce(self, label: str) -> None:
        """Conservation check at a quiesce point: free + referenced +
        LRU-cached must partition the pool (no-op with the sanitizer
        detached)."""
        if self.sanitizer is not None:
            self.sanitizer.check_conservation(self.allocator, self.cache,
                                              label)

    @property
    def available_blocks(self) -> int:
        """Allocatable headroom: truly free blocks plus cached blocks
        with refcount zero (the allocator evicts those on demand) — the
        admission-math notion of "free" once prefix caching is on."""
        free = self.allocator.free_blocks
        if self.cache is not None:
            free += self.cache.evictable_blocks
        return free

    def get_or_create(self, uid: int) -> SequenceDescriptor:
        if uid not in self.seqs:
            self.seqs[uid] = SequenceDescriptor(uid=uid, tokens=[])
        return self.seqs[uid]

    def blocks_needed(self, seq: SequenceDescriptor, new_tokens: int) -> int:
        total = len(seq.tokens) + new_tokens
        need = -(-total // self.block_size)  # ceil
        return max(0, need - len(seq.blocks))

    # ------------------------------------------------------------------
    # prefix cache plumbing
    def _match_limit(self, n_tokens: int) -> int:
        """Full blocks a fresh admission of ``n_tokens`` may reuse: at
        least one token must stay pending (the forward that yields the
        next-token logits), so a fully-cached prompt still matches only
        up to its last block boundary before token n-1."""
        return min(max(n_tokens - 1, 0) // self.block_size,
                   self.max_blocks_per_seq)

    def prefix_match(self, tokens) -> list[tuple]:
        """Longest cached chain a FRESH sequence with these tokens would
        reuse (``[(key, block), ...]``); pure query — admission uses
        :meth:`pin_prefix` + :meth:`extend` to act on it."""
        if self.cache is None:
            return []
        return self.cache.match([int(t) for t in tokens],
                                self._match_limit(len(tokens)))

    def admission_cost(self, tokens, full_need: int) -> int:
        """Blocks a fresh admission of ``tokens`` with a worst-case
        budget of ``full_need`` consumes from :attr:`available_blocks`:
        blocks to allocate (cache hits subtracted) plus parked hits the
        match pins out of the evictable pool — already-referenced hits
        are free. Used by the drivers' admission headroom math."""
        hits = self.prefix_match(tokens)
        return (full_need - len(hits)
                + sum(1 for _, b in hits
                      if self.allocator.refcount(b) == 0))

    def pin_prefix(self, matches: list[tuple]) -> None:
        """Take a reference on each matched block (pulling parked ones
        out of the LRU) so a concurrent allocation cannot evict them
        between the admission check and :meth:`extend`."""
        for _, b in matches:
            if self.allocator.refcount(b) == 0:
                self.cache.lru.pop(b, None)
            self.allocator.incref((b,))

    def unpin_prefix(self, matches: list[tuple]) -> None:
        self._release_blocks([b for _, b in matches])

    def _release_blocks(self, blocks: list[int]) -> None:
        """THE free-routing choke point (ISSUE 11 satellite): every
        release — flush, park, unpin — is decref, then the prefix
        cache's LRU park for indexed blocks, then ``allocator.free``
        for the rest; the cap path reaches ``free`` through
        :meth:`_free_sink`. No other route returns blocks, which is
        what lets blocksan audit the whole lifecycle at four hooks."""
        zeros = self.allocator.decref(blocks)
        if self.cache is not None:
            zeros = [b for b in zeros if not self.cache.release(b)]
        if zeros:
            self.allocator.free(zeros)

    def publish_full_blocks(self, seq: SequenceDescriptor) -> None:
        """Index every newly-completed full block of ``seq`` (called
        wherever ``seen`` advances — the block's KV is then entirely in
        the pool, and the sequence never writes at positions < seen, so
        sharing it is hazard-free). No-op with caching off."""
        if self.cache is None:
            return
        full = min(seq.seen // self.block_size, len(seq.blocks))
        while seq.published < full:
            i = seq.published
            toks = tuple(seq.tokens[i * self.block_size:
                                    (i + 1) * self.block_size])
            seq.cached_key = self.cache.publish(seq.cached_key, toks,
                                                seq.blocks[i])
            seq.published += 1

    def prefix_cache_metrics(self) -> dict:
        """Counters + occupancy gauges for serving_metrics() — zeros
        with caching off so consumers see one stable schema."""
        if self.cache is None:
            m = dict.fromkeys(PREFIX_STAT_KEYS, 0)
            m.update(prefix_hit_rate=0.0, prefix_cached_blocks=0,
                     prefix_evictable_blocks=0)
            return m
        m = dict(self.cache.stats)
        looked = m["prefix_hits"] + m["prefix_misses"]
        m["prefix_hit_rate"] = m["prefix_hits"] / max(looked, 1)
        m["prefix_cached_blocks"] = self.cache.cached_blocks
        m["prefix_evictable_blocks"] = self.cache.evictable_blocks
        return m

    def reset_prefix_stats(self) -> None:
        if self.cache is not None:
            self.cache.reset_stats()

    # ------------------------------------------------------------------
    def can_schedule(self, uid: int, new_tokens: int) -> bool:
        """reference: engine_v2.can_schedule:184 (cached-but-unreferenced
        blocks count as allocatable headroom)."""
        seq = self.seqs.get(uid) or SequenceDescriptor(uid=uid, tokens=[])
        need = self.blocks_needed(seq, new_tokens)
        total_blocks = len(seq.blocks) + need
        return (need <= self.available_blocks
                and total_blocks <= self.max_blocks_per_seq)

    def extend(self, uid: int, tokens: list[int],
               pinned: Optional[list[tuple]] = None) -> SequenceDescriptor:
        """Append tokens to a sequence, allocating blocks to cover them.

        A FRESH sequence first walks the prefix cache: the longest
        cached chain of full blocks is shared (refcount bump via
        ``pinned``, or matched+pinned here) and those tokens marked
        ``seen`` — chunked prefill and the fused-dispatch position math
        skip them entirely. The remainder (always including the tail /
        partial block) is privately allocated."""
        seq = self.get_or_create(uid)
        fresh = not seq.tokens and not seq.blocks and seq.seen == 0
        matches: list[tuple] = []
        own_pin = False
        if self.cache is not None and fresh:
            if pinned is not None:
                matches = pinned
            else:
                matches = self.prefix_match(tokens)
                own_pin = bool(matches)
        total_blocks = -(-(len(seq.tokens) + len(tokens))
                         // self.block_size)
        if total_blocks > self.max_blocks_per_seq:
            if pinned:
                self.unpin_prefix(pinned)
            raise RuntimeError(
                f"sequence {uid} exceeds max length "
                f"({self.max_blocks_per_seq * self.block_size} tokens)")
        if own_pin:
            self.pin_prefix(matches)
        try:
            fresh_blocks = self.allocator.allocate(
                max(0, total_blocks - len(seq.blocks) - len(matches)))
        except RuntimeError:
            if matches:
                self.unpin_prefix(matches)
            raise
        if matches:
            seq.blocks.extend(b for _, b in matches)
            seq.seen = len(matches) * self.block_size
            seq.published = len(matches)
            seq.cached_key = hash(matches[-1][0])
            self.cache.stats["prefill_tokens_saved"] += seq.seen
        if self.cache is not None and fresh:
            limit = self._match_limit(len(tokens))
            if limit > 0:
                self.cache.stats["prefix_hits"] += len(matches)
                self.cache.stats["prefix_misses"] += limit - len(matches)
        seq.blocks.extend(fresh_blocks)
        seq.tokens.extend(int(t) for t in tokens)
        return seq

    def reserve(self, uid: int, future_tokens: int) -> int:
        """Preallocate blocks so the sequence can grow by
        ``future_tokens`` WITHOUT further allocation. Required before a
        fused decode dispatch: its in-graph KV writes advance through
        the block table with no host in the loop, so every position the
        device may write must already map to a real block. Idempotent —
        only the missing delta is allocated. Returns the number of
        blocks newly allocated."""
        seq = self.seqs[uid]
        need = self.blocks_needed(seq, future_tokens)
        if need == 0:
            return 0
        if len(seq.blocks) + need > self.max_blocks_per_seq:
            raise RuntimeError(
                f"sequence {uid}: reserving {future_tokens} future tokens "
                f"exceeds the max length "
                f"({self.max_blocks_per_seq * self.block_size} tokens)")
        seq.blocks.extend(self.allocator.allocate(need))
        return need

    def history_tail(self, uid: int, window: int) -> np.ndarray:
        """The last ``window`` committed tokens (pending token
        included), RIGHT-aligned in a [window] int32 row with -1
        filling unused leading columns — the prompt-lookup drafter's
        seed (ISSUE 9). Prefix-cache-matched prompt blocks are part of
        ``seq.tokens`` like any other committed token, so a cache-hit
        admission seeds the same drafting window a cold one would."""
        row = np.full((window,), -1, np.int32)
        toks = self.seqs[uid].tokens[-window:]
        if toks:
            row[window - len(toks):] = toks
        return row

    def commit_device_tokens(self, uid: int, tokens: list[int]) -> None:
        """Append tokens a fused dispatch generated ON DEVICE. Their KV
        entries (all but the last token's) were already written in-graph,
        so ``seen`` advances with the history: afterwards exactly the
        last generated token is pending — it is the next dispatch's
        input. Blocks must have been preallocated via :meth:`reserve`
        (the device wrote through them).

        The commit length is VARIABLE (ISSUE 9): a speculative dispatch
        lands 1..1+draft_len tokens per row per tick, so callers pass
        whatever the device's per-row write pointer says — the only
        invariants are the single pending input before the call and
        the reserved block horizon covering the advance."""
        if not tokens:
            return
        seq = self.seqs[uid]
        if seq.pending != 1:
            raise RuntimeError(
                f"sequence {uid}: commit_device_tokens expects exactly "
                f"one pending token (the dispatch input), got "
                f"{seq.pending}")
        total = len(seq.tokens) + len(tokens)
        if -(-total // self.block_size) > len(seq.blocks):
            raise RuntimeError(
                f"sequence {uid}: device wrote past its reserved blocks "
                f"({total} tokens, {len(seq.blocks)} blocks) — reserve() "
                "was not called before the fused dispatch")
        seq.tokens.extend(int(t) for t in tokens)
        seq.seen += len(tokens)
        self.publish_full_blocks(seq)

    def flush(self, uid: int) -> None:
        """Release a finished sequence (reference: engine_v2.flush:242):
        dec-ref its blocks; cached blocks reaching refcount zero are
        parked in the LRU pool instead of freed."""
        seq = self.seqs.pop(uid, None)
        if seq is not None:
            self._release_blocks(seq.blocks)
            self._quiesce("flush")

    def import_sequence(self, uid: int, tokens: list[int], seen: int,
                        payload_blocks: int) -> SequenceDescriptor:
        """Accounting half of a cross-mesh KV import (ISSUE 13):
        allocate blocks covering the FULL migrated history (the engine
        scatters the payload into the first ``payload_blocks`` of
        them), rebuild the descriptor position-exactly, and RE-PUBLISH
        the sequence's full blocks into this manager's prefix cache —
        the importing replica's cache warms with the migrated chain, so
        follow-up same-prefix traffic lands warm here (the router's
        affinity key). Raises before any allocation when the sequence
        cannot fit; the caller owns payload transfer and quiesce."""
        uid = int(uid)
        if uid in self.seqs:
            raise RuntimeError(
                f"import_sequence: uid {uid} already live on this "
                "engine — migrated uids must be fresh")
        tokens = [int(t) for t in tokens]
        seen = int(seen)
        if not tokens or seen != len(tokens) - 1:
            raise RuntimeError(
                f"import_sequence: uid {uid} must arrive with exactly "
                f"one pending token (seen {seen}, {len(tokens)} tokens)"
                " — export happens at a dispatch boundary")
        n_total = -(-len(tokens) // self.block_size)
        if payload_blocks > n_total:
            raise RuntimeError(
                f"import_sequence: uid {uid} ships {payload_blocks} "
                f"payload blocks for a {n_total}-block history")
        if n_total > self.max_blocks_per_seq:
            raise RuntimeError(
                f"import_sequence: uid {uid} needs {n_total} blocks, "
                f"max {self.max_blocks_per_seq}")
        blocks = self.allocator.allocate(n_total)
        seq = SequenceDescriptor(uid=uid, tokens=tokens, seen=seen,
                                 blocks=blocks)
        self.seqs[uid] = seq
        # prefix-chain re-publish: the migrated full blocks index under
        # the same hash chain they carried on the exporter (content-
        # keyed), first-publisher-wins against anything already cached
        self.publish_full_blocks(seq)
        return seq

    def park(self, uid: int) -> list[int]:
        """Preemption swap-out (ISSUE 6): release a LIVE sequence's KV
        blocks and return its full token history for host-side
        retention. With the prefix cache enabled the sequence's
        PUBLISHED full blocks stay indexed (refcount-zero blocks park
        in the LRU rather than freeing), so a later restore —
        re-admitting ``prompt + generated`` as a fresh prompt — re-pins
        the cached chain and recomputes only the unpublished tail.
        Restores are position-exact: greedy and position-keyed
        stochastic decode both resume bit-identically."""
        seq = self.seqs.get(uid)
        if seq is None:
            return []
        tokens = list(seq.tokens)
        self.flush(uid)
        return tokens

    def block_table(self, seq: SequenceDescriptor) -> np.ndarray:
        """Padded [max_blocks_per_seq] table; unused entries point past the
        pool (scatter mode='drop' discards writes through them)."""
        t = np.full((self.max_blocks_per_seq,),
                    self.allocator.num_blocks, np.int32)
        t[:len(seq.blocks)] = seq.blocks
        return t
