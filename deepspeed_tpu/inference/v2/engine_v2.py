"""FastGen-style inference engine (reference: inference/v2/engine_v2.py
InferenceEngineV2:30 — put(batch_uids, batch_tokens):107 runs one forward
over a ragged batch of mixed prefill/decode sequences against the blocked
KV cache; query:158/can_schedule:184 gate admission; flush:242 frees a
sequence's KV blocks. DeepSpeed-MII drives put() in a loop = continuous
batching with Dynamic SplitFuse prompt chunking).

TPU translation: ragged batches become bucketed batches (XLA needs static
shapes — batch and chunk sizes round up to powers of two, one compiled
program per bucket). Prefill chunks and the decode batch run through
paged_forward (paged.py) against the block pool; page tables/sequence
state stay host-side (ragged.py). The pool arrays are donated through the
compiled step so KV writes are in-place.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...utils.logging import log_dist
from ..config import DeepSpeedInferenceConfig
from .paged import paged_forward
from .ragged import DSStateManager, SequenceDescriptor

PyTree = Any


def _bucket(n: int, lo: int = 1) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


def _batch_bucket(n: int) -> int:
    """Decode-batch bucket: powers of two up to 8, then multiples of 8.

    Power-of-two-only batch buckets waste up to ~2x on everything
    (weights reads excepted) — e.g. 24 live sequences padded to 32 rows
    cost +33% per tick. Sublane granularity on TPU is 8, so multiples
    of 8 bucket tightly with a bounded executable count (r4 serving
    profiling: this alone closed most of the v2-vs-v1 decode gap at
    moderate batch)."""
    return _bucket(n) if n <= 8 else -(-n // 8) * 8


class RaggedInferenceEngineConfig(DeepSpeedInferenceConfig):
    """reference: inference/v2/config_v2.py RaggedInferenceEngineConfig
    (state_manager block/pool sizing knobs)."""
    kv_block_size: int = 64
    num_kv_blocks: int = 256
    max_ragged_sequence_count: int = 32   # decode-batch bucket ceiling
    max_chunk_size: int = 256             # prefill chunk (SplitFuse budget)


class InferenceEngineV2:
    """reference: inference/v2/engine_v2.py:30"""

    def __init__(self, model, config: RaggedInferenceEngineConfig,
                 params: Optional[PyTree] = None):
        from ..engine import InferenceEngine
        # reuse v1 for param load/shard/dtype (policy+checkpoint layer)
        self._v1 = InferenceEngine(model, config, params=params)
        self.model = model
        self.params = self._v1.params
        self._config = config
        c = model.config
        self.dtype = config.jax_dtype

        bs = config.kv_block_size
        max_blocks_per_seq = -(-c.max_seq_len // bs)
        self.state_manager = DSStateManager(
            block_size=bs, num_blocks=config.num_kv_blocks,
            max_blocks_per_seq=max_blocks_per_seq)
        # logits of sequences finished as a side effect of another
        # caller's drain loop, held for their owner's next tick()
        self._finished_stash: dict[int, jnp.ndarray] = {}
        pool_shape = (c.num_layers, config.num_kv_blocks, bs,
                      c.num_kv_heads, c.head_dim)

        # TP serving (reference: model_implementations/sharding/): the
        # KV pools shard over the kv-heads dim of the v1 engine's tp
        # mesh; params are already tp-sharded by the v1 layer, so GSPMD
        # propagates head sharding through qkv/attention and inserts the
        # output-projection all-reduce.
        from jax.sharding import NamedSharding, PartitionSpec as P
        self.mesh = self._v1.mesh
        tp = self._v1.topology.model_parallel_size
        if tp > 1 and c.num_kv_heads % tp != 0:
            from ...utils.logging import warning_once
            warning_once(
                f"inference v2: num_kv_heads {c.num_kv_heads} not "
                f"divisible by tp={tp}; KV pools stay replicated")
            pool_spec = P()
        elif tp > 1:
            pool_spec = P(None, None, None, "tp", None)
        else:
            pool_spec = P()
        self._pool_sharding = NamedSharding(self.mesh, pool_spec)
        self.pools = jax.device_put(
            {"k": jnp.zeros(pool_shape, self.dtype),
             "v": jnp.zeros(pool_shape, self.dtype)},
            {"k": self._pool_sharding, "v": self._pool_sharding})
        # one jit; XLA caches one executable per bucket shape. tick() is
        # one dispatch per scheduler tick (logits_gather fused into the
        # step); for generation loops where per-dispatch latency matters
        # more than admission control, the v1/hybrid engines compile the
        # whole decode loop into a single program instead.
        # the blocked-flash kernel is an opaque custom call GSPMD cannot
        # partition: with tp>1 it would force pool gathers — use the jnp
        # paged path there (sharding-transparent); shard_map-wrapping the
        # kernel per tp shard is the follow-up
        self._step = jax.jit(
            functools.partial(paged_forward, self.model,
                              use_kernel=(tp <= 1)),
            donate_argnums=(1,),
            out_shardings=(None, {"k": self._pool_sharding,
                                  "v": self._pool_sharding}))
        # SplitFuse budget, floored to a power of two (bucket shapes must
        # never exceed the configured compute budget)
        self._chunk = 1 << (max(1, config.max_chunk_size).bit_length() - 1)
        pool_mib = (np.prod(pool_shape) * 2
                    * np.dtype(self.dtype).itemsize / 2**20)
        log_dist(
            f"InferenceEngineV2: {config.num_kv_blocks} KV blocks x {bs} "
            f"tokens ({pool_mib:.1f} MiB)")

    # ------------------------------------------------------------------
    def _run(self, uids: list[int]) -> jnp.ndarray:
        """One bucketed forward over the pending tokens of `uids`.
        Returns last-token logits [len(uids), V]."""
        mgr = self.state_manager
        seqs = [mgr.seqs[u] for u in uids]
        max_pending = max(s.pending for s in seqs)
        s_bucket = _bucket(min(max_pending, self._chunk))
        b_bucket = _batch_bucket(len(seqs))

        tokens = np.zeros((b_bucket, s_bucket), np.int32)
        pos0 = np.zeros((b_bucket,), np.int32)
        true_len = np.zeros((b_bucket,), np.int32)
        tables = np.stack(
            [mgr.block_table(s) for s in seqs]
            + [mgr.block_table(seqs[0])] * (b_bucket - len(seqs)))
        for i, seq in enumerate(seqs):
            n = min(seq.pending, s_bucket)
            tokens[i, :n] = seq.tokens[seq.seen:seq.seen + n]
            pos0[i] = seq.seen
            true_len[i] = n
        # context bucketing (the reference buckets KV lengths the same
        # way): narrow the block table to the LIVE context's power-of-two
        # block count, so attention cost scales with actual sequence
        # lengths instead of max_blocks_per_seq — the paged kernel's
        # grid and the gather path's page reads both shrink with it.
        # Bounded recompiles: one executable per (batch, chunk, context)
        # bucket triple, each dimension log2-many.
        live_blocks = -(-int((pos0 + true_len).max()) // mgr.block_size)
        k_blocks = min(_bucket(max(live_blocks, 1)), tables.shape[1])
        tables = tables[:, :k_blocks]
        # padded rows must not write: true_len 0 drops their scatters.
        # logits come back already gathered at each row's last valid
        # token (logits_gather fused into the compiled step)
        logits, self.pools = self._step(
            self.params, self.pools, jnp.asarray(tokens),
            jnp.asarray(pos0), jnp.asarray(tables), jnp.asarray(true_len))
        for i, seq in enumerate(seqs):
            seq.seen += int(true_len[i])
        return logits[:len(seqs)]

    # ------------------------------------------------------------------
    # reference API
    def schedule(self, batch_uids: Sequence[int],
                 batch_tokens: Sequence[Sequence[int]],
                 do_checks: bool = True) -> None:
        """Admit new tokens into the sequence state (KV blocks reserved,
        no compute) — the scheduling half of the reference's put():107.
        Raises before any state mutation if the batch cannot fit."""
        uids = [int(u) for u in batch_uids]
        mgr = self.state_manager
        for u, toks in zip(uids, batch_tokens):
            if len(toks) == 0:
                raise ValueError(
                    f"sequence {u}: schedule()/put() needs at least one "
                    f"token (an empty list would never finish a tick)")
        if do_checks:
            # cumulative admission over the whole batch, so a failure
            # raises before any state mutation
            need = 0
            for u, toks in zip(uids, batch_tokens):
                seq = mgr.seqs.get(u)
                seq_blocks = len(seq.blocks) if seq else 0
                seq_need = mgr.blocks_needed(
                    seq or SequenceDescriptor(uid=u, tokens=[]), len(toks))
                if seq_blocks + seq_need > mgr.max_blocks_per_seq:
                    raise RuntimeError(
                        f"sequence {u} would exceed the max length "
                        f"({mgr.max_blocks_per_seq * mgr.block_size} tokens)")
                need += seq_need
            if need > mgr.allocator.free_blocks:
                raise RuntimeError(
                    f"cannot schedule batch: needs {need} KV blocks, "
                    f"{mgr.allocator.free_blocks} free — the pool is "
                    "exhausted (flush finished sequences)")
        for u, toks in zip(uids, batch_tokens):
            mgr.extend(u, list(map(int, toks)))
            # re-admission invalidates any logits stashed when this uid
            # finished during another caller's drain: the stashed value
            # is from the old position and tick() must not surface it
            # while the uid has pending tokens again (mirrors flush()).
            # Popped only after extend() succeeds — a failed admission
            # (do_checks=False + exhausted pool) must leave the stash
            # intact for the original caller.
            self._finished_stash.pop(u, None)

    def tick(self) -> dict[int, jnp.ndarray]:
        """ONE scheduler tick (the compute half of the reference's
        put():107): a single bucketed forward over every sequence with
        pending tokens — prefill chunks (SplitFuse budget) and the decode
        batch ride the same pass. Returns {uid: last-token logits} for
        sequences whose pending tokens finished this tick (including any
        stashed by a concurrent put() that drained them as a side
        effect). Callers may schedule() new sequences between ticks —
        mid-prompt admission, which folding the loop into put() would
        forfeit."""
        mgr = self.state_manager
        out = dict(self._finished_stash)
        self._finished_stash.clear()
        run_uids = [u for u, s in mgr.seqs.items() if s.pending]
        run_uids = run_uids[:self._config.max_ragged_sequence_count]
        if run_uids:
            logits = self._run(run_uids)
            out.update({u: logits[i] for i, u in enumerate(run_uids)
                        if not mgr.seqs[u].pending})
        return out

    def put(self, batch_uids: Sequence[int],
            batch_tokens: Sequence[Sequence[int]],
            do_checks: bool = True) -> jnp.ndarray:
        """schedule() + tick()-until-drained for the given sequences;
        returns last-token logits [n, V] in uid order (the reference
        put():107 plus the caller loop DeepSpeed-MII wraps around it).
        Use schedule()/tick() directly for inter-tick admission."""
        uids = [int(u) for u in batch_uids]
        uid_set = set(uids)
        self.schedule(uids, batch_tokens, do_checks)
        mgr = self.state_manager
        final: dict[int, jnp.ndarray] = {}
        while any(mgr.seqs[u].pending for u in uids):
            for u, lg in self.tick().items():
                if u in uid_set:
                    final[u] = lg
                else:
                    # a sequence someone else schedule()d finished as a
                    # side effect of our drain: stash its logits for
                    # that caller's next tick() instead of dropping them
                    self._finished_stash[u] = lg
        return jnp.stack([final[u] for u in uids])

    def query(self, uid: int) -> tuple[int, int]:
        """(cached_tokens, allocated_blocks) for a sequence (reference:
        engine_v2.query:158)."""
        seq = self.state_manager.seqs.get(uid)
        if seq is None:
            return (0, 0)
        return (seq.seen, len(seq.blocks))

    def can_schedule(self, uid: int, n_tokens: int) -> bool:
        return self.state_manager.can_schedule(uid, n_tokens)

    @property
    def free_blocks(self) -> int:
        return self.state_manager.allocator.free_blocks

    def flush(self, uids) -> None:
        """Release finished sequences' KV blocks; accepts one uid or an
        iterable (reference: engine_v2.flush:242 takes uids)."""
        if isinstance(uids, (int, np.integer)):
            uids = [uids]
        for u in uids:
            self.state_manager.flush(int(u))
            self._finished_stash.pop(int(u), None)

    # ------------------------------------------------------------------
    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: int = 32) -> list[list[int]]:
        """Greedy continuous batching driver over schedule()/tick():
        admits prompts as KV blocks free up — including mid-prefill of
        other prompts, since admission happens between ticks — and
        decodes all live sequences together each tick. What DeepSpeed-MII
        implements on top of put() (reference: mii serving loop)."""
        mgr = self.state_manager
        bs = mgr.block_size
        pending = list(enumerate([list(map(int, p)) for p in prompts]))
        live: dict[int, list[int]] = {}
        reserved: dict[int, int] = {}   # uid -> worst-case block budget
        results: dict[int, list[int]] = {}
        max_live = self._config.max_ragged_sequence_count

        def admit():
            """Admit as many pending prompts as fit, reserving each one's
            worst-case block budget so live sequences can never exhaust
            the pool mid-decode."""
            batch: list[tuple[int, list[int]]] = []
            allocated = sum(len(mgr.seqs[u].blocks) for u in live)
            headroom = (mgr.allocator.free_blocks
                        - (sum(reserved.values()) - allocated))
            while pending and len(live) + len(batch) < max_live:
                uid, prompt = pending[0]
                need = -(-(len(prompt) + max_new_tokens) // bs)
                if need > mgr.max_blocks_per_seq or \
                        need > mgr.allocator.num_blocks:
                    raise ValueError(
                        f"prompt {uid}: {len(prompt)} tokens + "
                        f"{max_new_tokens} new can never fit the KV pool "
                        f"(needs {need} blocks)")
                if need > headroom:
                    break
                pending.pop(0)
                headroom -= need
                reserved[uid] = need
                batch.append((uid, prompt))
            if batch:
                self.schedule([u for u, _ in batch],
                              [p for _, p in batch])
                for uid, _ in batch:
                    live[uid] = []

        admit()
        while live or pending:
            if not live:
                admit()
                if not live:   # reservation math guarantees progress
                    raise RuntimeError(
                        "continuous-batching deadlock: pending prompts "
                        "but nothing admissible")
                continue
            # one tick advances every pending sequence one chunk; a
            # sequence whose pending drained yields logits -> sample
            finished = self.tick()
            decode_uids: list[int] = []
            for u in sorted(finished):
                if u not in live:
                    # not ours (scheduled by another caller): re-stash
                    self._finished_stash[u] = finished[u]
                    continue
                live[u].append(int(jnp.argmax(finished[u])))
                if len(live[u]) >= max_new_tokens:
                    results[u] = live.pop(u)[:max_new_tokens]
                    reserved.pop(u)
                    self.flush(u)
                else:
                    decode_uids.append(u)
            if decode_uids:
                self.schedule(decode_uids,
                              [[live[u][-1]] for u in decode_uids],
                              do_checks=False)  # blocks pre-reserved
            admit()
        return [results[i] for i in range(len(prompts))]
