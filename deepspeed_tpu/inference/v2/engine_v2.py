"""FastGen-style inference engine (reference: inference/v2/engine_v2.py
InferenceEngineV2:30 — put(batch_uids, batch_tokens):107 runs one forward
over a ragged batch of mixed prefill/decode sequences against the blocked
KV cache; query:158/can_schedule:184 gate admission; flush:242 frees a
sequence's KV blocks. DeepSpeed-MII drives put() in a loop = continuous
batching with Dynamic SplitFuse prompt chunking).

TPU translation: ragged batches become bucketed batches (XLA needs static
shapes — batch and chunk sizes round up to powers of two, one compiled
program per bucket). Prefill chunks and the decode batch run through
paged_forward (paged.py) against the block pool; page tables/sequence
state stay host-side (ragged.py). The pool arrays are donated through the
compiled step so KV writes are in-place.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Any, Literal, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from pydantic import Field, model_validator

from ...runtime.config_utils import DeepSpeedConfigModel
from ...utils.logging import log_dist
# telemetry guard: sys.modules probe, NOT an import — a disabled
# serving loop allocates nothing and pays one dict lookup per
# *dispatch* (never per token)
from ...utils.telemetry_probe import (NULL_CM as _NULLCM,
                                      active_telemetry as _telemetry)
from ..config import DeepSpeedInferenceConfig
from .paged import (fused_decode_loop, fused_serve_loop,
                    fused_spec_decode_loop, fused_spec_serve_loop,
                    paged_forward)
from .ragged import (KVExportState, PrefixCache, DSStateManager,
                     SequenceDescriptor, kv_block_bytes,
                     quantized_block_budget)

PyTree = Any

# serving_metrics() schema: raw counters kept in serving_stats (reset
# zeroes exactly these); the prefix-cache counters ride alongside via
# ragged.PREFIX_STAT_KEYS, and derived ratio/occupancy gauges are
# appended at read time. telemetry.bridges and bench.py consume the
# same names. The spec_* counters (ISSUE 9) stay zero with speculative
# decoding off: spec_proposed_tokens/spec_accepted_tokens are the
# acceptance-rate numerator/denominator, spec_hit_slots counts
# (row, tick) slots where the prompt-lookup drafter fired at all.
# fused_live_slots counts scheduled (row, step) slots whose row was
# still ACTIVE — the occupancy numerator; spec-off it equals
# fused_slot_tokens (one token per live slot), spec-on the device
# loops report it (tokens per live slot is then 1..1+draft_len).
SERVING_COUNTER_KEYS = (
    "host_dispatches", "fused_dispatches", "fused_steps", "fused_slots",
    "fused_slot_tokens", "fused_live_slots", "decoded_tokens",
    "spec_proposed_tokens", "spec_accepted_tokens", "spec_hit_slots")


class _LatencyProbe:
    """Serving-latency telemetry for one generation drive: TTFT and
    inter-token-latency histograms plus the admission-queue-depth gauge.
    Constructed only when telemetry is active; all call sites are
    guarded, so the disabled path carries none of this."""

    __slots__ = ("_ttft", "_itl", "_queue", "_admit_t", "_last_t")

    def __init__(self, reg):
        self._ttft = reg.histogram(
            "ds_serving_ttft_seconds",
            "time from admission to a sequence's first generated token")
        self._itl = reg.histogram(
            "ds_serving_itl_seconds",
            "inter-token latency (observed once per generated token; "
            "tokens landing in one fused drain share the drain "
            "interval evenly)")
        self._queue = reg.gauge(
            "ds_serving_queue_depth",
            "prompts still waiting for admission to the decode batch")
        self._admit_t: dict[int, float] = {}
        self._last_t: dict[int, float] = {}

    def admitted(self, uids, waiting: int) -> None:
        now = time.perf_counter()
        for u in uids:
            self._admit_t[u] = now
        self._queue.set(waiting, engine="v2")

    def tokens(self, uid: int, n: int, first: bool = False) -> None:
        """``n`` new tokens landed for ``uid`` (``first``: the batch
        starts with the sequence's first generated token)."""
        now = time.perf_counter()
        last = self._last_t.get(uid)
        if first:
            self._ttft.observe(now - self._admit_t.pop(uid, now))
            n -= 1
            if last is None:
                last = now
        if n > 0 and last is not None:
            per = (now - last) / n
            for _ in range(n):
                self._itl.observe(per)
        self._last_t[uid] = now

    def finished(self, uid: int) -> None:
        """Drop per-uid state. A probe used to die with one
        generate call; the serving loop keeps one alive for the
        server's lifetime, so finished/preempted uids must not
        accumulate."""
        self._admit_t.pop(uid, None)
        self._last_t.pop(uid, None)


def _bucket(n: int, lo: int = 1) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


def _batch_bucket(n: int) -> int:
    """Decode-batch bucket: powers of two up to 8, then multiples of 8.

    Power-of-two-only batch buckets waste up to ~2x on everything
    (weights reads excepted) — e.g. 24 live sequences padded to 32 rows
    cost +33% per tick. Sublane granularity on TPU is 8, so multiples
    of 8 bucket tightly with a bounded executable count (r4 serving
    profiling: this alone closed most of the v2-vs-v1 decode gap at
    moderate batch)."""
    return _bucket(n) if n <= 8 else -(-n // 8) * 8


class PrefixCacheConfig(DeepSpeedConfigModel):
    """Automatic prefix caching (ISSUE 4): full KV blocks are indexed by
    a hash chain over their token content and SHARED across requests —
    a new prompt whose leading blocks match a cached chain skips their
    prefill entirely (refcount bump instead of compute). Off by
    default; the disabled path is byte-identical to an engine without
    the feature."""
    enabled: bool = False
    # a match shorter than this many full blocks is ignored (tiny
    # matches save little prefill but fragment the pool's LRU)
    min_match_blocks: int = 1
    # cap on indexed blocks; 0 = bounded only by the pool. Exceeding it
    # evicts the least-recently-used unreferenced cached block.
    max_cached_blocks: int = 0


class SpeculativeConfig(DeepSpeedConfigModel):
    """Self-drafting speculative decoding in the fused serving path
    (ISSUE 9): a device-side prompt-lookup (n-gram) drafter proposes up
    to ``draft_len`` tokens per row per tick from the row's own recent
    token history, and the fused loop verifies them in ONE forward over
    ``1 + draft_len`` positions — committing 1..1+draft_len tokens per
    tick. No draft model, no extra weights; greedy output is
    bit-identical to spec-off, stochastic output is bit-identical for
    the same seed (targets are position-key sampled, drafts only decide
    how many land per forward). Off by default; the disabled path
    builds none of the spec executables."""
    enabled: bool = False
    # draft tokens proposed (and verified) per decode tick; the verify
    # forward runs over 1 + draft_len positions
    draft_len: int = Field(3, ge=1)
    # shortest trailing n-gram that may match earlier history; longer
    # = fewer but better-targeted drafts
    min_ngram: int = Field(2, ge=1)
    # device-side recent-token window the drafter searches (per row,
    # int32) — seeded at admission from the sequence's committed
    # history (prefix-cache-shared prompt tokens included) and
    # maintained in-graph
    history_window: int = Field(64, ge=8)

    @model_validator(mode="after")
    def _window_covers_match(self):
        need = self.min_ngram + self.draft_len + 1
        if self.history_window < need:
            raise ValueError(
                f"speculative.history_window ({self.history_window}) "
                f"must be >= min_ngram + draft_len + 1 ({need}): the "
                "window must hold one n-gram, its full continuation "
                "and the trailing n-gram it matches against")
        return self


class KVCacheConfig(DeepSpeedConfigModel):
    """Quantized KV cache (ISSUE 12): the paged KV pools store int8 or
    fp8-e4m3 codes with symmetric per-vector f32 scales riding the
    block tables in their own scale slabs (``pools["ks"]/["vs"]``, one
    scale per written (token, kv-head) vector — or per token with
    ``granularity="token"``). Dequantization is fused into the
    consumers — in-register inside the Pallas paged-decode fold, a
    fused multiply on the jnp reference path — so quantized blocks are
    read straight from HBM with no materialized fp16 copy, and
    quantize-on-write happens once in the same graph as the pool
    scatter. With ``grow_pool`` the allocator is sized in QUANTIZED
    bytes: the HBM budget of ``num_kv_blocks`` full-precision blocks
    yields 2-4x more quantized blocks, i.e. 2-4x more resident
    requests per chip. Off by default; the disabled path is
    byte-identical to an engine without the feature (no scale slabs,
    same executables). Accuracy model, dtype-selection guidance and
    the metric guide live in docs/serving.md."""
    enabled: bool = False
    # storage format of the KV payload pools: "fp16" keeps the
    # engine's compute dtype (quantization off even when enabled —
    # the explicit no-op rung of the dtype ladder); int8 = symmetric
    # [-127, 127] codes; fp8 = native float8_e4m3fn
    dtype: Literal["fp16", "int8", "fp8"] = "int8"
    # scale granularity: "head" = one f32 scale per written
    # (token, kv-head) vector of head_dim elements (tightest, the
    # default); "token" = one scale across all kv heads of a token
    # (1/num_kv_heads of the scale memory, slightly coarser). Both are
    # write-once — no read-modify-requantize of earlier tokens, which
    # is what keeps cached quantized blocks bit-stable under sharing.
    granularity: Literal["head", "token"] = "head"
    # size the pool in quantized bytes: grow num_kv_blocks to fill the
    # HBM budget the configured full-precision pool would have used.
    # False = keep the configured block count (pool bytes shrink
    # instead — the parity/testing mode).
    grow_pool: bool = True


class GraftsanConfig(DeepSpeedConfigModel):
    """Runtime concurrency/KV-accounting sanitizers (ISSUE 11,
    ``analysis/blocksan.py`` — the runtime half of the graftsan
    GL050-GL053 static pass). ``blocksan`` journals every KV-block
    accounting mutation with call-site provenance and asserts refcount
    >= 0, no double-free, and pool conservation (free + referenced +
    LRU-cached == pool) at every flush/park quiesce point, naming
    leaked blocks' allocation sites on failure; ``thread_affinity``
    stamps the engine-owning thread (the async server re-stamps its
    worker at loop start) and raises on JAX dispatch from any other
    thread. Off by default — the disabled path is one attribute load
    per accounting call and nothing is imported. Env ``DS_GRAFTSAN=1``
    force-enables both (the conftest/CI opt-in knob)."""
    enabled: bool = False
    blocksan: bool = True
    thread_affinity: bool = True
    # "raise" fails fast (tests/bench); "warn" logs, counts, and keeps
    # serving (violations still reach ds_blocksan_violations_total)
    mode: Literal["raise", "warn"] = "raise"
    # bounded journal of recent accounting ops kept for leak reports
    # and hang-dump forensics
    journal_size: int = Field(512, ge=16)


class InferenceMeshsanConfig(DeepSpeedConfigModel):
    """Runtime mesh-traffic sanitizer for the serving dispatch families
    (ISSUE 15, ``analysis/meshsan.py`` — the runtime half of the
    shardlint GL060-GL063 static pass; see the training-side
    ``meshsan`` block in runtime/config.py for the full model). The v2
    contract is strict: a tp-sharded forward moves bytes on ``tp``
    only, and any substantial all-to-all/collective-permute in a
    serving executable is the GSPMD silent-reshard signature
    (kilobyte-scale partitioner shuffles are tolerated). Checks ride
    the telemetry executable ledger's HLO walk, once per new
    executable. Off by default; env ``DS_MESHSAN=1`` force-enables."""
    enabled: bool = False
    mode: Literal["raise", "warn"] = "raise"
    # override the auto-seeded contract axes (None = {tp} when tp > 1)
    axes: Optional[list[str]] = None


class InferenceNumsanConfig(DeepSpeedConfigModel):
    """numsan numerics sanitizer, serving side (ISSUE 18,
    ``analysis/numsan.py`` — the runtime half of the numlint
    GL070-GL073 static pass; the training-side block is ``numsan`` in
    runtime/config.py). Probes are opt-in and cadence-gated:

    - every ``probe_interval``-th per-tick dispatch checks the batch
      logits for non-finite values and for ``|logit| > logits_limit``
      (the pre-NaN saturation signature of a mis-scaled KV cache) — a
      small fused reduction plus one host sync on the probe cadence;
    - with a quantized KV cache, the same cadence audits the scale
      slabs (``pools["ks"]/["vs"]``) for non-finite scales
      (``kv_scale_probe``);
    - every quantize site armed at trace time (the KV write,
      ``ops/pallas/quantization.saturation_probe``) reports its
      saturating-code fraction to ``ds_numsan_saturation_ratio{site}``;
      a fraction above ``saturation_ceiling`` is a finding, raised at
      the next dispatch boundary (``drain``).

    Off by default — nothing imported, executables byte-identical. Env
    ``DS_NUMSAN=1`` force-enables (the conftest/CI opt-in knob). Rule
    catalog + probe cost model: docs/static-analysis.md,
    "Numerics"."""
    enabled: bool = False
    mode: Literal["raise", "warn"] = "raise"
    # |logit| beyond this is a "logits-range" finding
    logits_limit: float = Field(1e4, gt=0.0)
    # check logits / KV scales every N-th per-tick dispatch (each
    # check costs one host sync)
    probe_interval: int = Field(16, ge=1)
    # audit the quantized KV scale slabs on the probe cadence
    kv_scale_probe: bool = True
    # saturating-code fraction above this is a finding; the healthy
    # baseline is ~1/head_dim (each written vector's absmax lands
    # exactly on the clip boundary)
    saturation_ceiling: float = Field(0.05, ge=0.0, le=1.0)
    # arm the in-graph quantize-site probes (KV write) at trace time
    saturation_probe: bool = True


class RaggedInferenceEngineConfig(DeepSpeedInferenceConfig):
    """reference: inference/v2/config_v2.py RaggedInferenceEngineConfig
    (state_manager block/pool sizing knobs + the fused-decode loop)."""
    kv_block_size: int = 64
    num_kv_blocks: int = 256
    max_ragged_sequence_count: int = 32   # decode-batch bucket ceiling
    max_chunk_size: int = 256             # prefill chunk (SplitFuse budget)
    # K decode ticks fused into one on-device loop per host dispatch
    # (decode_fused/generate_fused): forward, sampling, KV writes and
    # EOS/budget termination all run in-graph, so decode throughput
    # rides device compute instead of host dispatch RTT. 0/1 disables
    # fusion (per-tick behavior).
    fused_decode_steps: int = 8
    # in-graph sampling defaults (per-call overrides win). temperature
    # 0.0 = greedy; top_k/top_p 0 = no filter.
    sampling_temperature: float = 0.0
    sampling_top_k: int = 0
    sampling_top_p: float = 0.0
    # sequences terminate in-graph when they sample this token
    eos_token_id: Optional[int] = None
    # dispatch-chain depth for the fused drivers (ISSUE 6): how many
    # fused decode dispatches may be in flight before the host drains
    # one. 2 = the PR 1 double buffering (default path byte-identical);
    # deeper chains amortize the host round trip further at the cost
    # of admission latency (a waiting prompt rides out the chain).
    max_inflight_dispatches: int = Field(2, ge=1)
    # device-resident multi-tick serving (ISSUE 6): pre-staged requests
    # (prefilled, blocks reserved) are swapped into finished rows'
    # slots INSIDE the compiled loop (activity-mask swap + staged
    # token/position/table operands), and sampled tokens accumulate in
    # a device-side ring the host reads ONCE per dispatch chain instead
    # of once per dispatch. Off by default — the disabled path is
    # byte-identical to the PR 1 fused driver.
    fused_admission: bool = False
    # runtime sentinels (ISSUE 3, analysis/sentinels.py): every fused
    # decode dispatch runs under a recompile watch (a previously-seen
    # (jit key, operand shapes) signature must hit the executable
    # cache) and jax.transfer_guard("disallow") (implicit host<->device
    # transfers raise; the explicit token drain stays legal). Off by
    # default — zero overhead, nothing imported.
    sentinels: bool = False
    sentinel_mode: str = "raise"          # or "warn"
    # quantized KV cache (ISSUE 12): int8/fp8 pools with per-vector
    # scales, dequant fused into the paged-decode consumers, allocator
    # sized in quantized bytes (see docs/serving.md)
    kv_cache: KVCacheConfig = Field(default_factory=KVCacheConfig)
    # automatic prefix caching: ref-counted KV block sharing with
    # hash-chained reuse across requests (see docs/serving.md)
    prefix_cache: PrefixCacheConfig = Field(
        default_factory=PrefixCacheConfig)
    # speculative decoding (ISSUE 9): prompt-lookup drafting + in-graph
    # K-token verify in the fused decode/serve loops (see
    # docs/serving.md)
    speculative: SpeculativeConfig = Field(
        default_factory=SpeculativeConfig)
    # graftsan runtime sanitizers (ISSUE 11): KV block-accounting
    # journal + conservation checks and the thread-affinity checker
    # (see docs/static-analysis.md, "Concurrency domains & sanitizers")
    graftsan: GraftsanConfig = Field(default_factory=GraftsanConfig)
    # meshsan mesh-traffic sanitizer (ISSUE 15): per-executable
    # collective traffic contracts over the ledger's HLO walk (see
    # docs/static-analysis.md, "SPMD correctness")
    meshsan: InferenceMeshsanConfig = Field(
        default_factory=InferenceMeshsanConfig)
    # numsan numerics sanitizer (ISSUE 18): logits-range / KV-scale
    # probes + quantize-site saturation attribution (see
    # docs/static-analysis.md, "Numerics")
    numsan: InferenceNumsanConfig = Field(
        default_factory=InferenceNumsanConfig)


class InferenceEngineV2:
    """reference: inference/v2/engine_v2.py:30"""

    def __init__(self, model, config: RaggedInferenceEngineConfig,
                 params: Optional[PyTree] = None):
        from ..engine import InferenceEngine
        # reuse v1 for param load/shard/dtype (policy+checkpoint layer)
        self._v1 = InferenceEngine(model, config, params=params)
        # take v1's per-engine module copy (serving flags bound, any
        # training-engine moe_dispatcher stripped), not the raw model
        self.model = getattr(self._v1, "module", model)
        self.params = self._v1.params
        self._config = config
        c = model.config
        self.dtype = config.jax_dtype

        bs = config.kv_block_size
        max_blocks_per_seq = -(-c.max_seq_len // bs)

        # quantized KV cache (ISSUE 12): pool dtype, scale layout and
        # the block budget are resolved BEFORE the state manager so the
        # allocator is sized in quantized bytes — the HBM budget of
        # num_kv_blocks full-precision blocks yields proportionally
        # more quantized blocks (grow_pool), i.e. more resident
        # requests at equal pool bytes.
        kvc = config.kv_cache
        self._kv_quant = bool(kvc.enabled and kvc.dtype != "fp16")
        self._kv_scale_heads = (1 if kvc.granularity == "token"
                                else c.num_kv_heads)
        full_bytes = kv_block_bytes(
            bs, c.num_kv_heads, c.head_dim,
            np.dtype(self.dtype).itemsize)
        if self._kv_quant:
            self._kv_block_bytes = kv_block_bytes(
                bs, c.num_kv_heads, c.head_dim, 1,
                scale_heads=self._kv_scale_heads)
            nb = (quantized_block_budget(config.num_kv_blocks,
                                         full_bytes,
                                         self._kv_block_bytes)
                  if kvc.grow_pool else config.num_kv_blocks)
        else:
            self._kv_block_bytes = full_bytes
            nb = config.num_kv_blocks
        self.num_kv_blocks = nb

        pc = config.prefix_cache
        self.state_manager = DSStateManager(
            block_size=bs, num_blocks=nb,
            max_blocks_per_seq=max_blocks_per_seq,
            prefix_cache=(PrefixCache(
                block_size=bs, min_match_blocks=pc.min_match_blocks,
                max_cached_blocks=pc.max_cached_blocks)
                if pc.enabled else None))
        # logits of sequences finished as a side effect of another
        # caller's drain loop, held for their owner's next tick()
        self._finished_stash: dict[int, jnp.ndarray] = {}
        pool_shape = (c.num_layers, nb, bs, c.num_kv_heads, c.head_dim)

        # TP serving (reference: model_implementations/sharding/): the
        # KV pools shard over the kv-heads dim of the v1 engine's tp
        # mesh; params are already tp-sharded by the v1 layer, so GSPMD
        # propagates head sharding through qkv/attention and inserts the
        # output-projection all-reduce.
        from jax.sharding import NamedSharding, PartitionSpec as P
        self.mesh = self._v1.mesh
        tp = self._v1.topology.model_parallel_size
        if tp > 1 and c.num_kv_heads % tp != 0:
            from ...utils.logging import warning_once
            warning_once(
                f"inference v2: num_kv_heads {c.num_kv_heads} not "
                f"divisible by tp={tp}; KV pools stay replicated")
            pool_spec = P()
        elif tp > 1:
            pool_spec = P(None, None, None, "tp", None)
        else:
            pool_spec = P()
        self._pool_sharding = NamedSharding(self.mesh, pool_spec)
        if self._kv_quant:
            from ...ops.pallas.quantization import KV_STORE_DTYPES
            store = KV_STORE_DTYPES[kvc.dtype]
            scale_shape = pool_shape[:3] + (self._kv_scale_heads,)
            # scale slabs shard with their payload's kv-head axis when
            # per-head (and the pool is head-sharded); per-token scales
            # have no head axis to shard — replicated
            scale_spec = (P(None, None, None, "tp")
                          if pool_spec != P()
                          and self._kv_scale_heads > 1 else P())
            scale_sharding = NamedSharding(self.mesh, scale_spec)
            self._pool_shardings = {
                "k": self._pool_sharding, "v": self._pool_sharding,
                "ks": scale_sharding, "vs": scale_sharding}
            # zero-init scales dequantize untouched slots to exact 0.0
            # — the same dead-slot semantics as the fp16 pools, so the
            # kernel's sanitize_pools=False fast path stays valid
            self.pools = jax.device_put(
                {"k": jnp.zeros(pool_shape, store),
                 "v": jnp.zeros(pool_shape, store),
                 "ks": jnp.zeros(scale_shape, jnp.float32),
                 "vs": jnp.zeros(scale_shape, jnp.float32)},
                dict(self._pool_shardings))
        else:
            self._pool_shardings = {"k": self._pool_sharding,
                                    "v": self._pool_sharding}
            self.pools = jax.device_put(
                {"k": jnp.zeros(pool_shape, self.dtype),
                 "v": jnp.zeros(pool_shape, self.dtype)},
                dict(self._pool_shardings))
        # one jit; XLA caches one executable per bucket shape. tick() is
        # one dispatch per scheduler tick (logits_gather fused into the
        # step); for generation loops where per-dispatch latency matters
        # more than admission control, the v1/hybrid engines compile the
        # whole decode loop into a single program instead.
        # the blocked-flash kernel is an opaque custom call GSPMD cannot
        # partition: with tp>1 it would force pool gathers — use the jnp
        # paged path there (sharding-transparent); shard_map-wrapping the
        # kernel per tp shard is the follow-up
        self._step = jax.jit(
            functools.partial(paged_forward, self.model,
                              use_kernel=(tp <= 1)),
            donate_argnums=(1,),
            out_shardings=(None, dict(self._pool_shardings)))
        # fused-decode executables: one per (num_steps, sampling, eos)
        # combination; XLA adds a per-bucket-shape cache underneath
        self._fused_cache: dict = {}
        # sentinels (opt-in): lazily imported so a sentinel-off serving
        # process never pulls analysis/ or the telemetry package
        self._decode_sentinel = None
        self._hot_guard = None
        self._fused_sigs: set = set()
        # base PRNG key per seed, built once: PRNGKey(int) is an
        # implicit host->device upload, which must not ride every
        # fused dispatch (it would trip the transfer guard — and is
        # per-dispatch host work for a value that never changes)
        self._seed_keys: dict[int, jnp.ndarray] = {}
        if config.sentinels:
            from ...analysis.sentinels import (RecompileSentinel,
                                               hot_path_guard)
            self._decode_sentinel = RecompileSentinel(
                "fused_decode", mode=config.sentinel_mode, warmup_calls=0)
            self._hot_guard = hot_path_guard
        # graftsan runtime sanitizers (ISSUE 11): opt-in via the config
        # block or the DS_GRAFTSAN env knob; lazily imported so a
        # sanitizer-off process never loads analysis/blocksan
        self._blocksan = None
        self._affinity = None
        gs = config.graftsan
        if gs.enabled or os.environ.get("DS_GRAFTSAN", "") \
                not in ("", "0"):
            from ...analysis import blocksan as _bsan
            if gs.blocksan:
                self._blocksan = _bsan.BlockSanitizer(
                    self.num_kv_blocks, mode=gs.mode,
                    journal_size=gs.journal_size)
                if self._kv_quant:
                    # the scale pool partitions block-for-block with
                    # the KV pool; a scale slot outliving (or missing
                    # from) its block's lifecycle is a finding
                    self._blocksan.attach_scale_pool()
                self.state_manager.attach_sanitizer(self._blocksan)
                # registered process-wide so hang-watchdog dumps embed
                # the journal tail (telemetry/flightrec.dump_state)
                _bsan.set_blocksan(self._blocksan)
            if gs.thread_affinity:
                self._affinity = _bsan.ThreadAffinityChecker(mode=gs.mode)
        # meshsan (ISSUE 15): per-executable traffic contracts checked
        # at the dispatch-family registration choke point
        # (_device_truth_observe); opt-in, lazily imported, rides the
        # telemetry ledger's HLO walk
        self._meshsan = None
        ms = config.meshsan
        if ms.enabled or os.environ.get("DS_MESHSAN", "") \
                not in ("", "0"):
            from ...analysis import meshsan as _msan
            contract = _msan.seed_serving_contract(tp=tp)
            if ms.axes is not None:
                contract.axes = frozenset(ms.axes)
            self._meshsan = _msan.MeshSanitizer(mode=ms.mode)
            # the two ledger-observed dispatch families (prefill
            # registers under v2/dispatch — its span name is not a
            # ledger name)
            for fam in ("v2/dispatch", "v2/fused_dispatch"):
                self._meshsan.declare(fam, contract)
            _msan.set_meshsan(self._meshsan)
        # numsan (ISSUE 18): logits-range / KV-scale probes on the
        # dispatch path + trace-time-armed quantize-site saturation
        # attribution (the KV write probe in paged.py). Opt-in, lazily
        # imported; the off path traces byte-identical executables.
        self._numsan = None
        self._numsan_dispatches = 0
        self._logits_stats_fn = None
        ns = config.numsan
        self._numsan_kv_probe = bool(ns.kv_scale_probe)
        if ns.enabled or os.environ.get("DS_NUMSAN", "") \
                not in ("", "0"):
            from ...analysis import numsan as _nsan
            self._numsan = _nsan.NumericsSanitizer(
                mode=ns.mode,
                saturation_ceiling=ns.saturation_ceiling,
                logits_limit=ns.logits_limit,
                probe_interval=ns.probe_interval,
                saturation_probe=ns.saturation_probe)
            # registered process-wide: the quantize-site probes and
            # hang-watchdog dumps read it back without an engine ref
            _nsan.set_numsan(self._numsan)
        # serving counters behind serving_metrics(): host dispatches vs
        # decoded tokens measures how host-free the decode loop is.
        # Schema-driven (SERVING_COUNTER_KEYS) so reset/emission can
        # never drift from the key set consumers see.
        self.serving_stats = dict.fromkeys(SERVING_COUNTER_KEYS, 0)
        # SplitFuse budget, floored to a power of two (bucket shapes must
        # never exceed the configured compute budget)
        self._chunk = 1 << (max(1, config.max_chunk_size).bit_length() - 1)
        pool_mib = self.kv_pool_bytes() / 2**20
        log_dist(
            f"InferenceEngineV2: {nb} KV blocks x {bs} tokens "
            f"({pool_mib:.1f} MiB, kv dtype {self.kv_dtype})")

    # ------------------------------------------------------------------
    def _run(self, uids: list[int]) -> jnp.ndarray:
        """One bucketed forward over the pending tokens of `uids`.
        Returns last-token logits [len(uids), V]."""
        if self._affinity is not None:
            # runtime half of GL050: only the engine-owning thread may
            # reach a JAX dispatch (auto-binds on first use; the async
            # server re-stamps its worker at loop start)
            self._affinity.check("v2/_run")
        mgr = self.state_manager
        seqs = [mgr.seqs[u] for u in uids]
        max_pending = max(s.pending for s in seqs)
        s_bucket = _bucket(min(max_pending, self._chunk))
        b_bucket = _batch_bucket(len(seqs))

        tokens = np.zeros((b_bucket, s_bucket), np.int32)
        pos0 = np.zeros((b_bucket,), np.int32)
        true_len = np.zeros((b_bucket,), np.int32)
        tables = np.stack(
            [mgr.block_table(s) for s in seqs]
            + [mgr.block_table(seqs[0])] * (b_bucket - len(seqs)))
        for i, seq in enumerate(seqs):
            n = min(seq.pending, s_bucket)
            tokens[i, :n] = seq.tokens[seq.seen:seq.seen + n]
            pos0[i] = seq.seen
            true_len[i] = n
        # context bucketing (the reference buckets KV lengths the same
        # way): narrow the block table to the LIVE context's power-of-two
        # block count, so attention cost scales with actual sequence
        # lengths instead of max_blocks_per_seq — the paged kernel's
        # grid and the gather path's page reads both shrink with it.
        # Bounded recompiles: one executable per (batch, chunk, context)
        # bucket triple, each dimension log2-many.
        live_blocks = -(-int((pos0 + true_len).max()) // mgr.block_size)
        k_blocks = min(_bucket(max(live_blocks, 1)), tables.shape[1])
        tables = tables[:, :k_blocks]
        # padded rows must not write: true_len 0 drops their scatters.
        # logits come back already gathered at each row's last valid
        # token (logits_gather fused into the compiled step)
        self.serving_stats["host_dispatches"] += 1
        tel = _telemetry()
        dev_ops = (jnp.asarray(tokens), jnp.asarray(pos0),
                   jnp.asarray(tables), jnp.asarray(true_len))
        if tel is not None:
            # ISSUE 5 hooks BEFORE the dispatch: pools are donated
            # through the step, and first-sight ledger registration
            # must stay outside any sentinel watch
            self._device_truth_observe(tel, "v2/dispatch", self._step,
                                       dev_ops)
        # span measures the host-side dispatch (enqueue; the device work
        # itself lands in the XPlane via the TraceAnnotation mirror)
        with (tel.span("v2/dispatch",
                       dispatch_id=self.serving_stats["host_dispatches"],
                       rows=len(seqs), chunk=s_bucket)
              if tel is not None else _NULLCM):
            logits, self.pools = self._step(
                self.params, self.pools, *dev_ops)
        for i, seq in enumerate(seqs):
            seq.seen += int(true_len[i])
            # prefix cache: blocks this chunk completed are now fully in
            # the pool — index them for reuse (no-op when disabled)
            mgr.publish_full_blocks(seq)
        if self._numsan is not None:
            self._numsan_probe(logits[:len(seqs)])
        return logits[:len(seqs)]

    # ------------------------------------------------------------------
    # reference API
    def schedule(self, batch_uids: Sequence[int],
                 batch_tokens: Sequence[Sequence[int]],
                 do_checks: bool = True) -> None:
        """Admit new tokens into the sequence state (KV blocks reserved,
        no compute) — the scheduling half of the reference's put():107.
        Raises before any state mutation if the batch cannot fit."""
        uids = [int(u) for u in batch_uids]
        mgr = self.state_manager
        for u, toks in zip(uids, batch_tokens):
            if len(toks) == 0:
                raise ValueError(
                    f"sequence {u}: schedule()/put() needs at least one "
                    f"token (an empty list would never finish a tick)")
        # prefix-cache pre-pinning: matched blocks are ref-bumped BEFORE
        # any check or allocation, so (a) the admission math credits
        # exactly the blocks reuse will skip and (b) an earlier
        # sequence's allocation in this batch cannot evict a later
        # sequence's hit out from under it.
        pins: dict[int, list] = {}
        if mgr.cache is not None:
            for u, toks in zip(uids, batch_tokens):
                seq = mgr.seqs.get(u)
                if u not in pins and (seq is None
                                      or (not seq.tokens
                                          and not seq.blocks)):
                    m = mgr.prefix_match(toks)
                    if m:
                        mgr.pin_prefix(m)
                        pins[u] = m
        try:
            if do_checks:
                # cumulative admission over the whole batch, so a failure
                # raises before any state mutation
                need = 0
                for u, toks in zip(uids, batch_tokens):
                    seq = mgr.seqs.get(u)
                    seq_blocks = len(seq.blocks) if seq else 0
                    seq_need = mgr.blocks_needed(
                        seq or SequenceDescriptor(uid=u, tokens=[]),
                        len(toks))
                    if seq_blocks + seq_need > mgr.max_blocks_per_seq:
                        raise RuntimeError(
                            f"sequence {u} would exceed the max length "
                            f"({mgr.max_blocks_per_seq * mgr.block_size} "
                            f"tokens)")
                    need += seq_need - len(pins.get(u, ()))
                if need > mgr.available_blocks:
                    raise RuntimeError(
                        f"cannot schedule batch: needs {need} KV blocks, "
                        f"{mgr.available_blocks} allocatable — the pool "
                        "is exhausted (flush finished sequences)")
            for u, toks in zip(uids, batch_tokens):
                mgr.extend(u, list(map(int, toks)),
                           pinned=pins.pop(u, None))
                # re-admission invalidates any logits stashed when this
                # uid finished during another caller's drain: the stashed
                # value is from the old position and tick() must not
                # surface it while the uid has pending tokens again
                # (mirrors flush()). Popped only after extend() succeeds
                # — a failed admission (do_checks=False + exhausted pool)
                # must leave the stash intact for the original caller.
                self._finished_stash.pop(u, None)
        except BaseException:
            for m in pins.values():
                mgr.unpin_prefix(m)
            raise

    def tick(self) -> dict[int, jnp.ndarray]:
        """ONE scheduler tick (the compute half of the reference's
        put():107): a single bucketed forward over every sequence with
        pending tokens — prefill chunks (SplitFuse budget) and the decode
        batch ride the same pass. Returns {uid: last-token logits} for
        sequences whose pending tokens finished this tick (including any
        stashed by a concurrent put() that drained them as a side
        effect). Callers may schedule() new sequences between ticks —
        mid-prompt admission, which folding the loop into put() would
        forfeit."""
        mgr = self.state_manager
        out = dict(self._finished_stash)
        self._finished_stash.clear()
        run_uids = [u for u, s in mgr.seqs.items() if s.pending]
        run_uids = run_uids[:self._config.max_ragged_sequence_count]
        if run_uids:
            logits = self._run(run_uids)
            out.update({u: logits[i] for i, u in enumerate(run_uids)
                        if not mgr.seqs[u].pending})
        return out

    def put(self, batch_uids: Sequence[int],
            batch_tokens: Sequence[Sequence[int]],
            do_checks: bool = True) -> jnp.ndarray:
        """schedule() + tick()-until-drained for the given sequences;
        returns last-token logits [n, V] in uid order (the reference
        put():107 plus the caller loop DeepSpeed-MII wraps around it).
        Use schedule()/tick() directly for inter-tick admission."""
        uids = [int(u) for u in batch_uids]
        uid_set = set(uids)
        self.schedule(uids, batch_tokens, do_checks)
        mgr = self.state_manager
        final: dict[int, jnp.ndarray] = {}
        while any(mgr.seqs[u].pending for u in uids):
            for u, lg in self.tick().items():
                if u in uid_set:
                    final[u] = lg
                else:
                    # a sequence someone else schedule()d finished as a
                    # side effect of our drain: stash its logits for
                    # that caller's next tick() instead of dropping them
                    self._finished_stash[u] = lg
        return jnp.stack([final[u] for u in uids])

    def query(self, uid: int) -> tuple[int, int]:
        """(cached_tokens, allocated_blocks) for a sequence (reference:
        engine_v2.query:158)."""
        seq = self.state_manager.seqs.get(uid)
        if seq is None:
            return (0, 0)
        return (seq.seen, len(seq.blocks))

    def can_schedule(self, uid: int, n_tokens: int) -> bool:
        return self.state_manager.can_schedule(uid, n_tokens)

    @property
    def free_blocks(self) -> int:
        """Schedulable KV-block headroom. Matches the admission math:
        cached blocks with refcount zero count as free (the allocator
        evicts them on demand)."""
        return self.state_manager.available_blocks

    # ------------------------------------------------------------------
    # KV-pool byte truth (ISSUE 12): the numbers ds_kv_pool_bytes /
    # ds_kv_bytes_per_token export and the bench kvquant stage gates
    @property
    def kv_dtype(self) -> str:
        """Storage format of the KV payload pools ("fp16" family names
        the engine compute dtype when quantization is off)."""
        return (self._config.kv_cache.dtype if self._kv_quant
                else str(np.dtype(self.dtype)))

    def kv_pool_bytes(self) -> int:
        """Actual HBM bytes of the paged KV pools — payload slabs plus
        (when quantized) the per-vector scale slabs. Computed from the
        live arrays, so it is definitionally what the ledger's
        ``memory_analysis()`` sees as pool operand bytes."""
        return int(sum(int(np.prod(l.shape)) * l.dtype.itemsize
                       for l in self.pools.values()))

    def kv_bytes_per_token(self) -> float:
        """KV bytes one cached token costs across all layers (k+v,
        scales included) — pool bytes over pool token capacity."""
        return (self.kv_pool_bytes()
                / (self.num_kv_blocks * self._config.kv_block_size))

    def flush(self, uids) -> None:
        """Release finished sequences' KV blocks; accepts one uid or an
        iterable (reference: engine_v2.flush:242 takes uids)."""
        if isinstance(uids, (int, np.integer)):
            uids = [uids]
        for u in uids:
            self.state_manager.flush(int(u))
            self._finished_stash.pop(int(u), None)

    # ------------------------------------------------------------------
    # cross-mesh KV migration (ISSUE 13): park()/restore generalized so
    # the KV BYTES move between engines instead of being recomputed —
    # the transport between the prefill engine and the decode replicas
    # (and between decode replicas) in disaggregated serving. The
    # hand-off is host-mediated (device_get -> wire -> device scatter);
    # on a multi-slice TPU deployment this is exactly the ICI/DCN
    # boundary the bytes would cross anyway.

    def export_request(self, uid: int, *, n_generated: int = 0,
                       source: str = "") -> KVExportState:
        """Serialize one sequence's KV block set and release it from
        this engine: the blocks holding written KV (positions < seen)
        are gathered from the pools — quantized codes and scale slabs
        AS-IS, no dequantize — and the sequence is flushed (blocksan
        conservation runs at that quiesce; with the prefix cache on,
        published full blocks stay warm in the LRU like any park).
        Export happens at a dispatch boundary: exactly one pending
        token, which becomes the importing engine's first fused-
        dispatch input, so greedy continuation is bit-identical."""
        if self._affinity is not None:
            self._affinity.check("v2/export_request")
        mgr = self.state_manager
        seq = mgr.seqs.get(int(uid))
        if seq is None:
            raise RuntimeError(f"export_request: unknown uid {uid}")
        if seq.pending != 1:
            raise RuntimeError(
                f"export_request: sequence {uid} must have exactly one "
                f"pending token (a dispatch boundary), got {seq.pending}")
        bs = mgr.block_size
        n_payload = min(-(-seq.seen // bs), len(seq.blocks))
        if n_payload:
            idx = jnp.asarray(np.asarray(seq.blocks[:n_payload],
                                         np.int32))
            payload = jax.device_get(
                {k: jnp.take(v, idx, axis=1)
                 for k, v in self.pools.items()})
        else:
            # nothing written yet (single-token prompt): layout-only
            # payload, zero wire bytes
            payload = {k: np.zeros((v.shape[0], 0)
                                   + tuple(v.shape[2:]),
                                   np.dtype(v.dtype))
                       for k, v in self.pools.items()}
        handoff_id = None
        if self._blocksan is not None:
            handoff_id = self._blocksan.on_export(
                int(uid), seq.blocks[:n_payload], seq.seen)
        state = KVExportState(
            tokens=list(seq.tokens), n_generated=int(n_generated),
            seen=int(seq.seen), block_size=bs, kv_dtype=self.kv_dtype,
            payload=payload, handoff_id=handoff_id,
            source=source or f"engine-{id(self):x}")
        mgr.flush(int(uid))
        return state

    def _import_fn(self, width: int):
        """Donated pool scatter for one import, cached per power-of-two
        block-index width (pad indices point past the pool; mode='drop'
        discards their writes) — bounded executables, pools updated
        in place."""
        key = ("kv_import", width)
        if key not in self._fused_cache:
            def scatter(pools, idx, payload):
                return {k: pools[k].at[:, idx].set(payload[k],
                                                   mode="drop")
                        for k in pools}
            self._fused_cache[key] = jax.jit(
                scatter, donate_argnums=(0,),
                out_shardings=dict(self._pool_shardings))
        return self._fused_cache[key]

    def import_request(self, uid: int, state: KVExportState) -> int:
        """Admit a migrated sequence position-exactly: allocate blocks
        for the full history, scatter the travelled payload (quantized
        blocks + scales land untouched in their storage dtype), and
        re-publish the full-block chain into this engine's prefix
        cache. Returns the pending input token of the next fused
        dispatch. Raises — before any pool mutation — on a KV-layout
        mismatch or when the pool cannot hold the sequence."""
        if self._affinity is not None:
            self._affinity.check("v2/import_request")
        mgr = self.state_manager
        if state.kv_dtype != self.kv_dtype:
            raise ValueError(
                f"import_request: migrated KV dtype "
                f"{state.kv_dtype!r} != this engine's "
                f"{self.kv_dtype!r} — migration never converts "
                "payload formats")
        if state.block_size != mgr.block_size:
            raise ValueError(
                f"import_request: migrated block size "
                f"{state.block_size} != {mgr.block_size}")
        if set(state.payload) != set(self.pools):
            raise ValueError(
                f"import_request: payload slabs "
                f"{sorted(state.payload)} != pool slabs "
                f"{sorted(self.pools)}")
        for k, a in state.payload.items():
            pool = self.pools[k]
            want = (pool.shape[0],) + tuple(pool.shape[2:])
            got = (a.shape[0],) + tuple(a.shape[2:])
            if want != got:
                raise ValueError(
                    f"import_request: payload slab {k!r} shape "
                    f"{got} != pool layout {want}")
        n_payload = state.payload_blocks
        seq = mgr.import_sequence(int(uid), state.tokens, state.seen,
                                  n_payload)
        try:
            if n_payload:
                width = _bucket(n_payload)
                idx = np.full((width,), self.num_kv_blocks, np.int32)
                idx[:n_payload] = seq.blocks[:n_payload]
                pay = {}
                for k, a in state.payload.items():
                    if width > n_payload:
                        pad = np.zeros((a.shape[0],
                                        width - n_payload)
                                       + tuple(a.shape[2:]), a.dtype)
                        a = np.concatenate([a, pad], axis=1)
                    pay[k] = jnp.asarray(a)
                self.pools = self._import_fn(width)(
                    self.pools, jnp.asarray(idx), pay)
        except BaseException:
            mgr.flush(int(uid))     # no leak on a failed scatter
            raise
        if self._blocksan is not None:
            self._blocksan.on_import(int(uid),
                                     seq.blocks[:n_payload],
                                     state.handoff_id)
        elif state.handoff_id is not None:
            # the EXPORTER was sanitized: clear its in-transit entry
            # even though this pool runs unsanitized, or the hand-off
            # would read as dropped
            from ...analysis import blocksan as _bsan
            _bsan.record_import(state.handoff_id)
        mgr._quiesce("import")
        return int(state.tokens[-1])

    def sample_first_tokens(self, firsts: dict, temperature: float,
                            top_k: int, top_p: float,
                            seed: int) -> dict[int, int]:
        """Sample each uid's first generated token from its last-prompt
        logits with the SAME op and position keying as the in-graph
        fused loop (one batched device call). Shared by the serve
        loop's co-located prefill and the disaggregated prefill engine,
        so a hand-off's first token is bit-identical to the co-located
        one — sampling is position-keyed per (seed, uid, position),
        invariant to which engine ran the prefill."""
        from ...ops import sampling
        if not firsts:
            return {}
        mgr = self.state_manager
        uids_f = list(firsts)
        base = self._base_key(seed)
        row_keys = jax.vmap(lambda u: jax.random.fold_in(base, u))(
            jnp.asarray(np.asarray(uids_f, np.uint32)))
        keys = sampling.position_keys(
            row_keys,
            jnp.asarray(np.asarray([mgr.seqs[u].seen for u in uids_f])))
        toks_dev = sampling.sample_tokens_batched(
            jnp.stack([firsts[u] for u in uids_f]).astype(jnp.float32),
            keys, temperature=temperature, top_k=top_k, top_p=top_p)
        return {u: int(t)
                for u, t in zip(uids_f, jax.device_get(toks_dev))}

    def prefill_request(self, uid: int, prompt, *,
                        temperature: Optional[float] = None,
                        top_k: Optional[int] = None,
                        top_p: Optional[float] = None,
                        seed: int = 0) -> int:
        """Disaggregated-prefill producer half (ISSUE 13): chunked
        prefill of one prompt on THIS engine plus the first generated
        token, leaving the sequence at the exact dispatch-boundary
        state (one pending token) ``export_request`` ships — the same
        state the co-located serve loop reaches before its first fused
        dispatch, so the downstream decode is bit-identical either
        way. Returns the first token."""
        temperature, top_k, top_p, _ = self._sampling_args(
            temperature, top_k, top_p, None)
        uid = int(uid)
        self.schedule([uid], [[int(t) for t in prompt]])
        mgr = self.state_manager
        try:
            logits = None
            while mgr.seqs[uid].pending:
                logits = self._run([uid])
            tok = self.sample_first_tokens(
                {uid: logits[0]}, temperature, top_k, top_p, seed)[uid]
            mgr.extend(uid, [tok])
        except BaseException:
            self.flush(uid)
            raise
        self.serving_stats["decoded_tokens"] += 1
        return tok

    # ------------------------------------------------------------------
    # fused multi-step decode: K ticks per host dispatch, sampling and
    # termination in-graph (the FastGen kernel-resident decode loop)

    def _base_key(self, seed: int) -> jnp.ndarray:
        key = self._seed_keys.get(seed)
        if key is None:
            # bound the cache: seed is a caller-supplied kwarg, and a
            # server feeding a fresh seed per request must not grow
            # this dict forever (keys are cheap to rebuild)
            if len(self._seed_keys) >= 64:
                self._seed_keys.clear()
            key = self._seed_keys.setdefault(seed,
                                             jax.random.PRNGKey(seed))
        return key

    def _sampling_args(self, temperature, top_k, top_p, eos_id):
        """Per-call overrides over the config's sampling defaults."""
        c = self._config
        return (float(c.sampling_temperature if temperature is None
                      else temperature),
                int(c.sampling_top_k if top_k is None else top_k),
                float(c.sampling_top_p if top_p is None else top_p),
                (c.eos_token_id if eos_id is None else int(eos_id)))

    def _fused_fn(self, num_steps: int, temperature: float, top_k: int,
                  top_p: float, eos_id: Optional[int]):
        key = (num_steps, temperature, top_k, top_p, eos_id)
        if key not in self._fused_cache:
            tp = self._v1.topology.model_parallel_size
            pool_sh = dict(self._pool_shardings)
            self._fused_cache[key] = jax.jit(
                functools.partial(
                    fused_decode_loop, self.model, num_steps=num_steps,
                    eos_id=eos_id, temperature=temperature, top_k=top_k,
                    top_p=top_p, use_kernel=(tp <= 1)),
                donate_argnums=(1,),
                out_shardings=(None, None, None, None, None, None,
                               pool_sh))
        return self._fused_cache[key]

    def _serve_fn(self, num_steps: int, temperature: float, top_k: int,
                  top_p: float, eos_id: Optional[int]):
        """Ring-mode executable (ISSUE 6): the fused decode loop with
        in-graph admission of pre-staged requests and the device-side
        output ring (paged.fused_serve_loop). Cached beside the plain
        fused executables under a mode-tagged key."""
        key = ("serve", num_steps, temperature, top_k, top_p, eos_id)
        if key not in self._fused_cache:
            tp = self._v1.topology.model_parallel_size
            pool_sh = dict(self._pool_shardings)
            self._fused_cache[key] = jax.jit(
                functools.partial(
                    fused_serve_loop, self.model, num_steps=num_steps,
                    eos_id=eos_id, temperature=temperature, top_k=top_k,
                    top_p=top_p, use_kernel=(tp <= 1)),
                donate_argnums=(1,),
                out_shardings=(None,) * 11 + (pool_sh,))
        return self._fused_cache[key]

    def _spec_fn(self, num_steps: int, temperature: float, top_k: int,
                 top_p: float, eos_id: Optional[int]):
        """Speculative-decode executable (ISSUE 9): the fused decode
        loop with prompt-lookup drafting and the 1+draft_len verify
        forward (paged.fused_spec_decode_loop). draft_len/min_ngram
        are static from the config block (one executable family per
        setting)."""
        sp = self._config.speculative
        key = ("spec", num_steps, sp.draft_len, sp.min_ngram,
               temperature, top_k, top_p, eos_id)
        if key not in self._fused_cache:
            tp = self._v1.topology.model_parallel_size
            pool_sh = dict(self._pool_shardings)
            self._fused_cache[key] = jax.jit(
                functools.partial(
                    fused_spec_decode_loop, self.model,
                    num_steps=num_steps, draft_len=sp.draft_len,
                    min_ngram=sp.min_ngram, eos_id=eos_id,
                    temperature=temperature, top_k=top_k, top_p=top_p,
                    use_kernel=(tp <= 1)),
                donate_argnums=(1,),
                out_shardings=(None,) * 9 + (pool_sh,))
        return self._fused_cache[key]

    def _spec_serve_fn(self, num_steps: int, temperature: float,
                       top_k: int, top_p: float,
                       eos_id: Optional[int]):
        """Ring-mode speculative executable: in-graph admission +
        per-row device output ring + prompt-lookup verify
        (paged.fused_spec_serve_loop)."""
        sp = self._config.speculative
        key = ("spec_serve", num_steps, sp.draft_len, sp.min_ngram,
               temperature, top_k, top_p, eos_id)
        if key not in self._fused_cache:
            tp = self._v1.topology.model_parallel_size
            pool_sh = dict(self._pool_shardings)
            self._fused_cache[key] = jax.jit(
                functools.partial(
                    fused_spec_serve_loop, self.model,
                    num_steps=num_steps, draft_len=sp.draft_len,
                    min_ngram=sp.min_ngram, eos_id=eos_id,
                    temperature=temperature, top_k=top_k, top_p=top_p,
                    use_kernel=(tp <= 1)),
                donate_argnums=(1,),
                out_shardings=(None,) * 14 + (pool_sh,))
        return self._fused_cache[key]

    def _history_rows(self, uids: list[int], bb: int) -> np.ndarray:
        """Right-aligned recent-token history rows [bb, history_window]
        for the prompt-lookup drafter, -1-filled (pad rows all -1) —
        the committed history INCLUDING the pending token, so drafts
        continue from the next dispatch input. Prefix-cache-shared
        prompt blocks are in ``seq.tokens`` like any other committed
        token, so a cache-hit admission seeds the same window a cold
        one would."""
        hw = int(self._config.speculative.history_window)
        hist = np.full((bb, hw), -1, np.int32)
        for i, u in enumerate(uids):
            hist[i] = self.state_manager.history_tail(u, hw)
        return hist

    def _spec_operands(self, uids: list[int], k: int,
                       budgets: dict[int, int], seed: int):
        """:meth:`_fused_operands` plus the drafter's history window.
        The reserve horizon grows to ``k * (1 + draft_len)``: a
        K-step speculative dispatch may commit that many tokens per
        row (still budget-capped; in-graph drafts are clamped to
        ``remaining - 1`` so KV writes never pass the reserved
        blocks)."""
        el = int(self._config.speculative.draft_len)
        wide = {u: min(int(budgets[u]), k * (1 + el)) for u in uids}
        for u in uids:
            # _fused_operands reserves min(k, budget); top up to the
            # speculative horizon first (idempotent delta)
            self.state_manager.reserve(u, max(wide[u], 1))
        ops = self._fused_operands(uids, k, budgets, seed)
        hist = jnp.asarray(self._history_rows(uids, int(ops[0].shape[0])))
        return ops + (hist,)

    def _fused_operands(self, uids: list[int], k: int,
                        budgets: dict[int, int], seed: int):
        """Host-side build of one fused dispatch's operands. Every uid
        must have exactly ONE pending token (its next input — the last
        sampled/committed token); blocks covering the dispatch horizon
        are preallocated here so the in-graph KV writes always land in
        real blocks."""
        mgr = self.state_manager
        seqs = [mgr.seqs[u] for u in uids]
        for u, s in zip(uids, seqs):
            if s.pending != 1:
                raise RuntimeError(
                    f"fused decode: sequence {u} must have exactly one "
                    f"pending token (the dispatch input), got {s.pending}")
            mgr.reserve(u, min(k, max(int(budgets[u]), 1)))
        bb = _batch_bucket(len(seqs))
        tokens = np.zeros((bb,), np.int32)
        pos = np.zeros((bb,), np.int32)
        act = np.zeros((bb,), bool)
        rem = np.zeros((bb,), np.int32)
        for i, (u, s) in enumerate(zip(uids, seqs)):
            tokens[i] = s.tokens[-1]
            pos[i] = s.seen
            act[i] = budgets[u] > 0
            rem[i] = budgets[u]
        tables = np.stack([mgr.block_table(s) for s in seqs]
                          + [mgr.block_table(seqs[0])] * (bb - len(seqs)))
        # narrow to the blocks actually held (context + reserved
        # horizon) — bounded executables per power-of-two width
        kb = min(_bucket(max(max(len(s.blocks) for s in seqs), 1)),
                 tables.shape[1])
        tables = tables[:, :kb]
        # per-row PRNG keys: uid folded into the base key (pad rows get
        # sentinel ids); each loop step folds in the token position, so
        # sampling is invariant to the dispatch grouping
        base = self._base_key(seed)
        # via numpy: jnp.asarray of a LIST is an implicit
        # convert_element_type upload (trips the transfer guard); a
        # numpy array takes the explicit device_put path
        ids = jnp.asarray(np.asarray(
            list(uids) + [(1 << 30) + i for i in range(bb - len(uids))],
            np.uint32))
        row_keys = jax.vmap(lambda u: jax.random.fold_in(base, u))(ids)
        return (jnp.asarray(tokens), jnp.asarray(pos), jnp.asarray(tables),
                jnp.asarray(act), jnp.asarray(rem), row_keys)

    def _fused_dispatch_scope(self, fn_key: tuple, ops: tuple,
                              variant: str = "host"):
        """Sentinel scope for ONE fused dispatch: a new (jit key,
        operand shape/dtype, variant) signature may compile; a seen one
        must hit the executable cache — and under the transfer guard no
        implicit host transfer may ride the dispatch (operands are
        already device arrays; the loop carry never leaves the device).

        ``variant`` separates host-built operands from device-carry
        operands: their avals match but their shardings don't (fresh
        ``jnp.asarray`` uploads vs committed jit outputs), so XLA keeps
        one executable per variant — a fact this sentinel itself
        surfaced when first wired in."""
        if self._affinity is not None:
            # every fused dispatch path (decode_fused, chain mode, ring
            # mode) enters through this scope — one affinity choke point
            self._affinity.check("v2/fused_dispatch")
        s = self._decode_sentinel
        if s is None:
            return _NULLCM
        sig = (fn_key, variant,
               tuple((tuple(a.shape), str(a.dtype)) for a in ops))
        if sig not in self._fused_sigs:
            self._fused_sigs.add(sig)
            s.expect("new fused bucket/sampling signature")
        import contextlib
        stack = contextlib.ExitStack()
        stack.enter_context(s.watch())
        stack.enter_context(self._hot_guard())
        return stack

    def decode_fused(self, batch_uids: Sequence[int],
                     k_steps: Optional[int] = None, *,
                     budgets: Optional[dict[int, int]] = None,
                     temperature: Optional[float] = None,
                     top_k: Optional[int] = None,
                     top_p: Optional[float] = None,
                     eos_id: Optional[int] = None,
                     seed: int = 0) -> dict[int, list[int]]:
        """ONE fused dispatch: advance every uid up to
        ``min(k_steps, budgets[uid])`` tokens inside a single compiled
        while_loop — forward, sampling, KV writes and EOS/budget
        termination all on device. Each uid needs exactly one pending
        token (e.g. from put() + a sampled continuation, or a previous
        decode_fused). Generated tokens are committed to the sequence
        state; the last one stays pending as the next dispatch's input.
        Returns {uid: [sampled tokens]} (a row that sampled ``eos_id``
        includes it and stops)."""
        uids = [int(u) for u in batch_uids]
        if not uids:
            return {}
        cfg = self._config
        k = max(1, int(k_steps if k_steps is not None
                       else (cfg.fused_decode_steps or 8)))
        temperature, top_k, top_p, eos = self._sampling_args(
            temperature, top_k, top_p, eos_id)
        b = {u: int(budgets[u]) if budgets is not None else k
             for u in uids}
        st = self.serving_stats
        spec = self._config.speculative.enabled
        tel = _telemetry()
        t0 = time.perf_counter() if tel is not None else 0.0
        with (tel.span("v2/fused_dispatch",
                       dispatch_id=st["fused_dispatches"] + 1,
                       rows=len(uids), k=k)
              if tel is not None else _NULLCM):
            if spec:
                sp = self._config.speculative
                ops = self._spec_operands(uids, k, b, seed)
                fn = self._spec_fn(k, temperature, top_k, top_p, eos)
                fn_key = ("spec", k, sp.draft_len, sp.min_ngram,
                          temperature, top_k, top_p, eos)
            else:
                ops = self._fused_operands(uids, k, b, seed)
                fn = self._fused_fn(k, temperature, top_k, top_p, eos)
                fn_key = (k, temperature, top_k, top_p, eos)
            if tel is not None:
                self._device_truth_observe(tel, "v2/fused_dispatch",
                                           fn, ops)
            st["host_dispatches"] += 1
            st["fused_dispatches"] += 1
            with self._fused_dispatch_scope(fn_key, ops):
                if spec:
                    (out, out_ptr, steps, _, _, _, _, _, spec_stats,
                     self.pools) = fn(self.params, self.pools, *ops)
                else:
                    out, steps, _, _, _, _, self.pools = fn(
                        self.params, self.pools, *ops)
            toks = np.asarray(out)[:len(uids)]
            if spec:
                ptrs = np.asarray(out_ptr)[:len(uids)]
                self._absorb_spec_stats(np.asarray(spec_stats))
            mgr = self.state_manager
            res: dict[int, list[int]] = {}
            for i, u in enumerate(uids):
                row = [int(t) for t in
                       (toks[i, :ptrs[i]] if spec else toks[i])
                       if t >= 0]
                mgr.commit_device_tokens(u, row)
                res[u] = row
                st["decoded_tokens"] += len(row)
                st["fused_slot_tokens"] += len(row)
                if not spec:
                    # one token per live slot; the spec path's live-slot
                    # count arrives in the device stats instead
                    st["fused_live_slots"] += len(row)
            n_exec = int(steps)
            st["fused_steps"] += n_exec
            st["fused_slots"] += n_exec * len(uids)
        if tel is not None:
            self._record_dispatch_telemetry(
                tel, time.perf_counter() - t0)
        if self._numsan is not None:
            # the fused loop returns tokens, not logits — the numsan
            # work here is the dispatch-boundary choke point: cadenced
            # KV-scale audit, then surface any deferred quantize-site
            # saturation findings from the executed loop
            self._numsan_dispatches += 1
            if (self._numsan_dispatches
                    % self._numsan.probe_interval == 0):
                self.numsan_check_kv_pools()
            self._numsan.drain()
        return res

    def _absorb_spec_stats(self, stats) -> None:
        """Fold one dispatch's (or chain's) device spec counters —
        [proposed, accepted, hit_slots, live_slots] int32 — into
        serving_stats."""
        self.serving_stats["spec_proposed_tokens"] += int(stats[0])
        self.serving_stats["spec_accepted_tokens"] += int(stats[1])
        self.serving_stats["spec_hit_slots"] += int(stats[2])
        self.serving_stats["fused_live_slots"] += int(stats[3])

    def _numsan_probe(self, logits) -> None:
        """Per-tick dispatch numsan hook: every ``probe_interval``-th
        dispatch runs the fused logits stats (non-finite count +
        masked max|logit|) and, with a quantized cache, the KV-scale
        audit — one host sync on the cadence; then drains any deferred
        quantize-site saturation findings (always, pure host work)."""
        san = self._numsan
        self._numsan_dispatches += 1
        if self._numsan_dispatches % san.probe_interval == 0:
            if self._logits_stats_fn is None:
                self._logits_stats_fn = jax.jit(lambda x: (
                    jnp.sum(~jnp.isfinite(x)).astype(jnp.int32),
                    jnp.max(jnp.where(jnp.isfinite(x),
                                      jnp.abs(x), 0.0))))
            nf, ma = self._logits_stats_fn(logits)
            san.check_logits("v2/dispatch", int(nf), float(ma))
            self.numsan_check_kv_pools()
        san.drain()

    def numsan_check_kv_pools(self) -> None:
        """Audit the quantized KV scale slabs for non-finite scales (a
        non-finite activation quantized into the cache poisons every
        later read of its block). Rides the numsan probe cadence;
        callable directly for forensics. No-op without a quantized
        cache or with ``kv_scale_probe`` off."""
        if (self._numsan is None or not self._kv_quant
                or not self._numsan_kv_probe):
            return
        scales = jnp.concatenate([self.pools["ks"].reshape(-1),
                                  self.pools["vs"].reshape(-1)])
        finite = jnp.isfinite(scales)
        nf = int(jnp.sum(~finite))
        ms = float(jnp.max(jnp.where(finite, scales, 0.0)))
        self._numsan.check_kv_scales("v2/kv_pools", nf, ms)

    def _device_truth_observe(self, tel, name: str, fn,
                              dev_ops: tuple) -> None:
        """Flight-recorder heartbeat + executable-ledger observation
        for one v2 dispatch (ISSUE 5; no-ops unless the opt-in knobs
        enabled them). Must run BEFORE the dispatch: the KV pools are
        donated operands."""
        fr = tel.get_flight_recorder()
        if fr is not None:
            fr.progress("v2_dispatch", span=name)
        led = tel.get_ledger()
        if led is not None:
            entry = led.observe(name, fn,
                                (self.params, self.pools)
                                + tuple(dev_ops),
                                mesh=self.mesh)
            if self._meshsan is not None:
                # traffic-contract check (ISSUE 15): once per NEW
                # executable, a set lookup per later dispatch
                self._meshsan.observe_entry(entry)

    def _record_dispatch_telemetry(self, tel, dt: float) -> None:
        """Fused-dispatch boundary metrics (per DISPATCH — K tokens'
        worth of work — never per token)."""
        fr = tel.get_flight_recorder()
        if fr is not None:
            # drain completed = the decode loop made real progress
            # (the hang watchdog's deadline clock resets here)
            fr.progress("v2_drain")
        reg = tel.get_registry()
        if reg is None:
            return
        reg.histogram(
            "ds_serving_fused_dispatch_seconds",
            "host-blocking time of one fused decode dispatch: full "
            "dispatch (operands+enqueue+drain) on the decode_fused "
            "path, ring-buffer drain only on the double-buffered "
            "generate_fused path (its enqueue overlaps device "
            "work)").observe(dt)
        tel.bridges.collect_serving(reg, self.serving_metrics())
        reg.gauge("ds_serving_free_kv_blocks",
                  "schedulable blocks in the paged KV pool (truly free "
                  "plus evictable prefix-cached)").set(
            self.free_blocks, engine="v2")

    def serving_metrics(self) -> dict:
        """Decode-loop efficiency counters (monitor/bench surface):
        ``dispatches_per_token`` — host dispatches per decoded token
        (1.0 = per-tick; ~1/K with the fused loop) and
        ``fused_occupancy`` — fraction of scheduled (row, step) slots
        whose row was still LIVE (1.0 = every scheduled row decoded
        every step; rows going EOS/budget-inactive mid-loop lower it).
        Pad rows added by the batch bucketing are not counted — this
        measures scheduling efficiency over real sequences, not device
        utilization of the padded bucket. Spec-off the numerator equals
        the committed-token count; spec-on it comes from the device
        loops' live-slot counter, so occupancy stays a <= 1.0 fraction
        while ``tokens_per_dispatch`` carries the multiplier.

        With prefix caching the dict additionally carries the cache
        counters (``prefix_hits``/``prefix_misses`` at full-block
        granularity, ``prefix_evictions``, ``prefill_tokens_saved``)
        and occupancy gauges (``prefix_hit_rate``,
        ``prefix_cached_blocks``, ``prefix_evictable_blocks``) — zeros
        when the cache is disabled, so consumers always see one stable
        schema."""
        st = dict(self.serving_stats)
        st.update(self.state_manager.prefix_cache_metrics())
        st["dispatches_per_token"] = (
            st["host_dispatches"] / max(st["decoded_tokens"], 1))
        st["fused_occupancy"] = (
            st["fused_live_slots"] / max(st["fused_slots"], 1))
        # speculative decoding (ISSUE 9): tokens_per_dispatch is the
        # mean tokens COMMITTED per scheduled (row, tick) slot in the
        # fused loops — <= 1.0 spec-off (then it equals
        # fused_occupancy), > 1.0 when verified drafts multiply each
        # forward. spec_acceptance_rate = accepted / proposed drafts.
        st["tokens_per_dispatch"] = (
            st["fused_slot_tokens"] / max(st["fused_slots"], 1))
        st["spec_acceptance_rate"] = (
            st["spec_accepted_tokens"]
            / max(st["spec_proposed_tokens"], 1))
        # active dispatch-chain depth (ISSUE 6 knob) rides along so
        # consumers can correlate dispatch ratios with the configured
        # chain depth
        st["max_inflight_dispatches"] = int(
            self._config.max_inflight_dispatches)
        # KV-pool byte truth (ISSUE 12): pool footprint + per-token
        # cost in the ACTIVE storage format, so a quantized engine's
        # HBM win (and its block-count growth at equal budget) is read
        # straight off the serving metrics. kv_dtype is a string —
        # bridges attach it as the ds_kv_pool_bytes gauge's label;
        # numeric-only consumers (monitor events, --diff) skip it.
        st["kv_pool_bytes"] = self.kv_pool_bytes()
        st["kv_bytes_per_token"] = round(self.kv_bytes_per_token(), 3)
        st["kv_num_blocks"] = int(self.num_kv_blocks)
        st["kv_dtype"] = self.kv_dtype
        return st

    def reset_serving_metrics(self) -> None:
        for k in self.serving_stats:
            self.serving_stats[k] = 0
        self.state_manager.reset_prefix_stats()

    # ------------------------------------------------------------------
    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: int = 32,
                 eos_id: Optional[int] = None) -> list[list[int]]:
        """Greedy continuous batching driver over schedule()/tick():
        admits prompts as KV blocks free up — including mid-prefill of
        other prompts, since admission happens between ticks — and
        decodes all live sequences together each tick. What DeepSpeed-MII
        implements on top of put() (reference: mii serving loop).
        ``eos_id`` stops a sequence once it samples that token (the
        token is included in its output). One host round trip per
        decoded token — generate_fused() is the production path."""
        mgr = self.state_manager
        bs = mgr.block_size
        pending = list(enumerate([list(map(int, p)) for p in prompts]))
        live: dict[int, list[int]] = {}
        reserved: dict[int, int] = {}   # uid -> worst-case block budget
        results: dict[int, list[int]] = {}
        max_live = self._config.max_ragged_sequence_count
        # serving-latency telemetry (resolved once per generate call; a
        # per-token observe is one float append when enabled, nothing
        # when disabled)
        tel = _telemetry()
        reg = tel.get_registry() if tel is not None else None
        lat = _LatencyProbe(reg) if reg is not None else None
        # per-request traces (ISSUE 10): the per-tick driver records
        # the same lifecycle the fused serve loop does, so its requests
        # land in the access log / Perfetto tracks too
        rt = tel.get_request_recorder() if tel is not None else None
        if rt is not None:
            for uid, prompt in pending:
                rt.enqueue(uid, priority=1, prompt_tokens=len(prompt),
                           max_new_tokens=max_new_tokens)

        def admit():
            """Admit as many pending prompts as fit, reserving each one's
            worst-case block budget so live sequences can never exhaust
            the pool mid-decode. Prefix-cache hits shrink a prompt's
            admission cost to its UNCACHED blocks (plus pinning parked
            LRU blocks out of the evictable headroom), so a shared
            system prompt stops counting against capacity."""
            batch: list[tuple[int, list[int]]] = []
            allocated = sum(len(mgr.seqs[u].blocks) for u in live)
            headroom = (mgr.available_blocks
                        - (sum(reserved.values()) - allocated))
            while pending and len(live) + len(batch) < max_live:
                uid, prompt = pending[0]
                need = -(-(len(prompt) + max_new_tokens) // bs)
                if need > mgr.max_blocks_per_seq or \
                        need > mgr.allocator.num_blocks:
                    raise ValueError(
                        f"prompt {uid}: {len(prompt)} tokens + "
                        f"{max_new_tokens} new can never fit the KV pool "
                        f"(needs {need} blocks)")
                cost = mgr.admission_cost(prompt, need)
                if cost > headroom:
                    break
                pending.pop(0)
                headroom -= cost
                reserved[uid] = need
                batch.append((uid, prompt))
            if batch:
                self.schedule([u for u, _ in batch],
                              [p for _, p in batch])
                for uid, _ in batch:
                    live[uid] = []
            if lat is not None:
                lat.admitted([u for u, _ in batch], waiting=len(pending))
            if rt is not None:
                for uid, _ in batch:
                    seen = mgr.seqs[uid].seen
                    rt.admitted(uid, queue_depth=len(pending),
                                cached_tokens=seen,
                                cached_blocks=seen // bs)

        try:
            admit()
            while live or pending:
                if not live:
                    admit()
                    if not live:  # reservation math guarantees progress
                        raise RuntimeError(
                            "continuous-batching deadlock: pending "
                            "prompts but nothing admissible")
                    continue
                # one tick advances every pending sequence one chunk; a
                # sequence whose pending drained yields logits -> sample
                t_tick = time.perf_counter() if rt is not None else 0.0
                finished = self.tick()
                decode_uids: list[int] = []
                for u in sorted(finished):
                    if u not in live:
                        # not ours (scheduled by another caller): re-stash
                        self._finished_stash[u] = finished[u]
                        continue
                    # per-token host argmax IS the per-tick driver's cost
                    # model (one RTT per token, documented above);
                    # generate_fused() is the production path
                    live[u].append(int(jnp.argmax(finished[u])))  # graftlint: disable=GL004
                    self.serving_stats["decoded_tokens"] += 1
                    if lat is not None:
                        lat.tokens(u, 1, first=len(live[u]) == 1)
                    if rt is not None:
                        # each tick is this driver's dispatch window:
                        # tick wall lands in decode_active, inter-tick
                        # host time in boundary_gap
                        rt.tokens_landed(u, 1, window_start=t_tick,
                                         steps=1)
                    if (len(live[u]) >= max_new_tokens
                            or (eos_id is not None
                                and live[u][-1] == eos_id)):
                        results[u] = live.pop(u)[:max_new_tokens]
                        reserved.pop(u)
                        self.flush(u)
                        if rt is not None:
                            rt.finished(u, "completed")
                    else:
                        decode_uids.append(u)
                if decode_uids:
                    self.schedule(decode_uids,
                                  [[live[u][-1]] for u in decode_uids],
                                  do_checks=False)  # blocks pre-reserved
                admit()
        except BaseException:
            # an error mid-drive (e.g. a later prompt's oversized
            # ValueError raised from admit()) must not strand the
            # already-scheduled sequences' KV blocks on a shared engine
            for u in list(live):
                self.flush(u)
            if rt is not None:
                for u in list(live) + [uid for uid, _ in pending]:
                    rt.finished(u, "aborted")
            raise
        return [results[i] for i in range(len(prompts))]

    # ------------------------------------------------------------------
    def generate_fused(self, prompts: Sequence[Sequence[int]],
                       max_new_tokens: int = 32, *,
                       k_steps: Optional[int] = None,
                       temperature: Optional[float] = None,
                       top_k: Optional[int] = None,
                       top_p: Optional[float] = None,
                       eos_id: Optional[int] = None,
                       seed: int = 0) -> list[list[int]]:
        """Continuous batching where the host is ONLY an admission
        layer: every live sequence advances up to K tokens per dispatch
        inside the fused on-device loop (sampling, KV writes and
        EOS/budget termination in-graph), so decode throughput rides
        K·compute per host round trip instead of one RTT per token.

        Between dispatches the host admits new prompts, prefills them
        through the bucketed chunk path, and drains finished tokens
        from the dispatch's output ring buffer. Dispatches chain up to
        ``max_inflight_dispatches`` deep (default 2 — double
        buffering): while dispatches run on device, the host drains
        the OLDEST one's ring buffer — chaining works because the
        loop's carry (next tokens, positions, active masks) stays on
        device, so dispatch N+1 needs no host read of dispatch N. With
        ``fused_admission`` the chain goes further device-resident:
        waiting prompts are pre-staged and swapped into finished rows'
        slots inside the compiled loop, and the host reads one output
        ring per CHAIN instead of per dispatch. Greedy decode is
        token-identical to generate(); stochastic decode is
        dispatch-schedule-invariant (position-keyed sampling), so
        per-tick and fused-K agree there too.

        The scheduler itself lives in
        :class:`~.serve_loop.FusedServeLoop` (shared with the async
        serving front end, ``deepspeed_tpu.serving``); this wrapper
        runs it closed-loop over a fixed prompt list."""
        from .serve_loop import FusedServeLoop
        loop = FusedServeLoop(self, k_steps=k_steps,
                              temperature=temperature, top_k=top_k,
                              top_p=top_p, eos_id=eos_id, seed=seed,
                              strict=True)
        results: dict[int, list[int]] = {}
        for i, p in enumerate(prompts):
            loop.submit(p, max_new_tokens, uid=i)
            results[i] = []
        while loop.has_work():
            for evt in loop.step():
                results[evt.uid].extend(evt.tokens)
        return [results[i][:max_new_tokens]
                for i in range(len(prompts))]
